"""Prototype: sort-free dictionary build + rank via MXU matmuls, for the
value_bound <= 2^13 gcd/affine columns of the cfg2 shape (the dict32 part
of the rowgroup probe; production path parallel/sharded.encode_step_single
with val_bits = 13).

Idea: decompose v = hi*S + lo (S = 64; hi < 128, lo < 64 for 13-bit
values).  With one-hot matrices H (N x 128) and L (N x 64):

- histogram:  C = H^T @ L  is the (128 x 64) bin-count matrix — the
  whole 8192-bin histogram as ONE matmul (f32 accumulation is exact up
  to 2^24, so 64Ki rows can never overflow);
- presence/dictionary: bins with C > 0, in (hi, lo) row-major order =
  ascending value order;
- rank table: RT = cumsum(presence) - 1 over the flat 8192 bins maps a
  value to its ascending-unique index — and each row's rank is the
  bilinear form H[r] @ RT @ L[r]^T.  RT entries reach 8191, beyond
  bf16's exact-integer range (256), so RT splits into two planes
  RT = RThi*64 + RTlo with both planes < 256 — two bf16 matmuls
  M = H @ RTplane (N x 64), then rank = 64*rowsum(Mhi*L) + rowsum(Mlo*L).

The comparator network pays ~O(N log^2 N) data movement; this pays
3 matmuls of N*128*64 MACs on the MXU where MACs are nearly free, plus
one-hot builds on the VPU.  The catch is HBM traffic if H/L materialize
(N x 192 bf16 = 24 MB per column) — this XLA prototype measures exactly
that regime; a fused Pallas tile kernel would keep H/L in VMEM.

Identity: ranks + dictionary byte-identical to encode_step_single's
(packed, ulo, k) on CPU (asserted below).  `--tpu` times the (16, 64Ki)
dict32 shape vs the production kernel.
"""

from __future__ import annotations

import functools
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import numpy as np
import jax
import jax.numpy as jnp

S_LO = 64  # lo radix; hi radix = value_bound // S_LO


@functools.partial(jax.jit, static_argnames=("value_bound", "width"))
def dict_matmul(lo, count, value_bound: int = 1 << 13, width: int = 16):
    """(C, N) uint32 values < value_bound -> (indices (C, N) uint32,
    ulo (C, value_bound) uint32 ascending-unique-padded, k (C,) int32).
    Same contract as the pre-pack stage of encode_step_single: invalid
    rows (>= count) get index 0 and join no dictionary."""
    n = lo.shape[1]
    nhi = value_bound // S_LO
    iota = jnp.arange(n, dtype=jnp.int32)
    valid = iota < count

    def one_column(lc):
        hi = (lc // S_LO).astype(jnp.int32)
        lo_d = (lc % S_LO).astype(jnp.int32)
        # int8 one-hots (half the HBM footprint of bf16; native int8 MXU
        # with exact int32 accumulation); invalid rows all-zero so they
        # join no bin
        H = (hi[:, None] == jnp.arange(nhi)[None, :]) & valid[:, None]
        L = (lo_d[:, None] == jnp.arange(S_LO)[None, :]) & valid[:, None]
        Hb = H.astype(jnp.int8)
        Lb = L.astype(jnp.int8)
        counts = jax.lax.dot_general(
            Hb, Lb, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)  # (nhi, S_LO) histogram
        present = (counts > 0).reshape(-1)  # flat, ascending value order
        k = jnp.sum(present.astype(jnp.int32))
        rt = jnp.cumsum(present.astype(jnp.int32)) - 1  # value -> rank
        # dictionary: ascending present bin values compacted to the front
        # (packed single-operand sort over the 8192 bins — tiny next to N)
        bins = jnp.arange(value_bound, dtype=jnp.uint32)
        ulo = jnp.sort(jnp.where(present, bins, jnp.uint32(0xFFFFFFFF)))
        # rank per row as a bilinear form, rank-table split into int8-exact
        # planes (< 128):  rt = rt_hi * 128 + rt_lo, valid while
        # value_bound <= 2^14 (ranks < 16384) — assert statically
        assert value_bound // S_LO * S_LO == value_bound
        assert value_bound <= (1 << 14)
        rtm = rt.reshape(nhi, S_LO)
        rt_hi = (rtm // 128).astype(jnp.int8)
        rt_lo = (rtm % 128).astype(jnp.int8)
        mhi = jax.lax.dot_general(Hb, rt_hi, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        mlo = jax.lax.dot_general(Hb, rt_lo, (((1,), (0,)), ((), ())),
                                  preferred_element_type=jnp.int32)
        rank = (jnp.sum(mhi * Lb.astype(jnp.int32), axis=1) * 128
                + jnp.sum(mlo * Lb.astype(jnp.int32), axis=1))
        indices = jnp.where(valid, rank.astype(jnp.uint32), 0)
        return indices, ulo, k

    return jax.vmap(one_column)(lo)


@functools.partial(jax.jit, static_argnames=("value_bound", "interpret"))
def dict_matmul_pallas(lo, count, value_bound: int = 1 << 13,
                       interpret: bool = False):
    """Histogram/dict via XLA one-hot matmuls + ranks via the fused Pallas
    kernel (ops.pallas_rank) — same contract as dict_matmul."""
    from kpw_tpu.ops.pallas_rank import rank_pages_core

    n = lo.shape[1]
    nhi = value_bound // S_LO
    iota = jnp.arange(n, dtype=jnp.int32)
    valid = iota < count

    def hist_one(lc):
        hi = (lc // S_LO).astype(jnp.int32)
        lo_d = (lc % S_LO).astype(jnp.int32)
        H = ((hi[:, None] == jnp.arange(nhi)[None, :]) & valid[:, None]
             ).astype(jnp.bfloat16)
        L = ((lo_d[:, None] == jnp.arange(S_LO)[None, :]) & valid[:, None]
             ).astype(jnp.bfloat16)
        counts = jax.lax.dot_general(
            H, L, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        present = (counts > 0).reshape(-1)
        k = jnp.sum(present.astype(jnp.int32))
        rt = (jnp.cumsum(present.astype(jnp.int32)) - 1).reshape(nhi, S_LO)
        bins = jnp.arange(value_bound, dtype=jnp.uint32)
        ulo = jnp.sort(jnp.where(present, bins, jnp.uint32(0xFFFFFFFF)))
        return rt, ulo, k

    rt, ulo, k = jax.vmap(hist_one)(lo)
    lo_masked = jnp.where(valid[None, :], lo, jnp.uint32(value_bound))
    ranks = rank_pages_core(lo_masked, rt, interpret=interpret)
    return ranks.astype(jnp.uint32), ulo, k


@functools.partial(jax.jit, static_argnames=("value_bound", "interpret"))
def dict_full_pallas(lo, count, value_bound: int = 1 << 13,
                     interpret: bool = False):
    """Histogram AND ranks via the fused Pallas kernels — the one-hot
    matrices never exist in HBM; XLA only does presence/cumsum/dict-sort
    over the 8192 bins."""
    from kpw_tpu.ops.pallas_rank import (hist_pages_core, presence_to_dict,
                                         rank_pages_core)

    n = lo.shape[1]
    nhi = value_bound // S_LO
    iota = jnp.arange(n, dtype=jnp.int32)
    valid = iota < count
    lo_masked = jnp.where(valid[None, :], lo, jnp.uint32(value_bound))
    counts = hist_pages_core(lo_masked, nhi, interpret=interpret)
    rt, ulo, k = presence_to_dict(counts, nhi)
    ranks = rank_pages_core(lo_masked, rt, interpret=interpret)
    return ranks.astype(jnp.uint32), ulo, k


def check_identity():
    from kpw_tpu.parallel.sharded import encode_step_single

    rng = np.random.default_rng(5)
    for vb, n, c in ((1 << 13, 4096, 3), (1 << 13, 1 << 13, 2), (4096, 512, 4)):
        lo = jnp.asarray(rng.integers(0, vb, (c, n)).astype(np.uint32))
        for count in (n, n - 37, 1, 0):
            want_packed, want_ulo, want_k = encode_step_single(
                lo, jnp.int32(count), width=16, value_bound=vb)
            from kpw_tpu.ops.packing import bitpack_device

            for impl in (dict_matmul,
                         functools.partial(dict_matmul_pallas, interpret=True),
                         functools.partial(dict_full_pallas, interpret=True)):
                idx, ulo, k = impl(lo, jnp.int32(count), value_bound=vb)
                np.testing.assert_array_equal(np.asarray(k), np.asarray(want_k))
                for cc in range(c):
                    kk = int(k[cc])
                    np.testing.assert_array_equal(
                        np.asarray(ulo)[cc][:kk], np.asarray(want_ulo)[cc][:kk],
                        err_msg=f"dict col {cc} count {count}")
                # compare indices through the same bit-pack as production
                packed = jax.vmap(lambda m: bitpack_device(m, 16))(idx)
                np.testing.assert_array_equal(
                    np.asarray(packed), np.asarray(want_packed),
                    err_msg=f"indices count {count}")
    print("identity OK: dict_matmul + dict_matmul_pallas == encode_step_single")


def time_tpu(n_steps: int = 12):
    from bench import probe_time_loop
    from kpw_tpu.parallel.sharded import encode_step_single
    from kpw_tpu.ops.packing import bitpack_device
    from kpw_tpu.runtime.select import probe_link

    dispatch_s = probe_link()["dispatch_ms"] / 1e3
    rng = np.random.default_rng(11)
    N = 1 << 16
    C = 16  # the dict32 share of the cfg2 shape
    lo = jnp.asarray(rng.integers(0, 5000, (C, N)).astype(np.uint32))
    count = jnp.int32(N)

    def sort_part(i, x):
        packed, _, k = encode_step_single(x ^ i.astype(jnp.uint32), count,
                                          value_bound=1 << 13)
        return jnp.sum(packed, dtype=jnp.uint32) + jnp.sum(k).astype(jnp.uint32)

    def matmul_part(i, x):
        idx, ulo, k = dict_matmul(x ^ i.astype(jnp.uint32), count)
        packed = jax.vmap(lambda m: bitpack_device(m, 16))(idx)
        return (jnp.sum(packed, dtype=jnp.uint32)
                + jnp.sum(ulo, dtype=jnp.uint32) + jnp.sum(k).astype(jnp.uint32))

    def pallas_part(i, x):
        idx, ulo, k = dict_matmul_pallas(x ^ i.astype(jnp.uint32), count)
        packed = jax.vmap(lambda m: bitpack_device(m, 16))(idx)
        return (jnp.sum(packed, dtype=jnp.uint32)
                + jnp.sum(ulo, dtype=jnp.uint32) + jnp.sum(k).astype(jnp.uint32))

    probe_time_loop([(sort_part, (lo,))], "dict16x64Ki sort kernel", n_steps,
                    dispatch_s, reps=5)
    probe_time_loop([(matmul_part, (lo,))], "dict16x64Ki matmul kernel", n_steps,
                    dispatch_s, reps=5)
    def full_part(i, x):
        idx, ulo, k = dict_full_pallas(x ^ i.astype(jnp.uint32), count)
        packed = jax.vmap(lambda m: bitpack_device(m, 16))(idx)
        return (jnp.sum(packed, dtype=jnp.uint32)
                + jnp.sum(ulo, dtype=jnp.uint32) + jnp.sum(k).astype(jnp.uint32))

    probe_time_loop([(pallas_part, (lo,))], "dict16x64Ki matmul+pallas", n_steps,
                    dispatch_s, reps=5)
    probe_time_loop([(full_part, (lo,))], "dict16x64Ki full pallas", n_steps,
                    dispatch_s, reps=5)


if __name__ == "__main__":
    if "--tpu" in sys.argv:
        time_tpu()
    else:
        check_identity()
