#!/usr/bin/env bash
# Single CI entry point for the correctness tooling (ISSUE 7/13, README
# "Correctness tooling"): the gates, in cheap-to-expensive order, each
# failing fast and loudly.
#
#   1. lint suite        — python -m tools.analyze   (static analysis:
#                          lock discipline, hot imports, canonical
#                          names, fault isolation, swallowed exceptions,
#                          spawn safety, resource pairing, protocol
#                          exhaustiveness, clock discipline)
#   2. tier-1 pytest     — the fast suite (-m 'not slow'); compare the
#                          passed count against the baseline in
#                          CHANGES.md (this container carries ~31
#                          pre-existing environmental failures: python
#                          zstandard module + jax shard_map absent)
#   3. compaction smoke  — python bench.py --compact --smoke (reduced
#                          partitioned-run -> compaction -> crash-replay
#                          invariant; exits nonzero unless it holds, and
#                          never overwrites the committed artifact)
#   4. scan smoke        — python bench.py --scan --smoke (query-ready
#                          files: page-index pruning must actually be
#                          observed, bloom miss rejected, sort-on-compact
#                          verified; exits nonzero otherwise, committed
#                          artifact never overwritten)
#   5. e2e smoke         — python bench.py --e2e --smoke (reduced
#                          saturation replay through the full
#                          poll->shred->encode->publish->ack leg on the
#                          nogil assembly path; exits nonzero unless
#                          ack-lag drains to exactly 0, committed
#                          artifact never overwritten)
#   6. process-mode smoke — python bench.py --procs --smoke (reduced
#                          replay through >=2 spawned worker processes
#                          fed via the shared-memory ring; exits nonzero
#                          unless ack-lag drains to exactly 0, committed
#                          artifact never overwritten)
#   7. object-store smoke — python bench.py --objstore --smoke (reduced
#                          replay into the emulated object store:
#                          upload-hidden-under-encode overlap observed,
#                          remote compaction under the bandwidth budget,
#                          mid-multipart crash replay recovers; exits
#                          nonzero unless the invariant holds, committed
#                          artifact never overwritten)
#   8. nested smoke      — python bench.py --nested --smoke (reduced
#                          nested list<struct> replay through the fused
#                          pipeline + the fused-vs-fallback-vs-oracle
#                          file-byte identity check; exits nonzero
#                          unless ack-lag drains to 0 AND the bytes
#                          match, committed artifact never overwritten)
#   9. schedx smoke      — python -m tools.schedx --smoke (deterministic
#                          schedule explorer: the committed seed subset
#                          over the PR-11/12 race scenarios must run
#                          CLEAN — a violation report carries its replay
#                          seed and both participating stacks)
#  10. doc reconciliation — python tools/check_docs.py (every doc-cited
#                          number/name/test/pass/seed-count exists and
#                          matches)
#  11. sanitizer smoke   — bash tools/sanitize.sh --smoke (ASan/UBSan
#                          native build + fuzz; prints a LOUD notice and
#                          exits 0 when the toolchain is absent — never
#                          a silent pass)
#  12. tsan smoke        — bash tools/sanitize.sh --tsan --smoke
#                          (ThreadSanitizer build of the GIL-released
#                          entries driven from concurrent threads; the
#                          deliberate-race canary must be REPORTED first
#                          so the clean run is non-vacuous; loud SKIPPED
#                          when libtsan is absent — never a silent pass)
#  13. tenants smoke     — python bench.py --tenants --smoke (reduced
#                          multi-tenant mix: burst tenant under a small
#                          queue share + fault persona on one sink +
#                          poison stream on another topic; exits nonzero
#                          unless every route's ack-lag drains to 0 AND
#                          the containment counters show zero
#                          cross-tenant worker deaths; committed
#                          artifact never overwritten)
#  14. encodings smoke   — python bench.py --encodings --smoke (the
#                          adaptive-encoding chooser over the column-
#                          class corpus: exits nonzero unless the
#                          adaptive arm lands <= 0.80x the all-PLAIN
#                          arm's file bytes AND every arm's pyarrow
#                          read-back is value-exact; committed artifact
#                          never overwritten)
#  15. telemetry smoke   — python bench.py --obs --smoke (the
#                          cross-process telemetry plane: reduced
#                          proc-mode traced replay; exits nonzero unless
#                          ONE parent scrape carries the child-origin
#                          counters, the merged trace spans >= 2 pids,
#                          end-to-end ack-latency was observed, and the
#                          flight recorder stayed dump-free; committed
#                          artifact never overwritten)
#  16. rebalance smoke   — python bench.py --rebalance --smoke (the
#                          consumer-group drills: instance hard-kill
#                          with survivor reclaim, zombie fenced
#                          mid-publish + un-published, cooperative
#                          handoff with zero full resets; exits nonzero
#                          unless every leg reads back exactly-once AND
#                          the generation fence fired; committed
#                          artifact never overwritten)
#  17. proc-rebalance smoke — python bench.py --rebalance --procs
#                          --smoke (the drills with SPAWNED worker
#                          processes: revocation crossing the process
#                          boundary as ring fence descriptors, whole-
#                          instance SIGKILL with startup sweep, the
#                          zombie CHILD parked inside its publish; exits
#                          nonzero unless every leg reads back exactly-
#                          once AND the cross-process fence flush fired;
#                          committed artifact never overwritten)
#
# Usage: bash tools/ci.sh        (exit 0 = all gates green)

set -u -o pipefail
cd "$(dirname "$0")/.."

fail=0
step() { echo; echo "=== ci.sh [$1] $2 ==="; }

step 1/17 "lint suite (python -m tools.analyze)"
python -m tools.analyze || fail=1

step 2/17 "tier-1 pytest (-m 'not slow')"
# tier-1's exit code is nonzero on THIS container because of the known
# environmental failures (python zstandard + jax shard_map absent — see
# the CHANGES.md baseline), so the gate is mechanical instead of
# exit-code-based: fail on any collection error, or on more failures
# than the environmental ceiling (override with KPW_CI_MAX_FAILED).
T1_LOG="$(mktemp)"
JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider 2>&1 \
    | tee "$T1_LOG" | tail -5
t1_failed=$(grep -aoE '[0-9]+ failed' "$T1_LOG" | tail -1 | grep -oE '[0-9]+' || echo 0)
t1_errors=$(grep -aoE '[0-9]+ error' "$T1_LOG" | tail -1 | grep -oE '[0-9]+' || echo 0)
t1_passed=$(grep -aoE '[0-9]+ passed' "$T1_LOG" | tail -1 | grep -oE '[0-9]+' || echo 0)
max_failed="${KPW_CI_MAX_FAILED:-31}"
echo "tier-1: passed=$t1_passed failed=$t1_failed errors=$t1_errors (ceiling $max_failed)"
if [ "$t1_errors" -gt 0 ] || [ "$t1_failed" -gt "$max_failed" ] \
        || [ "$t1_passed" -eq 0 ]; then
    echo "tier-1 gate FAILED (errors, zero passes, or failures above the"
    echo "environmental ceiling — diff the failure list against CHANGES.md)"
    fail=1
fi
rm -f "$T1_LOG"

step 3/17 "compaction smoke (bench.py --compact --smoke)"
JAX_PLATFORMS=cpu python bench.py --compact --smoke || fail=1

step 4/17 "scan smoke (bench.py --scan --smoke)"
JAX_PLATFORMS=cpu python bench.py --scan --smoke || fail=1

step 5/17 "e2e smoke (bench.py --e2e --smoke)"
JAX_PLATFORMS=cpu python bench.py --e2e --smoke || fail=1

step 6/17 "process-mode smoke (bench.py --procs --smoke)"
JAX_PLATFORMS=cpu python bench.py --procs --smoke || fail=1

step 7/17 "object-store smoke (bench.py --objstore --smoke)"
JAX_PLATFORMS=cpu python bench.py --objstore --smoke || fail=1

step 8/17 "nested-replay smoke (bench.py --nested --smoke)"
JAX_PLATFORMS=cpu python bench.py --nested --smoke || fail=1

step 9/17 "schedule-explorer smoke (python -m tools.schedx --smoke)"
JAX_PLATFORMS=cpu python -m tools.schedx --smoke || fail=1

step 10/17 "doc reconciliation (tools/check_docs.py)"
python tools/check_docs.py || fail=1

step 11/17 "sanitizer smoke (tools/sanitize.sh --smoke)"
bash tools/sanitize.sh --smoke || fail=1

step 12/17 "tsan smoke (tools/sanitize.sh --tsan --smoke)"
bash tools/sanitize.sh --tsan --smoke || fail=1

step 13/17 "multi-tenant smoke (bench.py --tenants --smoke)"
JAX_PLATFORMS=cpu python bench.py --tenants --smoke || fail=1

step 14/17 "adaptive-encodings smoke (bench.py --encodings --smoke)"
JAX_PLATFORMS=cpu python bench.py --encodings --smoke || fail=1

step 15/17 "telemetry-plane smoke (bench.py --obs --smoke)"
JAX_PLATFORMS=cpu python bench.py --obs --smoke || fail=1

step 16/17 "rebalance smoke (bench.py --rebalance --smoke)"
JAX_PLATFORMS=cpu python bench.py --rebalance --smoke || fail=1

step 17/17 "proc-rebalance smoke (bench.py --rebalance --procs --smoke)"
JAX_PLATFORMS=cpu python bench.py --rebalance --procs --smoke || fail=1

echo
if [ "$fail" -ne 0 ]; then
    echo "ci.sh: FAILED (one or more gates above)"
    exit 1
fi
echo "ci.sh: all gates green (tier-1 failures must still be diffed against the CHANGES.md baseline)"
