"""Seeded mutation-fuzz harness for the parsing/validation attack
surface: every crash class PR 4 / PR 6 fixed by hand, as a standing
regression net.

History: the thrift ``CompactReader`` shipped with unbounded varints and
bare IndexErrors until PR 4 hardened it against the corrupt-file corpus;
``shred_flat_buf`` shipped with an end-points-only offset check until a
malformed INTERIOR offset was found post-review in PR 6 to be an
out-of-bounds C read.  Both were found by humans staring at code.  This
harness makes the search mechanical and repeatable: seeded mutations
(bit flips, truncations, splices, adversarial offset tables) over valid
inputs, with a strict allowed-outcome contract per target —

* ``thrift``  — ``CompactReader.read_struct`` over mutated footer bytes:
  must return a dict or raise ``ThriftDecodeError``; an IndexError /
  RecursionError / MemoryError / OverflowError is a crash.
* ``verify``  — ``io.verify.verify_bytes`` over mutated whole files:
  must RETURN a ``FileReport`` (ok or not), never raise.
* ``offsets`` — ``ProtoColumnarizer.columnarize_buffer`` over a valid
  payload buffer with mutated offset tables (and mutated payload bytes
  under a valid table): must return a ColumnBatch or raise
  ``ValueError`` / ``WireShredError``; anything else — in particular a
  native OOB read, which the ASan build (tools/sanitize.sh) turns into
  an abort — is a crash.
* ``index``   — the query-ready footer sections (ISSUE 9,
  ``core/index.py``): mutations aimed at the ColumnIndex / OffsetIndex /
  bloom-filter byte region of an indexed file.  ``verify_bytes`` must
  RETURN a report (the corrupt sections surfaced as errors, never an
  exception), and the reader stack (``read_file_index``,
  ``read_sorting_columns``, ``bloom_check``) must return or raise
  ``ThriftDecodeError`` — a scan planner fed a hostile file may refuse
  it, never crash on it.
* ``nested``  — the FUSED nested wire path (ISSUE 14):
  ``columnarize_buffer`` over a nested (list<struct>) schema with
  mutated offset tables and mutated wire bytes, driving the batched
  ``shred_nested_buf``/``nested_fill`` decoder output.  Must return a
  ColumnBatch or raise ``ValueError`` / ``WireShredError`` — an OOB in
  the decode, the span gather, or the level widening is a crash (the
  ASan build aborts on it).

Deterministic by construction: ``--seed`` fixes the whole run, and the
committed regression configuration is seed=20260803 (tools/ci.sh runs
it under the sanitizer build; tests/test_analyze.py runs a smaller
count in tier-1).

Run: ``python -m tools.fuzz [--seed N] [--iters N] [--target NAME]``
Exit 0 = zero crashes.
"""

from __future__ import annotations

import argparse
import io
import random
import sys

import numpy as np


def _make_parquet_bytes() -> bytes:
    """One small valid parquet file (two row groups, CRCs on) — the
    mutation substrate for the thrift/verify targets."""
    from kpw_tpu.core.schema import (Field, PhysicalType, Repetition,
                                     Schema)
    from kpw_tpu.core.writer import (ParquetFileWriter, WriterProperties,
                                     columns_from_arrays)

    sch = Schema([
        Field("a", Repetition.REQUIRED, physical_type=PhysicalType.INT64),
        Field("s", Repetition.REQUIRED,
              physical_type=PhysicalType.BYTE_ARRAY),
        Field("o", Repetition.OPTIONAL, physical_type=PhysicalType.INT32),
    ])
    sink = io.BytesIO()
    props = WriterProperties(row_group_size=8192, data_page_size=512,
                             page_checksums=True)
    w = ParquetFileWriter(sink, sch, props)
    rng = np.random.default_rng(7)
    rows = 600
    for _ in range(2):
        w.write_batch(columns_from_arrays(sch, {
            "a": rng.integers(0, 50, rows),
            "s": [f"v{i % 9}".encode() for i in range(rows)],
            "o": (rng.integers(0, 9, rows).astype(np.int32),
                  rng.random(rows) > 0.1),
        }))
        w.flush_row_group()
    w.close()
    return sink.getvalue()


def _make_indexed_bytes() -> bytes:
    """One valid QUERY-READY parquet file: page indexes, bloom filters on
    every eligible column, and a declared (true) sort order — the
    substrate whose index/bloom section the ``index`` target corrupts."""
    from kpw_tpu.core.schema import (Field, PhysicalType, Repetition,
                                     Schema)
    from kpw_tpu.core.writer import (ParquetFileWriter, WriterProperties,
                                     columns_from_arrays)

    sch = Schema([
        Field("a", Repetition.REQUIRED, physical_type=PhysicalType.INT64),
        Field("s", Repetition.REQUIRED,
              physical_type=PhysicalType.BYTE_ARRAY),
        Field("o", Repetition.OPTIONAL, physical_type=PhysicalType.INT32),
    ])
    sink = io.BytesIO()
    # blooms pinned on every column (auto mode would skip "a": unique
    # per row, never dictionary-accepted) — the target wants the largest
    # possible index/bloom section to corrupt
    props = WriterProperties(row_group_size=8192, data_page_size=512,
                             bloom_columns=("a", "s", "o"),
                             sorting_columns=(("a", False, False),))
    w = ParquetFileWriter(sink, sch, props)
    rng = np.random.default_rng(7)
    rows = 600
    for g in range(2):
        w.write_batch(columns_from_arrays(sch, {
            "a": np.arange(g * rows, (g + 1) * rows, dtype=np.int64),
            "s": [f"v{i % 9}".encode() for i in range(rows)],
            "o": (rng.integers(0, 9, rows).astype(np.int32),
                  rng.random(rows) > 0.1),
        }))
        w.flush_row_group()
    w.close()
    return sink.getvalue()


def _index_section_span(data: bytes) -> tuple[int, int]:
    """[start, end) of the file's index/bloom section: every bloom
    filter, ColumnIndex and OffsetIndex the footer points at lies between
    the last data-page byte and the footer.  Walked with raw footer fids
    (like the verifier) so the fuzzer aims its mutations, instead of
    spending most iterations on data-page bytes the verify target
    already covers."""
    from kpw_tpu.core.thrift import CompactReader

    footer_len = int.from_bytes(data[-8:-4], "little")
    footer_start = len(data) - 8 - footer_len
    fmd = CompactReader(data, footer_start).read_struct()
    offs = []
    for rg in fmd[4]:
        for cc in rg[1]:
            meta = cc.get(3, {})
            for holder, fid in ((cc, 4), (cc, 6), (meta, 14)):
                if isinstance(holder.get(fid), int):
                    offs.append(holder[fid])
    if not offs:
        raise AssertionError("index fuzz substrate carries no sections")
    return min(offs), footer_start


def _make_wire_batch():
    """(columnarizer, payload buffer, valid offsets) for the offsets
    target — a flat proto2 message batch, the ``RecordBatch`` handoff
    shape ``columnarize_buffer`` consumes."""
    from google.protobuf import (descriptor_pb2, descriptor_pool,
                                 message_factory)

    from kpw_tpu.models.proto_bridge import ProtoColumnarizer

    F = descriptor_pb2.FieldDescriptorProto
    pool = descriptor_pool.DescriptorPool()
    fdp = descriptor_pb2.FileDescriptorProto(
        name="fuzz_sample.proto", package="kpwfuzz", syntax="proto2")
    m = fdp.message_type.add(name="FuzzMessage")
    m.field.add(name="query", number=1, type=F.TYPE_STRING,
                label=F.LABEL_REQUIRED)
    m.field.add(name="timestamp", number=2, type=F.TYPE_INT64,
                label=F.LABEL_REQUIRED)
    m.field.add(name="page", number=3, type=F.TYPE_INT32,
                label=F.LABEL_OPTIONAL)
    fd = pool.Add(fdp)
    cls = message_factory.GetMessageClass(
        fd.message_types_by_name["FuzzMessage"])
    payloads = []
    for i in range(200):
        msg = cls(query=f"q-{i}-" + "x" * (i % 17), timestamp=i)
        if i % 3:
            msg.page = i % 11
        payloads.append(msg.SerializeToString())
    offs = np.zeros(len(payloads) + 1, np.int64)
    np.cumsum([len(p) for p in payloads], out=offs[1:])
    buf = b"".join(payloads)
    col = ProtoColumnarizer(cls)
    assert col.wire_capable, "fuzz schema must be wire-shreddable"
    return col, buf, offs


def _make_nested_wire_batch():
    """(columnarizer, payload buffer, valid offsets) for the nested
    target — a list<struct> schema (the cfg5/cfg7 shape) whose batches
    ride the fused shred_nested_buf/nested_fill path."""
    from google.protobuf import (descriptor_pb2, descriptor_pool,
                                 message_factory)

    from kpw_tpu.models.proto_bridge import ProtoColumnarizer

    F = descriptor_pb2.FieldDescriptorProto
    pool = descriptor_pool.DescriptorPool()
    fdp = descriptor_pb2.FileDescriptorProto(
        name="fuzz_nested.proto", package="kpwfuzzn", syntax="proto2")
    item = fdp.message_type.add(name="Item")
    item.field.add(name="sku", number=1, type=F.TYPE_STRING,
                   label=F.LABEL_REQUIRED)
    item.field.add(name="qty", number=2, type=F.TYPE_INT32,
                   label=F.LABEL_OPTIONAL)
    item.field.add(name="tags", number=3, type=F.TYPE_STRING,
                   label=F.LABEL_REPEATED)
    order = fdp.message_type.add(name="Order")
    order.field.add(name="order_id", number=1, type=F.TYPE_INT64,
                    label=F.LABEL_REQUIRED)
    order.field.add(name="items", number=2, type=F.TYPE_MESSAGE,
                    label=F.LABEL_REPEATED, type_name=".kpwfuzzn.Item")
    order.field.add(name="note", number=3, type=F.TYPE_STRING,
                    label=F.LABEL_OPTIONAL)
    fd = pool.Add(fdp)
    cls = message_factory.GetMessageClass(fd.message_types_by_name["Order"])
    payloads = []
    for i in range(200):
        msg = cls(order_id=i)
        for j in range(i % 4):
            it = msg.items.add()
            it.sku = f"sku-{(i + j) % 13}"
            if j % 2:
                it.qty = j
            for t in range(j % 3):
                it.tags.append(f"t{t}")
        if i % 3 == 0:
            msg.note = f"n-{i}" * (i % 5 + 1)
        payloads.append(msg.SerializeToString())
    offs = np.zeros(len(payloads) + 1, np.int64)
    np.cumsum([len(p) for p in payloads], out=offs[1:])
    buf = b"".join(payloads)
    col = ProtoColumnarizer(cls)
    col._wire = None  # pin the NESTED decoder
    assert col.wire_capable, "nested fuzz schema must be wire-shreddable"
    return col, buf, offs


def _mutate_bytes(rng: random.Random, data: bytes) -> bytes:
    """One seeded structural mutation: bit flips, truncation, splice,
    or a zero/0xFF run — the corruption shapes torn publishes and bad
    media actually produce."""
    b = bytearray(data)
    kind = rng.randrange(5)
    if kind == 0:      # flip 1..8 random bits
        for _ in range(rng.randint(1, 8)):
            i = rng.randrange(len(b))
            b[i] ^= 1 << rng.randrange(8)
    elif kind == 1:    # truncate
        return bytes(b[: rng.randrange(len(b))])
    elif kind == 2:    # splice a random window elsewhere
        n = rng.randint(1, min(64, len(b)))
        src = rng.randrange(len(b) - n + 1)
        dst = rng.randrange(len(b) - n + 1)
        b[dst: dst + n] = b[src: src + n]
    elif kind == 3:    # overwrite a run with 0x00/0xFF
        n = rng.randint(1, min(64, len(b)))
        at = rng.randrange(len(b) - n + 1)
        b[at: at + n] = bytes([rng.choice((0, 0xFF))]) * n
    else:              # random garbage run
        n = rng.randint(1, min(32, len(b)))
        at = rng.randrange(len(b) - n + 1)
        b[at: at + n] = bytes(rng.randrange(256) for _ in range(n))
    return bytes(b)


def _mutate_offsets(rng: random.Random, offs: np.ndarray,
                    buf_len: int) -> np.ndarray:
    """One adversarial offset table: the PR-6 crash class (a malformed
    INTERIOR entry) plus the whole family around it."""
    o = offs.copy()
    kind = rng.randrange(6)
    if kind == 0:      # corrupt one interior entry (the PR-6 OOB shape)
        i = rng.randrange(1, len(o) - 1) if len(o) > 2 else 0
        o[i] = rng.choice((-1, buf_len + rng.randint(1, 1 << 20),
                           rng.randint(0, max(buf_len, 1)) * -1,
                           (1 << 62)))
    elif kind == 1:    # descending pair
        i = rng.randrange(1, len(o))
        o[i] = o[i - 1] - rng.randint(1, 100)
    elif kind == 2:    # shift the whole window past the end
        o += buf_len
    elif kind == 3:    # random permutation of a slice
        i = rng.randrange(len(o))
        j = rng.randrange(len(o))
        o[i], o[j] = o[j], o[i]
    elif kind == 4:    # random table entirely, sorted or shuffled 50/50
        vals = [rng.randrange(-buf_len, 2 * buf_len + 1)
                for _ in range(len(o))]
        if rng.random() < 0.5:
            vals.sort()
        o = np.array(vals, np.int64)
    else:              # truncated / oversized table
        n = rng.randrange(0, len(o) + 4)
        o = np.resize(o, n)
    return o


def fuzz_thrift(seed: int, iters: int, report) -> int:
    from kpw_tpu.core.thrift import CompactReader, ThriftDecodeError

    data = _make_parquet_bytes()
    footer_len = int.from_bytes(data[-8:-4], "little")
    footer = data[len(data) - 8 - footer_len: len(data) - 8]
    rng = random.Random(seed)
    crashes = 0
    for i in range(iters):
        mutated = _mutate_bytes(rng, footer)
        try:
            CompactReader(mutated).read_struct()
        except ThriftDecodeError:
            pass                       # the designed outcome
        except Exception as e:         # anything else is the crash class
            crashes += 1
            report("thrift", i, e)
    return crashes


def fuzz_verify(seed: int, iters: int, report) -> int:
    from kpw_tpu.io.verify import FileReport, verify_bytes

    data = _make_parquet_bytes()
    rng = random.Random(seed + 1)
    crashes = 0
    for i in range(iters):
        mutated = _mutate_bytes(rng, data)
        try:
            rep = verify_bytes(mutated, "<fuzz>")
            if not isinstance(rep, FileReport):
                raise TypeError(f"verify_bytes returned {type(rep)}")
        except Exception as e:         # verify must never raise
            crashes += 1
            report("verify", i, e)
    return crashes


def fuzz_offsets(seed: int, iters: int, report) -> int:
    from kpw_tpu.models.proto_bridge import WireShredError

    col, buf, offs = _make_wire_batch()
    rng = random.Random(seed + 2)
    crashes = 0
    for i in range(iters):
        if i % 4 == 3:
            # valid table, mutated PAYLOAD: the decoder itself must
            # reject or fail soft, never walk out of the buffer
            table, payload = offs, _mutate_bytes(rng, buf)
            if len(payload) < len(buf):  # keep the table in-bounds
                payload = payload + b"\0" * (len(buf) - len(payload))
        else:
            table, payload = _mutate_offsets(rng, offs, len(buf)), buf
        try:
            col.columnarize_buffer(payload, table)
        except (ValueError, WireShredError):
            pass                       # the designed outcomes
        except Exception as e:
            crashes += 1
            report("offsets", i, e)
    return crashes


def fuzz_index(seed: int, iters: int, report) -> int:
    from kpw_tpu.core.index import (bloom_check, read_file_index,
                                    read_sorting_columns)
    from kpw_tpu.core.schema import PhysicalType
    from kpw_tpu.core.thrift import ThriftDecodeError
    from kpw_tpu.io.verify import FileReport, verify_bytes

    data = _make_indexed_bytes()
    sec_start, sec_end = _index_section_span(data)
    rng = random.Random(seed + 3)
    crashes = 0
    for i in range(iters):
        if i % 5 == 4:
            # whole-file mutation: footer pointers INTO the section get
            # corrupted too (offsets/lengths out of bounds, type flips)
            mutated = _mutate_bytes(rng, data)
        else:
            # aimed mutation: corrupt only index/bloom section bytes, the
            # footer still points at them confidently
            section = _mutate_bytes(rng, data[sec_start:sec_end])
            mutated = data[:sec_start] + section + data[sec_end:]
        try:
            rep = verify_bytes(mutated, "<fuzz>")
            if not isinstance(rep, FileReport):
                raise TypeError(f"verify_bytes returned {type(rep)}")
        except Exception as e:         # verify must never raise
            crashes += 1
            report("index", i, e)
        try:
            idx = read_file_index(mutated)
            read_sorting_columns(mutated)
            for rg in idx:
                for entry in rg:
                    # no defensive guards here: read_file_index already
                    # normalizes bloom_offset to int-or-None, and
                    # bloom_check must answer any in-file int with a
                    # result or ThriftDecodeError — pre-filtering would
                    # mask the very contract this target pins
                    off = entry.get("bloom_offset")
                    if off is not None:
                        bloom_check(mutated, off, b"probe",
                                    PhysicalType.BYTE_ARRAY)
        except ThriftDecodeError:
            pass                       # the designed reader outcome
        except Exception as e:
            crashes += 1
            report("index", i, e)
    return crashes


def fuzz_nested(seed: int, iters: int, report) -> int:
    """Adversarial wire bytes + offset tables through the fused nested
    decoder (shred_nested_buf -> nested_fill): a ColumnBatch, ValueError
    or WireShredError are the designed outcomes — anything else (or an
    ASan abort in the decode / span gather / level widening) is a crash."""
    from kpw_tpu.models.proto_bridge import WireShredError

    col, buf, offs = _make_nested_wire_batch()
    rng = random.Random(seed + 5)
    crashes = 0
    for i in range(iters):
        if i % 4 == 3:
            # valid table, mutated PAYLOAD: the nested decoder must reject
            # into the Python fallback or accept with exact semantics,
            # never walk out of the buffer
            table, payload = offs, _mutate_bytes(rng, buf)
            if len(payload) < len(buf):  # keep the table in-bounds
                payload = payload + b"\0" * (len(buf) - len(payload))
        else:
            table, payload = _mutate_offsets(rng, offs, len(buf)), buf
        try:
            col.columnarize_buffer(payload, table)
        except (ValueError, WireShredError):
            pass                       # the designed outcomes
        except Exception as e:
            crashes += 1
            report("nested", i, e)
    return crashes


def _make_assemble_plan():
    """(extension, buffers, page_tab, op_tab, values) — one valid lowered
    plan shaped like a real chunk (RAW body parts + RLE level/index ops +
    CRC flags + native stats), the mutation substrate for the ``assemble``
    target."""
    from kpw_tpu.core.metadata import (DATA_PAGE_PREFIX, DICT_PAGE_PREFIX,
                                       data_page_suffix, dict_page_suffix)
    from kpw_tpu.native import assemble

    asm = assemble()
    if asm is None:
        raise AssertionError("assemble extension must build for fuzzing")
    rng2 = np.random.default_rng(11)
    values = np.ascontiguousarray(rng2.integers(0, 1000, 512), np.int64)
    idx = np.ascontiguousarray(rng2.integers(0, 16, 512), np.uint32)
    levels = np.ascontiguousarray(rng2.integers(0, 2, 512), np.uint32)
    raw = bytes(rng2.integers(0, 256, 700, dtype=np.uint8))
    # nested-pipeline op substrates (OP_KINDS >= 4): a run table (the
    # device level planner's handoff) and a packed ByteColumn
    run_vals = np.ascontiguousarray(rng2.integers(0, 4, 40), np.uint32)
    run_lens = np.ascontiguousarray(rng2.integers(1, 20, 40), np.int32)
    ba_lens = rng2.integers(0, 9, 64)
    ba_offs = np.zeros(65, np.int64)
    np.cumsum(ba_lens, out=ba_offs[1:])
    ba_data = bytes(rng2.integers(0, 256, int(ba_offs[-1]), dtype=np.uint8))
    # BYTE_STREAM_SPLIT op substrate (OP_KINDS >= 5): 128 doubles' bytes
    bss_vals = np.ascontiguousarray(rng2.standard_normal(128), np.float64)
    buffers = (raw, idx, levels, values.view(np.uint8).tobytes(),
               DATA_PAGE_PREFIX, DICT_PAGE_PREFIX,
               data_page_suffix(256, 0, True), dict_page_suffix(16, 2, True),
               run_vals, run_lens, ba_data, ba_offs,
               bss_vals.view(np.uint8).tobytes())
    ops = np.array([
        [0, 0, 0, 700, 0],            # RAW whole buffer
        [1, 2, 0, 256, 1 | (2 << 8)],  # RLE levels, len32 mode
        [1, 1, 0, 256, 4 | (1 << 8)],  # RLE indices, width-byte mode
        [0, 3, 0, 2048, 0],           # RAW values-as-bytes slice
        [1, 1, 256, 512, 4 | (0 << 8)],  # RLE bare
        [2, 8, 0, 40, 2 | (2 << 8) | (9 << 16)],  # RLE-from-runs, len32
        [3, 10, 0, 64, 11 << 16],     # bytes-plain over the ByteColumn
        [4, 12, 0, 128, 8],           # BYTE_STREAM_SPLIT, 8-byte values
    ], np.int64)
    pages = np.array([
        [0, 1, 5, 7, 1, 0, 0],    # dict-ish page: RAW body, CRC on
        [1, 3, 4, 6, 1, 0, 256],  # data page: levels+indices, stats range
        [3, 5, 4, 6, 0, 256, 512],
        [5, 7, 4, 6, 1, 0, 0],    # nested-shaped page: runs + bytes-plain
        [7, 8, 4, 6, 1, 0, 0],    # BSS page: transposed byte planes
    ], np.int64)
    return asm, buffers, pages, ops, values


def fuzz_assemble(seed: int, iters: int, report) -> int:
    """Malformed page/op tables into the nogil assembler: the entry must
    return bytes or raise ValueError (every index validated BEFORE the
    GIL is released) — any other exception, or an OOB read the ASan
    build aborts on, is a crash.  Same contract PR 6 established for
    ``shred_flat_buf``."""
    asm, buffers, pages, ops, values = _make_assemble_plan()
    rng = random.Random(seed + 4)
    adversarial = (-1, 0, 1, -(1 << 62), (1 << 62), (1 << 40), 255, 256,
                   701, -700, 2 ** 31, -(2 ** 31))
    crashes = 0
    for i in range(iters):
        p = pages.copy()
        o = ops.copy()
        kind = rng.randrange(6)
        if kind == 0:      # scatter adversarial int64s into the page table
            for _ in range(rng.randint(1, 4)):
                p[rng.randrange(p.shape[0]), rng.randrange(7)] = rng.choice(
                    adversarial)
        elif kind == 1:    # scatter into the op table
            for _ in range(rng.randint(1, 4)):
                o[rng.randrange(o.shape[0]), rng.randrange(5)] = rng.choice(
                    adversarial)
        elif kind == 2:    # truncate/extend a table (stride misalignment)
            if rng.random() < 0.5:
                p = np.resize(p.reshape(-1), rng.randrange(0, p.size + 5))
            else:
                o = np.resize(o.reshape(-1), rng.randrange(0, o.size + 5))
        elif kind == 3:    # fully random small tables
            p = np.array([[rng.choice(adversarial) for _ in range(7)]
                          for _ in range(rng.randint(1, 4))], np.int64)
        elif kind == 4:    # random op kinds/aux over valid ranges
            for r in range(o.shape[0]):
                o[r, 0] = rng.randrange(-2, 7)  # incl. runs/bytes-plain/bss
                o[r, 4] = rng.choice(adversarial)
        else:              # both tables perturbed
            p[rng.randrange(p.shape[0]), rng.randrange(7)] = rng.choice(
                adversarial)
            o[rng.randrange(o.shape[0]), rng.randrange(5)] = rng.choice(
                adversarial)
        n_pages = p.size // 7
        meta = np.zeros((max(n_pages, 1), 3), np.int64)
        stats = np.zeros((max(n_pages, 1), 2), np.int64)
        mask = np.zeros(max(n_pages, 1), np.uint8)
        use_stats = rng.random() < 0.5
        try:
            asm.assemble_pages(buffers, p, o, rng.choice((0, 0, 1, 6, 9)),
                               3, values if use_stats else None,
                               2 if use_stats else 0, meta,
                               stats if use_stats else None,
                               mask if use_stats else None)
        except ValueError:
            pass                       # the designed outcome
        except Exception as e:
            crashes += 1
            report("assemble", i, e)
    return crashes


TARGETS = {"thrift": fuzz_thrift, "verify": fuzz_verify,
           "offsets": fuzz_offsets, "index": fuzz_index,
           "assemble": fuzz_assemble, "nested": fuzz_nested}
DEFAULT_SEED = 20260803


def run(seed: int = DEFAULT_SEED, iters: int = 1000,
        targets=tuple(TARGETS), verbose: bool = True) -> dict:
    """Programmatic entry (tests use this): returns
    {target: crash_count}; deterministic for a given (seed, iters)."""
    results: dict[str, int] = {}

    def report(target: str, i: int, e: BaseException) -> None:
        if verbose:
            print(f"CRASH {target}[iter {i}]: {type(e).__name__}: {e}",
                  file=sys.stderr)

    for name in targets:
        results[name] = TARGETS[name](seed, iters, report)
    return results


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.fuzz",
        description="seeded mutation fuzz over thrift/verify/offset "
                    "validators (exit 0 = zero crashes)")
    ap.add_argument("--seed", type=int, default=DEFAULT_SEED)
    ap.add_argument("--iters", type=int, default=1000,
                    help="iterations per target (default 1000)")
    ap.add_argument("--target", choices=sorted(TARGETS), action="append",
                    default=[], help="run only this target (repeatable)")
    args = ap.parse_args(argv)
    targets = args.target or sorted(TARGETS)
    results = run(args.seed, args.iters, targets)
    total = sum(results.values())
    for name in targets:
        print(f"fuzz {name}: {args.iters} iters, {results[name]} crash(es) "
              f"[seed {args.seed}]")
    print(f"tools.fuzz: {total} crash(es) total")
    return 1 if total else 0


if __name__ == "__main__":
    sys.exit(main())
