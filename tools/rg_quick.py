"""Focused re-run of the rowgroup probe's cfg2-shape combined loop and its
two components (dict48 / delta8) — skips the nullable and levels programs
whose compiles dominate the full probe's wall time.  Measures the SAME
workload spec (bench.make_rowgroup_specs) through the SAME escalation
policy (bench.probe_time_loop) as the committed artifact: for kernel
iteration only; artifact numbers come from bench.py --rowgroup."""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from bench import make_rowgroup_specs, probe_time_loop
from kpw_tpu.runtime.select import probe_link

sp = make_rowgroup_specs()
print(f"delta_budget={sp['delta_budget']}", file=sys.stderr)
dispatch_s = probe_link()["dispatch_ms"] / 1e3
print(f"dispatch={dispatch_s * 1e3:.1f} ms", file=sys.stderr)

probe_time_loop(sp["spec_dict"] + sp["spec_delta"], "cfg2shape", 12,
                dispatch_s, reps=5)
probe_time_loop(sp["spec_dict"], "dict48", 12, dispatch_s, reps=5)
probe_time_loop(sp["spec_delta"], "delta8", 12, dispatch_s, reps=5)
