"""Encoding-choice pass: a value encoding is CHOSEN in exactly one
place — ``kpw_tpu/core/select_encoding.py`` (the ISSUE 16 chooser).

Before the chooser, the ``delta_fallback`` rule lived in
``CpuChunkEncoder`` and each backend re-derived it; a second decision
point is exactly how the native path once diverged from the CPU oracle
by one encoding id.  This pass keeps the funnel closed: an
``Encoding.<NAME>`` literal in the production tree is a finding unless
it is *dispatch* (a comparison against an already-chosen encoding —
``if encoding == Encoding.DELTA_BINARY_PACKED``, membership tests over
literal tuples) or it lives in the chooser / the enum's own module.
Everything else — assigning an encoding, passing one to a header
composer, seeding a footer set — is either a real second decision point
or one of the sanctioned *mechanism* sites (dictionary acceptance, page
header fields, footer encoding lists), which carry per-site
``# lint: encoding-choice ok — <reason>`` annotations so a reviewer can
see the full closed list.

Scope: the production tree (full-repo runs) minus the chooser and
``core/schema.py`` (the enum definition).  Fixture / single-file runs
lint whatever file they are given, same exemptions.
"""

from __future__ import annotations

import ast

from .common import Config, Finding, ParsedFile, suppressed

PASS_NAME = "encoding-choice"
DESCRIPTION = ("Encoding.<NAME> literals outside comparisons are value-"
               "encoding choices — allowed only in core/select_encoding.py "
               "or under a justified annotation")

# the one decision point + the enum definition itself
_EXEMPT = frozenset({
    "kpw_tpu/core/select_encoding.py",
    "kpw_tpu/core/schema.py",
})


def _is_dispatch(node: ast.AST, parents: dict) -> bool:
    """True when the literal is a comparison operand (directly, or inside
    a literal tuple/set/list operand: ``enc in (Encoding.A, Encoding.B)``)
    — reading an already-made decision, not making one."""
    child = node
    parent = parents.get(child)
    while isinstance(parent, (ast.Tuple, ast.Set, ast.List)):
        child = parent
        parent = parents.get(child)
    if isinstance(parent, ast.Compare):
        return child is parent.left or child in parent.comparators
    return False


def run(files: dict[str, ParsedFile], cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    for pf in files.values():
        if pf.path in _EXEMPT:
            continue
        parents = {c: p for p in ast.walk(pf.tree)
                   for c in ast.iter_child_nodes(p)}
        for node in ast.walk(pf.tree):
            if not (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "Encoding"
                    and isinstance(node.ctx, ast.Load)):
                continue
            if _is_dispatch(node, parents):
                continue
            if suppressed(pf, PASS_NAME, node.lineno, findings):
                continue
            findings.append(Finding(
                PASS_NAME, pf.path, node.lineno,
                f"Encoding.{node.attr} used outside a comparison — value "
                f"encodings are chosen ONLY in core/select_encoding.py "
                f"(a second decision point is how backends drift); "
                f"mechanism sites need a justified annotation"))
    return findings
