"""Spawn-safety pass: every ``multiprocessing`` use must pin the spawn
start method; fork is banned outright.

The process-workers PR (runtime/procworkers.py) crosses an interpreter
boundary, and the repo's recorded gotcha is hard-won: **fork with live
jax/XLA threads deadlocks the child** (the forked interpreter inherits a
mutex snapshot whose owners no longer exist; bench.py's capacity probe
hit exactly this before pinning spawn).  The platform default start
method is fork on Linux, so any ``multiprocessing`` construction that
does NOT go through ``get_context("spawn")`` silently inherits the
deadlock.  This pass mechanizes the rule for the production tree:

* constructing start-method-sensitive objects (``Process``, ``Pool``,
  ``Queue``, ``Manager``, shared ctypes, ...) directly off the
  ``multiprocessing`` module — or importing those names from it — is a
  finding: route them through a ``get_context("spawn")`` context object;
* ``get_context()`` with no argument, a non-literal argument, or any
  method other than ``"spawn"`` is a finding;
* ``set_start_method`` with anything but ``"spawn"`` is a finding
  (``"spawn"`` itself is allowed but the context-object form is
  preferred: it cannot be clobbered by a library race);
* ``os.fork`` / ``os.forkpty`` anywhere in ``kpw_tpu/`` is a finding —
  the fork-after-jax-import pattern has no safe call site in a package
  that imports jax.

``multiprocessing.shared_memory`` carries no start method and is exempt.
Suppression: ``# lint: spawn-safety ok — <reason>`` per site.
"""

from __future__ import annotations

import ast

from .common import Config, Finding, ParsedFile, suppressed

PASS_NAME = "spawn-safety"
DESCRIPTION = ("multiprocessing must pin the spawn start method "
               "(fork with live jax threads deadlocks); no os.fork")

# names whose construction binds a start method; reaching them through
# the module object (default context = fork on Linux) is the bug class
_SENSITIVE = frozenset({
    "Process", "Pool", "Queue", "SimpleQueue", "JoinableQueue", "Pipe",
    "Manager", "Value", "Array", "Event", "Lock", "RLock", "Semaphore",
    "BoundedSemaphore", "Condition", "Barrier",
})


def _mp_aliases(tree: ast.Module) -> tuple[set[str], list]:
    """(names bound to the multiprocessing module, findings-worthy
    ``from multiprocessing import <sensitive>`` nodes)."""
    aliases: set[str] = set()
    bad_froms: list = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "multiprocessing":
                    aliases.add(a.asname or "multiprocessing")
        elif isinstance(node, ast.ImportFrom) and node.level == 0:
            if node.module == "multiprocessing":
                for a in node.names:
                    if a.name in _SENSITIVE:
                        bad_froms.append((node, a.name))
    return aliases, bad_froms


def _literal_arg(call: ast.Call):
    if call.args and isinstance(call.args[0], ast.Constant):
        return call.args[0].value
    for kw in call.keywords:
        if kw.arg == "method" and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


def run(files: dict[str, ParsedFile], cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    for pf in files.values():
        aliases, bad_froms = _mp_aliases(pf.tree)
        for node, name in bad_froms:
            if suppressed(pf, PASS_NAME, node.lineno, findings):
                continue
            findings.append(Finding(
                PASS_NAME, pf.path, node.lineno,
                f"`from multiprocessing import {name}` binds the platform "
                f"default start method (fork on Linux — deadlocks with "
                f"live jax threads); construct it off "
                f"get_context(\"spawn\") instead"))
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # <mp-alias>.<Sensitive>(...) — default-context construction
            if (isinstance(func, ast.Attribute)
                    and isinstance(func.value, ast.Name)
                    and func.value.id in aliases
                    and func.attr in _SENSITIVE):
                if not suppressed(pf, PASS_NAME, node.lineno, findings):
                    findings.append(Finding(
                        PASS_NAME, pf.path, node.lineno,
                        f"multiprocessing.{func.attr}(...) uses the "
                        f"platform default start method (fork on Linux — "
                        f"deadlocks with live jax threads); go through "
                        f"get_context(\"spawn\")"))
                continue
            fname = (func.attr if isinstance(func, ast.Attribute)
                     else func.id if isinstance(func, ast.Name) else None)
            if fname == "get_context":
                # only multiprocessing's get_context (module attr, or a
                # bare name imported from multiprocessing / used in a
                # module that imports it) — decimal.getcontext etc. don't
                # match this spelling
                method = _literal_arg(node)
                if method != "spawn":
                    if not suppressed(pf, PASS_NAME, node.lineno, findings):
                        findings.append(Finding(
                            PASS_NAME, pf.path, node.lineno,
                            f"get_context({method!r}) does not pin the "
                            f"spawn start method — fork with live jax "
                            f"threads deadlocks; use "
                            f"get_context(\"spawn\")"))
            elif fname == "set_start_method":
                method = _literal_arg(node)
                if method != "spawn":
                    if not suppressed(pf, PASS_NAME, node.lineno, findings):
                        findings.append(Finding(
                            PASS_NAME, pf.path, node.lineno,
                            f"set_start_method({method!r}) — only "
                            f"\"spawn\" is safe in a package with live "
                            f"jax threads"))
            elif (isinstance(func, ast.Attribute)
                  and isinstance(func.value, ast.Name)
                  and func.value.id == "os"
                  and func.attr in ("fork", "forkpty")):
                if not suppressed(pf, PASS_NAME, node.lineno, findings):
                    findings.append(Finding(
                        PASS_NAME, pf.path, node.lineno,
                        f"os.{func.attr}() in the production tree: the "
                        f"fork-after-jax-import pattern deadlocks the "
                        f"child; spawn a fresh interpreter instead"))
    return findings
