"""Swallowed-exception pass: no bare ``except:`` and no
``except Exception: pass`` outside annotated seams.

A handler that catches everything and does nothing erases the only
evidence a failure ever happened — in this codebase that shape has
twice hidden real bugs until a bench/number went wrong.  Specific
exception types with a do-nothing body (``except queue.Full: pass``)
are fine: the narrowness IS the handling.  What this pass rejects:

* ``except:`` with no type anywhere (also catches SystemExit/
  KeyboardInterrupt — never acceptable in production code);
* ``except Exception`` / ``except BaseException`` whose body does
  nothing (only ``pass`` / ``...`` / ``continue``) and logs nothing.

Deliberate seams (a ``__del__`` GC safety net, best-effort cleanup on a
path that already failed) are annotated inline:
``# lint: swallowed-exceptions ok — <reason>``.
"""

from __future__ import annotations

import ast

from .common import Config, Finding, ParsedFile, suppressed

PASS_NAME = "swallowed-exceptions"
DESCRIPTION = ("no bare except / no do-nothing except Exception outside "
               "annotated seams")

_BROAD = {"Exception", "BaseException"}


def _is_broad(type_node: ast.AST | None) -> bool:
    if type_node is None:
        return True  # bare except: — always flagged, even with a body
    if isinstance(type_node, ast.Name):
        return type_node.id in _BROAD
    if isinstance(type_node, ast.Tuple):
        return any(_is_broad(e) for e in type_node.elts)
    return False


def _does_nothing(body: list[ast.stmt]) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass) or isinstance(stmt, ast.Continue):
            continue
        if (isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)):
            continue  # docstring / ellipsis
        return False
    return True


def run(files: dict[str, ParsedFile], cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    for pf in files.values():
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            bare = node.type is None
            if not _is_broad(node.type):
                continue
            if not bare and not _does_nothing(node.body):
                continue  # broad catch WITH handling (log/fallback): ok
            if suppressed(pf, PASS_NAME, node.lineno, findings):
                continue
            what = ("bare `except:`" if bare
                    else "`except Exception`-class handler that does "
                         "nothing")
            findings.append(Finding(
                PASS_NAME, pf.path, node.lineno,
                f"{what} — narrow the type, handle (at least log) the "
                f"failure, or annotate the deliberate seam with its "
                f"justification"))
    return findings
