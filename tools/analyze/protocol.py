"""Protocol-exhaustiveness pass: the two cross-process protocol surfaces
that drifted (or nearly drifted) in PR 11/12, mechanized.

**Descriptor tags.**  The process-worker queues speak tagged tuples —
``("unit", seq, slot)`` out, ``("free"|"published"|"died"|...)`` back —
and a tag sent without a receiving handler (or a handler for a tag
nothing sends) is a protocol hole that only shows up as silently dropped
acks or dead code.  Within any module that dispatches on tags (a
``kind = msg[0]`` variable compared against string literals), every tag
staged into a queue-shaped receiver (``*.put(("tag", ...))`` on a
``*_q``/``*queue`` attribute) must have a matching comparison, and every
compared tag must be sent by someone.  Modules with sends but no
dispatch at all are skipped — there is no protocol table to drift.

**Capability forwarding.**  ``io/fs.py publish_file`` dispatches the
publish protocol on FileSystem CAPABILITIES: the ``supports_rename``
class attribute and the capability-gated ``publish_commit`` method (the
base raises TypeError by design).  A *wrapper* filesystem that forwards
operations to an inner one but not the capabilities silently flips the
wrapped sink's publish protocol — the ``FaultInjectingFileSystem`` bug
caught in PR-12 review: ``__getattr__`` does NOT forward them, because
the base class defines defaults that shadow it.  A wrapper (a FileSystem
subclass with >= 3 same-name delegating methods to one ``self.<inner>``
receiver) must therefore define every capability EXPLICITLY in its own
class body (property, method, or assignment), or carry a justified
annotation (``FailoverFileSystem`` rejects rename-less sides at
construction, so the inherited defaults are correct by contract — the
annotation records exactly that).

Suppression: ``# lint: protocol-exhaustiveness ok — <reason>`` per site.
"""

from __future__ import annotations

import ast

from .common import Config, Finding, ParsedFile, suppressed

PASS_NAME = "protocol-exhaustiveness"
DESCRIPTION = ("queue descriptor tags matched send<->handle both "
               "directions; wrapper filesystems must forward every "
               "publish capability explicitly")

_FS_MODULE = "kpw_tpu/io/fs.py"
# fallback capability set for partial scans (fixtures, single files)
# where io/fs.py is not in view — matches what the live base declares
_DEFAULT_CAPABILITIES = frozenset({"supports_rename", "publish_commit"})
_MIN_DELEGATIONS = 3


# -- descriptor tags ---------------------------------------------------------

def _queue_receiver(call: ast.Call) -> str | None:
    """The queue-ish receiver name of an ``X.put(...)`` call, else None."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "put"):
        return None
    recv = f.value
    name = (recv.attr if isinstance(recv, ast.Attribute)
            else recv.id if isinstance(recv, ast.Name) else None)
    if name is None:
        return None
    if name == "q" or name.endswith("_q") or name.endswith("queue"):
        return name
    return None


def _tag_protocol(pf: ParsedFile):
    """(sent tags with line numbers, handled tags with line numbers) for
    one module.  A handled tag is a string literal compared against a
    variable assigned from a ``<msg>[0]`` subscript — the repo's
    dispatch idiom."""
    sends: list[tuple[str, int]] = []
    kind_vars: set[str] = set()
    for node in ast.walk(pf.tree):
        if isinstance(node, ast.Call) and _queue_receiver(node) is not None:
            if (node.args and isinstance(node.args[0], ast.Tuple)
                    and node.args[0].elts
                    and isinstance(node.args[0].elts[0], ast.Constant)
                    and isinstance(node.args[0].elts[0].value, str)):
                sends.append((node.args[0].elts[0].value, node.lineno))
        elif isinstance(node, ast.Assign):
            if (len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Subscript)
                    and isinstance(node.value.slice, ast.Constant)
                    and node.value.slice.value == 0):
                kind_vars.add(node.targets[0].id)
    handles: list[tuple[str, int]] = []
    for node in ast.walk(pf.tree):
        if not isinstance(node, ast.Compare):
            continue
        if not (isinstance(node.left, ast.Name)
                and node.left.id in kind_vars
                and len(node.ops) == 1
                and isinstance(node.ops[0], (ast.Eq, ast.NotEq))):
            continue
        comp = node.comparators[0]
        if isinstance(comp, ast.Constant) and isinstance(comp.value, str):
            handles.append((comp.value, node.lineno))
    return sends, handles


# -- capability forwarding ----------------------------------------------------

def _base_capabilities(files: dict) -> set[str]:
    """Capability names off the FileSystem base: plain class attributes
    plus capability-gated methods (body raises TypeError — present but
    not part of the abstract surface)."""
    pf = files.get(_FS_MODULE)
    if pf is None:
        return set(_DEFAULT_CAPABILITIES)
    for node in pf.tree.body:
        if isinstance(node, ast.ClassDef) and node.name == "FileSystem":
            caps: set[str] = set()
            for item in node.body:
                if (isinstance(item, ast.Assign)
                        and len(item.targets) == 1
                        and isinstance(item.targets[0], ast.Name)):
                    caps.add(item.targets[0].id)
                elif isinstance(item, ast.FunctionDef):
                    for sub in ast.walk(item):
                        if (isinstance(sub, ast.Raise)
                                and isinstance(sub.exc, ast.Call)
                                and isinstance(sub.exc.func, ast.Name)
                                and sub.exc.func.id == "TypeError"):
                            caps.add(item.name)
                            break
            return caps
    return set(_DEFAULT_CAPABILITIES)


def _is_fs_subclass(cls: ast.ClassDef) -> bool:
    for base in cls.bases:
        name = (base.id if isinstance(base, ast.Name)
                else base.attr if isinstance(base, ast.Attribute) else "")
        if name.endswith("FileSystem"):
            return True
    return False


def _delegation_votes(cls: ast.ClassDef) -> dict[str, int]:
    """How many of the class's methods forward a SAME-NAME call to a
    common ``self.<attr>`` receiver — the wrapper signature.  Adapters
    that translate to a foreign API (HDFS -> pyarrow) do not match."""
    votes: dict[str, int] = {}
    for item in cls.body:
        if not isinstance(item, ast.FunctionDef):
            continue
        for sub in ast.walk(item):
            if not isinstance(sub, ast.Call):
                continue
            f = sub.func
            if (isinstance(f, ast.Attribute) and f.attr == item.name
                    and isinstance(f.value, ast.Attribute)
                    and isinstance(f.value.value, ast.Name)
                    and f.value.value.id == "self"):
                votes[f.value.attr] = votes.get(f.value.attr, 0) + 1
                break  # one vote per method
    return votes


def _defined_names(cls: ast.ClassDef) -> set[str]:
    out: set[str] = set()
    for item in cls.body:
        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.add(item.name)
        elif isinstance(item, ast.Assign):
            for t in item.targets:
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif (isinstance(item, ast.AnnAssign)
              and isinstance(item.target, ast.Name)):
            # annotated class attr (`supports_rename: bool = False`)
            out.add(item.target.id)
    return out


def run(files: dict[str, ParsedFile], cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    capabilities = _base_capabilities(files)
    for pf in files.values():
        # -- descriptor tags ------------------------------------------------
        sends, handles = _tag_protocol(pf)
        if handles:  # only modules that actually dispatch on tags
            sent_tags = {t for t, _ in sends}
            handled_tags = {t for t, _ in handles}
            for tag, line in sends:
                if tag in handled_tags:
                    continue
                if suppressed(pf, PASS_NAME, line, findings):
                    continue
                findings.append(Finding(
                    PASS_NAME, pf.path, line,
                    f"descriptor tag {tag!r} is sent across a queue but "
                    f"no handler in this module compares against it — "
                    f"the receiving side would drop it silently"))
            seen: set[str] = set()
            for tag, line in handles:
                if tag in sent_tags or tag in seen:
                    continue
                seen.add(tag)
                if suppressed(pf, PASS_NAME, line, findings):
                    continue
                findings.append(Finding(
                    PASS_NAME, pf.path, line,
                    f"handler compares against descriptor tag {tag!r} "
                    f"that nothing sends — dead protocol arm (renamed "
                    f"tag? stale handler?)"))
        # -- capability forwarding ------------------------------------------
        if pf.path == _FS_MODULE:
            continue  # the base itself defines the capabilities
        for node in pf.tree.body:
            if not isinstance(node, ast.ClassDef) or not _is_fs_subclass(node):
                continue
            votes = _delegation_votes(node)
            if not votes or max(votes.values()) < _MIN_DELEGATIONS:
                continue  # adapter or leaf implementation, not a wrapper
            defined = _defined_names(node)
            missing = sorted(c for c in capabilities if c not in defined)
            if not missing:
                continue
            if suppressed(pf, PASS_NAME, node.lineno, findings):
                continue
            inner = max(votes, key=lambda k: votes[k])
            findings.append(Finding(
                PASS_NAME, pf.path, node.lineno,
                f"wrapper filesystem {node.name} (delegates to "
                f"self.{inner}) does not forward capability(ies) "
                f"{', '.join(missing)} — the base-class defaults shadow "
                f"__getattr__, so wrapping a rename-less sink silently "
                f"flips its publish protocol; define them explicitly or "
                f"annotate why the defaults are correct"))
    return findings
