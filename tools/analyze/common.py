"""Shared infrastructure for the project lint passes (tools/analyze).

Every pass consumes the same parsed-file map and emits :class:`Finding`s;
the CLI (``python -m tools.analyze``) aggregates and exit-codes on them.
Suppression is explicit and justified: a line-level annotation comment

    # lint: <pass-name> ok — <one-line reason>

on the flagged line (or the line directly above it) allowlists exactly
that site for exactly that pass.  An annotation WITHOUT a reason is
itself a finding — the allowlist policy (README "Correctness tooling")
is that every exception carries its justification next to the code it
excuses, so a reviewer never has to hunt for why a rule was waived.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# the production tree the full-repo run scans; tests/, tools/ and bench
# scripts are not production code (they may import fault injection,
# swallow exceptions in teardown, etc. by design)
DEFAULT_ROOTS = ("kpw_tpu",)

_ANNOTATION = re.compile(
    r"#\s*lint:\s*(?P<passes>[a-z][a-z0-9-]*(?:\s*,\s*[a-z][a-z0-9-]*)*)"
    r"\s+ok(?P<rest>.*)$")
_REASON = re.compile(r"^\s*[—–-]+\s*(?P<reason>\S.*)$")


@dataclass(frozen=True)
class Finding:
    """One lint verdict, stable-keyed for exact-match tests."""

    pass_name: str
    file: str       # repo-relative path
    line: int
    message: str

    def __str__(self) -> str:
        return f"{self.file}:{self.line}: [{self.pass_name}] {self.message}"


@dataclass
class ParsedFile:
    """One source file, parsed once and shared by every pass."""

    path: str           # repo-relative, '/'-separated
    tree: ast.Module
    lines: list[str]    # raw source lines (1-indexed via lines[i-1])

    def annotation_for(self, pass_name: str, line: int):
        """The annotation covering ``line`` for ``pass_name``: returns
        (found, reason) — ``found`` True when an annotation names this
        pass on the flagged line itself or anywhere in the contiguous
        comment block directly above it (so a multi-line justification
        reads naturally); ``reason`` is None when the annotation is
        missing its justification."""
        candidates = [line]
        ln = line - 1
        while (1 <= ln <= len(self.lines)
               and self.lines[ln - 1].lstrip().startswith("#")
               and line - ln <= 12):
            candidates.append(ln)
            ln -= 1
        for ln in candidates:
            if not 1 <= ln <= len(self.lines):
                continue
            m = _ANNOTATION.search(self.lines[ln - 1])
            if m is None:
                continue
            passes = {p.strip() for p in m.group("passes").split(",")}
            if pass_name not in passes:
                continue
            rm = _REASON.match(m.group("rest"))
            return True, (rm.group("reason") if rm else None)
        return False, None


@dataclass
class Config:
    """Per-run knobs.  ``full_repo`` gates the bidirectional/completeness
    checks (e.g. "every STAGE_NAMES entry must be used somewhere") that
    are only meaningful when the whole production tree is in view —
    running a single fixture file must not fail registry completeness.
    ``hot_all`` (fixture/test mode) treats every scanned file as a
    hot module for the import pass."""

    full_repo: bool = True
    hot_all: bool = False


def rel(path: str) -> str:
    p = os.path.abspath(path)
    if p.startswith(REPO_ROOT):
        p = p[len(REPO_ROOT):].lstrip(os.sep)
    return p.replace(os.sep, "/")


def collect_files(paths=None) -> dict[str, ParsedFile]:
    """Parse every ``.py`` under ``paths`` (default: the production
    roots).  A file that does not parse is reported by the CLI as its own
    hard failure — the linter must never silently skip unparseable code."""
    roots = [os.path.join(REPO_ROOT, r) for r in DEFAULT_ROOTS] \
        if not paths else [os.path.abspath(p) for p in paths]
    out: dict[str, ParsedFile] = {}
    for root in roots:
        if os.path.isfile(root):
            _parse_into(out, root)
            continue
        for dirpath, dirnames, filenames in os.walk(root):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    _parse_into(out, os.path.join(dirpath, fn))
    return out


def _parse_into(out: dict, path: str) -> None:
    with open(path, encoding="utf-8") as f:
        src = f.read()
    tree = ast.parse(src, filename=path)
    out[rel(path)] = ParsedFile(path=rel(path), tree=tree,
                                lines=src.splitlines())


def suppressed(pf: ParsedFile, pass_name: str, line: int,
               findings: list, message_if_unjustified: str | None = None
               ) -> bool:
    """True when an annotation covers (pass, line).  A reason-less
    annotation does NOT suppress — it appends its own finding instead,
    so an empty waiver can never hide a defect."""
    found, reason = pf.annotation_for(pass_name, line)
    if not found:
        return False
    if reason is None:
        findings.append(Finding(
            pass_name, pf.path, line,
            message_if_unjustified
            or "allowlist annotation without a justification — write "
               "`# lint: %s ok — <reason>`" % pass_name))
        return True  # the site is annotated; the missing reason is the bug
    return True


def resolve_import(pf: ParsedFile, node: ast.ImportFrom) -> str:
    """Absolute dotted module for a (possibly relative) from-import —
    shared by every pass that reasons about imports, so hot-imports and
    fault-isolation can never disagree on the same statement."""
    if node.level == 0:
        return node.module or ""
    pkg_parts = pf.path.removesuffix(".py").split("/")[:-1]
    # level 1 = current package, each extra level pops one package
    base = pkg_parts[: len(pkg_parts) - (node.level - 1)]
    return ".".join(base + ([node.module] if node.module else []))


def dotted_name(node: ast.AST) -> str | None:
    """``a.b.c`` for a Name/Attribute chain, else None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None
