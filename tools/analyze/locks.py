"""Lock-discipline pass: no blocking calls under a held lock, and a
cycle-free static lock-acquisition graph.

Two rules, both learned the hard way in this repo (the PR-1
``string_stats`` race was a missing lock; the concurrency surface is now
~25 locks across ingest/core/io/runtime and growing toward the GIL-free
host leg on the roadmap):

1. **No blocking call while a lock is held.**  A ``threading.Lock`` /
   ``RLock`` / ``Condition`` guard should bracket memory mutation, not
   IO: a broker fetch/commit, a filesystem op, a queue put/get, a thread
   join or a sleep executed under a lock turns every sibling of that
   lock into a convoy behind the slowest IO — and under fault injection
   (io/faults latency/hang rules) into a de facto deadlock.  Waiting on
   the condition you HOLD is exempt (that is the release pattern).

2. **The static lock-order graph must be acyclic.**  Every syntactic
   ``with B:`` nested inside ``with A:`` records the edge A→B; a cycle
   between two locks means two call paths can acquire them in opposite
   orders — the classic inversion the runtime detector
   (kpw_tpu/utils/lockcheck.py) catches live.  Static nesting only sees
   one function at a time (no interprocedural inference — documented
   limitation; the runtime detector covers the cross-function case).

Lock-likeness is name-based: the context expression's last segment
matching ``lock|mutex|cond`` (``self._lock``, ``_DISPATCH_LOCK``,
``self._buf_cond``, ``log.lock``).  That convention is repo law — a lock
named ``foo`` is invisible to this pass, so don't name locks ``foo``.

Suppress one deliberate site with ``# lint: lock-discipline ok — <why>``.
"""

from __future__ import annotations

import ast
import re

from .common import Config, Finding, ParsedFile, dotted_name, suppressed

PASS_NAME = "lock-discipline"
DESCRIPTION = ("blocking calls under held threading locks + "
               "static lock-order cycle rejection")

_LOCK_RE = re.compile(r"(lock|mutex|cond)", re.I)

# attribute calls that block (or may block) by contract.  join is
# narrowed to thread-shaped receivers/timeout calls because str.join and
# os.path.join are ubiquitous; put/get are narrowed to queue/buffer-
# shaped receivers because dict.get is ubiquitous.
_BLOCKING_ATTRS = frozenset({
    "sleep",                                   # time.sleep / _time.sleep
    "fetch", "fetch_batch", "commit",          # broker IO
    "open_read", "open_write", "open_append",  # filesystem ops
    "rename", "durable_rename", "delete", "mkdirs", "list_files",
    "sync", "sync_dir",
})
_QUEUEISH_RE = re.compile(r"(^|_)(q|queue|buf)$|queue$", re.I)
_THREADISH_RE = re.compile(r"thread|proc|pool", re.I)


def _last_segment(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _is_lock_expr(node: ast.AST) -> str | None:
    name = dotted_name(node)
    if name is not None and _LOCK_RE.search(_last_segment(name)):
        return name
    return None


class _FunctionScanner(ast.NodeVisitor):
    """Walks one function body tracking the syntactically-held lock
    stack; records blocking-call findings and acquisition edges."""

    def __init__(self, pf: ParsedFile, cls: str | None, edges: dict,
                 findings: list) -> None:
        self.pf = pf
        self.cls = cls
        self.edges = edges          # (src, dst) -> (file, line)
        self.findings = findings
        self.held: list[tuple[str, str]] = []  # (canon, source-expr name)

    def _canon(self, name: str) -> str:
        mod = self.pf.path.rsplit("/", 1)[-1].removesuffix(".py")
        if name.startswith("self."):
            owner = self.cls or mod
            return f"{owner}.{name[len('self.'):]}"
        return f"{mod}.{name}" if "." not in name else name

    # nested defs get their own scanner (a closure's body does not run
    # under the enclosing with)
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        _FunctionScanner(self.pf, self.cls, self.edges,
                         self.findings).generic_visit(node)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        _FunctionScanner(self.pf, self.cls, self.edges,
                         self.findings).generic_visit(node)

    def visit_With(self, node: ast.With) -> None:
        n_acquired = 0
        for item in node.items:
            name = _is_lock_expr(item.context_expr)
            if name is not None:
                self._record_acquire(name, item.context_expr)
                self.held.append((self._canon(name), name))
                n_acquired += 1
            else:
                self.visit(item.context_expr)  # e.g. with fs.open_write(...)
        for stmt in node.body:
            self.visit(stmt)
        for _ in range(n_acquired):
            self.held.pop()

    def _record_acquire(self, name: str, node: ast.AST) -> None:
        canon = self._canon(name)
        for held_canon, _src in self.held:
            if held_canon != canon:
                self.edges.setdefault(
                    (held_canon, canon), (self.pf.path, node.lineno))

    def visit_Call(self, node: ast.Call) -> None:
        self.generic_visit(node)
        if not self.held:
            return
        line = node.lineno
        func = node.func
        label = None
        if isinstance(func, ast.Name):
            if func.id == "sleep":
                label = "sleep() (time.sleep)"
            elif func.id == "open":
                label = "builtin open()"
        elif isinstance(func, ast.Attribute):
            attr = func.attr
            recv = dotted_name(func.value)
            recv_seg = _last_segment(recv) if recv else ""
            if attr == "acquire":
                lock = _is_lock_expr(func.value)
                if lock is not None:
                    self._record_acquire(lock, node)
                return
            if attr in _BLOCKING_ATTRS:
                label = f"{recv or '<expr>'}.{attr}()"
            elif attr in ("wait", "wait_for"):
                # waiting on the condition you hold releases it — the
                # canonical producer/consumer pattern, never a finding;
                # waiting on anything ELSE while a lock is held blocks
                # with the lock still held
                if recv is None or recv not in {src for _, src in self.held}:
                    label = f"{recv or '<expr>'}.{attr}()"
            elif attr in ("put", "get") and _QUEUEISH_RE.search(recv_seg):
                label = f"{recv}.{attr}()"
            elif attr == "join" and (
                    _THREADISH_RE.search(recv_seg)
                    or any(kw.arg == "timeout" for kw in node.keywords)):
                label = f"{recv or '<expr>'}.join()"
        if label is None:
            return
        held_names = ", ".join(c for c, _ in self.held)
        if suppressed(self.pf, PASS_NAME, line, self.findings):
            return
        self.findings.append(Finding(
            PASS_NAME, self.pf.path, line,
            f"blocking call {label} while holding lock(s) {held_names} — "
            f"move the call outside the guarded section or annotate the "
            f"deliberate exception"))


def _find_cycles(edges: dict) -> list[list[str]]:
    """Every elementary cycle reachable in the edge set, deduplicated by
    node membership (one report per inversion pair/ring)."""
    graph: dict[str, list[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, []).append(b)
    seen_cycles: set[frozenset] = set()
    out: list[list[str]] = []
    for start in sorted(graph):
        stack = [(start, [start])]
        while stack:
            node, path = stack.pop()
            for nxt in graph.get(node, ()):
                if nxt == start and len(path) > 1:
                    key = frozenset(path)
                    if key not in seen_cycles:
                        seen_cycles.add(key)
                        out.append(path + [start])
                elif nxt not in path and len(path) < 8:
                    stack.append((nxt, path + [nxt]))
    return out


def run(files: dict[str, ParsedFile], cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    edges: dict[tuple[str, str], tuple[str, int]] = {}
    for pf in files.values():
        cls_of = _class_map(pf.tree)
        nested = _nested_functions(pf.tree)
        for node in ast.walk(pf.tree):
            if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and node not in nested):
                # nested defs are scanned by their enclosing function's
                # scanner (fresh held-stack) — scanning them again here
                # would duplicate every finding inside them
                scanner = _FunctionScanner(pf, cls_of.get(node), edges,
                                           findings)
                for stmt in node.body:
                    scanner.visit(stmt)
    for cycle in _find_cycles(edges):
        sites = []
        for a, b in zip(cycle, cycle[1:]):
            f, ln = edges[(a, b)]
            sites.append(f"{a}->{b} at {f}:{ln}")
        f0, ln0 = edges[(cycle[0], cycle[1])]
        findings.append(Finding(
            PASS_NAME, f0, ln0,
            "lock-order cycle: " + "; ".join(sites) + " — two call paths "
            "can acquire these locks in opposite orders (deadlock risk); "
            "pick one global order"))
    return findings


def _nested_functions(tree: ast.Module) -> set:
    """Function nodes defined inside another function (closures, local
    retry bodies) — owned by the enclosing function's scan."""
    out: set = set()
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for sub in ast.walk(node):
                if sub is not node and isinstance(
                        sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    out.add(sub)
    return out


def _class_map(tree: ast.Module) -> dict:
    """function node -> name of the innermost enclosing class."""
    out: dict = {}

    def walk(node: ast.AST, cls: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                walk(child, child.name)
            else:
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    out[child] = cls
                walk(child, cls)

    walk(tree, None)
    return out
