"""Stage-coverage pass: ``stage(...)`` call sites must be statically
traceable — a literal, registered name, actually entered.

The canonical-names pass closes the literal↔registry loop, but it can
only see string literals.  This pass covers the two ways a ``stage()``
site escapes that loop entirely:

* **dynamic names** — ``stage(f"worker.{x}")``, ``stage(name_var)``:
  the span records under a name no registry entry, dashboard anchor, or
  doc claim can reference, and the canonical-names pass silently skips
  the site.  Stage identity must be a literal; variability belongs in
  the ``**attrs`` kwargs (``stage("compactor.round", tenant=name)``),
  which ride the trace as span args.
* **never-entered sites** — a bare ``stage("x")`` expression statement:
  ``stage()`` returns a context manager, and one that is never entered
  records nothing.  The site *looks* instrumented (it has a registered
  name, the reverse-direction registry check is satisfied) while the
  leg runs untraced — exactly the gap this pass exists to close.

Scope: every scanned file (the seam is one global function, so there is
no module whitelist to maintain).  Only call sites named ``stage`` with
at least one positional argument are considered; ``**attrs`` keywords
are free-form by design.

Suppression: ``# lint: stage-coverage ok — <reason>`` per site.
"""

from __future__ import annotations

import ast

from .common import Config, Finding, ParsedFile, suppressed

PASS_NAME = "stage-coverage"
DESCRIPTION = ("stage() names must be string literals and the returned "
               "context manager must actually be entered")


def _is_stage_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    f = node.func
    name = (f.id if isinstance(f, ast.Name)
            else f.attr if isinstance(f, ast.Attribute) else None)
    return name == "stage"


def run(files: dict[str, ParsedFile], cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    for pf in files.values():
        # stage() calls that ARE entered: `with stage(...)` items (plain
        # and async), so the walk below can flag the rest
        entered: set[int] = set()
        for node in ast.walk(pf.tree):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    if _is_stage_call(item.context_expr):
                        entered.add(id(item.context_expr))
        for node in ast.walk(pf.tree):
            # a bare `stage("x")` statement: context manager built,
            # never entered, nothing recorded — the leg runs untraced
            if (isinstance(node, ast.Expr) and _is_stage_call(node.value)
                    and id(node.value) not in entered):
                if not suppressed(pf, PASS_NAME, node.lineno, findings):
                    findings.append(Finding(
                        PASS_NAME, pf.path, node.lineno,
                        "stage(...) result is discarded — the context "
                        "manager is never entered, so the site records "
                        "nothing; wrap the leg in `with stage(...)`"))
            if not _is_stage_call(node):
                continue
            if not node.args:
                continue  # zero-arg call is some other stage()
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                continue  # literal: canonical-names owns it from here
            if suppressed(pf, PASS_NAME, node.lineno, findings):
                continue
            spelled = ("an f-string" if isinstance(arg, ast.JoinedStr)
                       else "a non-literal expression")
            findings.append(Finding(
                PASS_NAME, pf.path, node.lineno,
                f"stage() name is {spelled} — dynamic stage names bypass "
                f"the STAGE_NAMES registry (and every doc/dashboard "
                f"anchor on it); use a registered literal name and put "
                f"the variability in **attrs"))
    return findings
