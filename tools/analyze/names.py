"""Canonical-name pass: stage/metric string literals ↔ the in-code
registries, both directions.

``tracing.STAGE_NAMES`` and ``metrics.METRIC_NAMES`` are the canonical
registries the docs cite from (tools/check_docs.py reconciles doc
claims against them since PR 2).  This pass closes the code side of the
loop:

* every string literal passed to ``stage(...)`` must be registered in
  ``STAGE_NAMES`` (a typo'd stage name would otherwise record spans
  under a name no dashboard/check knows);
* every string literal passed to a ``meter(...)`` / ``gauge(...)`` /
  ``histogram(...)`` constructor must be registered in ``METRIC_NAMES``;
* **reverse direction** (full-repo runs only): every registered stage
  name must actually be used by a ``stage(...)`` call, and every
  registered metric name must be the value of a module-level constant
  in ``runtime/metrics.py`` that production code references — a
  registry entry nothing emits is a doc claim about a ghost.

Metric names travel as constants (``M.WRITTEN_RECORDS_METER``), so the
constant table in metrics.py is cross-checked against METRIC_NAMES
exactly (same set, no orphans either way).
"""

from __future__ import annotations

import ast

from .common import Config, Finding, ParsedFile, suppressed

PASS_NAME = "canonical-names"
DESCRIPTION = ("stage()/meter/gauge literals registered in STAGE_NAMES/"
               "METRIC_NAMES, and registries fully used (both directions)")

_METRICS_MODULE = "kpw_tpu/runtime/metrics.py"
_TRACING_MODULE = "kpw_tpu/utils/tracing.py"
_METRIC_CTORS = ("meter", "gauge", "histogram")


def _registry(files: dict, path: str, tuple_name: str) -> tuple[set, int]:
    """The literal entries of ``tuple_name`` in ``path`` (empty when the
    module is not in the scanned set — fixture runs)."""
    pf = files.get(path)
    if pf is None:
        return set(), 0
    for node in pf.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == tuple_name
                and isinstance(node.value, (ast.Tuple, ast.List))):
            vals = set()
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value,
                                                                str):
                    vals.add(elt.value)
                elif isinstance(elt, ast.Name):
                    # entries referencing the metric constants by name
                    vals.add(("NAME", elt.id))
            return vals, node.lineno
    return set(), 0


def _metric_constants(files: dict) -> dict[str, str]:
    """metrics.py module-level ``UPPER = "dotted.name"`` constants."""
    pf = files.get(_METRICS_MODULE)
    if pf is None:
        return {}
    out: dict[str, str] = {}
    for node in pf.tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id.isupper()
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
                and "." in node.value.value):
            out[node.targets[0].id] = node.value.value
    return out


def _imported_registries() -> tuple[set, set]:
    """Fallback for partial scans (fixtures, single files): read the
    live registries from the installed package so literal checks still
    have something authoritative to check against."""
    try:
        from kpw_tpu.runtime.metrics import METRIC_NAMES
        from kpw_tpu.utils.tracing import STAGE_NAMES
        return set(STAGE_NAMES), set(METRIC_NAMES)
    except ImportError:
        return set(), set()


def run(files: dict[str, ParsedFile], cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    stage_reg, _ = _registry(files, _TRACING_MODULE, "STAGE_NAMES")
    metric_tuple, metric_line = _registry(files, _METRICS_MODULE,
                                          "METRIC_NAMES")
    constants = _metric_constants(files)
    if not stage_reg or not metric_tuple:
        imp_stages, imp_metrics = _imported_registries()
        stage_reg = stage_reg or imp_stages
        if not metric_tuple:
            metric_tuple = imp_metrics
    # METRIC_NAMES entries are constant references; resolve to values
    metric_reg: set[str] = set()
    named_constants: set[str] = set()
    for entry in metric_tuple:
        if isinstance(entry, tuple):
            named_constants.add(entry[1])
            if entry[1] in constants:
                metric_reg.add(constants[entry[1]])
        else:
            metric_reg.add(entry)

    stage_used: set[str] = set()
    constants_used: set[str] = set()
    metric_literals_used: set[str] = set()
    for pf in files.values():
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Attribute) and pf.path != _METRICS_MODULE:
                if node.attr in constants:
                    constants_used.add(node.attr)
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            fname = (func.id if isinstance(func, ast.Name)
                     else func.attr if isinstance(func, ast.Attribute)
                     else None)
            lit = (node.args[0].value
                   if node.args and isinstance(node.args[0], ast.Constant)
                   and isinstance(node.args[0].value, str) else None)
            if fname == "stage" and lit is not None:
                stage_used.add(lit)
                if stage_reg and lit not in stage_reg:
                    if not suppressed(pf, PASS_NAME, node.lineno, findings):
                        findings.append(Finding(
                            PASS_NAME, pf.path, node.lineno,
                            f"stage({lit!r}) not registered in "
                            f"tracing.STAGE_NAMES — register it (and "
                            f"document it) or fix the typo"))
            elif fname in _METRIC_CTORS and lit is not None:
                # only registry-shaped constructors take a NAME first arg
                # (MetricRegistry.meter/gauge/histogram); dotted-name shape
                # keeps incidental .get("key")-style calls out
                if "." not in lit:
                    continue
                metric_literals_used.add(lit)
                if metric_reg and lit not in metric_reg:
                    if not suppressed(pf, PASS_NAME, node.lineno, findings):
                        findings.append(Finding(
                            PASS_NAME, pf.path, node.lineno,
                            f"{fname}({lit!r}) not registered in "
                            f"metrics.METRIC_NAMES — register it (and "
                            f"document it) or fix the typo"))

    if not cfg.full_repo:
        return findings

    # reverse directions — registry completeness against actual use
    for name in sorted(stage_reg - stage_used):
        findings.append(Finding(
            PASS_NAME, _TRACING_MODULE, 1,
            f"STAGE_NAMES entry {name!r} is never used by any stage(...) "
            f"call — dead registry entry (docs may cite it); remove or "
            f"re-wire it"))
    # constant table <-> METRIC_NAMES exact correspondence
    for cname, value in sorted(constants.items()):
        if value not in metric_reg:
            findings.append(Finding(
                PASS_NAME, _METRICS_MODULE, metric_line or 1,
                f"metric constant {cname} = {value!r} missing from "
                f"METRIC_NAMES — register it"))
    by_value = {v: k for k, v in constants.items()}
    for value in sorted(metric_reg):
        if value not in by_value and value not in metric_literals_used:
            findings.append(Finding(
                PASS_NAME, _METRICS_MODULE, metric_line or 1,
                f"METRIC_NAMES entry {value!r} has no backing constant in "
                f"metrics.py and no literal constructor call — ghost "
                f"metric"))
    # every constant must be referenced by production code outside
    # metrics.py (a registered-but-never-marked metric is a ghost too)
    for cname in sorted(named_constants | set(constants)):
        if cname in constants and cname not in constants_used:
            findings.append(Finding(
                PASS_NAME, _METRICS_MODULE, metric_line or 1,
                f"metric constant {cname} ({constants[cname]!r}) is never "
                f"referenced outside metrics.py — nothing emits it"))
    return findings
