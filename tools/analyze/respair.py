"""Resource-pairing pass: every acquisition of an unlink/abort/free-shaped
resource must have a matching release in reach, or carry a per-site
justified annotation.

The PR-12 review caught two instances of the same class by hand: orphaned
multipart uploads (a ``create_multipart`` whose abort/complete could be
skipped on a crash path) and dead store-observer accumulation (an
``add_observer`` with NO removal API, attached unconditionally per
adapter — every recovery/verify flow leaked a callback forever).  PR 11's
shared-memory ring is the same shape (a ``SharedMemory`` create with no
``unlink`` leaks a ``/dev/shm`` segment past the process).  This pass
mechanizes the rule for the known acquire-shaped APIs in the tree:

* a call to an acquire name (table below) requires at least one call to
  one of its release names **in the same module** — module scope is the
  deliberate approximation: the repo's resource lifecycles are owned by
  one module each (ring, objectstore adapter, heartbeat), and a release
  living in a different module is exactly the drift this pass should
  surface for human review via an annotation;
* an acquire whose release set is EMPTY (no removal API exists —
  ``add_observer``) is always a finding: the annotation must justify why
  unbounded accumulation cannot happen (the PR-12 fix gated attachment,
  and the annotation records that reasoning next to the call);
* ``SharedMemory`` counts as an acquisition only when its ``create``
  keyword is present and not literally False — ``create=False`` is an
  attach, and only the creator may unlink (cpython #82300 discipline).

Suppression: ``# lint: resource-pairing ok — <reason>`` per site.
"""

from __future__ import annotations

import ast

from .common import Config, Finding, ParsedFile, suppressed

PASS_NAME = "resource-pairing"
DESCRIPTION = ("acquire-shaped calls (SharedMemory create, multipart "
               "create, ring staging, observer attach, heartbeat tokens) "
               "need a reachable release or a justified annotation")

# acquire callee name -> (release callee names, human description).
# An empty release tuple means no removal API exists: every call site
# must carry a justified annotation.
PAIRS: dict[str, tuple[tuple[str, ...], str]] = {
    "SharedMemory": (("unlink",), "shared-memory segment"),
    "create_multipart": (("abort_multipart", "complete_multipart"),
                         "multipart upload"),
    "write_slot_parts": (("note_free", "drain_unfreed_slots"),
                         "staged ring slot"),
    "io_started": (("io_finished",), "heartbeat pending-IO token"),
    "add_observer": ((), "store observer (no removal API exists)"),
}


def _callee_name(node: ast.Call) -> str | None:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def _is_acquisition(name: str, node: ast.Call) -> bool:
    if name != "SharedMemory":
        return True
    for kw in node.keywords:
        if kw.arg == "create":
            if isinstance(kw.value, ast.Constant) and kw.value.value is False:
                return False
            return True
    return False  # SharedMemory() default create=False: an attach


def run(files: dict[str, ParsedFile], cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    for pf in files.values():
        called: set[str] = set()
        acquires: list[tuple[str, ast.Call]] = []
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _callee_name(node)
            if name is None:
                continue
            called.add(name)
            if name in PAIRS and _is_acquisition(name, node):
                acquires.append((name, node))
        for name, node in acquires:
            releases, what = PAIRS[name]
            if releases and any(r in called for r in releases):
                continue
            if suppressed(pf, PASS_NAME, node.lineno, findings):
                continue
            if releases:
                findings.append(Finding(
                    PASS_NAME, pf.path, node.lineno,
                    f"{name}(...) acquires a {what} but no release "
                    f"({' / '.join(releases)}) is called anywhere in this "
                    f"module — a crash/early-exit path here leaks it; add "
                    f"the release or a justified annotation"))
            else:
                findings.append(Finding(
                    PASS_NAME, pf.path, node.lineno,
                    f"{name}(...) attaches a {what}: unbounded "
                    f"accumulation unless the call site is gated — "
                    f"justify with an annotation (the PR-12 dead-observer "
                    f"leak is this exact class)"))
    return findings
