"""Clock-discipline pass: heartbeat/watchdog/deadline code must measure
time with ``time.monotonic`` (CLOCK_MONOTONIC), never the wall clock.

The PR-11 heartbeat design leans on CLOCK_MONOTONIC being system-wide on
Linux (a child's ``started_at`` stamp is compared against the parent
watchdog's clock), and every stall/deadline computation in the tree is a
*liveness* question: an NTP step or DST jump must never condemn a
healthy worker or expire a live deadline.  ``time.time()`` in those
modules is therefore a finding — wall time belongs only to naming
(file timestamps) and operator-facing observability ages, which carry
per-site annotations where they live in a scoped module.

Scope: the declared module set below (full-repo runs).  For fixture /
single-file runs (``full_repo`` False) a file is scoped when its
basename carries a liveness cue (watchdog/heartbeat/deadline/clock/
stall) — the same pattern as the hot-imports fixture mode.

Suppression: ``# lint: clock-discipline ok — <reason>`` per site.
"""

from __future__ import annotations

import ast
import os

from .common import Config, Finding, ParsedFile, suppressed

PASS_NAME = "clock-discipline"
DESCRIPTION = ("heartbeat/watchdog/deadline code uses time.monotonic — "
               "time.time()/datetime wall clocks there are findings")

# the modules whose timing IS liveness: heartbeat cells + watchdog
# scanning (procworkers), the watchdog itself, retry deadlines, and the
# two runtime detectors (their probes reason about liveness windows)
CLOCK_SCOPED = frozenset({
    "kpw_tpu/runtime/watchdog.py",
    "kpw_tpu/runtime/procworkers.py",
    "kpw_tpu/runtime/retry.py",
    "kpw_tpu/utils/lockcheck.py",
    "kpw_tpu/utils/schedcheck.py",
})

_NAME_CUES = ("watchdog", "heartbeat", "deadline", "clock", "stall")

# wall-clock calls: time.time() and the datetime constructors people
# reach for instead of a monotonic source
_WALL_ATTRS = {
    ("time", "time"): "time.time()",
    ("datetime", "now"): "datetime.now()",
    ("datetime", "utcnow"): "datetime.utcnow()",
}


def _scoped(pf: ParsedFile, cfg: Config) -> bool:
    if pf.path in CLOCK_SCOPED:
        return True
    if cfg.full_repo:
        return False
    base = os.path.basename(pf.path).lower()
    return any(cue in base for cue in _NAME_CUES)


def run(files: dict[str, ParsedFile], cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    for pf in files.values():
        if not _scoped(pf, cfg):
            continue
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and isinstance(f.value, ast.Name)):
                continue
            spelled = _WALL_ATTRS.get((f.value.id, f.attr))
            if spelled is None:
                continue
            if suppressed(pf, PASS_NAME, node.lineno, findings):
                continue
            findings.append(Finding(
                PASS_NAME, pf.path, node.lineno,
                f"{spelled} in a heartbeat/watchdog/deadline module — "
                f"liveness math must use time.monotonic (an NTP step "
                f"would condemn a healthy worker); wall time here needs "
                f"a justified annotation"))
    return findings
