"""CLI for the lint suite: ``python -m tools.analyze [paths...]``.

Exit codes: 0 clean, 1 findings, 2 usage/parse error.  ``--json`` emits
the findings as a JSON array for tooling; ``--list`` prints the pass
registry (what check_docs reconciles README's pass citations against).
"""

from __future__ import annotations

import argparse
import json
import sys

from . import PASSES
from .common import Config, collect_files


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.analyze",
        description="project lint suite (see tools/analyze/__init__.py)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to scan (default: the production "
                         "tree, kpw_tpu/)")
    ap.add_argument("--pass", dest="only", action="append", default=[],
                    metavar="NAME", help="run only this pass (repeatable)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable findings")
    ap.add_argument("--list", action="store_true",
                    help="list registered passes and exit")
    ap.add_argument("--hot-all", action="store_true",
                    help="treat every scanned file as a hot module "
                         "(fixture/test mode for the hot-imports pass)")
    args = ap.parse_args(argv)

    if args.list:
        for name, mod in PASSES.items():
            print(f"{name}: {mod.DESCRIPTION}")
        return 0

    for name in args.only:
        if name not in PASSES:
            print(f"unknown pass {name!r}; known: {', '.join(PASSES)}",
                  file=sys.stderr)
            return 2

    try:
        files = collect_files(args.paths or None)
    except SyntaxError as e:
        print(f"parse error: {e}", file=sys.stderr)
        return 2
    cfg = Config(full_repo=not args.paths, hot_all=args.hot_all)

    findings = []
    for name, mod in PASSES.items():
        if args.only and name not in args.only:
            continue
        findings.extend(mod.run(files, cfg))
    findings.sort(key=lambda f: (f.file, f.line, f.pass_name))

    if args.json:
        print(json.dumps([f.__dict__ for f in findings], indent=1))
    else:
        for f in findings:
            print(f)
        ran = args.only or list(PASSES)
        print(f"tools.analyze: {len(findings)} finding(s) from "
              f"{len(ran)} pass(es) over {len(files)} file(s)",
              file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
