"""Project-specific static analysis: the bug classes this repo has
shipped-then-fixed, mechanized as lint passes.

Run the whole suite over the production tree:

    python -m tools.analyze              # exit 0 = clean

Or a subset / specific files (fixture tests use this):

    python -m tools.analyze --pass lock-discipline kpw_tpu/ingest
    python -m tools.analyze --hot-all tests/analyze_fixtures/hot_import.py

Passes (see each module's docstring for the rule and its history):

* ``lock-discipline`` — no blocking calls under held locks; static
  lock-order graph must be acyclic (tools/analyze/locks.py)
* ``hot-imports`` — no function-local imports in the hot modules
  (tools/analyze/hotimports.py, with the optional-dependency ALLOWLIST)
* ``canonical-names`` — stage()/metric literals registered in
  STAGE_NAMES/METRIC_NAMES, registries fully used (tools/analyze/names.py)
* ``fault-isolation`` — production never imports fault injection or
  tests/ (tools/analyze/faultiso.py)
* ``swallowed-exceptions`` — no bare/do-nothing broad handlers
  (tools/analyze/swallow.py)
* ``spawn-safety`` — multiprocessing must pin the spawn start method;
  no fork-after-jax-import (tools/analyze/spawnsafety.py)

Suppression is per-site and justified: ``# lint: <pass> ok — <reason>``
on the flagged line or the line above.  A reason-less annotation is
itself a finding.  The runtime complement (lock-order inversions only a
live interleaving exposes) is ``kpw_tpu/utils/lockcheck.py``.
"""

from __future__ import annotations

from . import faultiso, hotimports, locks, names, spawnsafety, swallow

# registration order = report order
PASSES = {
    locks.PASS_NAME: locks,
    hotimports.PASS_NAME: hotimports,
    names.PASS_NAME: names,
    faultiso.PASS_NAME: faultiso,
    swallow.PASS_NAME: swallow,
    spawnsafety.PASS_NAME: spawnsafety,
}

PASS_NAMES = tuple(PASSES)
