"""Project-specific static analysis: the bug classes this repo has
shipped-then-fixed, mechanized as lint passes.

Run the whole suite over the production tree:

    python -m tools.analyze              # exit 0 = clean

Or a subset / specific files (fixture tests use this):

    python -m tools.analyze --pass lock-discipline kpw_tpu/ingest
    python -m tools.analyze --hot-all tests/analyze_fixtures/hot_import.py

Passes (see each module's docstring for the rule and its history):

* ``lock-discipline`` — no blocking calls under held locks; static
  lock-order graph must be acyclic (tools/analyze/locks.py)
* ``hot-imports`` — no function-local imports in the hot modules
  (tools/analyze/hotimports.py, with the optional-dependency ALLOWLIST)
* ``canonical-names`` — stage()/metric literals registered in
  STAGE_NAMES/METRIC_NAMES, registries fully used (tools/analyze/names.py)
* ``fault-isolation`` — production never imports fault injection or
  tests/ (tools/analyze/faultiso.py)
* ``swallowed-exceptions`` — no bare/do-nothing broad handlers
  (tools/analyze/swallow.py)
* ``spawn-safety`` — multiprocessing must pin the spawn start method;
  no fork-after-jax-import (tools/analyze/spawnsafety.py)
* ``resource-pairing`` — acquire-shaped calls (SharedMemory create,
  multipart create, ring staging, observer attach, heartbeat tokens)
  need a reachable release or a justified annotation
  (tools/analyze/respair.py)
* ``protocol-exhaustiveness`` — queue descriptor tags matched
  send↔handle both directions; wrapper filesystems forward every
  publish capability explicitly (tools/analyze/protocol.py)
* ``clock-discipline`` — heartbeat/watchdog/deadline code uses
  time.monotonic, never the wall clock (tools/analyze/clocks.py)
* ``encoding-choice`` — value encodings are chosen only in
  core/select_encoding.py; ``Encoding.`` literals elsewhere must be
  comparisons or annotated mechanism sites (tools/analyze/encchoice.py)
* ``stage-coverage`` — stage() names must be string literals (dynamic
  names bypass the STAGE_NAMES registry) and the returned context
  manager must actually be entered (tools/analyze/stagecover.py)

Suppression is per-site and justified: ``# lint: <pass> ok — <reason>``
on the flagged line or the line above.  A reason-less annotation is
itself a finding.  The runtime complements are
``kpw_tpu/utils/lockcheck.py`` (lock-order inversions only a live
interleaving exposes) and ``kpw_tpu/utils/schedcheck.py`` + tools/schedx
(cross-process schedule exploration over the same protocol surfaces the
static passes lint).
"""

from __future__ import annotations

from . import (clocks, encchoice, faultiso, hotimports, locks, names,
               protocol, respair, spawnsafety, stagecover, swallow)

# registration order = report order
PASSES = {
    locks.PASS_NAME: locks,
    hotimports.PASS_NAME: hotimports,
    names.PASS_NAME: names,
    faultiso.PASS_NAME: faultiso,
    swallow.PASS_NAME: swallow,
    spawnsafety.PASS_NAME: spawnsafety,
    respair.PASS_NAME: respair,
    protocol.PASS_NAME: protocol,
    clocks.PASS_NAME: clocks,
    encchoice.PASS_NAME: encchoice,
    stagecover.PASS_NAME: stagecover,
}

PASS_NAMES = tuple(PASSES)
