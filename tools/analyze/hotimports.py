"""Hot-path import pass: no function-local imports in the declared hot
modules.

A function-local ``import`` costs a sys.modules dict probe plus binding
work on EVERY call — measured twice in this repo's history (PR 2 hoisted
``import random`` out of ``Histogram.update``; PR 6 hoisted the
logging/time/tracing imports out of the fetch loop after they showed up
in the e2e profile), and both times the import had crept back in by the
next perf pass.  This pass mechanizes the rule for the modules whose
functions sit on the per-record/per-batch path.

The allowlist below holds the DELIBERATE exceptions: optional-dependency
probes (jax backends, kafka, zstandard) that must fail lazily — an
eager module-top import would make the whole package unimportable
without the optional dep.  Every entry carries its one-line
justification; ``python tools/check_docs.py`` verifies the entries
README cites actually exist here.  One-off sites can alternatively be
annotated inline with ``# lint: hot-imports ok — <reason>``.
"""

from __future__ import annotations

import ast

from .common import (Config, Finding, ParsedFile, resolve_import,
                     suppressed)

PASS_NAME = "hot-imports"
DESCRIPTION = ("no function-local imports in hot modules (consumer, "
               "worker loop, row-group writer, pages, encodings)")

# the per-record / per-batch / per-row-group path: one function-local
# import here runs up to millions of times per second
HOT_MODULES = frozenset({
    "kpw_tpu/ingest/consumer.py",
    "kpw_tpu/runtime/writer.py",
    "kpw_tpu/core/writer.py",
    "kpw_tpu/core/pages.py",
    "kpw_tpu/core/encodings.py",
})

# (hot module, absolute imported module) -> one-line justification.
# Policy (README "Correctness tooling"): entries are for optional
# dependencies that must stay lazy — NOT for hot-loop convenience; a
# justification that reads "called rarely" belongs on an inline
# annotation at the call site instead, where the reviewer sees the loop.
ALLOWLIST: dict[tuple[str, str], str] = {
    ("kpw_tpu/runtime/writer.py", "kpw_tpu.ops.backend"):
        "fail-fast probe for the optional jax TPU backend at writer "
        "construction; an eager import would break CPU-only installs",
    ("kpw_tpu/runtime/writer.py", "kpw_tpu.parallel.mesh_encoder"):
        "fail-fast probe for the optional jax mesh backend at writer "
        "construction; an eager import would break CPU-only installs",
    ("kpw_tpu/runtime/writer.py", "kpw_tpu.runtime.select"):
        "select imports the chosen backend's module tree (jax/native) on "
        "use; deferred so cpu-backend writers never pay or require it",
}


def _import_candidates(pf: ParsedFile, node) -> list[list[str]]:
    """Per imported alias, the dotted names it may denote, least to most
    specific — ``from ..ops import backend`` can mean the module
    ``kpw_tpu.ops.backend`` or a name inside ``kpw_tpu.ops``, and the
    allowlist matches either."""
    if isinstance(node, ast.Import):
        return [[a.name] for a in node.names]
    base = resolve_import(pf, node)
    return [[base, f"{base}.{a.name}"] if base else [a.name]
            for a in node.names]


def run(files: dict[str, ParsedFile], cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    for pf in files.values():
        if not (cfg.hot_all or pf.path in HOT_MODULES):
            continue
        top_level = set(pf.tree.body)
        for node in ast.walk(pf.tree):
            if not isinstance(node, (ast.Import, ast.ImportFrom)):
                continue
            if node in top_level:
                continue
            for cands in _import_candidates(pf, node):
                if any((pf.path, c) in ALLOWLIST for c in cands):
                    continue
                if suppressed(pf, PASS_NAME, node.lineno, findings):
                    continue
                findings.append(Finding(
                    PASS_NAME, pf.path, node.lineno,
                    f"function-local import of {cands[-1]} in hot module "
                    f"— hoist to module top, or add an ALLOWLIST entry "
                    f"(tools/analyze/hotimports.py) with a justification "
                    f"if it is a deliberate lazy optional-dependency "
                    f"import"))
    if cfg.full_repo:
        # a stale allowlist is drift too: every entry must still point at
        # a hot module that actually contains a local import of that
        # module (otherwise the exception outlives the code it excused)
        live: set[tuple[str, str]] = set()
        for pf in files.values():
            if pf.path not in HOT_MODULES:
                continue
            top_level = set(pf.tree.body)
            for node in ast.walk(pf.tree):
                if (isinstance(node, (ast.Import, ast.ImportFrom))
                        and node not in top_level):
                    for cands in _import_candidates(pf, node):
                        live.update((pf.path, c) for c in cands)
        for key, why in sorted(ALLOWLIST.items()):
            if key not in live:
                findings.append(Finding(
                    PASS_NAME, key[0], 1,
                    f"stale ALLOWLIST entry {key[1]!r}: no function-local "
                    f"import of it remains — delete the entry "
                    f"(justification was: {why})"))
            if not why.strip():
                findings.append(Finding(
                    PASS_NAME, key[0], 1,
                    f"ALLOWLIST entry {key[1]!r} has an empty "
                    f"justification"))
    return findings
