"""Fault-isolation pass: production code never imports fault injection
or test code.

The chaos/degrade machinery (``kpw_tpu/io/faults.py``,
``kpw_tpu/ingest/faults.py``) is deliberately opt-in at the Builder
seam — PR 3's contract is "zero production import", because a
production worker that can reach injection code is one mis-wired flag
away from injecting faults into real traffic.  Same for ``tests/``:
production importing test helpers inverts the dependency arrow and
quietly ships test doubles.

The only sanctioned exceptions are the package ``__init__`` re-export
lines (the public names tests/benchmarks import), each annotated
inline with ``# lint: fault-isolation ok — <reason>``; the fault
modules themselves (and ``faults`` importing ``faults``) are exempt by
construction.
"""

from __future__ import annotations

import ast

from .common import (Config, Finding, ParsedFile, resolve_import,
                     suppressed)

PASS_NAME = "fault-isolation"
DESCRIPTION = ("production modules never import io/ingest fault "
               "injection or tests/")

_FAULT_MODULES = ("kpw_tpu.io.faults", "kpw_tpu.ingest.faults")


def _violation(mod: str) -> str | None:
    if mod in _FAULT_MODULES or any(mod.startswith(f + ".")
                                    for f in _FAULT_MODULES):
        return f"fault-injection module {mod}"
    if mod == "tests" or mod.startswith("tests."):
        return f"test code {mod}"
    return None


def run(files: dict[str, ParsedFile], cfg: Config) -> list[Finding]:
    findings: list[Finding] = []
    for pf in files.values():
        if pf.path.endswith("/faults.py"):
            continue  # injection implementing itself is not a leak
        for node in ast.walk(pf.tree):
            mods: list[str] = []
            if isinstance(node, ast.Import):
                mods = [a.name for a in node.names]
            elif isinstance(node, ast.ImportFrom):
                # the base module plus each imported name as a possible
                # submodule: catches `from .faults import X`,
                # `from . import faults` AND `from kpw_tpu.io import
                # faults` alike
                base = resolve_import(pf, node)
                mods = [base] if base else []
                mods += [f"{base}.{a.name}" if base else a.name
                         for a in node.names]
            for mod in mods:
                why = _violation(mod) if mod else None
                if why is None:
                    continue
                if suppressed(pf, PASS_NAME, node.lineno, findings):
                    continue
                findings.append(Finding(
                    PASS_NAME, pf.path, node.lineno,
                    f"production module imports {why} — fault injection "
                    f"and test helpers are opt-in at the Builder seam "
                    f"only; if this is the public re-export seam, "
                    f"annotate it with a justification"))
    return findings
