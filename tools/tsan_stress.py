"""Concurrent-thread stress over the GIL-released native entries — the
ThreadSanitizer leg's workload (``tools/sanitize.sh --tsan``).

The repo's native hot path deliberately runs WITHOUT the GIL:
``shred_flat_buf``/``gather_buf`` (PR 6) decode broker buffers while the
encode pipeline thread runs, ``assemble_pages`` (PR 10) assembles whole
column chunks concurrently from the encoder pool (including the
BYTE_STREAM_SPLIT transpose op, ISSUE 16), the fused nested entries
``shred_nested_buf``/``nested_fill`` (ISSUE 14) decode and materialize
list<struct> batches the same way, and ``kpw_byte_stream_split`` runs
GIL-free under ctypes from every encoder thread.  A data race in that
code is a real race no Python-level tool can see — so this driver
hammers all of them from several true-concurrent threads against the
``KPW_NATIVE_SANITIZE=tsan`` build, where TSan traps any racy access
instead of letting it silently corrupt a page.

Workload discipline (why this is race-clean by DESIGN, which is exactly
what TSan verifies): shared inputs are allocated once in the main thread
BEFORE the workers spawn (``pthread_create`` is TSan-visible sync, so
the handoff is ordered) and only READ concurrently; every output buffer
is thread-private.

Usage:  python -m tools.tsan_stress [--iters N] [--threads T]

Exit 0 = all iterations completed (under the tsan build with
``halt_on_error=1`` any detected race aborts the process loudly).
Running it without the tsan build is still a valid concurrency smoke —
outputs are cross-checked against the main thread's reference bytes.
"""

from __future__ import annotations

import argparse
import os
import sys
import threading

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
for _p in (_REPO, os.path.join(_REPO, "tests")):
    if _p not in sys.path:
        sys.path.insert(0, _p)

DEFAULT_ITERS = 200   # committed regression configuration per thread
DEFAULT_THREADS = 4


def _shred_inputs():
    """One contiguous wire-format batch + columnarizer, built in the
    main thread (shared read-only by every worker)."""
    from proto_helpers import sample_message_class

    from kpw_tpu.models.proto_bridge import ProtoColumnarizer

    cls = sample_message_class()
    col = ProtoColumnarizer(cls)
    payloads = [cls(query=f"q-{i}" * (i % 7 + 1), timestamp=i,
                    page_number=i % 11).SerializeToString()
                for i in range(400)]
    lens = np.fromiter(map(len, payloads), np.int64, count=len(payloads))
    offs = np.zeros(len(payloads) + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    return col, b"".join(payloads), offs


def _nested_inputs():
    """One contiguous NESTED wire batch + columnarizer forced onto the
    nested decoder (fused shred_nested_buf/nested_fill path), built in
    the main thread — shared read-only by every worker; each worker's
    decode handle and output arrays are thread-private."""
    from proto_helpers import nested_message_classes

    from kpw_tpu.models.proto_bridge import ProtoColumnarizer

    cls = nested_message_classes()
    col = ProtoColumnarizer(cls)
    col._wire = None  # pin the nested decoder
    assert col.wire_capable, "nested plan must engage"
    payloads = []
    for i in range(300):
        m = cls()
        m.order_id = i
        for j in range(i % 4):
            it = m.items.add()
            it.sku = f"sku{(i + j) % 9}"
            it.qty = j + 1
        payloads.append(m.SerializeToString())
    lens = np.fromiter(map(len, payloads), np.int64, count=len(payloads))
    offs = np.zeros(len(payloads) + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    return col, b"".join(payloads), offs


def _assemble_inputs():
    """A minimal valid RAW-op plan for ``assemble_pages`` (same shape as
    tests/test_assemble.py's valid-plan contract); page/op/meta tables
    are templates each thread COPIES (meta is an output array)."""
    from kpw_tpu.core.metadata import DATA_PAGE_PREFIX, data_page_suffix
    from kpw_tpu.native.build import load_assemble

    asm = load_assemble()
    body = bytes(range(1, 250)) * 8
    buffers = (body, DATA_PAGE_PREFIX, data_page_suffix(8, 0))
    pages = [[0, 1, 1, 2, 0, 0, 0]]
    ops = [[0, 0, 0, len(body), 0]]
    if getattr(asm, "OP_KINDS", 2) >= 5:
        # BYTE_STREAM_SPLIT page (ISSUE 16): the kOpBss transpose walks
        # a shared read-only value buffer from every worker
        bss = np.ascontiguousarray(
            np.random.default_rng(7).standard_normal(64), np.float64)
        buffers = buffers + (bss.view(np.uint8).tobytes(),)
        ops.append([4, 3, 0, 64, 8])
        pages.append([1, 2, 1, 2, 0, 0, 0])
    return asm, buffers, np.array(pages, np.int64), np.array(ops, np.int64)


def _bss_inputs():
    """One shared read-only float64 array for the GIL-free
    ``kpw_byte_stream_split`` ctypes entry; each worker's output string
    buffer is allocated inside the wrapper (thread-private)."""
    from kpw_tpu.native.build import load

    lib = load()
    if not hasattr(lib, "byte_stream_split"):
        return None, None
    vals = np.ascontiguousarray(
        np.random.default_rng(9).standard_normal(2048), np.float64)
    return lib, vals


def run(iters: int = DEFAULT_ITERS, threads: int = DEFAULT_THREADS) -> int:
    col, blob, offs, = _shred_inputs()
    ncol, nblob, noffs = _nested_inputs()
    asm, buffers, pages, ops = _assemble_inputs()
    bss_lib, bss_vals = _bss_inputs()

    # reference outputs from the main thread: workers must reproduce
    # them bit-for-bit (a race that slips past TSan would still corrupt)
    ref_batch = col.columnarize_buffer(blob, offs)
    ref_col0 = bytes(memoryview(ref_batch.chunks[0].values.data))
    nref = ncol.columnarize_buffer(nblob, noffs)
    nref_sku = bytes(memoryview(nref.chunks[1].values.data))
    nref_defs = np.asarray(nref.chunks[1].def_levels).tobytes()
    ref_meta = np.zeros((pages.shape[0], 3), np.int64)
    ref_out = asm.assemble_pages(buffers, pages, ops, 0, 3, None, 0,
                                 ref_meta, None, None)
    ref_bss = bss_lib.byte_stream_split(bss_vals) if bss_lib else None

    barrier = threading.Barrier(threads)
    errors: list[BaseException] = []
    mu = threading.Lock()

    def worker(widx: int) -> None:
        try:
            barrier.wait()
            for i in range(iters):
                batch = col.columnarize_buffer(blob, offs)
                if bytes(memoryview(batch.chunks[0].values.data)) \
                        != ref_col0:
                    raise AssertionError(
                        f"worker {widx} iter {i}: shred output diverged")
                nbatch = ncol.columnarize_buffer(nblob, noffs)
                if (bytes(memoryview(nbatch.chunks[1].values.data))
                        != nref_sku
                        or np.asarray(nbatch.chunks[1].def_levels).tobytes()
                        != nref_defs):
                    raise AssertionError(
                        f"worker {widx} iter {i}: nested shred diverged")
                meta = np.zeros((pages.shape[0], 3), np.int64)
                out = asm.assemble_pages(buffers, pages.copy(), ops.copy(),
                                         0, 3, None, 0, meta, None, None)
                if out != ref_out:
                    raise AssertionError(
                        f"worker {widx} iter {i}: assembled page diverged")
                if bss_lib is not None \
                        and bss_lib.byte_stream_split(bss_vals) != ref_bss:
                    raise AssertionError(
                        f"worker {widx} iter {i}: byte_stream_split diverged")
        except BaseException as e:  # noqa: BLE001 — reported to the runner
            with mu:
                errors.append(e)

    ts = [threading.Thread(target=worker, args=(w,)) for w in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    if errors:
        for e in errors:
            print(f"tsan_stress: FAILED: {e!r}", file=sys.stderr)
        return 1
    mode = os.environ.get("KPW_NATIVE_SANITIZE", "")
    print(f"tsan_stress: {threads} threads x {iters} iters over "
          f"shred_flat_buf/gather_buf/shred_nested_buf/nested_fill/"
          f"assemble_pages/byte_stream_split completed "
          f"(KPW_NATIVE_SANITIZE={mode or 'off'}); outputs byte-identical "
          f"to the single-thread reference")
    return 0


def canary(iters: int = 300) -> int:
    """Negative control: a DELIBERATE data race (two threads writing one
    shared meta output table through ``assemble_pages``) that TSan must
    report — run by tools/sanitize.sh with ``halt_on_error=0`` and its
    stderr grepped for the race warning, so a misconfigured preload can
    never report the clean run as 'sanitizers ran clean' vacuously."""
    asm, buffers, pages, ops = _assemble_inputs()
    # SHARED output: the planted race
    meta = np.zeros((pages.shape[0], 3), np.int64)
    barrier = threading.Barrier(2)

    def worker() -> None:
        barrier.wait()
        for _ in range(iters):
            asm.assemble_pages(buffers, pages.copy(), ops.copy(), 0, 3,
                               None, 0, meta, None, None)

    ts = [threading.Thread(target=worker) for _ in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    print("tsan_stress: canary completed (expect ThreadSanitizer data-race "
          "warnings on stderr under the tsan build)")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m tools.tsan_stress")
    ap.add_argument("--iters", type=int, default=DEFAULT_ITERS)
    ap.add_argument("--threads", type=int, default=DEFAULT_THREADS)
    ap.add_argument("--canary", action="store_true",
                    help="run the deliberate-race negative control")
    args = ap.parse_args(argv)
    if args.canary:
        return canary()
    return run(iters=args.iters, threads=args.threads)


if __name__ == "__main__":
    sys.exit(main())
