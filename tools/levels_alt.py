"""Prototype alternatives for the level-run extraction (the dominant cost
of the nullable-shape device phase: `ops.levels.level_runs_multi` spends
~8 ms/step of single-operand sort work at the 448-window probe shape).

The sort-based compaction (V0, production) places run payloads at their
rank by sorting 8Ki packed keys per window.  But the positions of run
ENDS are recoverable without any sort: with ``c = cumsum(is_end)``
(nondecreasing), the j-th run ends at the first position where c == j+1,
i.e. ``pos_j = #{i : c_i < j+1}`` — a monotone search.  Variants:

- V1 global count: pos_j = sum over the full window of (c < t_j) —
  one (run_bucket, bucket) broadcast compare-sum per window.
- V2 two-level count: count at block granularity first (run_bucket x S),
  then within the one block that contains the answer (row gather +
  run_bucket x B compare) — hierarchical search with ~bucket/B less
  compare work than V1.
- V3 searchsorted: jnp.searchsorted(c, t) — XLA's binary-search lowering.
- V4 chunk bitselect: run ends partition the valid region, so extraction
  is pure position compaction — chunked end-bitmasks + coarse monotone
  count + an unrolled 32-step bit select; only ~3 element gathers per
  output slot.

Measured on the v5e (448-window probe shape): v0 sort 7.3-8.3 ms/step;
v1 21.0; v2 29.3; v4 16.0 (v3 not timed to completion; its per-element
binary-search gathers bound it above v4).  Conclusion, twice over: TPU
gathers lose to the sort network even at a few gathered elements per
output — the packed single-operand sort extraction is the floor.

All return (run_vals, run_lens) bit-identical to V0 (asserted below on
random windows).  Run `python tools/levels_alt.py` for the CPU identity
check; `python tools/levels_alt.py --tpu` times all variants at the
probe's exact shape inside one jitted fori_loop, dispatch-subtracted.
"""

from __future__ import annotations

import functools
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

import jax
import jax.numpy as jnp

from kpw_tpu.ops.levels import level_runs_multi
from kpw_tpu.ops.packing import window_run_scan


def _ends_payload(padded, sid, start, count, bucket):
    v, _, _, run_len_here, is_end = window_run_scan(
        padded, sid, start, count, bucket)
    return v, run_len_here, is_end


def _gather_common(v, run_len_here, pos, valid):
    run_vals = jnp.where(valid, v[pos], 0)
    run_lens = jnp.where(valid, run_len_here[pos], 0).astype(jnp.int32)
    return run_vals, run_lens


def _one_v1(padded, sid, start, count, bucket, run_bucket):
    v, rlh, is_end = _ends_payload(padded, sid, start, count, bucket)
    c = jnp.cumsum(is_end.astype(jnp.int32))
    t = jnp.arange(run_bucket, dtype=jnp.int32) + 1
    pos = jnp.sum((c[None, :] < t[:, None]).astype(jnp.int32), axis=1)
    valid = t <= c[-1]
    return _gather_common(v, rlh, jnp.where(valid, pos, 0), valid)


def _one_v2(padded, sid, start, count, bucket, run_bucket, block=512):
    v, rlh, is_end = _ends_payload(padded, sid, start, count, bucket)
    c = jnp.cumsum(is_end.astype(jnp.int32))
    S = bucket // block
    cblk = c.reshape(S, block)
    cb = cblk[:, -1]                       # ends through end of block s
    t = jnp.arange(run_bucket, dtype=jnp.int32) + 1
    s_j = jnp.sum((cb[None, :] < t[:, None]).astype(jnp.int32), axis=1)
    s_j = jnp.minimum(s_j, S - 1)
    rows = jnp.take(cblk, s_j, axis=0)     # (run_bucket, block) row gather
    li = jnp.sum((rows < t[:, None]).astype(jnp.int32), axis=1)
    pos = s_j * block + li
    valid = t <= c[-1]
    return _gather_common(v, rlh, jnp.where(valid, pos, 0), valid)


def _one_v4(padded, sid, start, count, bucket, run_bucket, chunk=32):
    """Sort-free AND (mostly) gather-free: run ends PARTITION the valid
    region, so the whole extraction is sparse stream compaction of end
    positions.  Chunk the is_end mask (32 bits -> one u32 per chunk),
    cumsum chunk counts, locate output slot t's chunk by a coarse
    (run_bucket x S) monotone count, select the t-th set bit of the
    chunk's mask with an unrolled 32-step vector loop, and recover run
    lengths as diffs of consecutive end positions — only 3 element
    gathers per output slot (prefix, mask, value)."""
    v, _, is_end = _ends_payload(padded, sid, start, count, bucket)
    S = bucket // chunk
    ie = is_end.reshape(S, chunk)
    cnts = jnp.sum(ie.astype(jnp.int32), axis=1)
    prefix = jnp.cumsum(cnts)  # inclusive, monotone
    total = prefix[-1]
    t = jnp.arange(run_bucket, dtype=jnp.int32)  # 0-based end index
    r = jnp.sum((prefix[None, :] <= t[:, None]).astype(jnp.int32), axis=1)
    r = jnp.minimum(r, S - 1)
    before = jnp.where(r > 0, prefix[jnp.maximum(r - 1, 0)], 0)
    tl = t - before  # rank of the wanted end within its chunk
    weights = jnp.uint32(1) << jnp.arange(chunk, dtype=jnp.uint32)
    masks = jnp.sum(ie.astype(jnp.uint32) * weights[None, :], axis=1)
    m = masks[r]
    k = jnp.zeros(run_bucket, jnp.int32)
    pos_sel = jnp.zeros(run_bucket, jnp.int32)
    for b in range(chunk):  # unrolled: vector ops on (run_bucket,)
        bit = ((m >> jnp.uint32(b)) & 1).astype(jnp.int32)
        hit = (bit == 1) & (k == tl)
        pos_sel = jnp.where(hit, b, pos_sel)
        k = k + bit
    pos = r * chunk + pos_sel
    valid_t = t < total
    run_vals = jnp.where(valid_t, v[pos], 0)
    prev = jnp.concatenate([jnp.full((1,), -1, jnp.int32), pos[:-1]])
    run_lens = jnp.where(valid_t, pos - prev, 0).astype(jnp.int32)
    return run_vals, run_lens


def _one_v3(padded, sid, start, count, bucket, run_bucket):
    v, rlh, is_end = _ends_payload(padded, sid, start, count, bucket)
    c = jnp.cumsum(is_end.astype(jnp.int32))
    t = jnp.arange(run_bucket, dtype=jnp.int32) + 1
    pos = jnp.searchsorted(c, t, side="left").astype(jnp.int32)
    valid = t <= c[-1]
    return _gather_common(v, rlh, jnp.where(valid, jnp.minimum(pos, bucket - 1), 0),
                          valid)


def _multi(one, levels_all, sids, starts, counts, bucket, run_bucket, **kw):
    padded = jnp.pad(levels_all, ((0, 0), (0, bucket)))
    return jax.vmap(lambda s, a, c: one(padded, s, a, c, bucket, run_bucket,
                                        **kw))(sids, starts, counts)


VARIANTS = {
    "v1_global_count": functools.partial(_multi, _one_v1),
    "v2_two_level": functools.partial(_multi, _one_v2),
    "v3_searchsorted": functools.partial(_multi, _one_v3),
    "v4_chunk_bitselect": functools.partial(_multi, _one_v4),
}


def _probe_shape(seed=11, K=56, N=1 << 16, page=8192, null_p=0.02):
    rng = np.random.default_rng(seed)
    lvl = (rng.random((K, N)) > null_p).astype(np.uint32)
    pages_per = N // page
    sids = jnp.asarray(np.repeat(np.arange(K, dtype=np.int32), pages_per))
    starts = jnp.asarray(np.tile(np.arange(0, N, page, dtype=np.int32), K))
    counts = jnp.full(K * pages_per, page, jnp.int32)
    return jnp.asarray(lvl), sids, starts, counts, page


def check_identity():
    for null_p in (0.02, 0.5, 0.0):
        lvl, sids, starts, counts, page = _probe_shape(
            seed=3, K=8, N=1 << 14, null_p=null_p)
        rb = 1 << 13  # worst case: every element its own run
        want_v, want_l = level_runs_multi(lvl, sids, starts, counts, page,
                                          rb, 1)
        for name, fn in VARIANTS.items():
            got_v, got_l = fn(lvl, sids, starts, counts, page, rb)
            np.testing.assert_array_equal(np.asarray(want_v), np.asarray(got_v),
                                          err_msg=f"{name} vals null_p={null_p}")
            np.testing.assert_array_equal(np.asarray(want_l), np.asarray(got_l),
                                          err_msg=f"{name} lens null_p={null_p}")
        # ragged tail window
        counts2 = counts.at[0].set(1234)
        want = level_runs_multi(lvl, sids, starts, counts2, page, rb, 1)
        for name, fn in VARIANTS.items():
            got = fn(lvl, sids, starts, counts2, page, rb)
            np.testing.assert_array_equal(np.asarray(want[0]), np.asarray(got[0]))
            np.testing.assert_array_equal(np.asarray(want[1]), np.asarray(got[1]))
    print("identity OK: all variants byte-identical to level_runs_multi")


def time_variants(n_steps=12):
    from kpw_tpu.runtime.select import probe_link

    lvl, sids, starts, counts, page = _probe_shape()
    RB = 1024
    dispatch_s = probe_link()["dispatch_ms"] / 1e3

    def bench(name, fn):
        @jax.jit
        def loop(steps, lv):
            def body(i, acc):
                rv, rl = fn(lv ^ (i & 1).astype(jnp.uint32), sids, starts,
                            counts, page, RB)
                return (acc + jnp.sum(rl, dtype=jnp.int32).astype(jnp.uint32)
                        + jnp.sum(rv, dtype=jnp.uint32))
            return jax.lax.fori_loop(0, steps, body, jnp.uint32(0))

        t0 = time.perf_counter()
        np.asarray(loop(jnp.int32(n_steps), lvl))
        print(f"[{name}] compile+first {time.perf_counter() - t0:.1f}s",
              file=sys.stderr)
        steps = n_steps
        while True:
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(loop(jnp.int32(steps), lvl))
                best = min(best, time.perf_counter() - t0)
            if best >= dispatch_s * 4 or steps >= 1024:
                break
            steps *= 4
        per = (best - dispatch_s) / steps
        print(f"[{name}] {per * 1e3:.3f} ms/step ({steps} steps)")
        return per

    def v0(lv, sids, starts, counts, page, rb):
        return level_runs_multi(lv, sids, starts, counts, page, rb, 1)

    only = os.environ.get("KPW_LEVELS_ALT_ONLY")
    results = {"v0_sort": bench("v0_sort", v0)}
    for name, fn in VARIANTS.items():
        if only and only not in name:
            continue
        results[name] = bench(name, fn)
    return results


if __name__ == "__main__":
    if "--tpu" in sys.argv:
        time_variants()
    else:
        jax.config.update("jax_platforms", "cpu")
        check_identity()
