# tools/ is a package so the analyzer runs as `python -m tools.analyze`
# (the scripts in here — check_docs.py, rg_quick.py, ... — are still
# directly runnable; nothing imports this module for side effects).
