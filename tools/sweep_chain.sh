#!/bin/bash
# Run N back-to-back `bench.py --all` sweeps on an idle box (the
# bench-discipline rule: never interleave CPU-heavy work — concurrent load
# depresses both sides of every A/B and lands permanently in the
# artifact's vs_history).  Each sweep merges into BENCH_SWEEP_r05.json;
# the --all path aborts fast (rc=3) when the TPU backend is unavailable,
# so a sick tunnel wastes minutes, not a window.
#
# Usage: tools/sweep_chain.sh [N]   (default 3)
set -u
N="${1:-3}"
cd "$(dirname "$0")/.."
for i in $(seq 1 "$N"); do
  # wait until the box is actually idle — a single sleep would fall
  # through onto a still-busy box and poison the artifact's history
  while ! awk '{exit !($1 < 1.5)}' /proc/loadavg; do
    echo "box busy (loadavg $(cut -d' ' -f1 /proc/loadavg)); waiting 120s"
    sleep 120
  done
  echo "=== sweep $i/$N (loadavg $(cut -d' ' -f1 /proc/loadavg)) ==="
  python bench.py --all || { rc=$?; echo "sweep $i failed rc=$rc"; \
    [ "$rc" = 3 ] && { echo "backend unavailable; stopping chain"; exit 3; }; }
done
python - <<'EOF'
import json
try:
    r = json.load(open("BENCH_SWEEP_r05.json"))
except OSError:
    print("chain done: no sweep artifact was written")
else:
    c2 = r.get("configs", {}).get("config2", {})
    print(f"chain done: runs={r.get('sweep_runs')} "
          f"cfg2 vs_dist={c2.get('vs_dist')}")
EOF
