#!/usr/bin/env python
"""Mechanical doc <-> artifact reconciliation (VERDICT r4 next #5).

Round 4 shipped three stale hand-copied figures (sort-floor 1.35 vs the
artifact's 1.672; host assembly "9-12 ms" vs 7.6; a cfg3 prose/key
contradiction).  This checker greps PARITY.md / README.md for every
artifact-backed figure and diffs it against BENCH_SWEEP_r05.json, so a
quoted number that drifts from the artifact fails fast instead of
waiting for a judge to find it.

Each check: (doc file, regex with one capture group per expected value,
artifact paths).  Tolerance = 2.6% relative — wide enough for quoting
precision (5.132 -> "5.1"), far tighter than any real drift seen so far
(1.35 vs 1.672 is 19%).  A regex that stops matching ALSO fails: a
claim silently deleted or reworded away from its anchor is drift too.

Run: python tools/check_docs.py   (exit 0 = reconciled)
"""
from __future__ import annotations

import json
import os
import re
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
TOL = 0.026


def art(rec: dict, path: str):
    cur = rec
    for part in path.split("."):
        cur = cur[part]
    return cur


# (file, regex, (artifact paths, one per capture group))
CHECKS = [
    ("PARITY.md", r"device_sort_floor_fraction_dict48 = ([\d.]+)`",
     ["config2.device_sort_floor_fraction_dict48"]),
    ("PARITY.md", r"device: ([\d.]+) ms median / ([\d.]+) best per 64Ki-row",
     ["config2.rowgroup_ms_dist.median", "config2.rowgroup_ms_dist.best"]),
    ("PARITY.md", r"host assembly: \*\*([\d.]+) ms/row-group at 1 pinned",
     ["config2.projected_system.host_assembly_ms_1core"]),
    ("PARITY.md", r"`vs_dist` median \*\*([\d.]+)\*\*, p90 ([\d.]+),\s+best ([\d.]+)",
     ["config3.vs_dist.median", "config3.vs_dist.p90", "config3.vs_dist.best"]),
    ("PARITY.md", r"statistical parity \(([\d.]+)x median\)",
     ["config3.vs_dist.median"]),
    ("PARITY.md", r"records \*\*([\d.]+)x at one host core\*\* \(the ≥8x bar",
     ["config2.projected_system.median.projected_vs_baseline_1core"]),
    ("PARITY.md", r"single-run composition records\s+\*\*([\d.]+)x\*\* \(host-bound, ([\d.]+)M rows/s/chip",
     ["config2.projected_system.projected_vs_baseline_1core",
      ("config2.projected_system.projected_rows_per_sec_1core", 1e6)]),
    ("PARITY.md", r"\*\* best, ([\d.]+) ms\s+median over n=(\d+)",
     ["config2.projected_system.median.host_assembly_ms_median",
      "config2.projected_system.median.host_history_n"]),
    ("PARITY.md", r"\*\*affine shape\*\*[^|]*\| \*\*([\d.]+)\*\* \| \*\*([\d.]+)M\*\*",
     ["config2.tpu_rowgroup_affine_ms_per_step",
      ("config2.tpu_rowgroup_affine_rows_per_sec_per_chip", 1e6)]),
    ("README.md", r"measures \*\*([\d.]+) ms/step median, ([\d.]+) best",
     ["config2.rowgroup_ms_dist.median", "config2.rowgroup_ms_dist.best"]),
    ("README.md", r"measures ([\d.]+) ms best \(7",
     ["config2.tpu_rowgroup_nullable_ms_per_step"]),
    ("README.md", r"median-composed\s+projection records ([\d.]+)× at one host core\*\*",
     ["config2.projected_system.median.projected_vs_baseline_1core"]),
    ("README.md", r"host leg to a\s+([\d.]+) ms median \(n=(\d+)\)",
     ["config2.projected_system.median.host_assembly_ms_median",
      "config2.projected_system.median.host_history_n"]),
    ("README.md", r"best\s+single-run composition ([\d.]+)× \(host-bound at a ([\d.]+) ms host leg",
     ["config2.projected_system.projected_vs_baseline_1core",
      "config2.projected_system.host_assembly_ms_1core"]),
    ("README.md", r"the device phase drops to \*\*([\d.]+) ms = ([\d.]+)M",
     ["config2.tpu_rowgroup_affine_ms_per_step",
      ("config2.tpu_rowgroup_affine_rows_per_sec_per_chip", 1e6)]),
    # durability PR: fsync-overhead quotes reconcile against the crash
    # artifact (the `crash:` prefix routes the lookup there)
    ("README.md", r"committed fsync A/B:\s+\*\*\+([\d.]+)%\*\*",
     ["crash:fsync_overhead_pct"]),
    ("PARITY.md", r"records `fsync_overhead_pct` \*\*\+([\d.]+)%\*\*",
     ["crash:fsync_overhead_pct"]),
    # degraded-operation PR: spillover/reconciliation and close-deadline
    # quotes reconcile against the degrade artifact (`degrade:` prefix)
    ("README.md", r"spills (\d+) finals\s+to the fallback",
     ["degrade:outcome.spilled_files"]),
    ("README.md", r"all (\d+) acked offsets \(recorded as",
     ["degrade:outcome.acked_offsets_checked"]),
    ("README.md", r"close under a hung write returned in\s+([\d.]+)\s?s",
     ["degrade:close_deadline.returned_in_s"]),
    ("PARITY.md", r"all (\d+)\s+`acked_offsets_checked`",
     ["degrade:outcome.acked_offsets_checked"]),
    ("PARITY.md", r"close under a hung\s+write returned in ([\d.]+)\s?s",
     ["degrade:close_deadline.returned_in_s"]),
    # sustained-throughput PR: e2e headline + batch-ingest A/B quotes
    # reconcile against the e2e artifact (`e2e:` prefix)
    ("README.md", r"sustains\s+\*\*([\d.]+)k records/s\*\* \(median",
     [("e2e:records_per_sec_median", 1e3)]),
    ("README.md", r"batch-native RecordBatch ingest \*\*([\d.]+)x\*\* over",
     ["e2e:batch_ab.speedup_x"]),
    ("PARITY.md", r"`records_per_sec_median` \*\*([\d.]+)k\*\*",
     [("e2e:records_per_sec_median", 1e3)]),
    ("PARITY.md", r"`speedup_x`\s+\*\*([\d.]+)x\*\* \(arm medians",
     ["e2e:batch_ab.speedup_x"]),
    ("PARITY.md", r"p99 ack-lag ([\d.]+)k records \(`ack_lag_p99_records`",
     [("e2e:ack_lag_p99_records", 1e3)]),
    # nogil-assembly PR: the assembly-pool scaling A/B quotes (native vs
    # pure-Python arm, cfg2 shape) reconcile against the e2e artifact
    ("README.md", r"native path at \*\*([\d.]+)x\*\* with the pre-PR "
                  r"pure-Python loops at\s+\*\*([\d.]+)x\*\*",
     ["e2e:assembly_scaling.native.speedup_x",
      "e2e:assembly_scaling.python_fallback.speedup_x"]),
    ("PARITY.md", r"native path \*\*([\d.]+)x\*\* vs the\s+pre-PR "
                  r"pure-Python loops \*\*([\d.]+)x\*\*",
     ["e2e:assembly_scaling.native.speedup_x",
      "e2e:assembly_scaling.python_fallback.speedup_x"]),
    # partitioned-output/compaction PR: small-file reduction + invariant
    # quotes reconcile against the compaction artifact (`compact:` prefix)
    ("README.md", r"compacts \*\*(\d+)\*\* small files into \*\*(\d+)\*\* "
                  r"merged files \(\*\*([\d.]+)x\*\*",
     ["compact:file_count_before", "compact:file_count_after",
      "compact:reduction_x"]),
    ("README.md", r"all \*\*(\d+)\*\* acked offsets \(recorded as\s+"
                  r"`acked_offsets_checked`\)",
     ["compact:acked_offsets_checked"]),
    ("PARITY.md", r"`file_count_before` (\d+) → `file_count_after` (\d+), "
                  r"`reduction_x` \*\*([\d.]+)x\*\*",
     ["compact:file_count_before", "compact:file_count_after",
      "compact:reduction_x"]),
    ("PARITY.md", r"compaction run's \*\*(\d+)\*\* acked offsets",
     ["compact:acked_offsets_checked"]),
    # query-ready-files PR: page-skip / row-group-prune / bloom quotes
    # reconcile against the scan artifact (`scan:` prefix)
    ("README.md", r"planner skips\s+\*\*([\d.]+)%\*\* of data pages "
                  r"\(\*\*(\d+)\*\* of \*\*(\d+)\*\*\)",
     ["scan:pages.skipped_pct", "scan:pages.skipped", "scan:pages.total"]),
    ("README.md", r"fragment pushdown prunes \*\*(\d+)\*\* of\s+\*\*(\d+)\*\* row groups",
     ["scan:row_groups_pushdown.pruned", "scan:row_groups_pushdown.total"]),
    ("README.md", r"observed\s+FPP ([\d.]+) against the 0.01 budget",
     ["scan:bloom.observed_fpp"]),
    ("README.md", r"bloom config costs \+([\d.]+)% file bytes",
     ["scan:file_bytes.overhead_pct"]),
    ("PARITY.md", r"`skipped_pct` \*\*([\d.]+)%\*\*, `bytes_skipped_pct` ([\d.]+)%",
     ["scan:pages.skipped_pct", "scan:pages.bytes_skipped_pct"]),
    ("PARITY.md", r"fragment pushdown pruned (\d+) of (\d+) row groups",
     ["scan:row_groups_pushdown.pruned", "scan:row_groups_pushdown.total"]),
    ("PARITY.md", r"`observed_fpp` ([\d.]+) \(budget ([\d.]+)\)",
     ["scan:bloom.observed_fpp", "scan:bloom.configured_fpp"]),
    # process-parallel-workers PR: the 1v2 process sweep, its capacity
    # bracket, and the thread-mode context arm reconcile against the
    # procs artifact (`procs:` prefix, BENCH_E2E_r15.json); the r14
    # thread-sweep contrast quote reconciles against the e2e artifact
    ("README.md", r"records\s+`speedup_x` \*\*([\d.]+)x\*\* at 2 worker "
                  r"processes \(1 process \*\*([\d.]+)k\*\* vs 2\s+"
                  r"processes \*\*([\d.]+)k\*\*",
     ["procs:procs_sweep.speedup_x",
      ("procs:procs_sweep.1.records_per_sec_median", 1e3),
      ("procs:procs_sweep.2.records_per_sec_median", 1e3)]),
    ("README.md", r"`cpu_capacity_x` probes read\s+\*\*([\d.]+)\*\*–"
                  r"\*\*([\d.]+)\*\* of this box's 2 cores",
     ["procs:cpu_capacity_x.before", "procs:cpu_capacity_x.after"]),
    ("README.md", r"thread-mode context arm measured \*\*([\d.]+)k\*\*\s+"
                  r"records/s",
     [("procs:thread_baseline_records_per_sec", 1e3)]),
    ("README.md", r"r14 THREAD sweep measured 1→2 workers at "
                  r"\*\*([\d.]+)x\*\*",
     ["e2e:workers_sweep.speedup_x"]),
    ("PARITY.md", r"sweep records `speedup_x` \*\*([\d.]+)x\*\* at 2 "
                  r"worker processes",
     ["procs:procs_sweep.speedup_x"]),
    ("PARITY.md", r"reading\s+\*\*([\d.]+)\*\*–\*\*([\d.]+)\*\* of this "
                  r"box's 2 cores, `capacity_gated` true",
     ["procs:cpu_capacity_x.before", "procs:cpu_capacity_x.after"]),
    ("PARITY.md", r"r14 thread sweep's \*\*([\d.]+)x\*\* at 1→2 workers",
     ["e2e:workers_sweep.speedup_x"]),
    # object-store-tier PR: overlap / bandwidth-cap / crash-replay quotes
    # reconcile against the objstore artifact (`objstore:` prefix)
    ("README.md", r"hides \*\*([\d.]+)%\*\* of part-upload time under\s+"
                  r"encode",
     ["objstore:overlap.overlap_pct"]),
    ("README.md", r"at \*\*([\d.]+) MiB/s\*\* observed against a\s+"
                  r"\*\*([\d.]+) MiB/s\*\* budget",
     [("objstore:remote_compaction.observed_bytes_per_s", 1 << 20),
      ("objstore:remote_compaction.budget_bytes_per_s", 1 << 20)]),
    ("README.md", r"merges\s+\*\*(\d+)\*\* small objects into \*\*(\d+)\*\*",
     ["objstore:remote_compaction.file_count_before",
      "objstore:remote_compaction.file_count_after"]),
    ("README.md", r"all \*\*(\d+)\*\* acked offsets of the\s+"
                  r"mid-multipart\s+crash replay",
     ["objstore:crash_replay.acked_offsets_checked"]),
    ("PARITY.md", r"`overlap_pct` \*\*([\d.]+)%\*\*",
     ["objstore:overlap.overlap_pct"]),
    ("PARITY.md", r"`observed_bytes_per_s`\s+\*\*([\d.]+) MiB/s\*\* "
                  r"against the \*\*([\d.]+) MiB/s\*\* budget",
     [("objstore:remote_compaction.observed_bytes_per_s", 1 << 20),
      ("objstore:remote_compaction.budget_bytes_per_s", 1 << 20)]),
    ("PARITY.md", r"mid-multipart crash replay's \*\*(\d+)\*\* acked\s+"
                  r"offsets",
     ["objstore:crash_replay.acked_offsets_checked"]),
    # fused-nested-pipeline PR: the nested-vs-flat ratio, arm medians,
    # fused A/B, and capacity bracket reconcile against the nested
    # artifact (`nested:` prefix, BENCH_NESTED_r18.json)
    ("README.md", r"arm at \*\*([\d.]+)k\*\* records/s vs the flat cfg6\s+"
                  r"arm's \*\*([\d.]+)k\*\*",
     [("nested:nested_records_per_sec_median", 1e3),
      ("nested:flat_records_per_sec_median", 1e3)]),
    ("README.md", r"`nested_over_flat_x` \*\*([\d.]+)x\*\*,\s+far inside",
     ["nested:nested_over_flat_x"]),
    ("README.md", r"read \*\*([\d.]+)\*\*–\*\*([\d.]+)\*\* of 2 cores",
     ["nested:cpu_capacity_x.before", "nested:cpu_capacity_x.after"]),
    ("README.md", r"fused-route A/B\s+at \*\*([\d.]+)x\*\*",
     ["nested:fused_ab.speedup_x"]),
    ("PARITY.md", r"`nested_over_flat_x` \*\*([\d.]+)x\*\* \(nested "
                  r"\*\*([\d.]+)k\*\* vs flat \*\*([\d.]+)k\*\*",
     ["nested:nested_over_flat_x",
      ("nested:nested_records_per_sec_median", 1e3),
      ("nested:flat_records_per_sec_median", 1e3)]),
    ("PARITY.md", r"fused-vs-ctypes `speedup_x` \*\*([\d.]+)x\*\*",
     ["nested:fused_ab.speedup_x"]),
    ("PARITY.md", r"bracket recorded \*\*([\d.]+)\*\*–\*\*([\d.]+)\*\* "
                  r"of 2 cores",
     ["nested:cpu_capacity_x.before", "nested:cpu_capacity_x.after"]),
    # multi-tenant-bulkheads PR: tenant count, quota-throttle evidence,
    # victim SLA headroom and containment counters reconcile against the
    # tenants artifact (`tenants:` prefix, BENCH_TENANTS_r19.json)
    ("README.md", r"bulkheads across \*\*(\d+)\*\* tenants",
     ["tenants:tenants"]),
    ("README.md", r"burst tenant \(\*\*(\d+)\*\* records vs \*\*(\d+)\*\* "
                  r"per victim\)",
     ["tenants:burst_rows", "tenants:rows_per_victim"]),
    ("README.md", r"\*\*(\d+)\*\* quota-stall\s+episodes\s+"
                  r"\(\*\*([\d.]+)\s?s\*\*\s+parked\)",
     ["tenants:quota.burst_stalls", "tenants:quota.burst_stall_s"]),
    ("README.md", r"victim p99 ack-lag\s+\*\*([\d.]+)\s?s\*\* against "
                  r"the\s+\*\*([\d.]+)\s?s\*\* SLA",
     ["tenants:victim_ack_p99_s_max", "tenants:sla_seconds"]),
    ("README.md", r"\*\*(\d+)\*\* sibling\s+worker deaths and "
                  r"\*\*(\d+)\*\* of\s+\*\*(\d+)\*\* poison records "
                  r"dead-lettered",
     ["tenants:containment.sibling_worker_deaths",
      "tenants:containment.deadlettered_records",
      "tenants:containment.poison_records_produced"]),
    ("PARITY.md", r"`victim_ack_p99_s_max` \*\*([\d.]+)\s?s\*\* against "
                  r"the\s+\*\*([\d.]+)\s?s\*\* `sla_seconds`",
     ["tenants:victim_ack_p99_s_max", "tenants:sla_seconds"]),
    ("PARITY.md", r"`burst_stalls` \*\*(\d+)\*\* with\s+"
                  r"`victim_stalls_max` \*\*(\d+)\*\*",
     ["tenants:quota.burst_stalls", "tenants:quota.victim_stalls_max"]),
    ("PARITY.md", r"`sibling_worker_deaths` \*\*(\d+)\*\* across\s+"
                  r"\*\*(\d+)\*\* tenants",
     ["tenants:containment.sibling_worker_deaths", "tenants:tenants"]),
    # adaptive-encodings artifact (`encodings:` prefix,
    # BENCH_ENCODINGS_r20.json)
    ("README.md", r"Adaptive lands at \*\*([\d.]+)×\*\* the default\s+"
                  r"arm's file bytes \(a \*\*([\d.]+)%\*\* reduction",
     ["encodings:file_bytes_ratio_adaptive_vs_default",
      "encodings:bytes_reduction_vs_default_pct"]),
    ("README.md", r"and \*\*([\d.]+)×\*\* all-PLAIN, at \*\*([\d.]+)×\*\* "
                  r"the default arm's write\s+throughput",
     ["encodings:file_bytes_ratio_adaptive_vs_plain",
      "encodings:write_throughput_ratio_adaptive_vs_default"]),
    ("PARITY.md", r"`file_bytes_ratio_adaptive_vs_default` \*\*([\d.]+)\*\* "
                  r"\(a\s+`bytes_reduction_vs_default_pct` of "
                  r"\*\*([\d.]+)%\*\*\)",
     ["encodings:file_bytes_ratio_adaptive_vs_default",
      "encodings:bytes_reduction_vs_default_pct"]),
    ("PARITY.md", r"`file_bytes_ratio_adaptive_vs_plain` \*\*([\d.]+)\*\*,"
                  r"\s+with\s+"
                  r"`write_throughput_ratio_adaptive_vs_default` "
                  r"\*\*([\d.]+)\*\*",
     ["encodings:file_bytes_ratio_adaptive_vs_plain",
      "encodings:write_throughput_ratio_adaptive_vs_default"]),
    # cross-process telemetry plane PR: tracing-overhead A/B, per-tenant
    # ack-latency, and the merged-scrape counters reconcile against the
    # r21 observability artifact (`obs21:` prefix, BENCH_OBS_r21.json)
    ("README.md", r"tracing-overhead A/B records \*\*\+([\d.]+)%\*\* with\s+"
                  r"spans enabled",
     ["obs21:tracing_overhead.overhead_pct"]),
    ("README.md", r"analytics \*\*([\d.]+) ms\*\* p50 / \*\*([\d.]+) "
                  r"ms\*\* p99,\s+audit \*\*([\d.]+) ms\*\* p50 / "
                  r"\*\*([\d.]+) ms\*\* p99",
     [("obs21:ack_latency_s_by_tenant.analytics.p50_s", 1e-3),
      ("obs21:ack_latency_s_by_tenant.analytics.p99_s", 1e-3),
      ("obs21:ack_latency_s_by_tenant.audit.p50_s", 1e-3),
      ("obs21:ack_latency_s_by_tenant.audit.p99_s", 1e-3)]),
    ("PARITY.md", r"`overhead_pct` \*\*\+([\d.]+)%\*\* against the 3% "
                  r"gate",
     ["obs21:tracing_overhead.overhead_pct"]),
    ("PARITY.md", r"merged scrape carried \*\*(\d+)\*\* child snapshots\s+"
                  r"covering \*\*(\d+)\*\* child-written records",
     ["obs21:proc_leg.child_snapshots_merged",
      "obs21:proc_leg.children_merged_written_records"]),
    # consumer-group rebalance drills (`rebalance:` prefix,
    # BENCH_REBALANCE_r22.json)
    ("README.md", r"survivors reclaim after a \*\*([\d.]+) s\*\* blackout",
     ["rebalance:kill.rebalance_blackout_seconds"]),
    ("README.md", r"ack latency\s+\*\*([\d.]+) s\*\* p50 / "
                  r"\*\*([\d.]+) s\*\* p99 measured from the broker "
                  r"append\s+stamp",
     ["rebalance:kill.ack_latency_p50_s",
      "rebalance:kill.ack_latency_p99_s"]),
    ("README.md", r"\*\*(\d+)\*\* rows across the\s+three legs with "
                  r"\*\*(\d+)\*\* lost and \*\*(\d+)\*\* duplicated",
     ["rebalance:rows_total", "rebalance:lost", "rebalance:dups"]),
    ("README.md", r"\*\*(\d+)\*\* stale-generation commit fenced with the "
                  r"typed\s+error",
     ["rebalance:zombie.stale_commits_fenced"]),
    ("PARITY.md", r"`rebalance_blackout_seconds`\s+\*\*([\d.]+) s\*\* with "
                  r"`ack_latency_p99_s` \*\*([\d.]+) s\*\*",
     ["rebalance:kill.rebalance_blackout_seconds",
      "rebalance:kill.ack_latency_p99_s"]),
    ("PARITY.md", r"`stale_commits_fenced` \*\*(\d+)\*\* and cooperative\s+"
                  r"`full_resets` \*\*(\d+)\*\*",
     ["rebalance:zombie.stale_commits_fenced",
      "rebalance:cooperative.full_resets"]),
    # process-mode rebalance drills (`rebalproc:` prefix,
    # BENCH_REBALANCE_PROCS_r23.json)
    ("README.md", r"cross-process fence flush lands\s+\*\*([\d.]+) s\*\* "
                  r"after the joiner",
     ["rebalproc:handoff.join_to_first_fence_flush_s"]),
    ("README.md", r"survivor drains after a \*\*([\d.]+) s\*\*\s+blackout",
     ["rebalproc:kill.rebalance_blackout_seconds"]),
    ("README.md", r"\*\*(\d+)\*\* stale child ack\s+fenced and its file "
                  r"un-published",
     ["rebalproc:zombie_child.victim_fenced_acks"]),
    ("README.md", r"\*\*(\d+)\*\* rows\s+across the three process-mode "
                  r"legs with \*\*(\d+)\*\* lost and \*\*(\d+)\*\*\s+"
                  r"duplicated",
     ["rebalproc:rows_total", "rebalproc:lost", "rebalproc:dups"]),
    ("PARITY.md", r"`join_to_first_fence_flush_s` \*\*([\d.]+) s\*\*",
     ["rebalproc:handoff.join_to_first_fence_flush_s"]),
    ("PARITY.md", r"`rebalance_blackout_seconds` \*\*([\d.]+) s\*\* with\s+"
                  r"`tmp_debris_after_kill` \*\*(\d+)\*\*",
     ["rebalproc:kill.rebalance_blackout_seconds",
      "rebalproc:kill.tmp_debris_after_kill"]),
    ("PARITY.md", r"`victim_fenced_acks` \*\*(\d+)\*\* with the stale "
                  r"publish\s+un-published",
     ["rebalproc:zombie_child.victim_fenced_acks"]),
]


# --- cited-artifact-key reconciliation (VERDICT r5 ask #2) -----------------
# Round 5's docs cited three keys (`encode_side_vs_baseline`,
# `string_device_probe`, `writer_route`) that no committed sweep contains —
# present-tense "recorded as `key`" prose for artifacts that were never
# written.  This pass extracts every backtick-quoted snake_case token whose
# surrounding sentence claims artifact provenance (recorded/reported/
# tracked/metric/artifact/block) and fails unless the key actually exists
# somewhere in the committed sweep JSON.  A claim explicitly labeled
# "pending"/"next sweep" is exempt: promising a key is honest, asserting a
# nonexistent one is drift.

KEY_DOCS = ("PARITY.md", "README.md", "BASELINE.md")
_KEY_TOKEN = re.compile(r"`([a-z][a-z0-9]*(?:_[a-z0-9]+)+)`")
# provenance cue, looked for in a TIGHT window right before/after the
# token: a doc only "cites an artifact key" when it claims the number is
# recorded/reported/tracked there (or names a per-config block/metric) —
# a cue two sentences away must not turn a code identifier into a claim
_CITE_CUE = re.compile(
    r"\brecorded\b|\breported\b|\btracked\b|\bartifact\b|\bmetric\b", re.I)
_PENDING_CUE = re.compile(r"\bpending\b|\bnext sweep\b|\bwill be\b", re.I)
_WINDOW_BEFORE, _WINDOW_AFTER = 90, 50


def _artifact_key_set(obj, out: set) -> set:
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.add(k)
            _artifact_key_set(v, out)
    elif isinstance(obj, list):
        for v in obj:
            _artifact_key_set(v, out)
    return out


# --- cited stage/metric-name reconciliation (observability PR) -------------
# Docs cite pipeline stage names (`rowgroup.assemble`) and metric names
# (`parquet.writer.ack.lag.records`).  Both live in canonical in-code
# registries — tracing.STAGE_NAMES and metrics.METRIC_NAMES — so a rename
# there would silently orphan every doc claim built on the old name.  This
# pass extracts every backtick-quoted dotted lowercase token whose first
# segment matches a registry prefix (consumer/worker/rowgroup/encode/
# parquet) and fails unless the full name exists in a registry.

NAME_DOCS = ("PARITY.md", "README.md")
_DOTTED_TOKEN = re.compile(r"`([a-z][a-z0-9_]*(?:\.[a-z0-9_]+)+)`")


# --- cited-test reconciliation (durability PR) ------------------------------
# Docs cite pytest names as evidence ("quarantine semantics — `test_...`").
# A citation of a test that does not exist is the worst kind of drift: a
# guarantee with imaginary proof.  Every backticked `test_*` token in the
# docs must match a real `def test_*` under tests/ (a trailing `*` makes it
# a prefix pattern, e.g. `test_page_checksums_*`).  On top of that,
# quarantine/verify claims specifically must be BACKED: a doc that talks
# about quarantining or the structural verifier must cite at least one
# existing test whose name exercises that path.

_TEST_TOKEN = re.compile(r"`(test_[a-z0-9_]+\*?)`")
# what counts as a durability CLAIM: quarantine prose, the durability
# knobs, or "structurally/independently verified" guarantees — but NOT
# every use of the word "verified" ("verified by pyarrow" in neutral
# feature prose is a statement about a test, not a recovery guarantee)
_DURABILITY_CLAIM = re.compile(
    r"quarantin|verify_on_(?:publish|startup)"
    r"|structural(?:ly)?[ -]verif|independent(?:ly)? verif", re.I)
_DURABILITY_TEST = re.compile(r"quarantine|verif|crash|corrupt|torn")


def _test_function_names() -> set:
    names = set()
    tdir = os.path.join(ROOT, "tests")
    for fn in os.listdir(tdir):
        if not fn.endswith(".py"):
            continue
        with open(os.path.join(tdir, fn)) as f:
            names.update(re.findall(r"^def (test_[a-zA-Z0-9_]+)",
                                    f.read(), re.M))
    return names


def _token_exists(tok: str, test_names: set) -> bool:
    if tok.endswith("*"):
        return any(n.startswith(tok[:-1]) for n in test_names)
    return tok in test_names


def check_cited_tests(docs: dict, test_names: set | None = None) -> list[str]:
    if test_names is None:
        test_names = _test_function_names()
    failures = []
    for fname in sorted(set(KEY_DOCS) | set(NAME_DOCS)):
        seen = set()
        for m in _TEST_TOKEN.finditer(docs[fname]):
            tok = m.group(1)
            if tok in seen:
                continue
            seen.add(tok)
            if not _token_exists(tok, test_names):
                failures.append(
                    f"{fname}: cites test `{tok}` that does not exist "
                    f"under tests/")
    return failures


def check_durability_claims(docs: dict,
                            test_names: set | None = None) -> list[str]:
    """A doc making quarantine/verify claims with no matching cited test
    fails: the durability guarantees are exactly the kind of prose that
    outlives the code that enforced it."""
    if test_names is None:
        test_names = _test_function_names()
    failures = []
    for fname in NAME_DOCS:
        text = docs[fname]
        if not _DURABILITY_CLAIM.search(text):
            continue
        backed = [m.group(1) for m in _TEST_TOKEN.finditer(text)
                  if _DURABILITY_TEST.search(m.group(1))
                  and _token_exists(m.group(1), test_names)]
        if not backed:
            failures.append(
                f"{fname}: makes quarantine/verify claims but cites no "
                f"existing quarantine/verify/crash test as evidence")
    return failures


def _canonical_names() -> set:
    sys.path.insert(0, ROOT)
    from kpw_tpu.runtime.metrics import METRIC_NAMES
    from kpw_tpu.utils.tracing import STAGE_NAMES

    return set(METRIC_NAMES) | set(STAGE_NAMES)


# --- analyze: lint suite <-> docs reconciliation (ISSUE 7) -------------------
# README's "Correctness tooling" section names the lint passes and the
# hot-import allowlist entries.  Existence: every backticked kebab-case
# pass name cited near the word "pass" must be registered in
# tools/analyze (a renamed/removed pass must not survive in prose), and
# every backticked dotted module cited near "allowlist" must be a live
# ALLOWLIST key (a stale doc allowlist is a waiver nobody holds).
# Completeness (the REVERSE of the PR-2 existence check): every
# registered canonical metric/stage name must be documented —
# check_cited_names only proves cited names exist; this proves existing
# names are cited.

_PASS_TOKEN = re.compile(r"`([a-z]+(?:-[a-z]+)+)`")
_PASS_CUE = re.compile(r"\bpass\b|\blint\b", re.I)
_ALLOW_CUE = re.compile(r"allowlist", re.I)
_SECTION_RE = re.compile(
    r"^##\s+Correctness tooling.*?(?=^##\s|\Z)", re.M | re.S)


def _analyze_registry():
    sys.path.insert(0, ROOT)
    from tools.analyze import PASS_NAMES
    from tools.analyze.hotimports import ALLOWLIST

    return set(PASS_NAMES), {mod for (_path, mod) in ALLOWLIST}


def check_analyze_docs(docs: dict) -> list[str]:
    failures = []
    m = _SECTION_RE.search(docs["README.md"])
    if m is None:
        return ["README.md: no '## Correctness tooling' section (the "
                "lint suite must be documented)"]
    section = m.group(0)
    pass_names, allow_mods = _analyze_registry()
    for tok_m in _PASS_TOKEN.finditer(section):
        tok = tok_m.group(1)
        window = section[max(0, tok_m.start() - _WINDOW_BEFORE):
                         tok_m.end() + _WINDOW_AFTER]
        if _PASS_CUE.search(window) and tok not in pass_names:
            failures.append(
                f"README.md: Correctness tooling cites lint pass `{tok}` "
                f"not registered in tools/analyze")
    for tok_m in _DOTTED_TOKEN.finditer(section):
        tok = tok_m.group(1)
        window = section[max(0, tok_m.start() - _WINDOW_BEFORE):
                         tok_m.end() + _WINDOW_AFTER]
        if (_ALLOW_CUE.search(window) and tok.startswith("kpw_tpu.")
                and tok not in allow_mods):
            failures.append(
                f"README.md: Correctness tooling cites allowlist entry "
                f"`{tok}` absent from tools/analyze/hotimports.ALLOWLIST")
    # every registered pass must be documented in the section at all
    for name in sorted(pass_names):
        if f"`{name}`" not in section:
            failures.append(
                f"README.md: lint pass `{name}` is registered in "
                f"tools/analyze but not documented in the Correctness "
                f"tooling section")
    return failures


# --- schedule-explorer / tsan claim reconciliation (ISSUE 13) ---------------
# README's Correctness tooling section quotes the schedule explorer's
# committed seed-set size and scenario count, and the tsan leg's
# iteration configuration.  Those are CLAIMS about committed files
# (tools/schedx/seeds.json, tools/sanitize.sh) and reconcile
# mechanically like every bench number: quoted counts must equal the
# committed ones, and every scenario must commit a non-empty refind set
# — a scenario without its negative control is a detector nobody has
# proven can detect.

_SCHEDX_ANCHOR = re.compile(
    r"\*\*(\d+)\*\*\s+committed\s+seeds\s+across\s+\*\*(\d+)\*\*\s+scenarios")
_TSAN_ANCHOR = re.compile(
    r"\*\*(\d+)\*\*\s+iterations\s+per\s+thread\s+across\s+"
    r"\*\*(\d+)\*\*\s+threads")


def _schedx_committed() -> dict:
    with open(os.path.join(ROOT, "tools", "schedx", "seeds.json")) as f:
        return json.load(f)["scenarios"]


def _tsan_committed() -> tuple:
    with open(os.path.join(ROOT, "tools", "sanitize.sh")) as f:
        sh = f.read()
    it = re.search(r"^TSAN_ITERS=(\d+)", sh, re.M)
    th = re.search(r"^TSAN_THREADS=(\d+)", sh, re.M)
    return (int(it.group(1)) if it else None,
            int(th.group(1)) if th else None)


def check_schedx_claims(docs: dict, scenarios: dict | None = None,
                        tsan: tuple | None = None) -> list[str]:
    if scenarios is None:
        scenarios = _schedx_committed()
    if tsan is None:
        tsan = _tsan_committed()
    failures = []
    text = docs["README.md"]
    m = _SCHEDX_ANCHOR.search(text)
    total = sum(len(v.get("seeds", [])) for v in scenarios.values())
    if m is None:
        failures.append(
            "README.md: schedule-explorer seed-count claim anchor not "
            "found (/**N** committed seeds across **M** scenarios/)")
    elif (int(m.group(1)), int(m.group(2))) != (total, len(scenarios)):
        failures.append(
            f"README.md: quotes {m.group(1)} committed seeds / "
            f"{m.group(2)} scenarios but tools/schedx/seeds.json commits "
            f"{total} / {len(scenarios)}")
    for name, v in sorted(scenarios.items()):
        if not v.get("refind_seeds"):
            failures.append(
                f"tools/schedx/seeds.json: scenario {name} commits no "
                f"refind_seeds — its negative control is unproven")
    it, th = tsan
    m = _TSAN_ANCHOR.search(text)
    if m is None:
        failures.append(
            "README.md: tsan iteration-count claim anchor not found "
            "(/**N** iterations per thread across **T** threads/)")
    elif it is None or th is None:
        failures.append(
            "tools/sanitize.sh: TSAN_ITERS/TSAN_THREADS assignments not "
            "found — the committed tsan configuration moved")
    elif (int(m.group(1)), int(m.group(2))) != (it, th):
        failures.append(
            f"README.md: quotes tsan {m.group(1)} iters x {m.group(2)} "
            f"threads but tools/sanitize.sh commits {it} x {th}")
    return failures


def check_name_completeness(docs: dict) -> list[str]:
    """Every registered canonical metric/stage name must appear
    (backticked) somewhere in README or PARITY — completeness, the
    reverse direction of check_cited_names."""
    names = _canonical_names()
    text = "".join(docs[f] for f in NAME_DOCS)
    return [
        f"canonical name `{n}` (tracing.STAGE_NAMES / "
        f"metrics.METRIC_NAMES) is documented nowhere in "
        f"{'/'.join(NAME_DOCS)} — document it or unregister it"
        for n in sorted(names) if f"`{n}`" not in text
    ]


def check_cited_names(docs: dict, names: set | None = None) -> list[str]:
    if names is None:
        names = _canonical_names()
    prefixes = {n.split(".", 1)[0] for n in names}
    failures = []
    for fname in NAME_DOCS:
        seen = set()
        for m in _DOTTED_TOKEN.finditer(docs[fname]):
            tok = m.group(1)
            if (tok.split(".", 1)[0] not in prefixes or tok in names
                    or tok in seen):
                continue
            seen.add(tok)
            failures.append(
                f"{fname}: cites stage/metric name `{tok}` absent from the "
                f"canonical registry (tracing.STAGE_NAMES / "
                f"metrics.METRIC_NAMES)")
    return failures


def check_cited_keys(full_record: dict, docs: dict) -> list[str]:
    keys = _artifact_key_set(full_record, set())
    failures = []
    for fname in KEY_DOCS:
        text = docs[fname]
        seen = set()
        for m in _KEY_TOKEN.finditer(text):
            tok = m.group(1)
            if tok in keys or (fname, tok) in seen:
                continue
            if tok.startswith("test_"):
                continue  # pytest names, never artifact keys
            window = text[max(0, m.start() - _WINDOW_BEFORE):
                          m.end() + _WINDOW_AFTER]
            if not _CITE_CUE.search(window) or _PENDING_CUE.search(window):
                continue
            seen.add((fname, tok))
            failures.append(
                f"{fname}: cites artifact key `{tok}` absent from the "
                f"committed sweep JSON")
    return failures


def main() -> int:
    sweep_path = os.environ.get("KPW_BENCH_SWEEP_PATH",
                                os.path.join(ROOT, "BENCH_SWEEP_r05.json"))
    full_record = json.load(open(sweep_path))
    rec = full_record["configs"]
    # the observability artifact (bench.py --obs) is a second committed
    # key source: docs citing its keys reconcile against it the same way;
    # the chaos artifact (bench.py --chaos) is the third
    obs_path = os.environ.get("KPW_OBS_PATH",
                              os.path.join(ROOT, "BENCH_OBS_r06.json"))
    key_record: dict = {"sweep": full_record}
    if os.path.exists(obs_path):
        key_record["obs"] = json.load(open(obs_path))
    chaos_path = os.environ.get("KPW_CHAOS_PATH",
                                os.path.join(ROOT, "BENCH_CHAOS_r07.json"))
    if os.path.exists(chaos_path):
        key_record["chaos"] = json.load(open(chaos_path))
    # the crash/durability artifact (bench.py --crash) is the fourth
    crash_path = os.environ.get("KPW_CRASH_PATH",
                                os.path.join(ROOT, "BENCH_CRASH_r08.json"))
    if os.path.exists(crash_path):
        key_record["crash"] = json.load(open(crash_path))
    # the degraded-operation artifact (bench.py --degrade) is the fifth
    degrade_path = os.environ.get(
        "KPW_DEGRADE_PATH", os.path.join(ROOT, "BENCH_DEGRADE_r09.json"))
    if os.path.exists(degrade_path):
        key_record["degrade"] = json.load(open(degrade_path))
    # the sustained-throughput artifact (bench.py --e2e) is the sixth
    e2e_path = os.environ.get(
        "KPW_E2E_PATH", os.path.join(ROOT, "BENCH_E2E_r14.json"))
    if os.path.exists(e2e_path):
        key_record["e2e"] = json.load(open(e2e_path))
    # the partitioned-output/compaction artifact (bench.py --compact) is
    # the seventh
    compact_path = os.environ.get(
        "KPW_COMPACT_PATH", os.path.join(ROOT, "BENCH_COMPACT_r12.json"))
    if os.path.exists(compact_path):
        key_record["compact"] = json.load(open(compact_path))
    # the query-ready-files artifact (bench.py --scan) is the eighth
    scan_path = os.environ.get(
        "KPW_SCAN_PATH", os.path.join(ROOT, "BENCH_SCAN_r13.json"))
    if os.path.exists(scan_path):
        key_record["scan"] = json.load(open(scan_path))
    # the process-parallel-workers artifact (bench.py --procs) is the
    # ninth
    procs_path = os.environ.get(
        "KPW_PROCS_PATH", os.path.join(ROOT, "BENCH_E2E_r15.json"))
    if os.path.exists(procs_path):
        key_record["procs"] = json.load(open(procs_path))
    # the object-store-tier artifact (bench.py --objstore) is the tenth
    objstore_path = os.environ.get(
        "KPW_OBJSTORE_PATH", os.path.join(ROOT, "BENCH_OBJSTORE_r16.json"))
    if os.path.exists(objstore_path):
        key_record["objstore"] = json.load(open(objstore_path))
    # the nested-vs-flat fused-pipeline artifact (bench.py --nested) is
    # the eleventh
    nested_path = os.environ.get(
        "KPW_NESTED_PATH", os.path.join(ROOT, "BENCH_NESTED_r18.json"))
    if os.path.exists(nested_path):
        key_record["nested"] = json.load(open(nested_path))
    # the multi-tenant-bulkheads artifact (bench.py --tenants) is the
    # twelfth
    tenants_path = os.environ.get(
        "KPW_TENANTS_PATH", os.path.join(ROOT, "BENCH_TENANTS_r19.json"))
    if os.path.exists(tenants_path):
        key_record["tenants"] = json.load(open(tenants_path))
    # the adaptive-encodings artifact (bench.py --encodings) is the
    # thirteenth
    encodings_path = os.environ.get(
        "KPW_ENCODINGS_PATH", os.path.join(ROOT, "BENCH_ENCODINGS_r20.json"))
    if os.path.exists(encodings_path):
        key_record["encodings"] = json.load(open(encodings_path))
    # the cross-process telemetry-plane artifact (bench.py --obs) is the
    # fourteenth
    obs21_path = os.environ.get(
        "KPW_OBS21_PATH", os.path.join(ROOT, "BENCH_OBS_r21.json"))
    if os.path.exists(obs21_path):
        key_record["obs21"] = json.load(open(obs21_path))
    # the consumer-group rebalance-drill artifact (bench.py --rebalance)
    # is the fifteenth
    rebalance_path = os.environ.get(
        "KPW_REBALANCE_PATH",
        os.path.join(ROOT, "BENCH_REBALANCE_r22.json"))
    if os.path.exists(rebalance_path):
        key_record["rebalance"] = json.load(open(rebalance_path))
    # the process-mode rebalance-drill artifact (bench.py --rebalance
    # --procs) is the sixteenth
    rebalproc_path = os.environ.get(
        "KPW_REBALANCE_PROCS_PATH",
        os.path.join(ROOT, "BENCH_REBALANCE_PROCS_r23.json"))
    if os.path.exists(rebalproc_path):
        key_record["rebalproc"] = json.load(open(rebalproc_path))
    docs = {f: open(os.path.join(ROOT, f)).read()
            for f in ({c[0] for c in CHECKS} | set(KEY_DOCS)
                      | set(NAME_DOCS))}
    failures = check_cited_keys(key_record, docs)
    failures += check_cited_names(docs)
    failures += check_cited_tests(docs)
    failures += check_durability_claims(docs)
    failures += check_analyze_docs(docs)
    failures += check_name_completeness(docs)
    failures += check_schedx_claims(docs)
    for fname, pattern, paths in CHECKS:
        m = re.search(pattern, docs[fname])
        if not m:
            failures.append(f"{fname}: claim anchor not found: /{pattern}/")
            continue
        for group, spec in zip(m.groups(), paths):
            scale = 1.0
            if isinstance(spec, tuple):
                spec, scale = spec
            root = rec
            if spec.startswith("crash:"):
                root, spec = key_record.get("crash", {}), spec[6:]
            elif spec.startswith("degrade:"):
                root, spec = key_record.get("degrade", {}), spec[8:]
            elif spec.startswith("e2e:"):
                root, spec = key_record.get("e2e", {}), spec[4:]
            elif spec.startswith("compact:"):
                root, spec = key_record.get("compact", {}), spec[8:]
            elif spec.startswith("scan:"):
                root, spec = key_record.get("scan", {}), spec[5:]
            elif spec.startswith("procs:"):
                root, spec = key_record.get("procs", {}), spec[6:]
            elif spec.startswith("objstore:"):
                root, spec = key_record.get("objstore", {}), spec[9:]
            elif spec.startswith("nested:"):
                root, spec = key_record.get("nested", {}), spec[7:]
            elif spec.startswith("tenants:"):
                root, spec = key_record.get("tenants", {}), spec[8:]
            elif spec.startswith("encodings:"):
                root, spec = key_record.get("encodings", {}), spec[10:]
            elif spec.startswith("obs21:"):
                root, spec = key_record.get("obs21", {}), spec[6:]
            elif spec.startswith("rebalproc:"):
                root, spec = key_record.get("rebalproc", {}), spec[10:]
            elif spec.startswith("rebalance:"):
                root, spec = key_record.get("rebalance", {}), spec[10:]
            try:
                expect = float(art(root, spec)) / scale
            except (KeyError, TypeError):
                failures.append(f"{fname}: artifact key missing: {spec}")
                continue
            got = float(group)
            if abs(got - expect) > TOL * max(abs(expect), 1e-9):
                failures.append(
                    f"{fname}: quotes {got} but artifact {spec} = "
                    f"{expect:.4g} (drift {abs(got - expect) / expect:.1%})")
    if failures:
        print("DOC/ARTIFACT DRIFT:")
        for f in failures:
            print(" -", f)
        return 1
    print(f"check_docs: {len(CHECKS)} claims reconciled against "
          f"{os.path.basename(sweep_path)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
