#!/usr/bin/env python
"""Mechanical doc <-> artifact reconciliation (VERDICT r4 next #5).

Round 4 shipped three stale hand-copied figures (sort-floor 1.35 vs the
artifact's 1.672; host assembly "9-12 ms" vs 7.6; a cfg3 prose/key
contradiction).  This checker greps PARITY.md / README.md for every
artifact-backed figure and diffs it against BENCH_SWEEP_r05.json, so a
quoted number that drifts from the artifact fails fast instead of
waiting for a judge to find it.

Each check: (doc file, regex with one capture group per expected value,
artifact paths).  Tolerance = 2.6% relative — wide enough for quoting
precision (5.132 -> "5.1"), far tighter than any real drift seen so far
(1.35 vs 1.672 is 19%).  A regex that stops matching ALSO fails: a
claim silently deleted or reworded away from its anchor is drift too.

Run: python tools/check_docs.py   (exit 0 = reconciled)
"""
from __future__ import annotations

import json
import os
import re
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
TOL = 0.026


def art(rec: dict, path: str):
    cur = rec
    for part in path.split("."):
        cur = cur[part]
    return cur


# (file, regex, (artifact paths, one per capture group))
CHECKS = [
    ("PARITY.md", r"device_sort_floor_fraction_dict48 = ([\d.]+)`",
     ["config2.device_sort_floor_fraction_dict48"]),
    ("PARITY.md", r"device: ([\d.]+) ms median / ([\d.]+) best per 64Ki-row",
     ["config2.rowgroup_ms_dist.median", "config2.rowgroup_ms_dist.best"]),
    ("PARITY.md", r"host assembly: \*\*([\d.]+) ms/row-group at 1 pinned",
     ["config2.projected_system.host_assembly_ms_1core"]),
    ("PARITY.md", r"`vs_dist` median \*\*([\d.]+)\*\*, p90 ([\d.]+),\s+best ([\d.]+)",
     ["config3.vs_dist.median", "config3.vs_dist.p90", "config3.vs_dist.best"]),
    ("PARITY.md", r"statistical parity \(([\d.]+)x median\)",
     ["config3.vs_dist.median"]),
    ("PARITY.md", r"records \*\*([\d.]+)x at 2 host cores\*\* \(the core count",
     ["config2.projected_system.median.projected_vs_baseline_2core"]),
    ("PARITY.md", r"and ([\d.]+)x at one core",
     ["config2.projected_system.median.projected_vs_baseline_1core"]),
    ("PARITY.md", r"single-run composition records ([\d.]+)x at one core /\s+\*\*([\d.]+)x at 2 cores\*\*",
     ["config2.projected_system.projected_vs_baseline_1core",
      "config2.projected_system.projected_vs_baseline_2core"]),
    ("PARITY.md", r"\*\*affine shape\*\*[^|]*\| \*\*([\d.]+)\*\* \| \*\*([\d.]+)M\*\*",
     ["config2.tpu_rowgroup_affine_ms_per_step",
      ("config2.tpu_rowgroup_affine_rows_per_sec_per_chip", 1e6)]),
    ("README.md", r"measures \*\*([\d.]+) ms/step median, ([\d.]+) best",
     ["config2.rowgroup_ms_dist.median", "config2.rowgroup_ms_dist.best"]),
    ("README.md", r"measures ([\d.]+) ms best \(7",
     ["config2.tpu_rowgroup_nullable_ms_per_step"]),
    ("README.md", r"median-composed\s+projection records ([\d.]+)× at 2 host cores\*\* \(([\d.]+)× at one\)",
     ["config2.projected_system.median.projected_vs_baseline_2core",
      "config2.projected_system.median.projected_vs_baseline_1core"]),
    ("README.md", r"best\s+single-run composition ([\d.]+)×/([\d.]+)×",
     ["config2.projected_system.projected_vs_baseline_1core",
      "config2.projected_system.projected_vs_baseline_2core"]),
    ("README.md", r"the device phase drops to \*\*([\d.]+) ms = ([\d.]+)M",
     ["config2.tpu_rowgroup_affine_ms_per_step",
      ("config2.tpu_rowgroup_affine_rows_per_sec_per_chip", 1e6)]),
]


def main() -> int:
    sweep_path = os.environ.get("KPW_BENCH_SWEEP_PATH",
                                os.path.join(ROOT, "BENCH_SWEEP_r05.json"))
    rec = json.load(open(sweep_path))["configs"]
    docs = {f: open(os.path.join(ROOT, f)).read()
            for f in {c[0] for c in CHECKS}}
    failures = []
    for fname, pattern, paths in CHECKS:
        m = re.search(pattern, docs[fname])
        if not m:
            failures.append(f"{fname}: claim anchor not found: /{pattern}/")
            continue
        for group, spec in zip(m.groups(), paths):
            scale = 1.0
            if isinstance(spec, tuple):
                spec, scale = spec
            try:
                expect = float(art(rec, spec)) / scale
            except (KeyError, TypeError):
                failures.append(f"{fname}: artifact key missing: {spec}")
                continue
            got = float(group)
            if abs(got - expect) > TOL * max(abs(expect), 1e-9):
                failures.append(
                    f"{fname}: quotes {got} but artifact {spec} = "
                    f"{expect:.4g} (drift {abs(got - expect) / expect:.1%})")
    if failures:
        print("DOC/ARTIFACT DRIFT:")
        for f in failures:
            print(" -", f)
        return 1
    print(f"check_docs: {len(CHECKS)} claims reconciled against "
          f"{os.path.basename(sweep_path)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
