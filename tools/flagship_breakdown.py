"""On-chip breakdown of the flagship encode step (VERDICT r2 "next" #2):
times each pipeline prefix of the ORIGINAL three-variadic-sort formulation
(sort, build, rank compaction, unscramble, pack XLA vs Pallas) plus the
SHIPPED ``encode_step_single`` (single-operand-sort reformulation), inside
one jitted fori_loop per variant, dispatch-subtracted — the old variants
are the comparison baseline that motivated the reformulation (measured:
old full+pack 11.7 ms/step, shipped 6.75, 64x65Ki on v5e).  Run from
/root/repo (axon backend); CPU run is only a shape check.

Usage: python tools/flagship_breakdown.py [steps]
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp


def main() -> None:
    n_steps = int(sys.argv[1]) if len(sys.argv) > 1 else 64
    C, N = 64, 1 << 16
    rng = np.random.default_rng(7)
    lo_host = rng.integers(0, 1000, (C, N)).astype(np.uint32)
    count = jnp.int32(N)
    iota = jnp.arange(N, dtype=jnp.int32)
    big = jnp.uint32(0xFFFFFFFF)

    from kpw_tpu.ops.packing import bitpack_device
    from kpw_tpu.ops.pallas_bitpack import bitpack_pages_core

    def col_sort1(lc):
        llo = jnp.where(iota < count, lc, big)
        slo, spos = jax.lax.sort((llo, iota), num_keys=1, is_stable=True)
        return jnp.sum(slo) + jnp.sum(spos.astype(jnp.uint32))

    def _build(lc):
        llo = jnp.where(iota < count, lc, big)
        slo, spos = jax.lax.sort((llo, iota), num_keys=1, is_stable=True)
        sval = iota < jnp.sum((iota < count).astype(jnp.int32))
        same = jnp.concatenate([jnp.zeros((1,), bool), slo[1:] == slo[:-1]])
        is_new = sval & ~same
        uid = jnp.cumsum(is_new.astype(jnp.int32)) - 1
        return slo, spos, is_new, uid

    def col_build(lc):
        slo, spos, is_new, uid = _build(lc)
        return jnp.sum(uid.astype(jnp.uint32)) + jnp.sum(slo)

    def col_rank(lc):
        slo, spos, is_new, uid = _build(lc)
        rank = jnp.where(is_new, uid, N)
        _, ulo = jax.lax.sort((rank, slo), num_keys=1)
        return jnp.sum(ulo) + jnp.sum(uid.astype(jnp.uint32))

    def col_unscramble(lc):
        slo, spos, is_new, uid = _build(lc)
        rank = jnp.where(is_new, uid, N)
        _, ulo = jax.lax.sort((rank, slo), num_keys=1)
        _, indices = jax.lax.sort((spos, uid), num_keys=1)
        return jnp.sum(ulo) + jnp.sum(indices.astype(jnp.uint32))

    def col_indices(lc):
        slo, spos, is_new, uid = _build(lc)
        rank = jnp.where(is_new, uid, N)
        _, ulo = jax.lax.sort((rank, slo), num_keys=1)
        _, indices = jax.lax.sort((spos, uid), num_keys=1)
        return jnp.where(iota < count, indices.astype(jnp.uint32), 0), ulo

    def full_xla(lo):
        def one(lc):
            masked, ulo = col_indices(lc)
            return jnp.sum(bitpack_device(masked, 16),
                           dtype=jnp.uint32) + jnp.sum(ulo)

        return jnp.sum(jax.vmap(one)(lo))

    def full_pallas(lo):
        def one(lc):
            return col_indices(lc)

        masked, ulo = jax.vmap(one)(lo)  # (C, N)
        packed = bitpack_pages_core(masked, 16)
        return jnp.sum(packed, dtype=jnp.uint32) + jnp.sum(ulo)

    def vm(col_fn):
        def f(lo):
            return jnp.sum(jax.vmap(col_fn)(lo))

        return f

    from kpw_tpu.parallel.sharded import encode_step_single

    def shipped(lo):
        packed, ulo, k = encode_step_single(lo, count)
        return (jnp.sum(packed, dtype=jnp.uint32) + jnp.sum(ulo)
                + jnp.sum(k).astype(jnp.uint32))

    variants = {
        "old sort1": vm(col_sort1),
        "old build(sort+scan)": vm(col_build),
        "old rank(2 sorts)": vm(col_rank),
        "old unscramble(3 sorts)": vm(col_unscramble),
        "old full+pack XLA": full_xla,
        "old full+pack Pallas": full_pallas,
        "SHIPPED encode_step_single": shipped,
    }

    dev = jax.devices()[0]
    print(f"device: {dev}", file=sys.stderr)
    if dev.platform == "cpu":
        n_steps = 2
    lo = jax.device_put(jnp.asarray(lo_host), dev)
    try:
        from kpw_tpu.runtime.select import probe_link

        dispatch_s = probe_link()["dispatch_ms"] / 1e3
    except Exception:
        dispatch_s = 0.0

    for name, fn in variants.items():
        @jax.jit
        def loop(x, fn=fn):
            def body(i, acc):
                return acc + fn(x ^ i.astype(jnp.uint32))

            return jax.lax.fori_loop(0, n_steps, body, jnp.uint32(0))

        t0 = time.perf_counter()
        np.asarray(loop(lo))
        compile_s = time.perf_counter() - t0
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            np.asarray(loop(lo))
            best = min(best, time.perf_counter() - t0)
        per = (best - dispatch_s) / n_steps
        print(f"{name:24s} {per * 1e3:8.3f} ms/step  "
              f"(compile {compile_s:.1f}s, loop {best:.3f}s)")


if __name__ == "__main__":
    main()
