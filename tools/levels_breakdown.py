"""On-chip breakdown of the level-stream device programs — the dominant
component of the whole-row-group phase (9.8 of ~16 ms/step at the probe
shape).  Times, per fori_loop step at the probe's 56-stream x 8 Ki-page
shape: the raw run scan alone, the stats program, the runs-extraction
program, and stats+runs together (what the row-group probe's level_part
runs) — so the split between scan work and compaction sorts is measured,
not guessed.  Run from /root/repo (axon backend).

Usage: python tools/levels_breakdown.py [steps]
"""

from __future__ import annotations

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import jax
import jax.numpy as jnp


def main() -> None:
    n_steps = int(sys.argv[1]) if len(sys.argv) > 1 else 48
    K, N, PAGE, RUN_BUCKET = 56, 1 << 16, 8192, 1024
    rng = np.random.default_rng(11)
    lvl = (rng.random((K, N)) > 0.02).astype(np.uint32)
    lvl_all = jnp.asarray(lvl)
    pages_per = N // PAGE
    sids = jnp.asarray(np.repeat(np.arange(K, dtype=np.int32), pages_per))
    starts = jnp.asarray(np.tile(np.arange(0, N, PAGE, dtype=np.int32), K))
    counts = jnp.full(K * pages_per, PAGE, jnp.int32)

    from kpw_tpu.ops.levels import level_runs_multi, level_stats_multi
    from kpw_tpu.ops.packing import window_run_scan

    def scan_only(i, lv):
        lv = lv ^ (i & 1).astype(jnp.uint32)
        padded = jnp.pad(lv, ((0, 0), (0, PAGE)))

        def one(sid, start, count):
            v, valid, run_id, run_len_here, is_end = window_run_scan(
                padded, sid, start, count, PAGE)
            return (jnp.sum(run_id) + jnp.sum(run_len_here)
                    + jnp.sum(is_end.astype(jnp.int32)))

        return jnp.sum(jax.vmap(one)(sids, starts, counts)).astype(jnp.uint32)

    def stats_only(i, lv):
        lv = lv ^ (i & 1).astype(jnp.uint32)
        long_sum, n_runs = level_stats_multi(lv, sids, starts, counts, PAGE)
        return (jnp.sum(long_sum) + jnp.sum(n_runs)).astype(jnp.uint32)

    def runs_only(i, lv):
        lv = lv ^ (i & 1).astype(jnp.uint32)
        rv, rl = level_runs_multi(lv, sids, starts, counts, PAGE, RUN_BUCKET,
                                  1)  # width-1 levels: one-sort compaction
        return (jnp.sum(rv) + jnp.sum(rl).astype(jnp.uint32))

    def both(i, lv):
        return stats_only(i, lv) + runs_only(i, lv)

    variants = {
        "scan only": scan_only,
        "stats program": stats_only,
        "runs program": runs_only,
        "stats+runs (probe's level_part)": both,
    }

    dev = jax.devices()[0]
    print(f"device: {dev}", file=sys.stderr)
    if dev.platform == "cpu":
        n_steps = 2
    lv = jax.device_put(lvl_all, dev)
    try:
        from kpw_tpu.runtime.select import probe_link

        dispatch_s = probe_link()["dispatch_ms"] / 1e3
    except Exception:
        dispatch_s = 0.0

    for name, fn in variants.items():
        @jax.jit
        def loop(steps, x, fn=fn):
            def body(i, acc):
                return acc + fn(i, x)

            return jax.lax.fori_loop(0, steps, body, jnp.uint32(0))

        t0 = time.perf_counter()
        np.asarray(loop(jnp.int32(n_steps), lv))
        compile_s = time.perf_counter() - t0
        steps = n_steps
        while True:
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(loop(jnp.int32(steps), lv))
                best = min(best, time.perf_counter() - t0)
            if best >= dispatch_s * 4 or steps >= 1024:
                break
            steps *= 4
        per = (best - dispatch_s) / steps
        print(f"{name:34s} {per * 1e3:8.3f} ms/step  "
              f"({steps} steps, compile {compile_s:.1f}s)")


if __name__ == "__main__":
    main()
