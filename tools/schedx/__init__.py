"""schedx: the deterministic concurrency-schedule explorer CLI.

Runs the scenario drivers in ``tools/schedx/scenarios.py`` — the PR-11/12
cross-process race windows reconstructed over REAL repo code — across a
committed seed set (``tools/schedx/seeds.json``), with every preemption
schedule determined by its seed (see ``kpw_tpu/utils/schedcheck.py``).

    python -m tools.schedx               # committed seeds, exit 0 = clean
    python -m tools.schedx --smoke       # CI subset of the seeds
    python -m tools.schedx --revert      # negative control: pre-fix shapes
    python -m tools.schedx --scenario ring-free-respawn --seeds 0:64

The current tree must be CLEAN across the whole committed seed set
(tests/test_schedx.py pins it); ``--revert`` swaps each scenario's
historical pre-fix method back in test-locally, and the committed
``refind_seeds`` must re-find every historical race — the negative
control proving the explorer detects what it claims to."""

from __future__ import annotations

import json
import os

from .scenarios import HISTORY, SCENARIOS

SEED_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "seeds.json")


def load_seeds(path: str | None = None) -> dict:
    with open(path or SEED_FILE) as f:
        return json.load(f)["scenarios"]
