"""CLI for the schedule explorer: ``python -m tools.schedx``.

Exit codes: 0 = every explored schedule clean, 1 = violations found
(each reported with its replay seed and both participating stacks),
2 = usage error.  ``--revert`` is the negative-control mode: it expects
violations (that is the point) and exits 0 iff every scenario's
committed ``refind_seeds`` re-found its historical race."""

from __future__ import annotations

import argparse
import sys

from . import HISTORY, SCENARIOS, load_seeds


def _parse_seed_range(spec: str) -> list[int]:
    if ":" in spec:
        a, b = spec.split(":", 1)
        return list(range(int(a), int(b)))
    return [int(spec)]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m tools.schedx",
        description="deterministic concurrency-schedule explorer "
                    "(see tools/schedx/__init__.py)")
    ap.add_argument("--scenario", action="append", default=[],
                    metavar="NAME", help="run only this scenario "
                    "(repeatable; default: all)")
    ap.add_argument("--seeds", default=None, metavar="N|A:B",
                    help="explicit seed or seed range (default: the "
                         "committed seed set in tools/schedx/seeds.json)")
    ap.add_argument("--smoke", action="store_true",
                    help="CI subset: first 4 committed seeds per scenario")
    ap.add_argument("--revert", action="store_true",
                    help="negative control: reintroduce each scenario's "
                         "pre-fix shape test-locally and REQUIRE the "
                         "committed refind_seeds to re-find the race")
    ap.add_argument("--virtual", action="store_true",
                    help="virtual delays (yield loops) for fast wide "
                         "seed walks; committed seeds use wall delays")
    ap.add_argument("--list", action="store_true",
                    help="list scenarios and exit")
    args = ap.parse_args(argv)

    if args.list:
        for name in SCENARIOS:
            print(f"{name}: {HISTORY[name]}")
        return 0
    for name in args.scenario:
        if name not in SCENARIOS:
            print(f"unknown scenario {name!r}; known: "
                  f"{', '.join(SCENARIOS)}", file=sys.stderr)
            return 2

    committed = load_seeds()
    names = args.scenario or list(SCENARIOS)
    failures = 0
    for name in names:
        entry = committed.get(name, {})
        if args.seeds is not None:
            seeds = _parse_seed_range(args.seeds)
        elif args.revert:
            seeds = entry.get("refind_seeds", [])
        else:
            seeds = entry.get("seeds", [])
        if args.smoke:
            seeds = seeds[:4]
        found: list[int] = []
        for seed in seeds:
            checker = SCENARIOS[name](seed, revert=args.revert,
                                      virtual=args.virtual)
            if checker.violations:
                found.append(seed)
                for v in checker.violations:
                    first = str(v).splitlines()[0]
                    print(f"[{name} seed={seed}] {type(v).__name__}: "
                          f"{first}")
                    if args.seeds is not None or not args.revert:
                        # full report (both stacks) for unexpected finds
                        print(str(v))
        if args.revert:
            ok = bool(found)
            print(f"schedx --revert {name}: {len(found)}/{len(seeds)} "
                  f"seeds re-found the {HISTORY[name]} "
                  f"({'OK' if ok else 'FAILED — fix revert found nothing'})")
            if not ok and seeds:
                failures += 1
        else:
            print(f"schedx {name}: {len(seeds)} seed(s) explored, "
                  f"{len(found)} violation(s)")
            failures += len(found)
    if args.revert:
        return 1 if failures else 0
    if failures:
        print(f"schedx: {failures} violated schedule(s) — each report "
              f"above carries its replay seed", file=sys.stderr)
        return 1
    print("schedx: all explored schedules clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
