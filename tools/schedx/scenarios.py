"""Schedule-exploration scenarios: the cross-process race windows PR 11
and PR 12 fixed by hand, each reconstructed as two REAL repo code paths
racing under a seeded ``kpw_tpu.utils.schedcheck`` install.

Every scenario runs the production classes (``ProcessWorkerPool``,
``_ProcWorkerSlot``, ``ShmBatchRing``, ``_ProcHeartbeat``,
``ObjectStoreFileSystem``) — not models of them — and relies on the
invariant probes registered inside those classes to detect a violated
schedule.  ``revert=True`` swaps in the PRE-FIX shape of exactly one
method (reintroduced test-locally below, the negative-control pattern of
``test_fuzz_reporting_path_detects_crashes``): under the reverted fix a
committed subset of seeds MUST re-find the historical race, and under
the current tree every committed seed must run clean — both pinned by
tests/test_schedx.py.

Determinism: a seed fully determines which preemption points park and
for how long (per-(seed, label, occurrence) coins — see
``SchedCheck._coin``); a parked thread stays parked while the racing
thread's whole critical region completes, so on any box the schedule a
seed selects replays.  The scenarios keep their racy regions tiny
(microseconds) against delays of tens of milliseconds for exactly this
reason.
"""

from __future__ import annotations

import collections
import contextlib
import os
import queue
import shutil
import sys
import tempfile
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
for _p in (_REPO, os.path.join(_REPO, "tests")):
    if _p not in sys.path:
        sys.path.insert(0, _p)

from kpw_tpu.utils import schedcheck  # noqa: E402


# ---------------------------------------------------------------------------
# harness plumbing
# ---------------------------------------------------------------------------

class _ScenarioWriter:
    """The minimal writer surface ``ProcessWorkerPool`` touches on the
    probed paths (collector free/died branches, respawn bookkeeping) —
    real ``Meter``s so the production code runs unmodified."""

    def __init__(self, b) -> None:
        from kpw_tpu.runtime.metrics import Histogram, Meter

        self._b = b
        self._restart_counts = collections.defaultdict(int)
        for name in ("_written_records", "_written_bytes",
                     "_flushed_records", "_flushed_bytes", "_failed",
                     "_verified", "_verify_failed", "_quarantined",
                     "_rotated_time", "_rotated_size", "_indexed",
                     "_bloom_bytes_meter", "_native_asm_chunks",
                     "_native_asm_pages"):
            setattr(self, name, Meter())
        self._file_size_histogram = Histogram()
        self.deaths_notified = 0

    def _notify_worker_death(self, index=None, reason=None) -> None:
        self.deaths_notified += 1

    # PR-17 telemetry-plane seams: the pool banks/absorbs child counters
    # on the respawn and snapshot paths — no-ops here, the scenarios
    # probe the ring/death races, not the merged scrape
    def _bank_child_telemetry(self, index) -> None:
        pass

    def _absorb_child_telemetry(self, payload) -> None:
        pass


def _make_pool(tmpdir: str, workers: int = 1, ring_slots: int = 4):
    from proto_helpers import sample_message_class

    from kpw_tpu import Builder
    from kpw_tpu.runtime.procworkers import ProcessWorkerPool

    b = (Builder().proto_class(sample_message_class())
         .target_dir(tmpdir).instance_name("schedx")
         .process_workers(workers, ring_slots=ring_slots,
                          slot_bytes=1 << 16))
    return ProcessWorkerPool(_ScenarioWriter(b))


def _close_pool(pool) -> None:
    pool._stop.set()
    for s in pool.slots:
        with contextlib.suppress(OSError, ValueError):
            s.work_q.close()
    with contextlib.suppress(OSError, ValueError):
        pool.ack_q.close()
    pool.ring.close()
    pool.ring.unlink()


class _Patch:
    def __init__(self, owner, name, replacement) -> None:
        self.owner, self.name = owner, name
        self.original = getattr(owner, name)
        setattr(owner, name, replacement)

    def undo(self) -> None:
        setattr(self.owner, self.name, self.original)


def _run_threads(targets, timeout_s: float = 10.0) -> None:
    """Run the racing parties; a ScheduleViolation raised inside a party
    is already recorded on the checker — swallow it there so the harness
    reports through ``checker.violations`` uniformly.  Anything ELSE is
    harness/regression breakage and must not read as a clean seed: a
    non-violation exception re-raises here, and a party still alive
    after the join (deadlock) is an explicit failure."""
    errors: list[BaseException] = []

    def wrap(fn):
        def body():
            try:
                fn()
            except schedcheck.ScheduleViolation:
                pass
            except BaseException as e:  # noqa: BLE001 — re-raised below
                errors.append(e)
        return body

    threads = [threading.Thread(target=wrap(t), daemon=True)
               for t in targets]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout_s)
    hung = [t for t in threads if t.is_alive()]
    if hung:
        raise RuntimeError(
            f"{len(hung)} racing part(y/ies) still running after "
            f"{timeout_s}s — deadlocked schedule, NOT a clean seed")
    if errors:
        raise errors[0]


# ---------------------------------------------------------------------------
# scenario: ring slot double-free (PR-11 stale free ack vs. respawn)
# ---------------------------------------------------------------------------

def _legacy_drain_unfreed_slots(self):
    # PR-11 PRE-FIX shape, reintroduced test-locally for the negative
    # control: returns the un-freed slots WITHOUT marking them freed, so
    # a stale `free` ack that lands after the respawn reclaim finds its
    # ledger entry still live and recycles the same ring slot again
    with self._mu:
        return [e["slot"] for e in self._ledger.values() if not e["freed"]]


def ring_free_respawn(seed: int, revert: bool = False,
                      virtual: bool = False):
    """A child died after sending its last ``free`` ack: the collector
    handles the stale ack while the supervisor respawn reclaims the dead
    child's un-drained slots.  Exactly one of them may recycle the ring
    slot; the double-recycle probe in ``ProcessWorkerPool._recycle_slot``
    catches the schedules where both do."""
    from kpw_tpu.runtime import procworkers as pw

    # perturbation is ONE-SIDED (the stale-ack party only) and the delays
    # dwarf thread-scheduling noise on a loaded box: a seed's verdict
    # then depends only on its own coins, never on how long the racing
    # respawn happened to take — that is what makes the seed replay
    checker = schedcheck.install(
        seed=seed, virtual=virtual, max_delay_s=0.25,
        labels=("proc.collector.free", "proc.slot.note_free"))
    patches = []
    if revert:
        patches.append(_Patch(pw._ProcWorkerSlot, "drain_unfreed_slots",
                              _legacy_drain_unfreed_slots))
    tmpdir = tempfile.mkdtemp(prefix="schedx-ring-")
    try:
        pool = _make_pool(tmpdir)
        try:
            ri = pool._get_free_slot()
            pool.slots[0].note_dispatch(seq=1, runs=[(0, 0, 5)], count=5,
                                        nbytes=10, slot_idx=ri)
            _run_threads([
                lambda: pool._handle(("free", 0, ri, 1)),
                lambda: pool.respawn_slot(0),
            ])
        finally:
            _close_pool(pool)
    finally:
        for p in patches:
            p.undo()
        schedcheck.uninstall()
        shutil.rmtree(tmpdir, ignore_errors=True)
    return checker


# ---------------------------------------------------------------------------
# scenario: heartbeat torn read (PR-11 pending-without-start)
# ---------------------------------------------------------------------------

def _legacy_hb_publish(self, widx, label_code, pending, started_at):
    # PR-11 PRE-FIX single-path version, reintroduced test-locally: one
    # write order for set AND clear with the pending flag flipped BEFORE
    # the started_at stamp — a racing watchdog read between the two
    # observes pending=1 paired with the previous clear's 0.0 clock and
    # computes an enormous stall age (healthy child condemned)
    if self._hb_i is None:
        return
    schedcheck.note_hb_write(widx)
    self._hb_i[widx, 0] = label_code
    self._hb_i[widx, 1] = 1 if pending else 0
    schedcheck.point("proc.hb.publish.legacy")
    self._hb_f[widx, 2] = started_at
    self._hb_f[widx, 3] = time.monotonic()


def _legacy_stall(self):
    # PR-11 PRE-FIX stall(): no started_at==0.0 guard — the historical
    # fix was two-layer (publish write order AND this guard), so the
    # negative control reverts both.  The probe call is the same
    # computation-site invariant the fixed stall() carries.
    from kpw_tpu.runtime.procworkers import _HB_LABELS

    code, pending, started_at, _beat = self._ring.hb_read(self._widx)
    if not pending:
        return 0.0, None
    schedcheck.note_hb_sample(self._widx, True, started_at)
    label = (_HB_LABELS[code - 1]
             if 1 <= code <= len(_HB_LABELS) else "io")
    return max(0.0, time.monotonic() - started_at), label


def heartbeat_torn_read(seed: int, revert: bool = False,
                        virtual: bool = False):
    """A child's heartbeat publisher cycles pending set/clear around
    short IO ops while the parent-side watchdog adapter samples
    ``stall()`` concurrently — the torn-read probe in
    ``_ProcHeartbeat.stall`` rejects any schedule where pending is
    observable without its started_at stamp."""
    from kpw_tpu.runtime import procworkers as pw

    checker = schedcheck.install(
        seed=seed, virtual=virtual, max_delay_s=0.01,
        labels=("proc.hb.publish", "proc.hb.publish.legacy"))
    patches = []
    if revert:
        patches.append(_Patch(pw.ShmBatchRing, "hb_publish",
                              _legacy_hb_publish))
        patches.append(_Patch(pw._ProcHeartbeat, "stall", _legacy_stall))
    ring = pw.ShmBatchRing(1, 1 << 15)
    hb = pw._ProcHeartbeat(ring, 0)
    done = threading.Event()
    try:
        def publisher():
            try:
                for _ in range(40):
                    ring.hb_publish(0, 1, True, time.monotonic())
                    ring.hb_publish(0, 0, False, 0.0)
            finally:
                done.set()

        def watchdog_reader():
            while not done.is_set():
                try:
                    hb.stall()
                except schedcheck.ScheduleViolation:
                    pass  # recorded; keep sampling the remaining cycles

        _run_threads([publisher, watchdog_reader], timeout_s=20.0)
    finally:
        for p in patches:
            p.undo()
        schedcheck.uninstall()
        ring.close()
        ring.unlink()
    return checker


# ---------------------------------------------------------------------------
# scenario: background uploader spawn race (PR-12)
# ---------------------------------------------------------------------------

def _legacy_ensure_uploader(self):
    # PR-12 PRE-FIX shape, reintroduced test-locally: the singleton is
    # liveness-checked and assigned under the lock but STARTED outside
    # it — a concurrent first-part submitter observes is_alive() False
    # on the not-yet-started thread and spawns a second drainer on the
    # same queue (two drainers reorder a dirty re-upload behind its
    # stale original)
    with self._mu:
        if self._uploader is not None and self._uploader.is_alive():
            return
        if self._q is None:
            self._q = queue.Queue()
        t = threading.Thread(target=self._uploader_loop,
                             name="KPW-objstore-uploader", daemon=True)
        self._uploader = t
        schedcheck.note_uploader_spawn(id(self))
    schedcheck.point("objstore.uploader.legacy")
    t.start()


def uploader_spawn_race(seed: int, revert: bool = False,
                        virtual: bool = False):
    """Two encode threads submit their first completed part concurrently
    on a fresh adapter; the uploader-singleton probe rejects any
    schedule that spawns a second drainer loop."""
    from kpw_tpu.io import objectstore as objs

    checker = schedcheck.install(
        seed=seed, virtual=virtual, max_delay_s=0.1,
        labels=("objstore.uploader.ensure", "objstore.uploader.legacy",
                "thread.start:KPW-objstore-uploader"))
    patches = []
    if revert:
        patches.append(_Patch(objs.ObjectStoreFileSystem,
                              "_ensure_uploader", _legacy_ensure_uploader))
    store = objs.EmulatedObjectStore()
    fs = objs.ObjectStoreFileSystem(store, "schedx", part_size=4096)
    try:
        pendings = []
        for name in ("a", "b"):
            p = objs._Pending(fs._key(f"/t/{name}.tmp"))
            p.upload_id = store.create_multipart("schedx", p.key)
            pendings.append(p)
        _run_threads([
            lambda: fs._submit_part(pendings[0], 1, b"x" * 4096),
            lambda: fs._submit_part(pendings[1], 1, b"y" * 4096),
        ])
        # wait out the drainer(s) so the store teardown is quiet
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            with fs._mu:
                if all(p.inflight == 0 for p in pendings):
                    break
            time.sleep(0.005)
    finally:
        for p in patches:
            p.undo()
        schedcheck.uninstall()
        # poison the drainer(s): the production loop only exits on None
        # and a daemon thread parked in q.get() would otherwise outlive
        # every seed run, pinning the adapter+store for process lifetime
        if fs._q is not None:
            for _ in range(2):  # a reverted run may have spawned two
                fs._q.put(None)
    return checker


# ---------------------------------------------------------------------------
# scenario: stale death notice vs. respawned slot (PR-11)
# ---------------------------------------------------------------------------

def _legacy_handle_died(pool, msg):
    # PR-11 PRE-FIX died branch, reintroduced test-locally: no
    # sender-pid check — a death notice delayed in the ack queue past
    # the supervisor's respawn condemns the index's healthy replacement
    _, widx, pid, reason = msg
    schedcheck.point("proc.collector.died")
    slot = pool.slots[widx]
    acted = not slot.failed and not slot.condemned
    schedcheck.note_death_notice(slot.pid, pid, acted)
    if acted:
        slot.exit_reason = reason
        slot.failed = True
        pool.w._failed.mark()
        pool.w._notify_worker_death()


def stale_death_notice(seed: int, revert: bool = False,
                       virtual: bool = False):
    """A delayed ``died`` message from the slot's previous occupant races
    the supervisor respawn that already replaced it; the death-notice
    probe rejects any schedule that condemns a process other than the
    sender."""
    # one-sided perturbation (see ring_free_respawn): only the delivery
    # parks, and its park must dwarf the racing respawn's slot rebuild
    # (proto descriptor closure + spawn Process/Queue objects — tens of
    # ms under load) for the seed to replay
    checker = schedcheck.install(
        seed=seed, virtual=virtual, max_delay_s=0.4,
        labels=("proc.collector.died",))
    tmpdir = tempfile.mkdtemp(prefix="schedx-died-")
    try:
        pool = _make_pool(tmpdir)
        try:
            old_pid = 4242
            pool.slots[0].pid = old_pid  # the notice's sender

            def deliver():
                msg = ("died", 0, old_pid, "child terminated")
                if revert:
                    _legacy_handle_died(pool, msg)
                else:
                    pool._handle(msg)

            def respawn():
                pool.respawn_slot(0)
                pool.slots[0].pid = 5151  # replacement reported ready

            _run_threads([deliver, respawn])
        finally:
            _close_pool(pool)
    finally:
        schedcheck.uninstall()
        shutil.rmtree(tmpdir, ignore_errors=True)
    return checker


# ---------------------------------------------------------------------------
# scenario: zombie commit vs. cooperative handoff (PR-18 generation fence)
# ---------------------------------------------------------------------------

def _legacy_commit(self, group, topic, partition, offset,
                   generation=None, member_id=None):
    # PR-18 PRE-FENCE shape, reintroduced test-locally: the committer's
    # identity rides along but is never CHECKED — only the monotonic
    # guard protects the offset state, so an old owner's in-flight ack
    # delayed past the handoff silently clobbers the new owner's
    # partition (the exact zombie window the generation fence closes)
    from kpw_tpu.utils import schedcheck as _sc

    _sc.point("broker.commit.fence")
    with self._lock:
        key = (group, topic)
        self._sweep_locked(key)
        if generation is not None and member_id is not None:
            _sc.note_commit_accepted(id(self), key + (partition,),
                                     member_id)
        ckey = (group, topic, partition)
        if offset > self._committed.get(ckey, 0):
            self._committed[ckey] = offset


def stale_commit_fence(seed: int, revert: bool = False,
                       virtual: bool = False):
    """The revocation-vs-in-flight-publish race: an old owner's ack
    commit parks at the fence point (``broker.commit.fence`` sits
    deliberately OUTSIDE the broker lock so a delayed commit cannot block
    the handoff parties) while the cooperative handoff completes
    (``confirm_revocation`` records the new owner).  The fixed tree
    fences the late commit with ``StaleGenerationError``; the reverted
    pre-fence shape accepts it, and the commit-ownership probe
    (``schedcheck.note_commit_accepted``) rejects the schedule."""
    from kpw_tpu.ingest import broker as brk

    # one-sided perturbation (see ring_free_respawn): only the zombie's
    # commit passes the installed label — the handoff party never parks,
    # so a seed's verdict depends on its own coin alone
    checker = schedcheck.install(
        seed=seed, virtual=virtual, max_delay_s=0.25,
        labels=("broker.commit.fence",))
    patches = []
    if revert:
        patches.append(_Patch(brk.FakeBroker, "commit", _legacy_commit))
    try:
        b = brk.FakeBroker(session_timeout_s=30.0, revocation_drain_s=30.0)
        b.create_topic("t", 2)
        b.join_group("g", "t", "a")  # owns both partitions
        gen_a = b.generation("g", "t")
        b.join_group("g", "t", "b")  # one partition moves a->b: drain opens
        rev = b.group_stats("g", "t")["revoking"]
        assert rev, "a live-member handoff must open a drain window"
        p = rev[0]

        def zombie_commit():
            # the old owner's in-flight ack: legitimate inside the drain
            # window, fenced (fixed tree) or silently accepted (reverted)
            # once the handoff completed underneath it
            try:
                b.commit("g", "t", p, 5, generation=gen_a, member_id="a")
            except brk.StaleGenerationError:
                pass  # the fence doing its job — a clean schedule

        _run_threads([
            zombie_commit,
            lambda: b.confirm_revocation("g", "t", "a", [p]),
        ])
    finally:
        for pch in patches:
            pch.undo()
        schedcheck.uninstall()
    return checker


# ---------------------------------------------------------------------------
# scenario: revocation back-out vs. collector free (PR-19 cross-process fence)
# ---------------------------------------------------------------------------

def _legacy_backout_units(self, parts):
    # PR-19 PRE-FIX shape, reintroduced test-locally: revoked units are
    # popped with no regard for the commit-to-send / freed handshake —
    # an entry whose unit the dispatcher already sent (and whose ring
    # slot the child already freed back through the collector) is backed
    # out anyway, recycling the same ring slot a second time
    with self._mu:
        out = []
        for seq, e in list(self._ledger.items()):
            if e["runs"] and all(r[0] in parts for r in e["runs"]):
                self._ledger.pop(seq)
                self._unacked_count = max(
                    0, self._unacked_count - e["count"])
                out.append(e["slot"])
        return out


def proc_revoke_vs_free(seed: int, revert: bool = False,
                        virtual: bool = False):
    """The rebalance listener backs out a revoked unit while the
    collector handles the child's ``free`` ack for the same ring slot
    (the unit was dispatched after all — the revocation raced the
    commit-to-send window).  The fixed ``backout_units`` only takes
    entries with ``sent=False and freed=False`` under the ledger lock,
    so exactly one party recycles; the double-recycle probe in
    ``ProcessWorkerPool._recycle_slot`` rejects any schedule where both
    do."""
    from kpw_tpu.runtime import procworkers as pw

    # one-sided perturbation (see ring_free_respawn): only the back-out
    # party parks — at ``proc.revoke.backout``, BEFORE its ledger pop —
    # so a seed's verdict depends on its own coin alone
    checker = schedcheck.install(
        seed=seed, virtual=virtual, max_delay_s=0.25,
        labels=("proc.revoke.backout",))
    patches = []
    if revert:
        patches.append(_Patch(pw._ProcWorkerSlot, "backout_units",
                              _legacy_backout_units))
    tmpdir = tempfile.mkdtemp(prefix="schedx-revoke-")
    try:
        pool = _make_pool(tmpdir)
        try:
            ri = pool._get_free_slot()
            slot = pool.slots[0]
            slot.note_dispatch(seq=1, runs=[(3, 0, 64)], count=64,
                               nbytes=128, slot_idx=ri)
            # the dispatcher committed to sending: the child WILL free
            # this slot, so the revocation back-out must leave it alone
            slot.mark_sent(1)
            _run_threads([
                lambda: pool._handle(("free", 0, ri, 1)),
                lambda: pool.backout_undispatched(slot, frozenset({3})),
            ])
        finally:
            _close_pool(pool)
    finally:
        for p in patches:
            p.undo()
        schedcheck.uninstall()
        shutil.rmtree(tmpdir, ignore_errors=True)
    return checker


# registration order = report order; names are the CLI / seeds.json keys
SCENARIOS = {
    "ring-free-respawn": ring_free_respawn,
    "heartbeat-torn-read": heartbeat_torn_read,
    "uploader-spawn-race": uploader_spawn_race,
    "stale-death-notice": stale_death_notice,
    "stale-commit-fence": stale_commit_fence,
    "proc-revoke-vs-free": proc_revoke_vs_free,
}

# which historical PR the reverted fix belongs to (reporting only)
HISTORY = {
    "ring-free-respawn": "PR-11 shm ring slot double-free",
    "heartbeat-torn-read": "PR-11 heartbeat torn read",
    "uploader-spawn-race": "PR-12 uploader-thread spawn race",
    "stale-death-notice": "PR-11 stale death notice",
    "stale-commit-fence": "PR-18 zombie commit vs cooperative handoff",
    "proc-revoke-vs-free": "PR-19 revocation back-out vs collector free",
}
