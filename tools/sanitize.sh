#!/usr/bin/env bash
# Sanitizer legs of the correctness tooling (ISSUE 7 ASan/UBSan, ISSUE 13
# TSan — coverage is NOT ASan/UBSan-only since round 17):
#
#   default        : ASan/UBSan — build the native shredders/codecs with
#                    -fsanitize=address,undefined and run the shred/
#                    gather/offset-validation and verify/thrift test
#                    subsets plus the seeded mutation-fuzz harness under
#                    them.  Every native OOB/UB the hardening PRs fixed
#                    by hand (thrift CompactReader, the shred_flat_buf
#                    malformed-offset read) traps loudly here instead of
#                    reading garbage.
#   --tsan         : ThreadSanitizer — build with -fsanitize=thread
#                    (KPW_NATIVE_SANITIZE=tsan, separate _kpw_*_tsan.so
#                    caches) and drive the GIL-released entries
#                    (shred_flat_buf / gather_buf / assemble_pages) from
#                    concurrent threads via python -m tools.tsan_stress.
#                    A deliberate-race canary (--canary) must be REPORTED
#                    by TSan first, so the clean run is never vacuous.
#
# Usage:  bash tools/sanitize.sh [--smoke] [--tsan]
#   --smoke  : smaller iteration counts (CI entry point; defaults are
#              the committed regression configuration below)
#   --tsan   : run ONLY the TSan leg (tools/ci.sh runs both as separate
#              steps so each skips/fails independently)
#
# Skip policy: when g++ or the sanitizer runtimes are absent the script
# prints an UNMISSABLE notice and exits 0 — a missing toolchain must
# never silently pass for "sanitizers ran clean" (the notice is the
# difference), and must not fail CI on boxes that legitimately lack it.
#
# Mechanics worth knowing (cost us a debugging session each):
#   * the host python is NOT instrumented, so libasan/libubsan/libtsan
#     must be LD_PRELOADed or the sanitized .so fails to load;
#   * PYTHONMALLOC=malloc is REQUIRED for ASan to see Python-owned
#     buffers — pymalloc arenas bypass malloc interception, and without
#     this an out-of-bounds read into a neighboring arena page is
#     silent (verified with a deliberate OOB through gather_buf);
#   * the TSan artifacts must be PREBUILT by an un-preloaded python:
#     forking g++ out of a TSan-preloaded interpreter that already has
#     live threads (jax's import machinery) deadlocks in subprocess —
#     so the tsan leg builds first, preloads second;
#   * sanitized artifacts cache as _kpw_*_san.so / _kpw_*_tsan.so next
#     to the normal ones (kpw_tpu/native/build.py KPW_NATIVE_SANITIZE),
#     so this script never pollutes the fast build.

set -u -o pipefail
cd "$(dirname "$0")/.."

FUZZ_ITERS=2000          # committed regression configuration (seed is
SEED=20260803            # tools/fuzz.py DEFAULT_SEED — keep in sync)
TSAN_ITERS=200           # committed per-thread iteration count
TSAN_THREADS=4
MODE=asan
for arg in "$@"; do
    case "$arg" in
        --smoke) FUZZ_ITERS=500; TSAN_ITERS=60 ;;
        --tsan)  MODE=tsan ;;
        *) echo "unknown arg: $arg" >&2; exit 2 ;;
    esac
done

loud_skip() {
    echo "=============================================================="
    echo "SANITIZER SMOKE SKIPPED (NOT PASSED): $1"
    echo "The $2 leg did not run. Install g++ with the sanitizer"
    echo "runtimes to exercise it. This is a loud no-op, never a pass."
    echo "=============================================================="
    exit 0
}

command -v g++ >/dev/null 2>&1 || loud_skip "g++ not found" "$MODE"

if [ "$MODE" = "tsan" ]; then
    TSAN_LIB="$(g++ -print-file-name=libtsan.so)"
    [ -e "$TSAN_LIB" ] || loud_skip "libtsan.so not found ($TSAN_LIB)" "TSan"
    # canary: the preload must produce a working interpreter (TSan's
    # shadow mappings can fail on exotic kernels) — a broken runtime is
    # a SKIP, not a silent pass and not a spurious failure
    if ! LD_PRELOAD="$TSAN_LIB" python -c "print('ok')" >/dev/null 2>&1; then
        loud_skip "libtsan preload cannot start python on this host" "TSan"
    fi
    export JAX_PLATFORMS=cpu
    echo "== sanitize.sh --tsan: prebuilding tsan artifacts (no preload) =="
    # prebuild WITHOUT the preload: forking g++ from a TSan-preloaded,
    # already-threaded interpreter deadlocks in subprocess
    KPW_NATIVE_SANITIZE=tsan python -c "
from kpw_tpu.native import build
build._build(); build._build_pyshred(); build._build_assemble()
print('tsan artifacts built')" || exit 1
    echo "== sanitize.sh --tsan: deliberate-race canary (must be REPORTED) =="
    CANARY_LOG="$(mktemp)"
    # exitcode=0 makes TSan's own reports exit clean, so a NONZERO exit
    # here is unambiguously harness breakage (import error, .so failed
    # to load) — a hard failure, never a skip
    if ! KPW_NATIVE_SANITIZE=tsan LD_PRELOAD="$TSAN_LIB" \
        TSAN_OPTIONS="halt_on_error=0 exitcode=0" \
        python -m tools.tsan_stress --canary >"$CANARY_LOG" 2>&1; then
        echo "sanitize.sh: the tsan canary HARNESS crashed (see below) —"
        echo "this is a broken gate, not a missing toolchain"
        tail -10 "$CANARY_LOG"
        exit 1
    fi
    if ! grep -q "WARNING: ThreadSanitizer: data race" "$CANARY_LOG"; then
        echo "TSan did NOT report the deliberate race — the leg would be"
        echo "vacuous; treating as a loud skip (see $CANARY_LOG)"
        tail -5 "$CANARY_LOG"
        loud_skip "deliberate-race canary not reported" "TSan"
    fi
    rm -f "$CANARY_LOG"
    echo "== sanitize.sh --tsan: concurrent native entries, 0 races required =="
    KPW_NATIVE_SANITIZE=tsan LD_PRELOAD="$TSAN_LIB" \
        TSAN_OPTIONS="halt_on_error=1" \
        python -m tools.tsan_stress --iters "$TSAN_ITERS" \
            --threads "$TSAN_THREADS" || {
        echo "sanitize.sh: TSan FOUND RACES (or the stress diverged)"; exit 1; }
    echo "sanitize.sh: tsan leg clean (threads=$TSAN_THREADS, iters=$TSAN_ITERS)"
    exit 0
fi

ASAN_LIB="$(g++ -print-file-name=libasan.so)"
UBSAN_LIB="$(g++ -print-file-name=libubsan.so)"
[ -e "$ASAN_LIB" ] || loud_skip "libasan.so not found ($ASAN_LIB)" "ASan/UBSan"
[ -e "$UBSAN_LIB" ] || loud_skip "libubsan.so not found ($UBSAN_LIB)" "ASan/UBSan"

export KPW_NATIVE_SANITIZE=1
export PYTHONMALLOC=malloc
export LD_PRELOAD="$ASAN_LIB $UBSAN_LIB"
export ASAN_OPTIONS="detect_leaks=0:abort_on_error=1"
export UBSAN_OPTIONS="print_stacktrace=1:halt_on_error=1"
export JAX_PLATFORMS=cpu

echo "== sanitize.sh: building sanitized native libs + running subsets =="

rc=0
# shred/gather + native codec + verify/thrift subsets.  The one
# deselect is the pre-existing ENVIRONMENTAL failure (python zstandard
# module absent in this container — fails identically without the
# sanitizer; see CHANGES.md tier-1 baseline notes), not a sanitizer
# finding.
python -m pytest \
    tests/test_wire_shred.py tests/test_native.py tests/test_verify.py \
    --deselect tests/test_native.py::test_native_encoder_delta_identity \
    -q -p no:cacheprovider || rc=1

# offset-validation pins from the batch-ingest suite (the PR-6 crash
# class), without spinning the full streaming scenarios under ASan
python -m pytest tests/test_batch_ingest.py \
    -k "columnarize_buffer or byte_identical" \
    -q -p no:cacheprovider || rc=1

# nogil page-assembly subset (ISSUE 10): the lowered-table validation
# contract + byte-identity pins run against the SANITIZED _kpw_assemble
# build, so a table the validator wrongly admits traps as an ASan abort
# instead of a silent OOB gather
python -m pytest tests/test_assemble.py \
    -k "malformed or valid_plan or stats_require or unsupported or byte_identical" \
    -q -p no:cacheprovider || rc=1

# fused nested pipeline subset (ISSUE 14): the batched nested decoder +
# nested_fill geometry contract and the fused/ctypes/oracle byte-identity
# matrix, against the SANITIZED builds — a span-gather or level-widening
# OOB traps instead of reading a neighboring arena page (the streaming
# writer suites are excluded: thread-heavy, covered by tier-1)
python -m pytest tests/test_nested_shred.py tests/test_nested_fused.py \
    -k "not writer_streams" \
    -q -p no:cacheprovider || rc=1

# adaptive-encodings subset (ISSUE 16): the BYTE_STREAM_SPLIT
# oracle/ctypes/device byte-identity matrix and the cross-backend
# adaptive file pin run against the SANITIZED libs, so a transpose
# stride bug traps as an ASan abort instead of shipping scrambled planes
python -m pytest tests/test_encodings_adaptive.py \
    -k "bss or backends" \
    -q -p no:cacheprovider || rc=1

# seeded mutation fuzz: thrift reader, verifier page walk, offset-table
# validator — zero crashes/sanitizer findings required
python -m tools.fuzz --seed "$SEED" --iters "$FUZZ_ITERS" || rc=1

if [ "$rc" -ne 0 ]; then
    echo "sanitize.sh: FAILURES under the sanitizer build (see above)"
    exit 1
fi
echo "sanitize.sh: sanitized subsets + fuzz (iters=$FUZZ_ITERS, seed=$SEED) all clean"
echo "(TSan leg runs separately: bash tools/sanitize.sh --tsan)"
