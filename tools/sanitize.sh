#!/usr/bin/env bash
# ASan/UBSan leg of the correctness tooling (ISSUE 7): build the native
# shredders/codecs with -fsanitize=address,undefined and run the
# shred/gather/offset-validation and verify/thrift test subsets plus the
# seeded mutation-fuzz harness under them.  Every native OOB/UB the
# hardening PRs fixed by hand (thrift CompactReader, the shred_flat_buf
# malformed-offset read) traps loudly here instead of reading garbage.
#
# Usage:  bash tools/sanitize.sh [--smoke]
#   --smoke  : smaller fuzz iteration count (CI entry point; default is
#              the committed regression configuration below)
#
# Skip policy: when g++ or the sanitizer runtimes are absent the script
# prints an UNMISSABLE notice and exits 0 — a missing toolchain must
# never silently pass for "sanitizers ran clean" (the notice is the
# difference), and must not fail CI on boxes that legitimately lack it.
#
# Mechanics worth knowing (cost us a debugging session each):
#   * the host python is NOT instrumented, so libasan/libubsan must be
#     LD_PRELOADed or the sanitized .so fails to load;
#   * PYTHONMALLOC=malloc is REQUIRED for ASan to see Python-owned
#     buffers — pymalloc arenas bypass malloc interception, and without
#     this an out-of-bounds read into a neighboring arena page is
#     silent (verified with a deliberate OOB through gather_buf);
#   * sanitized artifacts cache as _kpw_*_san.so next to the normal
#     ones (kpw_tpu/native/build.py KPW_NATIVE_SANITIZE=1), so this
#     script never pollutes the fast build.

set -u -o pipefail
cd "$(dirname "$0")/.."

FUZZ_ITERS=2000          # committed regression configuration (seed is
SEED=20260803            # tools/fuzz.py DEFAULT_SEED — keep in sync)
if [ "${1:-}" = "--smoke" ]; then
    FUZZ_ITERS=500
fi

loud_skip() {
    echo "=============================================================="
    echo "SANITIZER SMOKE SKIPPED (NOT PASSED): $1"
    echo "The ASan/UBSan leg did not run. Install g++ with libasan/"
    echo "libubsan to exercise it. This is a loud no-op, never a pass."
    echo "=============================================================="
    exit 0
}

command -v g++ >/dev/null 2>&1 || loud_skip "g++ not found"
ASAN_LIB="$(g++ -print-file-name=libasan.so)"
UBSAN_LIB="$(g++ -print-file-name=libubsan.so)"
[ -e "$ASAN_LIB" ] || loud_skip "libasan.so not found ($ASAN_LIB)"
[ -e "$UBSAN_LIB" ] || loud_skip "libubsan.so not found ($UBSAN_LIB)"

export KPW_NATIVE_SANITIZE=1
export PYTHONMALLOC=malloc
export LD_PRELOAD="$ASAN_LIB $UBSAN_LIB"
export ASAN_OPTIONS="detect_leaks=0:abort_on_error=1"
export UBSAN_OPTIONS="print_stacktrace=1:halt_on_error=1"
export JAX_PLATFORMS=cpu

echo "== sanitize.sh: building sanitized native libs + running subsets =="

rc=0
# shred/gather + native codec + verify/thrift subsets.  The one
# deselect is the pre-existing ENVIRONMENTAL failure (python zstandard
# module absent in this container — fails identically without the
# sanitizer; see CHANGES.md tier-1 baseline notes), not a sanitizer
# finding.
python -m pytest \
    tests/test_wire_shred.py tests/test_native.py tests/test_verify.py \
    --deselect tests/test_native.py::test_native_encoder_delta_identity \
    -q -p no:cacheprovider || rc=1

# offset-validation pins from the batch-ingest suite (the PR-6 crash
# class), without spinning the full streaming scenarios under ASan
python -m pytest tests/test_batch_ingest.py \
    -k "columnarize_buffer or byte_identical" \
    -q -p no:cacheprovider || rc=1

# nogil page-assembly subset (ISSUE 10): the lowered-table validation
# contract + byte-identity pins run against the SANITIZED _kpw_assemble
# build, so a table the validator wrongly admits traps as an ASan abort
# instead of a silent OOB gather
python -m pytest tests/test_assemble.py \
    -k "malformed or valid_plan or stats_require or unsupported or byte_identical" \
    -q -p no:cacheprovider || rc=1

# seeded mutation fuzz: thrift reader, verifier page walk, offset-table
# validator — zero crashes/sanitizer findings required
python -m tools.fuzz --seed "$SEED" --iters "$FUZZ_ITERS" || rc=1

if [ "$rc" -ne 0 ]; then
    echo "sanitize.sh: FAILURES under the sanitizer build (see above)"
    exit 1
fi
echo "sanitize.sh: sanitized subsets + fuzz (iters=$FUZZ_ITERS, seed=$SEED) all clean"
