"""Sort-based dictionary build on device (first-occurrence order).

parquet-mr builds dictionaries with a per-record Java hash map inside
DictionaryValuesWriter (reference ParquetFile.java:97-99 funnels every record
through it).  A hash map is the wrong shape for a TPU; the device-native
formulation is a segmented sort:

  1. lexsort by (validity, key_hi, key_lo, position) — equal values become
     adjacent, ties keep original order, padding sinks to the end;
  2. "new unique" flags + prefix sum -> dense unique ids in value order;
  3. scatter-min of positions per unique id -> first-occurrence position;
  4. argsort those positions -> the reorder that makes the dictionary match
     the CPU oracle's first-occurrence order exactly;
  5. scatter ranks back through the sort permutation -> per-row indices.

Keys are the value's *bit pattern* split into (hi, lo) uint32 halves, so no
64-bit arithmetic is needed on device (TPU int64 is emulated) and float
uniqueness is bitwise — identical to the CPU oracle
(core.encodings.dictionary_build).

Everything is O(n log n) in static shapes; `count` is a traced scalar so one
compiled program serves every batch in the same padding bucket.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .packing import pad_bucket


@functools.partial(jax.jit, static_argnums=(3,))
def _dict_build(hi: jax.Array, lo: jax.Array, count, wide: bool):
    n = lo.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    valid = pos < count
    invalid = (~valid).astype(jnp.int32)
    if wide:
        order = jnp.lexsort((pos, lo, hi, invalid))
        shi = hi[order]
    else:
        order = jnp.lexsort((pos, lo, invalid))
    slo = lo[order]
    spos = pos[order]
    svalid = valid[order]

    same = slo[1:] == slo[:-1]
    if wide:
        same = same & (shi[1:] == shi[:-1])
    prev_same = jnp.concatenate([jnp.zeros((1,), bool), same])
    is_new = svalid & ~prev_same
    uid = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    k = uid[n - 1] + 1  # pads inherit the last uid via cumsum; count==0 -> 0

    safe_uid = jnp.where(svalid, uid, n)
    first_pos = jnp.full(n + 1, n, jnp.int32).at[safe_uid].min(spos, mode="drop")[:n]
    occ_order = jnp.argsort(first_pos)  # stable: uniques by first occurrence, pads last
    rank = jnp.zeros(n, jnp.int32).at[occ_order].set(pos)
    idx_sorted = rank[jnp.clip(uid, 0, n - 1)]
    indices = jnp.zeros(n, jnp.uint32).at[spos].set(idx_sorted.astype(jnp.uint32))
    occ_first = first_pos[occ_order]
    return occ_first, indices, k


def split_keys(arr: np.ndarray) -> tuple[np.ndarray | None, np.ndarray]:
    """Bit-pattern (hi, lo) uint32 keys for a fixed-width column; hi is None
    for 32-bit types."""
    if arr.dtype.itemsize == 4:
        return None, arr.view(np.uint32)
    u = arr.view(np.uint64)
    return (u >> np.uint64(32)).astype(np.uint32), (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)


class DictBuildHandle:
    """In-flight device dictionary build for one column chunk."""

    def __init__(self, values: np.ndarray):
        n = len(values)
        bucket = pad_bucket(n)
        hi, lo = split_keys(np.ascontiguousarray(values))
        lo_p = np.zeros(bucket, np.uint32)
        lo_p[:n] = lo
        wide = hi is not None
        if wide:
            hi_p = np.zeros(bucket, np.uint32)
            hi_p[:n] = hi
        else:
            hi_p = lo_p  # unused operand placeholder
        self.values = values
        self.n = n
        self.occ_first, self.indices, self._k = _dict_build(
            jnp.asarray(hi_p), jnp.asarray(lo_p), jnp.int32(n), wide)

    def result(self) -> tuple[np.ndarray, jax.Array]:
        """Block on the unique count and return (dict_values, device indices).
        dict_values is in first-occurrence order, matching the CPU oracle."""
        k = int(self._k)
        occ = np.asarray(self.occ_first)[:k]
        return self.values[occ], self.indices
