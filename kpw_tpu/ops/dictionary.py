"""Sort-based dictionary build on device (ascending bit-pattern order).

parquet-mr builds dictionaries with a per-record Java hash map inside
DictionaryValuesWriter (reference ParquetFile.java:97-99 funnels every record
through it).  A hash map is the wrong shape for a TPU; the device-native
formulation is a segmented sort:

  1. sort by (key_hi, key_lo, position), invalid slots lifted to the max
     key — equal values become adjacent, padding sinks to the end;
  2. "new unique" flags + prefix sum -> dense unique ids; since the sort is
     ascending, the dense id IS the final dictionary index (the canonical
     dictionary order is ascending bit pattern — see
     core.encodings.dictionary_build, the byte-identical CPU oracle);
  3. one more sort on (rank, keys) compacts the unique keys to the front,
     so the host only ever transfers ~k dictionary entries, not n values;
  4. one more sort on (position, id) unscrambles per-row indices back to
     row order — sorts, never gathers/scatters, which the TPU vector units
     pay for catastrophically (measured 13x on a v5e for this kernel).

Keys are the value's *bit pattern* split into (hi, lo) uint32 halves, so no
64-bit arithmetic is needed on device (TPU int64 is emulated) and float
uniqueness is bitwise.

The build is *column-batched*: all same-width columns of a row group are
stacked into one (C, N) array and run through a single vmapped program —
one XLA dispatch and one host sync for a whole 64-column row group instead
of 64 (the TPU-native answer to the reference encoding columns one at a
time per record).  Everything is O(n log n) in static shapes; `count` is a
traced scalar so one compiled program serves every batch in a padding
bucket.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .packing import (
    packed_reorder as _packed_reorder,
    pad_bucket,
    prefers_scatters as _prefers_scatters,
)


def _dict_build_one(hi, lo, count, wide: bool,
                    scatters: bool | None = None,
                    val_bits: int | None = None):
    """Fused sort-based build-and-rank, gather/scatter-free (TPU vector
    units pay catastrophically for per-element scatters — see
    parallel/dict_merge.default_rank_method): value+position sort, rank
    compaction sort, position-unscramble sort.  Same shape as the flagship
    ``encode_step_single`` kernel.  ``indices``/``dlo`` tails past
    ``count``/``k`` are unspecified (masked by callers).

    ``val_bits`` (narrow path only, and only when ``val_bits + pos_bits <=
    32``) is a static host-known bound: all valid ``lo`` values are
    ``< 2**val_bits``.  The build then rides ONE single-operand u32 sort of
    ``(value << pos_bits) | pos`` — stable by construction, positions being
    unique — and the compaction sorts u16 when the bound fits 16 bits: the
    sub-32-bit sort-key reformulation of VERDICT r3 next #1 (same math as
    parallel/sharded.encode_step_single)."""
    n = lo.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    valid = pos < count
    big = jnp.uint32(0xFFFFFFFF)
    pos_bits = max((n - 1).bit_length(), 1)
    packed16 = (not wide and val_bits is not None
                and val_bits + pos_bits <= 32)
    llo = jnp.where(valid, lo, big)  # invalids sort to the tail
    # is_stable is load-bearing: a VALID value whose bit pattern equals the
    # 0xFFFFFFFF pad sentinel (int -1, some NaNs) ties with the pads, and
    # the prefix-validity claim below (sval = valid) holds only if
    # stability keeps the valid entries (earlier input positions) ahead of
    # the pads on that tie.
    if wide:
        lhi = jnp.where(valid, hi, big)
        shi, slo, spos = jax.lax.sort((lhi, llo, pos), num_keys=2,
                                      is_stable=True)
    elif packed16:
        # a valid packed key can only equal the sentinel when the value is
        # 2**val_bits - 1 at pos n-1 with the bits exactly filling 32; pos
        # n-1 valid means count == n, so no invalid slot exists to collide
        key = jnp.where(valid, (lo << pos_bits) | pos.astype(jnp.uint32), big)
        s = jnp.sort(key)
        slo = s >> pos_bits
        spos = (s & jnp.uint32((1 << pos_bits) - 1)).astype(jnp.int32)
    else:
        slo, spos = jax.lax.sort((llo, pos), num_keys=1, is_stable=True)

    # valid is a prefix predicate, so post-sort validity is the same mask
    sval = valid
    same = slo[1:] == slo[:-1]
    if wide:
        same = same & (shi[1:] == shi[:-1])
    prev_same = jnp.concatenate([jnp.zeros((1,), bool), same])
    is_new = sval & ~prev_same
    uid = jnp.cumsum(is_new.astype(jnp.int32)) - 1
    k = jnp.sum(is_new.astype(jnp.int32))

    # ascending sort => uid is the dictionary slot.  Compaction and the
    # row-order unscramble are hardware-selected (same principle as
    # parallel/dict_merge.default_rank_method): CPU scatters are cheap and
    # variadic sorts are not, TPU is the reverse.
    if _prefers_scatters() if scatters is None else scatters:
        indices = jnp.zeros(n, jnp.uint32).at[spos].set(uid.astype(jnp.uint32))
        slot = jnp.where(is_new, uid, n)
        dlo = jnp.zeros(n + 1, jnp.uint32).at[slot].set(slo, mode="drop")[:n]
        if wide:
            dhi = jnp.zeros(n + 1, jnp.uint32).at[slot].set(shi,
                                                            mode="drop")[:n]
        else:
            dhi = dlo  # unused placeholder
        return dhi, dlo, indices, k
    # TPU: compact keys to the front and unscramble uid by original
    # position — sorts, never scatters.  Where shapes permit, the two
    # reorders ride XLA's SINGLE-OPERAND sort fast path instead of
    # variadic sorts (same reformulation as the flagship kernel,
    # parallel/sharded.encode_step_single — each variadic (key, payload)
    # sort costs ~2x the single-key sort on the v5e comparator network):
    # the narrow dictionary is sorted directly from its masked values, and
    # (pos, uid) pack into one u32 key when pos_bits + uid_bits <= 32.
    if wide:
        rank = jnp.where(is_new, uid, n)
        _, dhi, dlo = jax.lax.sort((rank, shi, slo), num_keys=1)
    elif packed16 and val_bits <= 16:
        # u16 compaction: half the comparator payload; a real 0xFFFF value
        # shares the pad's bit pattern and still lands at slot k-1
        dlo = jnp.sort(jnp.where(is_new, slo, big).astype(jnp.uint16)
                       ).astype(jnp.uint32)
        dhi = dlo  # unused placeholder
    else:
        dlo = jnp.sort(jnp.where(is_new, slo, big))
        dhi = dlo  # unused placeholder
    pos_bits = max((n - 1).bit_length(), 1)
    if 2 * pos_bits <= 32:  # uid < k <= n needs at most pos_bits bits
        suid, _ = _packed_reorder(spos, uid, pos_bits)
    else:
        _, suid = jax.lax.sort((spos, uid), num_keys=1)
    return dhi, dlo, suid.astype(jnp.uint32), k


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _dict_build_batch(hi, lo, counts, wide: bool,
                      scatters: bool | None = None,
                      val_bits: int | None = None):
    """Vmapped over columns: hi/lo (C, N), counts (C,).  ``scatters``
    overrides the hardware selection (None = auto; a static jit arg so
    both branches stay testable on any platform); ``val_bits`` engages the
    packed sub-32-bit build (see :func:`_dict_build_one`)."""
    return jax.vmap(
        lambda h, l, c: _dict_build_one(h, l, c, wide, scatters, val_bits))(
            hi, lo, counts)


def _dict_build_bins_one(ids, count, R: int):
    """Sort-free dictionary build for bounded-range non-negative ints:
    ``ids`` are (value - column_min) offsets < R.  Presence scatter + prefix
    sum replaces the O(n log n) sort with O(n + R) VPU work — ascending
    order falls out of the bin layout for free."""
    n = ids.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    valid = pos < count
    safe_ids = jnp.where(valid, ids, R).astype(jnp.int32)
    present = jnp.zeros(R + 1, jnp.int32).at[safe_ids].set(1, mode="drop")[:R]
    kpre = jnp.cumsum(present)
    indices = (kpre[jnp.clip(safe_ids, 0, R - 1)] - 1).astype(jnp.uint32)
    k = kpre[R - 1]
    slot = jnp.where(present > 0, kpre - 1, R)
    dkey = jnp.zeros(R + 1, jnp.uint32).at[slot].set(
        jnp.arange(R, dtype=jnp.uint32), mode="drop")[:R]
    return dkey, indices, k


@functools.partial(jax.jit, static_argnums=(2,))
def _dict_build_bins_batch(ids, counts, R: int):
    """Vmapped over columns: ids (C, N), counts (C,)."""
    return jax.vmap(lambda i, c: _dict_build_bins_one(i, c, R))(ids, counts)


@functools.partial(jax.jit, static_argnums=(2,))
def _trim_keys(dhi, dlo, cap: int):
    """Static-size slice of the compacted dictionary keys for host transfer."""
    return (jax.lax.dynamic_slice(dhi, (0, 0), (dhi.shape[0], cap)),
            jax.lax.dynamic_slice(dlo, (0, 0), (dlo.shape[0], cap)))


@functools.partial(jax.jit, static_argnums=(1,))
def _trim_one(d, cap: int):
    return jax.lax.dynamic_slice(d, (0, 0), (d.shape[0], cap))


def split_keys(arr: np.ndarray) -> tuple[np.ndarray | None, np.ndarray]:
    """Bit-pattern (hi, lo) uint32 keys for a fixed-width column; hi is None
    for 32-bit types."""
    if arr.dtype.itemsize == 4:
        return None, arr.view(np.uint32)
    u = arr.view(np.uint64)
    return (u >> np.uint64(32)).astype(np.uint32), (u & np.uint64(0xFFFFFFFF)).astype(np.uint32)


def join_keys(hi: np.ndarray, lo: np.ndarray, dtype: np.dtype) -> np.ndarray:
    """Inverse of :func:`split_keys`: reassemble values from key halves."""
    if dtype.itemsize == 4:
        return lo.astype(np.uint32).view(dtype)
    u = (hi.astype(np.uint64) << np.uint64(32)) | lo.astype(np.uint64)
    return u.view(dtype)


class BatchDictBuild:
    """One launched dictionary build covering several same-width columns.

    ``columns`` is a list of np arrays with identical length; all are packed
    into one (C, bucket) device batch and one vmapped program.  ``result(i)``
    blocks (once, for the whole batch) and returns column i's
    (dict_values, device_indices_row) in CPU-oracle (ascending) order.

    ``bases`` (with ``val_bits``) engages the packed sub-32-bit sort build:
    a list of per-column (base, stride) pairs.  Every column must be a
    non-negative integer column (so ascending value order equals ascending
    bit-pattern order, the oracle's dictionary order) with
    ``(max - base) / stride < 2**val_bits`` and stride dividing every
    ``value - base`` exactly (stride 1, or the gcd the planner measured);
    the kernel sorts the affine offsets and ``result`` maps them back as
    ``base + stride * offset``.  Works for 64-bit columns too — a
    narrow-range int64 column skips the wide hi/lo variadic sort entirely.
    """

    def __init__(self, columns: list[np.ndarray], wide: bool,
                 bases: list[tuple[int, int]] | None = None,
                 val_bits: int | None = None):
        self.dtypes = [c.dtype for c in columns]
        self.wide = wide
        self.bases = bases
        C = len(columns)
        n = len(columns[0])
        self.n = n
        bucket = pad_bucket(n)
        self.bucket = bucket
        lo_p = np.zeros((C, bucket), np.uint32)
        hi_p = np.zeros((C, bucket), np.uint32) if wide else lo_p
        for c, arr in enumerate(columns):
            if bases is not None:
                base, stride = bases[c]
                off = (np.ascontiguousarray(arr).astype(np.uint64)
                       - np.uint64(base))
                if stride != 1:
                    off //= np.uint64(stride)
                lo_p[c, :n] = off.astype(np.uint32)
                continue
            hi, lo = split_keys(np.ascontiguousarray(arr))
            lo_p[c, :n] = lo
            if wide:
                hi_p[c, :n] = hi
        counts = np.full(C, n, np.int32)
        self.dhi, self.dlo, self.indices, self._k = _dict_build_batch(
            jnp.asarray(hi_p), jnp.asarray(lo_p), jnp.asarray(counts),
            False if bases is not None else wide, None,
            val_bits if bases is not None else None)
        self._k_host: np.ndarray | None = None
        self._keys_host: tuple[np.ndarray, np.ndarray] | None = None

    def unique_counts(self) -> np.ndarray:
        """Per-column unique counts; first call syncs the batch."""
        if self._k_host is None:
            self._k_host = np.asarray(self._k)
        return self._k_host

    def _key_tables(self) -> tuple[np.ndarray, np.ndarray]:
        if self._keys_host is None:
            kmax = int(self.unique_counts().max()) if len(self.dtypes) else 0
            cap = min(self.bucket, pad_bucket(max(kmax, 1)))
            dhi, dlo = _trim_keys(self.dhi, self.dlo, cap)
            self._keys_host = (np.asarray(dhi), np.asarray(dlo))
        return self._keys_host

    def _join(self, i: int, k: int, dhi: np.ndarray, dlo: np.ndarray) -> np.ndarray:
        if self.bases is not None:  # affine offsets: base + stride * offset
            base, stride = self.bases[i]
            return (dlo[i, :k].astype(np.uint64) * np.uint64(stride)
                    + np.uint64(base)).astype(self.dtypes[i])
        return join_keys(dhi[i, :k], dlo[i, :k], self.dtypes[i])

    def result(self, i: int) -> tuple[np.ndarray, jax.Array]:
        k = int(self.unique_counts()[i])
        dhi, dlo = self._key_tables()
        return self._join(i, k, dhi, dlo), self.indices[i]

    # -- sync-free accessors for the fused row-group planner ---------------
    def counts_device(self) -> jax.Array:
        return self._k

    def key_tables_device(self, cap: int):
        """Trimmed key tables as *device* arrays (no host sync); the planner
        folds them into one bulk readback."""
        return _trim_keys(self.dhi, self.dlo, min(cap, self.bucket))

    def values_from_tables(self, i: int, k: int, tables) -> np.ndarray:
        dhi, dlo = tables
        return self._join(i, k, dhi, dlo)


class BinDictBuild:
    """Bounded-range batch: sort-free binning build (see _dict_build_bins_one).
    ``bases`` holds per-column (base, stride) affine transforms; only valid
    for non-negative integer columns whose (max - base) / stride < R with
    stride dividing every value - base — then ascending offset order equals
    ascending bit-pattern order, so the output matches the CPU oracle
    exactly.  Uploads 4 bytes/row regardless of the column's width (offsets,
    not values)."""

    def __init__(self, columns: list[np.ndarray],
                 bases: list[tuple[int, int]], R: int):
        self.dtypes = [c.dtype for c in columns]
        self.bases = bases
        self.R = R
        C = len(columns)
        n = len(columns[0])
        self.n = n
        bucket = pad_bucket(n)
        self.bucket = bucket
        ids = np.zeros((C, bucket), np.uint32)
        for c, arr in enumerate(columns):
            base, stride = bases[c]
            off = arr.astype(np.uint64) - np.uint64(base)
            if stride != 1:
                off //= np.uint64(stride)
            ids[c, :n] = off.astype(np.uint32)
        counts = np.full(C, n, np.int32)
        self.dkey, self.indices, self._k = _dict_build_bins_batch(
            jnp.asarray(ids), jnp.asarray(counts), R)
        self._k_host: np.ndarray | None = None
        self._dkey_host: np.ndarray | None = None

    def unique_counts(self) -> np.ndarray:
        if self._k_host is None:
            self._k_host = np.asarray(self._k)
        return self._k_host

    def _key_table(self) -> np.ndarray:
        if self._dkey_host is None:
            kmax = int(self.unique_counts().max()) if len(self.dtypes) else 0
            cap = min(self.R, pad_bucket(max(kmax, 1)))
            self._dkey_host = np.asarray(_trim_one(self.dkey, cap))
        return self._dkey_host

    def result(self, i: int) -> tuple[np.ndarray, jax.Array]:
        k = int(self.unique_counts()[i])
        base, stride = self.bases[i]
        offsets = self._key_table()[i, :k].astype(np.uint64)
        dict_values = (offsets * np.uint64(stride)
                       + np.uint64(base)).astype(self.dtypes[i])
        return dict_values, self.indices[i]

    # -- sync-free accessors for the fused row-group planner ---------------
    def counts_device(self) -> jax.Array:
        return self._k

    def key_tables_device(self, cap: int):
        return _trim_one(self.dkey, min(cap, self.R))

    def values_from_tables(self, i: int, k: int, tables) -> np.ndarray:
        base, stride = self.bases[i]
        offsets = tables[i, :k].astype(np.uint64)
        return (offsets * np.uint64(stride)
                + np.uint64(base)).astype(self.dtypes[i])


RANGE_MAX = 1 << 20  # largest bin table the sort-free path will allocate


def _int_stats(arr: np.ndarray):
    """(vmin, vmax, gcd_of_offsets | None) — one fused native pass
    (kpw_int_stats_*) when the C++ library is available for the dtype,
    else numpy min/max with the gcd left to the lazy sample-rejecting
    :func:`_gcd_stride` pass (None marks it not-yet-computed)."""
    try:
        from ..native import lib as _native_lib

        L = _native_lib()
    except Exception:
        L = None
    if L is not None:
        try:
            st = L.int_stats(arr)
        except Exception:
            st = None
        if st is not None:
            return st
    return int(arr.min()), int(arr.max()), None


def _gcd_stride(arr: np.ndarray, vmin: int, span: int, limit: int):
    """Quantization stride for the affine offset paths: g = gcd of
    (arr - vmin), engaged when the raw span misses ``limit`` but span // g
    fits — quantized columns (currency cents on a fixed tick, timestamps
    at a coarser granularity than their unit) are common and their offsets
    compress to span/g dictionary slots.  A cheap sound rejector runs
    first: the gcd over ALL offsets divides the gcd over any subset, so a
    sample gcd of 1 (or one too small to close the gap) disproves
    eligibility without the full pass.  Returns g > 1, or None."""
    if span <= 0:
        return None
    t = arr.dtype.type
    g = int(np.gcd.reduce(arr[:1024] - t(vmin)))
    # an all-constant prefix gives sample gcd 0 (everything divides 0):
    # that is inconclusive, not a rejection — only a nonzero sample gcd
    # that is 1 or too small to close the gap disproves eligibility
    if g != 0 and (g <= 1 or span // g >= limit):
        return None
    g = int(np.gcd.reduce(arr - t(vmin)))
    return g if g > 1 and span // g < limit else None


def affine_stride(arr: np.ndarray, vmin: int, span: int, g_all, limit: int):
    """Eligibility decision for the affine/bounded offset paths, shared by
    :func:`build_dictionaries`' mode selection and the mesh encoder's
    bounded-route consult (parallel/mesh_encoder._bounded_route) so the
    two cannot drift: 1 when the raw span fits ``limit``; the gcd stride
    g > 1 when ``span // g`` fits (from the fused native pass when
    available — ``g_all`` — else the lazy sample-rejecting
    :func:`_gcd_stride`); None when ineligible."""
    if span < limit:
        return 1
    if g_all is not None:
        return g_all if g_all > 1 and span // g_all < limit else None
    return _gcd_stride(arr, vmin, span, limit)


def build_dictionaries(columns: list[np.ndarray]):
    """Launch dictionary builds for a row group's columns, batching columns
    that can share one vmapped program.  Returns one handle per column with
    ``.unique_counts()[j]``/``.result(j)`` semantics as (batch, j) pairs.

    Mode selection per column:
    - CPU: non-negative ints with (max - min) < RANGE_MAX -> binning batch,
      grouped by bin-table bucket (sort-free, O(n + R));
    - TPU: non-negative ints whose (max - min) offsets fit the packed
      sub-32-bit sort key (val_bits + pos_bits <= 32, val_bits capped at
      16) -> packed-sort batch — ONE single-operand build sort + u16
      compaction instead of the variadic sort (VERDICT r3 next #1; covers
      64-bit columns too, offsets being narrow regardless of value width);
    - either affine path also engages through a gcd stride when the raw
      span is too wide but (max - min) / gcd(values - min) fits (offsets
      are divided on host, values reconstruct as base + stride * offset);
    - everything else -> lexsort batch, grouped by key width.
    """
    groups: dict = {}
    metas: list = [None] * len(columns)
    use_bins = _prefers_scatters()
    for i, arr in enumerate(columns):
        # group key carries the EXACT length: a batch stacks columns into one
        # (C, N) array, so all members must share N (nullable columns with
        # different null counts land in different batches)
        mode = None
        if arr.dtype.kind in "iu" and len(arr):
            vmin, vmax, g_all = _int_stats(arr)
            span = vmax - vmin

            def stride_for(limit: int):
                return affine_stride(arr, vmin, span, g_all, limit)

            if use_bins:
                if vmin >= 0:
                    g = stride_for(RANGE_MAX)
                    if g:
                        mode = ("bins", len(arr), pad_bucket(span // g + 1))
                        metas[i] = (vmin, g)
            else:
                vbits = min(16, 32 - max((pad_bucket(len(arr)) - 1)
                                         .bit_length(), 1))
                if vmin >= 0 and vbits >= 1:
                    g = stride_for(1 << vbits)
                    if g:
                        mode = ("sort16", len(arr), vbits)
                        metas[i] = (vmin, g)
        if mode is None:
            mode = ("sort", len(arr), arr.dtype.itemsize == 8)
        groups.setdefault(mode, []).append(i)
    handles: list = [None] * len(columns)
    for mode, idxs in groups.items():
        cols = [columns[i] for i in idxs]
        if mode[0] == "bins":
            batch = BinDictBuild(cols, [metas[i] for i in idxs], mode[2])
        elif mode[0] == "sort16":
            batch = BatchDictBuild(cols, wide=False,
                                   bases=[metas[i] for i in idxs],
                                   val_bits=mode[2])
        else:
            batch = BatchDictBuild(cols, wide=mode[2])
        for j, i in enumerate(idxs):
            handles[i] = (batch, j)
    return handles


class DictBuildHandle:
    """Single-column convenience wrapper over build_dictionaries."""

    def __init__(self, values: np.ndarray):
        self.values = values
        self.n = len(values)
        self._batch, self._j = build_dictionaries([values])[0]

    def result(self) -> tuple[np.ndarray, jax.Array]:
        """Block on the unique count and return (dict_values, device indices).
        dict_values is in ascending bit-pattern order, matching the CPU
        oracle."""
        return self._batch.result(self._j)
