"""Device encoding of repetition/definition level streams (BASELINE.md
config 5: nested list<struct> rep/def-level RLE on TPU).

Level streams are tiny-width integers (bit_width(max_level), usually 1-3
bits) with two regimes:

- high-entropy streams take the oracle's pure bit-pack fast path
  (core.encodings.rle_hybrid_encode) — served by the same device bit-pack
  program as dictionary indices (ops.packing.pack_pages_multi, pallas-backed
  on TPU);
- run-dominated streams (the common case: def levels are mostly max_def)
  take the mixed RLE path.  There the O(n) work is the run *scan*; the
  assembly is O(runs).  The stats pass (classification + run-count
  sizing) is scan-FREE — windowed shifts of the run-start mask,
  ops.packing._run_long_stats; the extraction pass labels runs on device
  (cumsum run ids, hardware-selected scatter/sort compaction — see
  ops.packing._run_scan/compact_by_rank — vmapped over pages; run
  lengths fall out as diffs of compacted end positions, so the labeling
  max-scan is dead code XLA removes) and only the compact run list is
  transferred, which the host replays through
  core.encodings.rle_hybrid_from_runs for a byte-identical stream.

Both programs window into one stacked (K, maxN) array of every level stream
in the row group, so the whole group costs two round trips regardless of
column count — same planner shape as the value path (ops.backend).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .packing import (_run_long_stats, _window_slice, compact_by_rank,
                      window_run_scan)


@functools.partial(jax.jit, static_argnums=(4,))
def level_stats_multi(levels_all: jax.Array, stream_ids: jax.Array,
                      starts: jax.Array, counts: jax.Array, bucket: int):
    """Per page window: (long_sum, n_runs) — ``long_sum`` is the total length
    of runs >= 8 (the oracle's bit-pack-vs-mixed decision input) and
    ``n_runs`` the run count (sizes the phase-B run gather)."""
    padded = jnp.pad(levels_all, ((0, 0), (0, bucket)))

    def one(sid, start, count):
        v, valid = _window_slice(padded, sid, start, count, bucket)
        long_sum, n_runs, _ = _run_long_stats(v, valid)
        return long_sum, n_runs

    return jax.vmap(one)(stream_ids, starts, counts)


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def level_runs_multi(levels_all: jax.Array, stream_ids: jax.Array,
                     starts: jax.Array, counts: jax.Array, bucket: int,
                     run_bucket: int, level_bits: int = 16):
    """Extract each page window's run list: (run_vals (P, run_bucket) uint32,
    run_lens (P, run_bucket) int32).  ``run_bucket`` must be >= the page's
    n_runs from :func:`level_stats_multi`; excess slots are zero.
    ``level_bits`` is a static bound on the level VALUES' bit width (the
    planner passes the streams' actual width, 1-3 bits for real schemas) —
    small enough bounds let the whole compaction ride ONE single-operand
    u32 sort per window (rank+value+length in one packed key; measured on
    v5e: the run-extraction program dominated the level path at ~8 ms of
    sort work per 448-window step before the packing)."""
    padded = jnp.pad(levels_all, ((0, 0), (0, bucket)))

    def one(sid, start, count):
        v, _, run_id, _, is_end = window_run_scan(
            padded, sid, start, count, bucket)
        # one compaction keyed on run ENDS covers both outputs: a run's
        # value is constant, so v at the end position is the run value.
        # Run ids are a dense prefix: hardware-selected scatter/sort
        # (see compact_by_rank).  Lengths are NOT carried through the
        # sort: runs partition the valid prefix, so length_j = end_pos_j -
        # end_pos_{j-1} (end_pos_{-1} = -1) — carrying the END POSITION
        # and diffing the compacted slots lets XLA dead-code-eliminate
        # window_run_scan's associative max-scan (run_len_here's only use
        # here) from this program entirely.
        pos = jnp.arange(bucket, dtype=jnp.int32)
        end_rank = jnp.where(is_end, run_id, run_bucket)
        run_vals, end_pos = compact_by_rank(
            end_rank, (v, pos), run_bucket,
            value_bits=(level_bits, max((bucket - 1).bit_length(), 1)))
        n_ends = jnp.sum(is_end.astype(jnp.int32))
        keep = jnp.arange(run_bucket, dtype=jnp.int32) < n_ends
        prev = jnp.concatenate([jnp.full((1,), -1, jnp.int32),
                                end_pos[:-1].astype(jnp.int32)])
        run_lens = jnp.where(keep, end_pos.astype(jnp.int32) - prev, 0)
        return run_vals, run_lens

    return jax.vmap(one)(stream_ids, starts, counts)
