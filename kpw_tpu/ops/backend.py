"""TpuChunkEncoder — the TPU EncoderBackend.

Drop-in for the CPU reference encoder at the pluggable boundary described in
SURVEY.md §1 (the reference funnels every record through
``ParquetFile.write`` -> parquet-mr ColumnWriter, ParquetFile.java:59-62;
here a whole column chunk is encoded at once).  Output bytes are identical to
``CpuChunkEncoder`` — the tests assert file-level byte equality — but the hot
math runs on device:

- dictionary build: sorted-unique kernel (ops.dictionary), launched for ALL
  columns of a row group up front (``prepare``/``encode_many``) so device
  compute overlaps host page assembly — the TPU-native version of the
  reference's thread-per-file parallelism (KafkaProtoParquetWriter.java:40-41).
- index pages: device bit-packing + run-stats (ops.packing); the rare
  long-run pages fall back to the host RLE assembler to keep the stream
  byte-identical to the oracle.

Strings (BYTE_ARRAY) keep the host hash-map dictionary — variable-length
bytes don't belong on the MXU/VPU; their dictionary *indices* are still
integers and could be device-packed, which matters only for very large
string pages (future work, SURVEY.md §7 hard part f).
"""

from __future__ import annotations

import numpy as np

from ..core import encodings as enc
from ..core.pages import ColumnChunkData, CpuChunkEncoder, EncoderOptions
from ..core.schema import PhysicalType
from ..core.thrift import varint_bytes
from .dictionary import DictBuildHandle
from .packing import pack_page_host, pad_bucket

import jax.numpy as jnp


class _DeviceIndices:
    """Dictionary indices living on device, sliceable per page via
    lax.dynamic_slice (padded so any (start, bucket) slice is in bounds)."""

    def __init__(self, dev, n: int):
        self.dev = dev  # (pad_bucket(n),) uint32
        self.n = n
        self._padded = {}  # bucket -> device array of len pad_bucket(n)+bucket
        self._host = None  # lazy host copy for the mixed-RLE fallback

    def padded_for(self, bucket: int):
        arr = self._padded.get(bucket)
        if arr is None:
            arr = jnp.concatenate([self.dev, jnp.zeros(bucket, jnp.uint32)])
            self._padded[bucket] = arr
        return arr

    def host(self) -> np.ndarray:
        if self._host is None:
            self._host = np.asarray(self.dev)[: self.n]
        return self._host

    def __len__(self) -> int:
        return self.n

    def __getitem__(self, sl):  # CPU-path compatibility (unused in hot path)
        return self.host()[sl]


class TpuChunkEncoder(CpuChunkEncoder):
    """Byte-identical TPU implementation of the chunk encoder."""

    def __init__(self, options: EncoderOptions, min_device_rows: int = 4096) -> None:
        super().__init__(options)
        self.min_device_rows = min_device_rows

    # -- eligibility -------------------------------------------------------
    def _device_eligible(self, values, pt: int) -> bool:
        return (
            isinstance(values, np.ndarray)
            and values.dtype.kind in "iuf"
            and values.dtype.itemsize in (4, 8)
            and pt not in (PhysicalType.BOOLEAN, PhysicalType.BYTE_ARRAY,
                           PhysicalType.FIXED_LEN_BYTE_ARRAY)
            and len(values) >= self.min_device_rows
        )

    # -- launch/finish (pipelined via encode_many) -------------------------
    def prepare(self, chunk: ColumnChunkData):
        if not self._dictionary_viable(chunk):
            return None
        pt = chunk.column.leaf.physical_type
        if not self._device_eligible(chunk.values, pt):
            return None
        return DictBuildHandle(chunk.values)

    def _finish_prepare(self, pre):
        if pre is None:
            return None
        dict_values, indices_dev = pre.result()
        return dict_values, _DeviceIndices(indices_dev, pre.n)

    # -- primitive overrides ----------------------------------------------
    def _dictionary_build(self, values, pt: int):
        if not self._device_eligible(values, pt):
            return super()._dictionary_build(values, pt)
        handle = DictBuildHandle(values)
        dict_values, indices_dev = handle.result()
        return dict_values, _DeviceIndices(indices_dev, handle.n)

    def _indices_body(self, indices, va: int, vb: int, dict_size: int) -> bytes:
        if not isinstance(indices, _DeviceIndices):
            return super()._indices_body(indices, va, vb, dict_size)
        width = enc.bit_width(max(dict_size - 1, 0))
        count = vb - va
        if count == 0:
            return bytes([width])
        if width == 0:
            return bytes([0]) + varint_bytes(count << 1)
        bucket = pad_bucket(count)
        packed, long_sum, any_long = pack_page_host(
            indices.padded_for(bucket), va, count, width, bucket)
        # Mirror the CPU oracle's RLE-vs-bitpack decision exactly
        # (core.encodings.rle_hybrid_encode).
        if not any_long or long_sum < max(8, count // 10):
            groups = (count + 7) // 8
            body = varint_bytes((groups << 1) | 1) + packed[: groups * width].tobytes()
        else:
            body = enc.rle_hybrid_encode(indices.host()[va:vb], width)
        return bytes([width]) + body
