"""TpuChunkEncoder — the TPU EncoderBackend.

Drop-in for the CPU reference encoder at the pluggable boundary described in
SURVEY.md §1 (the reference funnels every record through
``ParquetFile.write`` -> parquet-mr ColumnWriter, ParquetFile.java:59-62;
here a whole column chunk is encoded at once).  Output bytes are identical to
``CpuChunkEncoder`` — the tests assert file-level byte equality — but the hot
math runs on device, dispatch-batched per row group:

- phase A (one XLA program per dtype-width group): ALL columns' dictionary
  builds, stacked (C, N) and vmapped (ops.dictionary.BatchDictBuild);
- one host sync for the unique counts; dictionary-vs-plain decisions made
  from the counts alone (fixed-width plain size is k * itemsize);
- phase B (async): every data page's bit-pack + run-stats launched for all
  columns before any result is read, so device compute overlaps host page
  assembly — the TPU-native version of the reference's thread-per-file
  parallelism (KafkaProtoParquetWriter.java:40-41);
- the rare long-run pages fall back to the host RLE assembler to keep the
  stream byte-identical to the oracle.

Strings (BYTE_ARRAY) keep the host hash-map dictionary — variable-length
bytes don't belong on the MXU/VPU; their dictionary *indices* are still
integers and could be device-packed, which matters only for very large
string pages (future work, SURVEY.md §7 hard part f).
"""

from __future__ import annotations

import numpy as np

from ..core import encodings as enc
from ..core.pages import ColumnChunkData, CpuChunkEncoder, EncoderOptions
from ..core.schema import PhysicalType
from ..core.thrift import varint_bytes
from .dictionary import DictBuildHandle, build_dictionaries
from .packing import pack_page, pack_page_host, pad_bucket
from ..utils.tracing import stage

import jax
import jax.numpy as jnp


class _DeviceIndices:
    """Dictionary indices living on device, sliceable per page via
    lax.dynamic_slice (padded so any (start, bucket) slice is in bounds).
    ``prefetched`` holds page packs launched ahead of assembly."""

    def __init__(self, dev, n: int):
        self.dev = dev  # (pad_bucket(n),) uint32
        self.n = n
        self._padded = {}  # bucket -> device array of len pad_bucket(n)+bucket
        self._host = None  # lazy host copy for the mixed-RLE fallback
        self.prefetched = {}  # (va, vb, width) -> (packed, long_sum, any_long) device

    def padded_for(self, bucket: int):
        arr = self._padded.get(bucket)
        if arr is None:
            arr = jnp.concatenate([self.dev, jnp.zeros(bucket, jnp.uint32)])
            self._padded[bucket] = arr
        return arr

    def host(self) -> np.ndarray:
        if self._host is None:
            self._host = np.asarray(self.dev)[: self.n]
        return self._host

    def __len__(self) -> int:
        return self.n

    def __getitem__(self, sl):  # CPU-path compatibility (unused in hot path)
        return self.host()[sl]


class TpuChunkEncoder(CpuChunkEncoder):
    """Byte-identical TPU implementation of the chunk encoder."""

    def __init__(self, options: EncoderOptions, min_device_rows: int = 4096) -> None:
        super().__init__(options)
        self.min_device_rows = min_device_rows

    # -- eligibility -------------------------------------------------------
    def _device_eligible(self, values, pt: int) -> bool:
        return (
            isinstance(values, np.ndarray)
            and values.dtype.kind in "iuf"
            and values.dtype.itemsize in (4, 8)
            and pt not in (PhysicalType.BOOLEAN, PhysicalType.BYTE_ARRAY,
                           PhysicalType.FIXED_LEN_BYTE_ARRAY)
            and len(values) >= self.min_device_rows
        )

    # -- batched launch (pipelined via encode_many) ------------------------
    def encode_many(self, chunks: list[ColumnChunkData], base_offset: int):
        with stage("encode.launch"):
            pres = self._prepare_all(chunks)
        with stage("encode.assemble"):
            out = []
            offset = base_offset
            for chunk, pre in zip(chunks, pres):
                e = self.encode(chunk, offset, pre=pre)
                offset += len(e.blob)
                out.append(e)
        return out

    def _prepare_all(self, chunks):
        """Phase A/B launcher: batched dict builds, then page-pack prefetch."""
        slots: list = [None] * len(chunks)
        eligible = [
            (i, chunk) for i, chunk in enumerate(chunks)
            if self._dictionary_viable(chunk)
            and self._device_eligible(chunk.values, chunk.column.leaf.physical_type)
        ]
        handles = build_dictionaries([chunk.values for _, chunk in eligible])
        for (i, chunk), (batch, j) in zip(eligible, handles):
            k = int(batch.unique_counts()[j])  # syncs once per batch (cached)
            n = len(chunk.values)
            itemsize = chunk.values.dtype.itemsize
            will_use_dict = (
                k <= max(1, int(n * self.options.max_dictionary_ratio))
                and k * itemsize <= self.options.dictionary_page_size_limit
            )
            dict_values, dev_idx = batch.result(j)
            di = _DeviceIndices(dev_idx, batch.n)
            slots[i] = (dict_values, di)
            if will_use_dict:
                self._prelaunch_pages(chunk, len(dict_values), di)
        return slots

    def _prelaunch_pages(self, chunk: ColumnChunkData, dict_size: int,
                         di: _DeviceIndices) -> None:
        """Launch every page's pack+run-stats before any readback (async
        dispatch).  Page geometry mirrors CpuChunkEncoder.encode exactly."""
        width = enc.bit_width(max(dict_size - 1, 0))
        if width == 0:
            return
        col = chunk.column
        def_levels = chunk.def_levels
        if def_levels is not None:
            present = np.asarray(def_levels) == col.max_def
            value_offsets = np.concatenate([[0], np.cumsum(present)])
        for a, b in self._page_slot_ranges(chunk, chunk.estimated_bytes()):
            if def_levels is not None:
                va, vb = int(value_offsets[a]), int(value_offsets[b])
            else:
                va, vb = a, b
            count = vb - va
            if count <= 0:
                continue
            bucket = pad_bucket(count)
            di.prefetched[(va, vb, width)] = pack_page(
                di.padded_for(bucket), jnp.int32(va), jnp.int32(count),
                bucket, width)

    # -- primitive overrides ----------------------------------------------
    def _dictionary_build(self, values, pt: int):
        if not self._device_eligible(values, pt):
            return super()._dictionary_build(values, pt)
        handle = DictBuildHandle(values)
        dict_values, indices_dev = handle.result()
        return dict_values, _DeviceIndices(indices_dev, handle.n)

    def _indices_body(self, indices, va: int, vb: int, dict_size: int) -> bytes:
        if not isinstance(indices, _DeviceIndices):
            return super()._indices_body(indices, va, vb, dict_size)
        width = enc.bit_width(max(dict_size - 1, 0))
        count = vb - va
        if count == 0:
            return bytes([width])
        if width == 0:
            return bytes([0]) + varint_bytes(count << 1)
        pre = indices.prefetched.pop((va, vb, width), None)
        if pre is not None:
            packed_d, long_d, any_d = pre
            packed, long_sum, any_long = np.asarray(packed_d), int(long_d), bool(any_d)
        else:
            bucket = pad_bucket(count)
            packed, long_sum, any_long = pack_page_host(
                indices.padded_for(bucket), va, count, width, bucket)
        # Mirror the CPU oracle's RLE-vs-bitpack decision exactly
        # (core.encodings.rle_hybrid_encode).
        if not any_long or long_sum < max(8, count // 10):
            groups = (count + 7) // 8
            body = varint_bytes((groups << 1) | 1) + packed[: groups * width].tobytes()
        else:
            body = enc.rle_hybrid_encode(indices.host()[va:vb], width)
        return bytes([width]) + body
