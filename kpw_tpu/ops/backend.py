"""TpuChunkEncoder — the TPU EncoderBackend.

Drop-in for the CPU reference encoder at the pluggable boundary described in
SURVEY.md §1 (the reference funnels every record through
``ParquetFile.write`` -> parquet-mr ColumnWriter, ParquetFile.java:59-62;
here a whole column chunk is encoded at once).  Output bytes are identical to
``CpuChunkEncoder`` — the tests assert file-level byte equality — but the hot
math runs on device, dispatch-batched per row group:

- phase A (one XLA program per dtype-width group): ALL columns' dictionary
  builds, stacked (C, N) and vmapped (ops.dictionary.BatchDictBuild);
- one host sync for the unique counts; dictionary-vs-plain decisions made
  from the counts alone (fixed-width plain size is k * itemsize);
- phase B (async): every data page's bit-pack + run-stats launched for all
  columns before any result is read, so device compute overlaps host page
  assembly — the TPU-native version of the reference's thread-per-file
  parallelism (KafkaProtoParquetWriter.java:40-41);
- the rare long-run pages fall back to the host RLE assembler to keep the
  stream byte-identical to the oracle.

Strings (BYTE_ARRAY) build their dictionary on host (native C++ hash —
variable-length bytes don't belong on the MXU/VPU), but their dictionary
*indices* are integers like any other dictionary column and ride the same
batched device bit-pack phase (_StringDictPlanner, SURVEY.md §7 hard
part f).
"""

from __future__ import annotations

import struct

import numpy as np

from ..core import encodings as enc
from ..core.pages import ColumnChunkData, EncoderOptions, PreparedRowGroup
from ..native.encoder import NativeChunkEncoder
from ..core.schema import Encoding, PhysicalType
from ..core.thrift import varint_bytes
from ..core.bytecol import ByteColumn
from .bss import byte_stream_split_device
from .delta import (assemble_delta_page, delta_binary_packed_device,
                    delta_bits_bucket, delta_length_byte_array_device,
                    delta_pages_multi)
from .dictionary import DictBuildHandle, build_dictionaries
from .levels import level_runs_multi, level_stats_multi
from .packing import (gather_index_slices, pack_page, pack_page_host,
                      pack_pages_multi, pack_pages_only, pad_bucket)
from ..utils.tracing import stage

import jax
import jax.numpy as jnp


class _DeviceIndices:
    """Dictionary indices living on device, sliceable per page via
    lax.dynamic_slice (padded so any (start, bucket) slice is in bounds).
    ``prefetched`` holds page packs launched ahead of assembly."""

    def __init__(self, dev, n: int):
        self.dev = dev  # (pad_bucket(n),) uint32
        self.n = n
        self._padded = {}  # bucket -> device array of len pad_bucket(n)+bucket
        self._host = None  # lazy host copy for the mixed-RLE fallback
        self.prefetched = {}  # (va, vb, width) -> (packed, long_sum, any_long) device

    def padded_for(self, bucket: int):
        arr = self._padded.get(bucket)
        if arr is None:
            arr = jnp.concatenate([self.dev, jnp.zeros(bucket, jnp.uint32)])
            self._padded[bucket] = arr
        return arr

    def host(self) -> np.ndarray:
        if self._host is None:
            self._host = np.asarray(self.dev)[: self.n]
        return self._host

    def __len__(self) -> int:
        return self.n

    def __getitem__(self, sl):  # CPU-path compatibility (unused in hot path)
        return self.host()[sl]


class _PageBodies:
    """Fully-resolved data-page value bodies for one dictionary column,
    keyed by the page's (va, vb) present-value range.  Stands in for the
    ``indices`` object in encode(): len() is the present-value count the
    dictionary ratio check needs."""

    __slots__ = ("n", "bodies")

    def __init__(self, n: int) -> None:
        self.n = n
        self.bodies: dict[tuple[int, int], bytes] = {}

    def __len__(self) -> int:
        return self.n


class _LevelPages:
    """One page's planned rep+def level streams as op descriptors —
    ``("raw", part)`` (bytes or a zero-copy device-readback view, length
    prefix already included) and ``("runs", vals u32, lens i32, width)``
    (compact device run table, replayed by the native assembler's
    RLE-from-runs op).  ``blob()`` composes the exact same bytes on host
    for the Python page loop / runs-op-less assemblers — byte-identity
    between the two consumers holds by construction
    (kpw_rle_hybrid_from_runs_u32 is the C twin of
    core.encodings.rle_hybrid_from_runs)."""

    __slots__ = ("ops", "_blob")

    def __init__(self, ops: list) -> None:
        self.ops = ops
        self._blob: bytes | None = None

    def blob(self) -> bytes:
        if self._blob is None:
            out = []
            for d in self.ops:
                if d[0] == "raw":
                    p = d[1]
                    out.append(p if isinstance(p, bytes)
                               else np.asarray(p).tobytes())
                else:
                    _, rv, rl, width = d
                    payload = enc.rle_hybrid_from_runs(
                        rv.astype(np.uint64), rl, width)
                    out.append(struct.pack("<I", len(payload)) + payload)
            self._blob = b"".join(out)
        return self._blob


class _LevelPlanner:
    """Device encoding of every rep/def level stream in a row group
    (BASELINE.md config 5), folded into the planner's two round trips:

    phase A (joins sync 1): run stats for all level pages — long-run mass
    (the oracle's bit-pack-vs-mixed decision, core.encodings
    .rle_hybrid_encode) and run counts (sizing the phase-B gather);
    phase B (joins sync 2): high-entropy pages reuse the value path's
    bit-pack program (pack_pages_multi — pallas-backed on TPU); run-heavy
    pages get their compact run list extracted on device and replayed
    through rle_hybrid_from_runs on host, byte-identical by construction.
    """

    def __init__(self, encoder: "TpuChunkEncoder", chunks) -> None:
        streams = []  # (chunk_idx, kind, levels ndarray, width)
        self._pages = []  # (stream_row, chunk_idx, kind, a, b, width)
        for i, chunk in enumerate(chunks):
            col = chunk.column
            if chunk.num_slots < encoder.min_device_rows:
                continue
            kinds = []
            if col.max_rep > 0:
                kinds.append(("rep", np.asarray(chunk.rep_levels),
                              enc.bit_width(col.max_rep)))
            if col.max_def > 0:
                kinds.append(("def", np.asarray(chunk.def_levels),
                              enc.bit_width(col.max_def)))
            if not kinds:
                continue
            ranges = encoder._slot_ranges(chunk)
            for kind, levels, width in kinds:
                row = len(streams)
                streams.append(levels)
                for a, b in ranges:
                    if b > a:
                        self._pages.append((row, i, kind, a, b, width))
        self.empty = not self._pages
        self.plans: dict[int, dict] = {}
        self._chunks = chunks
        self._stat_groups = []  # (pages, device (long_sum, n_runs))
        self._b_groups = []  # (mode, pages-with-meta, device arrays)
        if self.empty:
            return
        # levels are 1-3 bit values: stack as uint8 (kernels widen on device)
        # to quarter the host->device transfer.
        maxn = max(len(s) for s in streams)
        stacked = np.zeros((len(streams), maxn), np.uint8)
        for r, s in enumerate(streams):
            stacked[r, : len(s)] = s
        self._dev = jnp.asarray(stacked)
        # phase A: stats jobs grouped by window bucket
        by_bucket: dict[int, list] = {}
        for page in self._pages:
            _, _, _, a, b, _ = page
            by_bucket.setdefault(pad_bucket(b - a), []).append(page)
        for bucket, rows in by_bucket.items():
            stats = level_stats_multi(
                self._dev,
                jnp.asarray(np.array([p[0] for p in rows], np.int32)),
                jnp.asarray(np.array([p[3] for p in rows], np.int32)),
                jnp.asarray(np.array([p[4] - p[3] for p in rows], np.int32)),
                bucket)
            self._stat_groups.append((rows, stats))

    def stats_device(self):
        return [g[1] for g in self._stat_groups]

    def launch_phase_b(self, stats_host) -> None:
        """Classify pages from phase-A stats and launch phase-B programs."""
        fast: dict[tuple[int, int], list] = {}  # (bucket, width) -> pages
        slow: dict[int, list] = {}  # bucket -> (page, n_runs)
        for (rows, _), (long_h, runs_h) in zip(self._stat_groups, stats_host):
            for r, page in enumerate(rows):
                _, _, _, a, b, width = page
                count = b - a
                if int(long_h[r]) < max(8, count // 10):
                    fast.setdefault((pad_bucket(count), width), []).append(page)
                else:
                    slow.setdefault(pad_bucket(count), []).append(
                        (page, int(runs_h[r])))
        for (bucket, width), pages in fast.items():
            packed = pack_pages_only(  # stats already known from phase A
                self._dev,
                jnp.asarray(np.array([p[0] for p in pages], np.int32)),
                jnp.asarray(np.array([p[3] for p in pages], np.int32)),
                jnp.asarray(np.array([p[4] - p[3] for p in pages], np.int32)),
                bucket, width)
            self._b_groups.append(("fast", pages, packed))
        for bucket, entries in slow.items():
            run_bucket = pad_bucket(max(n for _, n in entries))
            # actual level width of the grouped streams (1-3 bits for real
            # schemas): tight enough for the one-sort packed compaction
            level_bits = max(max(p[5] for p, _ in entries), 1)
            runs = level_runs_multi(
                self._dev,
                jnp.asarray(np.array([p[0] for p, _ in entries], np.int32)),
                jnp.asarray(np.array([p[3] for p, _ in entries], np.int32)),
                jnp.asarray(np.array([p[4] - p[3] for p, _ in entries], np.int32)),
                bucket, run_bucket, level_bits)
            self._b_groups.append(("slow", entries, runs))

    def phase_b_device(self):
        return [g[2] for g in self._b_groups]

    def assemble(self, fetched) -> None:
        """Fold the fetched device outputs into per-(chunk, page) level
        plans.  Each plan entry (:class:`_LevelPages`) carries op
        DESCRIPTORS, not composed bytes: bit-packed pages as zero-copy
        [v1 length+varint header, packed row view] raw parts, run-heavy
        pages as their compact (run_vals, run_lens) tables — which the
        native lowering hands to the assembler's RLE-from-runs op so the
        O(runs) replay happens inside the one nogil call per chunk.  The
        Python page loop (and a runs-op-less assembler) composes the same
        bytes on demand via :meth:`_LevelPages.blob`."""
        parts: dict[tuple[int, int, int], dict] = {}  # (i, a, b) -> kind -> ops
        for (mode, items, _), host in zip(self._b_groups, fetched):
            if mode == "fast":
                packed_h = host
                for r, (row, i, kind, a, b, width) in enumerate(items):
                    count = b - a
                    groups = (count + 7) // 8
                    head = varint_bytes((groups << 1) | 1)
                    packed = packed_h[r, : groups * width]
                    # v1 length prefix + bit-pack header composed WITHOUT
                    # materializing the packed bytes (the row view rides
                    # to the sink / native call as a buffer)
                    hdr = struct.pack(
                        "<I", len(head) + groups * width) + head
                    parts.setdefault((i, a, b), {})[kind] = [
                        ("raw", hdr), ("raw", packed)]
            else:
                vals_h, lens_h = host
                for r, ((row, i, kind, a, b, width), n_runs) in enumerate(items):
                    parts.setdefault((i, a, b), {})[kind] = [
                        ("runs",
                         np.ascontiguousarray(vals_h[r, :n_runs], np.uint32),
                         np.ascontiguousarray(lens_h[r, :n_runs], np.int32),
                         width)]
        for (i, a, b), kinds in parts.items():
            chunk = self._chunks[i]
            col = chunk.column
            ops: list = []
            for kind, max_level in (("rep", col.max_rep), ("def", col.max_def)):
                if max_level > 0:
                    ops.extend(kinds[kind])
            # entries carry the chunk itself so a consumer can identity-check
            # against id() reuse (plans may survive an aborted _prepare_all)
            self.plans.setdefault(id(chunk), (chunk, {}))[1][(a, b)] = \
                _LevelPages(ops)


def _trivial_body(width: int, count: int) -> bytes | None:
    """Data-page index body for the no-device-job cases — empty page (just
    the width byte) and width-0 single-value dictionary (one RLE run header,
    no value bytes).  One definition for every planner/assembly site."""
    if count == 0:
        return bytes([width])
    if width == 0:
        return bytes([0]) + varint_bytes(count << 1)
    return None


# (width, count) -> the constant pure-bit-pack page prefix
# `width byte + varint((groups << 1) | 1)` — identical for every page of
# the same geometry, so one row group's worth of pages shares a handful
# of prefixes instead of re-concatenating them per page
_BP_PREFIXES: dict[tuple[int, int], bytes] = {}


def _bitpack_page_prefix(width: int, count: int) -> bytes:
    key = (width, count)
    pre = _BP_PREFIXES.get(key)
    if pre is None:
        if len(_BP_PREFIXES) > 4096:  # page geometries are few; cap anyway
            _BP_PREFIXES.clear()
        groups_n = (count + 7) // 8
        pre = _BP_PREFIXES[key] = (bytes([width])
                                   + varint_bytes((groups_n << 1) | 1))
    return pre


def _hybrid_body(packed_row, long_sum: int, count: int, width: int,
                 idx_fallback):
    """One definition of the planner's data-page body assembly: device
    bit-pack bytes when the oracle's RLE-vs-bitpack decision
    (core.encodings.rle_hybrid_encode: long-run mass < max(8, n//10)) says
    pure bit-pack, else the exact mixed host RLE over ``idx_fallback()``.
    The bit-pack case returns a PARTS LIST [shared prefix, packed view] —
    no per-page tobytes copy, no concat; the bytes reach the sink as-is
    (encode() and the writer gather parts verbatim)."""
    if long_sum < max(8, count // 10):
        groups_n = (count + 7) // 8
        return [_bitpack_page_prefix(width, count),
                packed_row[: groups_n * width]]
    return bytes([width]) + enc.rle_hybrid_encode(idx_fallback(), width)


class _StringDictPlanner:
    """Byte-array dictionary columns in the row-group batch (SURVEY.md §7
    hard part f): the dictionary itself builds on host (native C++ hash —
    variable-length bytes don't belong on the VPU), but the *indices* are
    integers like any other dictionary column, so their page packing joins
    the planner's batched device phase (pack_pages_multi — pallas-backed on
    TPU) instead of encoding page by page on host."""

    def __init__(self, encoder: "TpuChunkEncoder", chunks) -> None:
        self._items = []  # (i, chunk, dict_values, idx, width, pages)
        self._rejected = []  # (i, dict_values, idx): budget-rejected builds
        self._groups = []
        opts = encoder.options
        self.empty = True
        if encoder._lib is None or not opts.enable_dictionary:
            return
        for i, chunk in enumerate(chunks):
            pt = chunk.column.leaf.physical_type
            values = chunk.values
            if (not encoder._dictionary_viable(chunk)
                    or not encoder.chooser.dictionary_wanted(chunk.column)
                    or not encoder._bytes_native_ok(values, pt)
                    or len(values) < encoder.min_device_rows):
                continue
            n = len(values)
            max_k = max(1, int(n * opts.max_dictionary_ratio))
            built = encoder._bytes_dictionary(values, max_k)
            if built is None:
                continue  # ratio abort: encode() re-derives cheaply
            dict_values, idx = built
            k = len(dict_values)
            plain_len = sum(map(len, dict_values))
            if pt == PhysicalType.BYTE_ARRAY:
                plain_len += 4 * k  # FLBA PLAIN has no length prefixes
            if plain_len > opts.dictionary_page_size_limit:
                # byte-budget rejection: hand the built dict through the
                # slot so encode() re-derives the rejection without a
                # second O(n) build
                self._rejected.append((i, dict_values, idx))
                continue
            width = enc.bit_width(max(k - 1, 0))
            pages = encoder._page_value_ranges(chunk)
            self._items.append((i, chunk, dict_values, idx, width, pages))
        self.empty = not self._items and not self._rejected
        if not self._items:
            return
        maxn = max(len(idx) for _, _, _, idx, _, _ in self._items)
        stacked = np.zeros((len(self._items), maxn), np.uint32)
        for r, (_, _, _, idx, _, _) in enumerate(self._items):
            stacked[r, : len(idx)] = idx
        dev = jnp.asarray(stacked)
        by_key: dict[tuple[int, int], list] = {}
        for r, (i, chunk, _, _, width, pages) in enumerate(self._items):
            if width == 0:
                continue  # single-value dicts have no packed body
            for va, vb in pages:
                if vb - va > 0:
                    by_key.setdefault((pad_bucket(vb - va), width), []).append(
                        (r, va, vb))
        for (bucket, width), rows in by_key.items():
            packed, long_sum = pack_pages_multi(
                dev,
                jnp.asarray(np.array([r for r, _, _ in rows], np.int32)),
                jnp.asarray(np.array([va for _, va, _ in rows], np.int32)),
                jnp.asarray(np.array([vb - va for _, va, vb in rows], np.int32)),
                bucket, width)
            self._groups.append((rows, width, (packed, long_sum)))

    def device_outputs(self):
        return [g[2] for g in self._groups]

    def fill_slots(self, fetched, slots) -> None:
        """Assemble page bodies (device bit-pack or host RLE for long-run
        pages — the index array is already host-resident) and install
        (dict_values, _PageBodies) into the planner slots."""
        for i, dict_values, idx in self._rejected:
            slots[i] = (dict_values, idx)  # encode() re-derives the rejection
        bodies: dict[int, _PageBodies] = {}
        for r, (i, chunk, dict_values, idx, width, pages) in enumerate(self._items):
            pb = bodies[r] = _PageBodies(len(idx))
            for va, vb in pages:  # width-0 / empty pages have no device job
                body = _trivial_body(width, vb - va)
                if body is not None:
                    pb.bodies[(va, vb)] = body
            slots[i] = (dict_values, pb)
        for (rows, width, _), (packed_h, long_h) in zip(self._groups, fetched):
            for row, (r, va, vb) in enumerate(rows):
                bodies[r].bodies[(va, vb)] = _hybrid_body(
                    packed_h[row], int(long_h[row]), vb - va, width,
                    lambda r=r, va=va, vb=vb: self._items[r][3][va:vb])


class _DeltaPlanner:
    """Batched device delta encoding for the row group's non-dictionary
    pages (BASELINE config 3), folded into the planner's phase B: one
    ``delta_pages_multi`` launch per (bucket, bit_size) group instead of
    one dispatch per page.

    Covers chunks whose encoding is statically known to be a delta
    fallback (dictionary disabled or not viable): int32/int64 columns pack
    their values; byte-array columns pack their *length* vector (the
    DELTA_LENGTH payload is a host concat of the packed string window)."""

    def __init__(self, encoder: "TpuChunkEncoder", chunks) -> None:
        self.plans: dict[int, tuple] = {}  # id(chunk) -> (chunk, {(va,vb): bytes})
        self._jobs = []  # (row, chunk, bit_size, pages)
        streams: list[np.ndarray] = []  # per-job int64/int32-ring lo streams
        opts = encoder.options
        chooser = encoder.chooser
        if not (opts.delta_fallback or opts.adaptive_encodings or opts.encodings):
            self.empty = True  # every column resolves to PLAIN: nothing here
            return
        for i, chunk in enumerate(chunks):
            col = chunk.column
            if (encoder._dictionary_viable(chunk)
                    and chooser.dictionary_wanted(col)):
                continue  # dictionary path (or rejected later: per-page route)
            pt = col.leaf.physical_type
            if chooser.peek(col) is None:
                # adaptive & not yet pinned: the decision is made inside
                # encode() (row group 1 stats) — launch_many may run ahead
                # of the pinning assemble, so pre-planning here would race.
                # Correctness lives in encode()'s per-page route.
                continue
            enc_kind = encoder._fallback_encoding(pt, col)
            values = chunk.values
            if len(values) < encoder.min_device_rows:
                continue
            if enc_kind == Encoding.DELTA_BINARY_PACKED and isinstance(
                    values, np.ndarray):
                bit_size = 32 if pt == PhysicalType.INT32 else 64
                # normalize to the column's ring dtype exactly like the
                # oracle (np.ascontiguousarray(values, itype)) — an int32
                # array in an INT64 column must sign-extend into the hi
                # plane, not leave it zero
                stream = np.ascontiguousarray(
                    values, np.int32 if bit_size == 32 else np.int64)
            elif enc_kind == Encoding.DELTA_LENGTH_BYTE_ARRAY and isinstance(
                    values, ByteColumn):
                # lengths ride the 32-bit ring per the spec
                stream = np.ascontiguousarray(values.lens(), np.int32)
                bit_size = 32
            else:
                continue
            pages = [(va, vb) for va, vb in encoder._page_value_ranges(chunk)
                     if vb - va >= 2]
            if not pages:
                continue
            row = len(streams)
            streams.append(stream)
            self._jobs.append((row, chunk, bit_size, pages))
        self.empty = not self._jobs
        self._groups = []
        self._streams = streams
        if self.empty:
            return
        maxn = max(len(s) for s in streams)
        hi_all = np.zeros((len(streams), maxn), np.uint32)
        lo_all = np.zeros((len(streams), maxn), np.uint32)
        for r, s in enumerate(streams):
            if s.dtype.itemsize == 8:
                u = np.ascontiguousarray(s).view(np.uint64)
                hi_all[r, : len(s)] = (u >> np.uint64(32)).astype(np.uint32)
                lo_all[r, : len(s)] = u.astype(np.uint32)
            else:
                lo_all[r, : len(s)] = np.ascontiguousarray(s).view(np.uint32)
        hi_d = jnp.asarray(hi_all)
        lo_d = jnp.asarray(lo_all)
        # host-known stream ranges bound every miniblock width statically
        # (delta_bits_bucket), shrinking the pack grid — near-sorted
        # timestamps and string lengths drop from the 256-byte worst-case
        # slot to 4*max_bits
        row_bits = {row: delta_bits_bucket(
            int(s.max()) - int(s.min()) if len(s) else 0,
            32 if s.dtype.itemsize == 4 else 64)
            for row, s in enumerate(streams)}
        # group pages by (bucket, bit_size); the group's budget is its
        # WIDEST member's, so mixed-range groups still launch one program
        # (narrower streams just ride a larger-than-needed grid)
        by_key: dict[tuple[int, int], list] = {}
        for row, chunk, bit_size, pages in self._jobs:
            for va, vb in pages:
                by_key.setdefault((pad_bucket(vb - va), bit_size), []).append(
                    (row, chunk, va, vb))
        for (bucket, bit_size), items in by_key.items():
            max_bits = max(row_bits[row] for row, _, _, _ in items)
            dev = delta_pages_multi(
                hi_d, lo_d,
                jnp.asarray(np.array([row for row, _, _, _ in items], np.int32)),
                jnp.asarray(np.array([va for _, _, va, _ in items], np.int32)),
                jnp.asarray(np.array([vb - va for _, _, va, vb in items],
                                     np.int32)),
                bucket, bit_size, max_bits)
            self._groups.append((items, bit_size, dev, max_bits))

    def device_outputs(self):
        return [g[2] for g in self._groups]

    def assemble(self, fetched) -> None:
        for (items, bit_size, _, max_bits), host in zip(self._groups, fetched):
            mh, ml, widths, packed = host
            for r, (row, chunk, va, vb) in enumerate(items):
                count = vb - va
                first = int(self._streams[row][va])  # ring dtype already
                body = assemble_delta_page(first, count, mh[r], ml[r],
                                           widths[r], packed[r], bit_size,
                                           max_bits=max_bits)
                if isinstance(chunk.values, ByteColumn):
                    body += chunk.values[va:vb].payload()
                self.plans.setdefault(id(chunk), (chunk, {}))[1][(va, vb)] = body


class TpuChunkEncoder(NativeChunkEncoder):
    """Byte-identical TPU implementation of the chunk encoder.

    Host-side work that stays off the device (string dictionaries, delta
    fallbacks, small chunks below min_device_rows) rides the native C++
    primitives via the superclass; everything is byte-identical to the CPU
    oracle either way."""

    def __init__(self, options: EncoderOptions, min_device_rows: int = 4096) -> None:
        super().__init__(options)
        self.min_device_rows = min_device_rows

    # -- eligibility -------------------------------------------------------
    def _device_eligible(self, values, pt: int) -> bool:
        return (self._fixed_width_ok(values, pt)
                and len(values) >= self.min_device_rows)

    # -- split row-group encode (pipelined via launch_many/assemble_many) --
    # encode_many itself is inherited (launch + assemble inline).  The
    # writer's overlapped pipeline calls the halves from different
    # threads: row group N+1's launch_many (device dispatch + the two
    # bulk readbacks) runs while row group N is still in assemble_many
    # (pure host page building) — so the host-assembly leg hides under
    # the next group's device leg instead of serializing after it.

    split_launch_overlaps = True  # launch = real device work (see base)

    def launch_many(self, chunks: list[ColumnChunkData]) -> PreparedRowGroup:
        """Device phase only: planner dispatches + the bulk readbacks.
        All results travel in the handle — nothing lands on ``self``, so
        a concurrent assemble_many of the PREVIOUS row group never sees
        this one's state."""
        slots: list = [None] * len(chunks)
        with stage("encode.launch"):
            launched = self._launch_all(chunks, slots)
        return PreparedRowGroup(slots, state=launched)

    def assemble_many(self, chunks: list[ColumnChunkData],
                      prepared: PreparedRowGroup, base_offset: int):
        """Host phase: post-fetch body assembly (``encode.bodies``) + the
        column-parallel page/blob/stats loop (``encode.assemble``), the
        split the --hostasm bench attributes.  Serialized by the caller
        (one row group in assembly at a time), so installing the planner's
        id()-keyed plans on the instance for the duration is safe —
        launch_many never reads them."""
        launched = prepared.state
        prepared.state = None  # plans are consumed exactly once
        if launched is not None:
            with stage("encode.bodies"):
                self._assemble_bodies(chunks, prepared.pres, *launched)
        with stage("encode.assemble"):
            try:
                # Column-parallel host assembly (VERDICT r3 next #2): after
                # the plan every per-page body is resolved, so encode() is
                # pure host work — header/stats/blob assembly and
                # compression through GIL-releasing native primitives
                # (superclass shards it across the shared pool, encode at
                # 0 + footer-offset shift, byte-identical to sequential).
                return super().assemble_many(chunks, prepared, base_offset)
            finally:
                # keyed by id(chunk) — must not outlive the chunk objects.
                # Pop only THIS row group's ranges: the dispatch thread may
                # already have populated the cache for the next group.
                self._level_plans = {}
                self._delta_plans = {}
                cache = getattr(self, "_ranges_cache", None)
                if cache:
                    for c in chunks:
                        cache.pop(id(c), None)

    def _slot_ranges(self, chunk: ColumnChunkData) -> list[tuple[int, int]]:
        cache = getattr(self, "_ranges_cache", None)
        if cache is None:
            cache = self._ranges_cache = {}
        hit = cache.get(id(chunk))
        if hit is not None and hit[0] is chunk:  # guard against id() reuse
            return hit[1]
        if len(cache) > 1024:  # direct encode() callers never clear
            cache.clear()
        ranges = super()._slot_ranges(chunk)
        cache[id(chunk)] = (chunk, ranges)
        return ranges

    def _page_value_ranges(self, chunk: ColumnChunkData) -> list[tuple[int, int]]:
        """The (va, vb) present-value range of every data page, mirroring the
        slot->value mapping in CpuChunkEncoder.encode exactly (page bodies are
        keyed by these ranges at assembly time)."""
        col = chunk.column
        def_levels = chunk.def_levels
        if def_levels is not None:
            present = np.asarray(def_levels) == col.max_def
            value_offsets = np.concatenate([[0], np.cumsum(present)])
        out = []
        for a, b in self._slot_ranges(chunk):
            if def_levels is not None:
                out.append((int(value_offsets[a]), int(value_offsets[b])))
            else:
                out.append((a, b))
        return out

    def _launch_all(self, chunks, slots):
        """Launch + sync phases of the planner (device dispatches and the
        two bulk readbacks).  Returns None when nothing is device-eligible,
        else the argument pack for :meth:`_assemble_bodies`."""
        lvl = _LevelPlanner(self, chunks)  # phase A launched here
        dlt = _DeltaPlanner(self, chunks)  # delta pages launched here
        eligible = [
            (i, chunk) for i, chunk in enumerate(chunks)
            if self._dictionary_viable(chunk)
            and self.chooser.dictionary_wanted(chunk.column)
            and self._device_eligible(chunk.values, chunk.column.leaf.physical_type)
        ]
        opts = self.options
        handles = (build_dictionaries([chunk.values for _, chunk in eligible])
                   if eligible else [])
        # after the numeric launches so the host string hashing overlaps
        # the device dictionary builds
        sdp = _StringDictPlanner(self, chunks)
        if not eligible and lvl.empty and dlt.empty and sdp.empty:
            return None

        batches: list = []
        for batch, _ in handles:
            if batch not in batches:
                batches.append(batch)
        counts_host, lvl_stats_host = jax.device_get(  # sync 1: counts + level stats
            ([b.counts_device() for b in batches], lvl.stats_device()))
        for b, kv in zip(batches, counts_host):
            b._k_host = np.asarray(kv)
        if not lvl.empty:
            lvl.launch_phase_b(lvl_stats_host)

        col_plans = []
        jobs: dict = {}  # (batch_id, bucket, width) -> (batch, [page rows])
        accepted_kmax: dict = {}
        for (i, chunk), (batch, j) in zip(eligible, handles):
            k = int(batch.unique_counts()[j])
            n = len(chunk.values)
            itemsize = chunk.values.dtype.itemsize
            ok_ratio = k <= max(1, int(n * opts.max_dictionary_ratio))
            will = ok_ratio and k * itemsize <= opts.dictionary_page_size_limit
            width = enc.bit_width(max(k - 1, 0))
            pages = self._page_value_ranges(chunk)
            col_plans.append((i, chunk, batch, j, k, width, will, pages))
            if will:
                accepted_kmax[id(batch)] = max(accepted_kmax.get(id(batch), 1), k)
                if width > 0:
                    for va, vb in pages:
                        count = vb - va
                        if count <= 0:
                            continue
                        bucket = pad_bucket(count)
                        jobs.setdefault((id(batch), bucket, width),
                                        (batch, []))[1].append((i, j, va, vb, count))

        group_meta = []
        group_dev = []
        for (bid, bucket, width), (batch, rows) in jobs.items():
            packed, long_sum = pack_pages_multi(
                batch.indices,
                jnp.asarray(np.array([r[1] for r in rows], np.int32)),
                jnp.asarray(np.array([r[2] for r in rows], np.int32)),
                jnp.asarray(np.array([r[4] for r in rows], np.int32)),
                bucket, width)
            group_meta.append((rows, width, batch))
            group_dev.append((packed, long_sum))
        tables_dev = {
            id(b): b.key_tables_device(pad_bucket(accepted_kmax[id(b)]))
            for b in batches if id(b) in accepted_kmax
        }

        fetched = jax.device_get(  # sync 2: bulk
            (group_dev, tables_dev,
             lvl.phase_b_device() if not lvl.empty else [],
             dlt.device_outputs() if not dlt.empty else [],
             sdp.device_outputs() if not sdp.empty else []))
        groups_host, tables_host, lvl_host, dlt_host, sdp_host = fetched
        return (lvl, dlt, sdp, col_plans, group_meta, groups_host,
                tables_host, lvl_host, dlt_host, sdp_host)

    def _assemble_bodies(self, chunks, slots, lvl, dlt, sdp, col_plans,
                         group_meta, groups_host, tables_host, lvl_host,
                         dlt_host, sdp_host):
        """Post-fetch HOST body assembly — separated (and stage-traced as
        ``encode.bodies``) so the bench can attribute the TPU path's host
        side: together with ``encode.assemble`` this is the per-row-group
        host work that neither rides the chip nor the PCIe link (VERDICT
        r3 next #2).  Includes the rare sync-3 long-run gather."""
        if not lvl.empty:
            lvl.assemble(lvl_host)
            self._level_plans = lvl.plans
        if not dlt.empty:
            dlt.assemble(dlt_host)
            self._delta_plans = dlt.plans
        if not sdp.empty:
            sdp.fill_slots(sdp_host, slots)

        bodies_by_slot: dict[int, _PageBodies] = {}

        def bodies_for(i: int, n: int) -> _PageBodies:
            pb = bodies_by_slot.get(i)
            if pb is None:
                pb = bodies_by_slot[i] = _PageBodies(n)
            return pb

        fallback: dict = {}  # (batch_id) -> (batch, [(i, j, va, vb, count, width)])
        for (rows, width, batch), (packed_h, long_h) in zip(group_meta, groups_host):
            longs = long_h.tolist()  # one bulk convert, not per-page int()
            for row, (i, j, va, vb, count) in enumerate(rows):
                # oracle decision (core.encodings.rle_hybrid_encode): pure
                # bit-pack unless long-run mass reaches max(8, n // 10)
                if longs[row] < max(8, count // 10):
                    groups_n = (count + 7) // 8
                    # parts list: shared prefix + zero-copy packed view
                    # (written to the sink without a tobytes bounce).
                    # Accepted trade: the view's base pins the whole
                    # padded readback matrix until the row group clears
                    # the IO stage — bounded by the pipeline's ~2
                    # in-flight groups, and pad_bucket keeps the padding
                    # within the bucket granularity of the real pages.
                    body = [_bitpack_page_prefix(width, count),
                            packed_h[row, : groups_n * width]]
                    bodies_for(i, len(chunks[i].values)).bodies[(va, vb)] = body
                else:
                    fallback.setdefault(id(batch), (batch, []))[1].append(
                        (i, j, va, vb, count, width))

        if fallback:  # sync 3 (rare): long-run pages need exact host RLE
            fb_dev = []
            fb_meta = []
            for batch, rows in fallback.values():
                bucket = pad_bucket(max(r[4] for r in rows))
                fb_dev.append(gather_index_slices(
                    batch.indices,
                    jnp.asarray(np.array([r[1] for r in rows], np.int32)),
                    jnp.asarray(np.array([r[2] for r in rows], np.int32)),
                    bucket))
                fb_meta.append(rows)
            for rows, sl in zip(fb_meta, jax.device_get(fb_dev)):
                for row, (i, j, va, vb, count, width) in enumerate(rows):
                    body = bytes([width]) + enc.rle_hybrid_encode(
                        sl[row, :count], width)
                    bodies_for(i, len(chunks[i].values)).bodies[(va, vb)] = body

        for i, chunk, batch, j, k, width, will, pages in col_plans:
            pb = bodies_for(i, len(chunk.values))
            if will:
                dict_values = batch.values_from_tables(j, k, tables_host[id(batch)])
                for va, vb in pages:  # width-0 / empty pages have no device job
                    body = _trivial_body(width, vb - va)
                    if body is not None:
                        pb.bodies.setdefault((va, vb), body)
            else:
                # Rejected dictionary: encode() only needs len()/dtype to
                # re-derive the rejection, so skip the key-table transfer.
                dict_values = np.zeros(k, chunk.values.dtype)
            slots[i] = (dict_values, pb)
        return slots

    # -- primitive overrides ----------------------------------------------
    def _values_body(self, values, pt: int, encoding: int) -> bytes:
        """Delta fallbacks ride the device kernels (SURVEY §7 step 5:
        per-column delta & delta-length-byte-array) for large chunks; small
        ones and everything else fall through to the native host path.

        This is the *fallback* route: pages of statically-known delta chunks
        are batched by _DeltaPlanner into one dispatch per (bucket, ring)
        group and served from the plan via _values_page_body; only small
        chunks and dictionary-*rejected* columns (unknowable at plan time)
        land here, paying one round trip per page."""
        if len(values) >= self.min_device_rows:
            if (encoding == Encoding.DELTA_BINARY_PACKED
                    and isinstance(values, np.ndarray)):
                bit_size = 32 if pt == PhysicalType.INT32 else 64
                return delta_binary_packed_device(values, bit_size)
            if encoding == Encoding.DELTA_LENGTH_BYTE_ARRAY:
                return delta_length_byte_array_device(values)
            if (encoding == Encoding.BYTE_STREAM_SPLIT
                    and pt in enc._PLAIN_DTYPES):
                # coerce to the PLAIN dtype first, like the native route —
                # the transpose must see the on-wire value bytes
                return byte_stream_split_device(
                    np.ascontiguousarray(values, enc._PLAIN_DTYPES[pt]))
        return super()._values_body(values, pt, encoding)

    def _planned_body(self, chunk, va: int, vb: int) -> bytes | None:
        """Device-plan lookup shared by the body and parts overrides: one
        place owns the id()-keyed cache protocol and its identity re-check."""
        plans = getattr(self, "_delta_plans", None)
        if plans:
            hit = plans.get(id(chunk))
            if hit is not None and hit[0] is chunk:  # guard against id() reuse
                return hit[1].get((va, vb))
        return None

    def _values_page_body(self, chunk, va: int, vb: int, pt: int,
                          encoding: int) -> bytes:
        body = self._planned_body(chunk, va, vb)
        if body is not None:
            return body
        return super()._values_page_body(chunk, va, vb, pt, encoding)

    def _values_page_parts(self, chunk, va: int, vb: int, pt: int,
                           encoding: int) -> list:
        """Planned device-encoded bodies take precedence: without this, the
        native superclass's DELTA_LENGTH parts override would re-encode on
        host what the batched device plan already produced."""
        body = self._planned_body(chunk, va, vb)
        if body is not None:
            return [body]
        return super()._values_page_parts(chunk, va, vb, pt, encoding)

    def _planned_level_entry(self, chunk, a: int, b: int):
        """The planner's :class:`_LevelPages` entry for slots [a, b), or
        None — one place owns the id()-keyed cache protocol."""
        plans = getattr(self, "_level_plans", None)
        if plans:
            hit = plans.get(id(chunk))
            if hit is not None and hit[0] is chunk:  # guard against id() reuse
                return hit[1].get((a, b))
        return None

    def _planned_levels_blob(self, chunk, a: int, b: int) -> bytes | None:
        """The planner's device-encoded rep+def blob for slots [a, b) when
        one exists — consulted by both the Python page loop (via
        _levels_page_blob) and the native assembly lowering when the
        loaded assembler predates the RLE-from-runs op."""
        entry = self._planned_level_entry(chunk, a, b)
        return entry.blob() if entry is not None else None

    def _planned_level_ops(self, chunk, a: int, b: int) -> list | None:
        """Planned level streams as ops for the nogil lowering: raw parts
        stay raw (zero-copy views included), run tables ride the
        assembler's RLE-from-runs op — the device->file handoff with no
        host replay loop at all."""
        entry = self._planned_level_entry(chunk, a, b)
        return entry.ops if entry is not None else None

    def _levels_page_blob(self, chunk, a: int, b: int) -> bytes:
        body = self._planned_levels_blob(chunk, a, b)
        if body is not None:
            return body
        return super()._levels_page_blob(chunk, a, b)

    def _dictionary_build(self, values, pt: int):
        if not self._device_eligible(values, pt):
            return super()._dictionary_build(values, pt)
        handle = DictBuildHandle(values)
        dict_values, indices_dev = handle.result()
        return dict_values, _DeviceIndices(indices_dev, handle.n)

    def _indices_body(self, indices, va: int, vb: int, dict_size: int) -> bytes:
        if isinstance(indices, _PageBodies):
            body = indices.bodies.get((va, vb))
            if body is None:
                raise RuntimeError(
                    f"page ({va},{vb}) missing from row-group plan — page "
                    "geometry drifted between planning and assembly")
            return body
        if not isinstance(indices, _DeviceIndices):
            return super()._indices_body(indices, va, vb, dict_size)
        width = enc.bit_width(max(dict_size - 1, 0))
        count = vb - va
        trivial = _trivial_body(width, count)
        if trivial is not None:
            return trivial
        pre = indices.prefetched.pop((va, vb, width), None)
        if pre is not None:
            packed_d, long_d, any_d = pre
            packed, long_sum, any_long = np.asarray(packed_d), int(long_d), bool(any_d)
        else:
            bucket = pad_bucket(count)
            packed, long_sum, any_long = pack_page_host(
                indices.padded_for(bucket), va, count, width, bucket)
        # Mirror the CPU oracle's RLE-vs-bitpack decision exactly
        # (core.encodings.rle_hybrid_encode).
        if not any_long or long_sum < max(8, count // 10):
            groups = (count + 7) // 8
            body = varint_bytes((groups << 1) | 1) + packed[: groups * width].tobytes()
        else:
            body = enc.rle_hybrid_encode(indices.host()[va:vb], width)
        return bytes([width]) + body
