"""Device bit-packing for parquet's RLE/bit-pack hybrid pages.

The CPU oracle is ``kpw_tpu.core.encodings.bitpack`` (parquet LSB-first bit
order).  Here the same layout is produced with statically-shaped device ops:
value bit j of value i lands at overall bit position ``i*width + j``; bytes
are LSB-first.  Formulated as a (n, width) bit-matrix -> reshape(-1, 8) ->
dot with byte weights, which XLA fuses into a single elementwise+reduce
program on the VPU (no MXU needed — this is bandwidth-bound).

Shapes are bucketed to powers of two and jit keys are (bucket, width), so at
most ~log2(n_max) * 32 programs ever compile.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
import numpy as np


def packed_reorder(order_key, payload, payload_bits: int):
    """Reorder ``payload`` by ascending ``order_key`` with ONE
    single-operand u32 sort of ``(order_key << payload_bits) | payload`` —
    XLA's sort fast path (~2x the variadic (key, payload) comparator on
    the v5e vector units).  Preconditions the CALLER must establish
    statically: ``order_key < 2**(32 - payload_bits)``,
    ``payload < 2**payload_bits``, and order keys unique (or tie order a
    don't-care).  Returns (reordered_payload, reordered_order_key) — the
    second output lets rank-compaction callers mask dropped slots.

    The one definition of the pack/sort/unpack transform used by the
    flagship kernel (parallel/sharded), the backend dictionary builder
    (ops/dictionary), and compact_by_rank below — a bound-condition fix
    here reaches all of them."""
    key = ((order_key.astype(jnp.uint32) << payload_bits)
           | payload.astype(jnp.uint32))
    s = jnp.sort(key)
    return s & jnp.uint32((1 << payload_bits) - 1), s >> payload_bits


def pad_bucket(n: int, minimum: int = 256) -> int:
    """Power-of-two padding bucket (multiple of 8) to bound recompilation."""
    return 1 << max(int(math.ceil(math.log2(max(n, 1)))), int(math.log2(minimum)))


def prefers_scatters() -> bool:
    """Hardware selection shared by every device kernel with a
    scatter-or-sort choice (dictionary compaction, bins gate, run
    compaction): per-element scatters/gathers are cheap on CPU and
    catastrophic on TPU vector units — measured 69 vs 12 ms/step for the
    bins dictionary build and 161 vs 12 ms/step for the scatter dictionary
    compaction on the same 64x65k batch on a v5e.  Evaluated per call (no
    process-lifetime cache) so a platform flip after first use — test
    harnesses toggling jax_platforms, late TPU init — re-selects the right
    kernel variant; jax.default_backend() is itself cached per config."""
    return jax.default_backend() == "cpu"


def compact_by_rank(rank, values, out_size: int,
                    scatters: bool | None = None,
                    value_bits: tuple | None = None):
    """Place each of ``values`` (one array or a tuple sharing ``rank``) at
    slot ``rank[i]`` for ranks < ``out_size``; ranks >= out_size are
    dropped; unfilled slots are zero.  Ranks below out_size must be a DENSE
    prefix 0..m-1 with one writer per slot (true for run ids and dictionary
    ranks) — the sort branches rely on density to make position == slot —
    and ``out_size`` must not exceed ``len(rank)`` (the sort branches
    cannot mint slots past the input length).  Scatter-drop on CPU; on TPU
    one variadic sort with the values riding along, OR — when the caller
    supplies ``value_bits`` (a static per-value bound on each value's bit
    width) and ``rank_bits + value_bits[i] <= 32`` — one SINGLE-OPERAND
    u32 sort per value on the key ``(rank << bits) | value``, XLA's sort
    fast path (~2x the variadic comparator on v5e; same reformulation as
    parallel/sharded.encode_step_single).  ``scatters`` overrides for
    tests."""
    single = not isinstance(values, tuple)
    vals = (values,) if single else values
    assert out_size <= rank.shape[0], (out_size, rank.shape)
    safe = jnp.minimum(rank, out_size)
    if prefers_scatters() if scatters is None else scatters:
        out = tuple(
            jnp.zeros(out_size + 1, v.dtype).at[safe].set(
                v, mode="drop")[:out_size]
            for v in vals)
    elif (value_bits is not None and all(b is not None for b in value_bits)
          and max(out_size.bit_length(), 1) + sum(value_bits) <= 32):
        # ALL values + the rank fit one u32 key: fold the value fields into
        # ONE payload and ride the shared packed_reorder transform — one
        # single-operand sort compacts everything (the level-run
        # extraction's case: rank_bits + level_bits + length_bits <= 32
        # for every realistic schema)
        total = sum(value_bits)
        payload = jnp.zeros(rank.shape, jnp.uint32)
        for v, bits in zip(vals, value_bits):
            payload = (payload << bits) | v.astype(jnp.uint32)
        sp, sr = packed_reorder(safe, payload, total)
        keep = sr[:out_size] < out_size
        out = []
        shift = 0
        for v, bits in reversed(list(zip(vals, value_bits))):
            field = (sp[:out_size] >> shift) & jnp.uint32((1 << bits) - 1)
            out.append(jnp.where(keep, field, 0).astype(v.dtype))
            shift += bits
        out = tuple(reversed(out))
    elif (value_bits is not None
          and all(b is not None
                  and max(out_size.bit_length(), 1) + b <= 32
                  for b in value_bits)):
        out = []
        for v, bits in zip(vals, value_bits):
            sv, sr = packed_reorder(safe, v, bits)
            out.append(jnp.where(sr[:out_size] < out_size, sv[:out_size],
                                 0).astype(v.dtype))
        out = tuple(out)
    else:
        sorted_all = jax.lax.sort((safe, *vals), num_keys=1)
        sr = sorted_all[0][:out_size]
        keep = sr < out_size
        out = tuple(
            jnp.where(keep, sv[:out_size], jnp.zeros((), sv.dtype))
            for sv in sorted_all[1:])
    return out[0] if single else out


@functools.partial(jax.jit, static_argnums=(1,))
def bitpack_device(values: jax.Array, width: int) -> jax.Array:
    """Pack uint32 ``values`` (length a multiple of 8, already masked so
    entries beyond the true count are zero) into parquet LSB-first bytes.
    Returns (len(values) * width // 8,) uint8."""
    v = values.astype(jnp.uint32)
    bits = ((v[:, None] >> jnp.arange(width, dtype=jnp.uint32)) & 1).astype(jnp.uint8)
    flat = bits.reshape(-1, 8)
    weights = (jnp.uint16(1) << jnp.arange(8, dtype=jnp.uint16)).astype(jnp.uint16)
    return (flat.astype(jnp.uint16) * weights).sum(axis=1).astype(jnp.uint8)


@functools.partial(jax.jit, static_argnums=(3, 4))
def pack_page(idx_full: jax.Array, start, count, bucket: int, width: int):
    """Encode one data page's dictionary indices.

    ``idx_full`` is the whole chunk's index array padded so that any
    ``dynamic_slice`` of size ``bucket`` starting at a valid page offset stays
    in bounds (see backend._DeviceIndices).  Returns:

    - packed: (bucket * width // 8,) uint8 — parquet bit-packed groups body
      (the caller slices to ceil(count/8)*width bytes);
    - long_sum: total length of runs >= 8 within [start, start+count) — the
      input to the CPU oracle's RLE-vs-bitpack decision
      (core.encodings.rle_hybrid_encode);
    - any_long: whether any run >= 8 exists.
    """
    page = jax.lax.dynamic_slice(idx_full, (start,), (bucket,))
    pos = jnp.arange(bucket, dtype=jnp.int32)
    valid = pos < count
    v = jnp.where(valid, page, 0).astype(jnp.uint32)

    packed = bitpack_device(v, width)

    # run-length stats (for the hybrid decision, mirrored from the CPU path)
    long_sum, _, any_long = _run_long_stats(v, valid)
    return packed, long_sum, any_long


def pack_page_host(idx_full: jax.Array, start: int, count: int, width: int,
                   bucket: int) -> tuple[np.ndarray, int, bool]:
    """Host wrapper: returns (packed bytes ndarray, long_sum, any_long)."""
    packed, long_sum, any_long = pack_page(
        idx_full, jnp.int32(start), jnp.int32(count), bucket, width)
    return np.asarray(packed), int(long_sum), bool(any_long)


def _run_scan(v, valid):
    """Scatter-free run labeling over one masked window: returns (newrun,
    run_id, run_start, run_len_here, is_end).  ``run_len_here`` is the run
    length up to and including each position (a max-scan of run-start
    positions replaces the scatter-add histogram, which is catastrophic on
    TPU vector units); ``is_end`` marks the last valid position of each
    run, where run_len_here is the run's total length."""
    n = v.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    newrun = _newrun(v, valid)
    run_id = jnp.cumsum(newrun.astype(jnp.int32)) - 1
    run_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(newrun, pos, -1))
    run_len_here = pos - run_start + 1
    nxt_break = jnp.concatenate([newrun[1:] | ~valid[1:],
                                 jnp.ones((1,), bool)])
    is_end = valid & nxt_break
    return newrun, run_id, run_start, run_len_here, is_end


def _newrun(v, valid):
    """THE run-start mask — the one definition of where runs begin, shared
    by the labeling scan (:func:`_run_scan`) and the scan-free stats
    (:func:`_run_long_stats`) so run semantics cannot drift between them
    (both must stay byte-identical to core.encodings._runs)."""
    return jnp.concatenate([jnp.ones((1,), bool), v[1:] != v[:-1]]) & valid


def _window_slice(padded, row, start, count, bucket: int):
    """THE window slice/mask convention: slice [start, start+bucket) of
    ``padded[row]``, zero-mask past ``count``.  Returns (v uint32, valid
    bool) — shared by every per-window device program in this module and
    ops.levels."""
    page = jax.lax.dynamic_slice(padded, (row, start), (1, bucket))[0]
    pos = jnp.arange(bucket, dtype=jnp.int32)
    valid = pos < count
    return jnp.where(valid, page, 0).astype(jnp.uint32), valid


def _run_long_stats(v, valid):
    """Scan-free run statistics over one masked window: (long_sum, n_runs,
    any_long), where ``long_sum`` is the total length of runs >= 8 — the
    RLE-vs-bitpack decision mass of core.encodings.rle_hybrid_encode.

    Computed from windowed SHIFTS of the run-start mask instead of the
    labeling scans: a position is the >=8th element of its run iff no run
    start lies at it or in the 6 positions behind it, and a run is long
    iff it contains an exactly-8th element (a >=8th element whose run
    start sits exactly 7 back), which each long run has exactly once, so

        long_sum = #(>=8th elements) + 7 * #(exactly-8th elements).

    Byte-identical to summing ``run_len_here`` at long ends (asserted by
    the level/value identity suites); programs that only need these stats
    drop :func:`_run_scan`'s cumsum AND associative max-scan entirely."""
    newrun = _newrun(v, valid)

    def back(x, k):  # x[q-k], False-padded at the window head
        return jnp.concatenate([jnp.zeros((k,), bool), x[:-k]])

    near_start = newrun
    for k in range(1, 7):
        near_start = near_start | back(newrun, k)
    ge8 = valid & ~near_start
    ex8 = ge8 & back(newrun, 7)
    n_ex8 = jnp.sum(ex8.astype(jnp.int32))
    long_sum = jnp.sum(ge8.astype(jnp.int32)) + 7 * n_ex8
    return long_sum, jnp.sum(newrun.astype(jnp.int32)), n_ex8 > 0


def window_run_scan(padded, row, start, count, bucket: int):
    """The run-LABELING window program (run ids / lengths / ends), used by
    programs that extract runs (ops.levels.level_runs_multi).  Stats-only
    programs (pack_page, _slice_mask_stats, level_stats_multi) use the
    scan-free :func:`_run_long_stats` instead; both build on the same
    :func:`_newrun` run-start mask and :func:`_window_slice` masking
    convention, so run semantics cannot drift from the CPU oracle
    (core.encodings._runs).

    Slices window [start, start+bucket) of ``padded[row]``, zero-masks past
    ``count``, labels runs.  Returns (v uint32 (bucket,), valid bool
    (bucket,), run_id int32 (bucket,), run_len_here int32 (bucket,),
    is_end bool (bucket,)) — see :func:`_run_scan`."""
    v, valid = _window_slice(padded, row, start, count, bucket)
    _, run_id, _, run_len_here, is_end = _run_scan(v, valid)
    return v, valid, run_id, run_len_here, is_end


def _slice_mask_stats(idx_all, col_ids, starts, counts, bucket):
    """vmap over pages: slice each page window, zero-mask past its count, and
    compute the long-run mass for the RLE-vs-bitpack decision.  Returns
    (v (P, bucket) uint32, long_sum (P,) int32)."""
    padded = jnp.pad(idx_all, ((0, 0), (0, bucket)))

    def one(cid, start, count):
        v, valid = _window_slice(padded, cid, start, count, bucket)
        long_sum, _, _ = _run_long_stats(v, valid)
        return v, long_sum

    return jax.vmap(one)(col_ids, starts, counts)


def _slice_mask(idx_all, col_ids, starts, counts, bucket):
    """Like :func:`_slice_mask_stats` without the run scan — for callers that
    already know the page's stats (the level planner's phase B)."""
    padded = jnp.pad(idx_all, ((0, 0), (0, bucket)))

    def one(cid, start, count):
        v, _ = _window_slice(padded, cid, start, count, bucket)
        return v

    return jax.vmap(one)(col_ids, starts, counts)


@functools.partial(jax.jit, static_argnums=(4, 5))
def _pack_pages_multi_xla(idx_all, col_ids, starts, counts, bucket: int, width: int):
    v, long_sum = _slice_mask_stats(idx_all, col_ids, starts, counts, bucket)
    return jax.vmap(lambda p: bitpack_device(p, width))(v), long_sum


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def _pack_pages_multi_pallas(idx_all, col_ids, starts, counts, bucket: int,
                             width: int, interpret: bool):
    from .pallas_bitpack import bitpack_pages_core

    v, long_sum = _slice_mask_stats(idx_all, col_ids, starts, counts, bucket)
    return bitpack_pages_core(v, width, interpret), long_sum


# Below this many total values the pallas launch is dispatch-dominated and
# the fused-XLA program wins (measured on v5e: crossover ~1M values).
_PALLAS_MIN_VALUES = 1 << 20


def use_pallas(n_values: int) -> tuple[bool, bool]:
    """(use, interpret) for the bit-pack dispatch.  KPW_PALLAS=0 disables,
    =1 forces, =interpret forces the interpreter (CPU CI); default: real
    Mosaic kernels on TPU for large batches only."""
    mode = os.environ.get("KPW_PALLAS", "auto")
    if mode == "0":
        return False, False
    if mode == "interpret":
        return True, True
    if mode == "1":
        return True, False
    return (jax.default_backend() == "tpu"
            and n_values >= _PALLAS_MIN_VALUES), False


@functools.partial(jax.jit, static_argnums=(4, 5))
def _pack_only_xla(idx_all, col_ids, starts, counts, bucket: int, width: int):
    v = _slice_mask(idx_all, col_ids, starts, counts, bucket)
    return jax.vmap(lambda p: bitpack_device(p, width))(v)


@functools.partial(jax.jit, static_argnums=(4, 5, 6))
def _pack_only_pallas(idx_all, col_ids, starts, counts, bucket: int,
                      width: int, interpret: bool):
    from .pallas_bitpack import bitpack_pages_core

    v = _slice_mask(idx_all, col_ids, starts, counts, bucket)
    return bitpack_pages_core(v, width, interpret)


def pack_pages_only(idx_all: jax.Array, col_ids: jax.Array, starts: jax.Array,
                    counts: jax.Array, bucket: int, width: int) -> jax.Array:
    """:func:`pack_pages_multi` without the run-stats pass — for pages whose
    RLE-vs-bitpack decision is already known.  Returns packed
    (P, bucket*width//8) uint8."""
    pal, interp = use_pallas(len(col_ids) * bucket)
    if pal:
        return _pack_only_pallas(idx_all, col_ids, starts, counts, bucket,
                                 width, interp)
    return _pack_only_xla(idx_all, col_ids, starts, counts, bucket, width)


def pack_pages_multi(idx_all: jax.Array, col_ids: jax.Array, starts: jax.Array,
                     counts: jax.Array, bucket: int, width: int):
    """Pack many pages — possibly from different columns of one (C, N) index
    batch — in a single program (one dispatch for the whole group instead of
    one per page; essential when dispatch latency is high).

    Returns (packed (P, bucket*width//8) uint8, long_sum (P,) int32) where
    long_sum is the total length of runs >= 8 in each page (the input to the
    oracle's RLE-vs-bitpack decision; a page has a long run iff long_sum > 0).

    On TPU with enough work the bit-pack runs as a pallas kernel
    (pallas_bitpack.py: VMEM-resident bit expand + MXU byte fold); otherwise
    the fused-XLA formulation.  Both are byte-identical to the CPU oracle.
    """
    pal, interp = use_pallas(len(col_ids) * bucket)
    if pal:
        return _pack_pages_multi_pallas(
            idx_all, col_ids, starts, counts, bucket, width, interp)
    return _pack_pages_multi_xla(idx_all, col_ids, starts, counts, bucket, width)


@functools.partial(jax.jit, static_argnums=(3,))
def gather_index_slices(idx_all: jax.Array, col_ids: jax.Array,
                        starts: jax.Array, bucket: int) -> jax.Array:
    """Fetch index windows [start, start+bucket) for several (column, start)
    pairs in one program — used to pull only the rare long-run pages to the
    host for the exact mixed RLE stream."""
    padded = jnp.pad(idx_all, ((0, 0), (0, bucket)))

    def one(cid, start):
        return jax.lax.dynamic_slice(padded, (cid, start), (1, bucket))[0]

    return jax.vmap(one)(col_ids, starts)
