"""Device-side BYTE_ARRAY dictionary build (VERDICT r4 next #8 probe).

Strings are the one dictionary family with no device path: the production
route is the C++ host hash (native/src/encode.cc dict_build_bytes), with a
k-way union for mesh merges.  This module prototypes the device
formulation so its win/loss can be measured honestly at cfg1's shape.

The trick is a SINGLE u64 sort key per string that is bijective and
order-preserving for short strings:

    key = (first 7 bytes, zero-padded, big-endian) << 8 | min(len, 8)

- big-endian packing makes u64 ascending == lexicographic ascending of
  the 7-byte prefix;
- the length byte disambiguates zero-padding (b"a" vs b"a\\x00") and
  orders a string before its proper extensions ("ab" < "abc"), matching
  bytes comparison;
- two DISTINCT strings map to the same key only when both have len >= 8
  and share their first 7 bytes — exactly the groups that need a host
  tie-break (suffix sort), detectable as key-groups containing a row
  with len >= 8.  Everything else reconstructs from the key alone, no
  per-row host work.

The u64 keys then ride the existing device dictionary machinery
(ops.dictionary.DictBuildHandle -> the fused build sort on TPU), and the
host splices tie-broken groups into the ascending order.  Output is
byte-identical to core.encodings.dictionary_build / the C++ host hash
(asserted in tests/test_strings_device.py).

Reference behavior anchor: parquet-mr's DictionaryValuesWriter builds one
byte-array hash per column on the host (SURVEY.md §2.2); this is the
TPU-native counter-design, not a translation.
"""

from __future__ import annotations

import numpy as np

from ..core.bytecol import ByteColumn


def prefix_keys(col: ByteColumn) -> np.ndarray:
    """(n,) uint64 sort keys: 7 zero-padded prefix bytes big-endian, then
    min(len, 8) in the low byte (see module docstring for why this is
    order-preserving and near-bijective)."""
    n = len(col)
    if n == 0:
        return np.zeros(0, np.uint64)
    data = np.frombuffer(col.data, np.uint8) if not isinstance(
        col.data, np.ndarray) else col.data.view(np.uint8)
    offs = col.offsets
    starts = offs[:-1]
    lens = np.diff(offs)
    take = np.minimum(lens, 7)
    if len(data) == 0:
        # all rows are empty strings: no bytes to gather, keys are pure
        # length bytes (all zero) — the fancy index below would read a
        # zero-length array
        return np.zeros(n, np.uint64)
    # gather a (n, 7) byte block; rows shorter than 7 read clamped
    # positions and are masked to the zero pad
    j = np.arange(7)
    idx = np.minimum(starts[:, None] + j, len(data) - 1)
    block = np.where(j[None, :] < take[:, None], data[idx], 0)
    key = np.zeros(n, np.uint64)
    for b in range(7):  # 7 shifts over vectors, not a per-row loop
        key |= block[:, b].astype(np.uint64) << np.uint64(8 * (7 - b))
    key |= np.minimum(lens, 8).astype(np.uint64)
    return key


def _key_to_bytes(key: int) -> bytes:
    """Inverse of :func:`prefix_keys` for unambiguous keys (len <= 7 or
    the canonical prefix of a len-8 marker)."""
    ln = key & 0xFF
    pre = int(key >> 8).to_bytes(7, "big")
    return pre[: min(ln, 7)]


def device_string_dictionary(col: ByteColumn, max_k: int | None = None,
                             timings: dict | None = None):
    """Byte-array dictionary via the device key build + host tie-break.

    Returns (dict_values list[bytes] ascending lexicographic, indices
    uint32) identical to ``core.encodings.dictionary_build``, or None when
    the unique count exceeds ``max_k`` (the host paths' abort contract).
    ``timings`` (optional dict) receives the phase breakdown in ms —
    ``prefix_ms`` (host key extraction), ``device_ms`` (key dictionary
    build incl. readback), ``tiebreak_ms`` (host suffix resolution) — so
    the bench probe can report where the time goes.
    """
    import time

    from .dictionary import DictBuildHandle

    n = len(col)
    t0 = time.perf_counter()
    keys = prefix_keys(col)
    t1 = time.perf_counter()
    if n == 0:
        return [], np.zeros(0, np.uint32)
    handle = DictBuildHandle(keys)
    kdict, kidx = handle.result()
    # device batches pad rows to the static bucket: trim to the real n
    kidx = np.asarray(kidx)[:n].astype(np.uint32, copy=False)
    t2 = time.perf_counter()
    k_keys = len(kdict)
    lens = np.diff(col.offsets)
    # ambiguous key-groups: contain a row with len >= 8 (key bijective
    # otherwise).  Distinct suffixes expand such a group into several
    # dictionary slots; lexicographic order within the group equals
    # suffix order (shared 7-byte prefix).
    ambiguous = np.zeros(k_keys, bool)
    long_rows = np.nonzero(lens >= 8)[0]
    ambiguous[kidx[long_rows]] = True
    t_tie0 = time.perf_counter()
    if not ambiguous.any():
        dict_values = [_key_to_bytes(int(k)) for k in kdict]
        out_idx = kidx
        if max_k is not None and len(dict_values) > max_k:
            return None
    else:
        # per ambiguous group: sort the distinct full strings; splice
        group_members: dict[int, dict[bytes, int]] = {}
        for r in long_rows:
            g = int(kidx[r])
            group_members.setdefault(g, {}).setdefault(col[int(r)], 0)
        extra = np.zeros(k_keys, np.int64)  # additional slots per group
        group_rank: dict[int, dict[bytes, int]] = {}
        group_order: dict[int, list[bytes]] = {}
        for g, members in group_members.items():
            ordered = sorted(members)
            group_order[g] = ordered
            group_rank[g] = {v: i for i, v in enumerate(ordered)}
            extra[g] = len(ordered) - 1
        base = np.concatenate([[0], np.cumsum(extra)[:-1]])  # slot shift
        dict_values: list[bytes] = []
        for g in range(k_keys):
            if ambiguous[g]:
                dict_values.extend(group_order[g])
            else:
                dict_values.append(_key_to_bytes(int(kdict[g])))
        if max_k is not None and len(dict_values) > max_k:
            return None
        out_idx = (kidx.astype(np.int64) + base[kidx]).astype(np.uint32)
        if long_rows.size:
            # rows in ambiguous groups add their within-group rank
            sub = np.fromiter(
                (group_rank[int(kidx[r])][col[int(r)]] for r in long_rows),
                np.uint32, long_rows.size)
            out_idx[long_rows] += sub
    t3 = time.perf_counter()
    if timings is not None:
        timings["prefix_ms"] = round((t1 - t0) * 1e3, 3)
        timings["device_ms"] = round((t2 - t1) * 1e3, 3)
        timings["tiebreak_ms"] = round((t3 - t_tie0) * 1e3, 3)
        # how much of the column fell to the per-row host tie-break loop
        # (ADVICE r5 #3): rows with len >= 8 pay Python-level work in two
        # passes, so a mostly-long column degenerates toward a full host
        # loop — the probe's reader needs that denominator, not just the ms
        timings["tiebreak_row_fraction"] = round(long_rows.size / n, 4)
    return dict_values, out_idx
