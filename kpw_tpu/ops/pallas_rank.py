"""Pallas TPU kernel for dictionary RANK extraction over narrow-range
values — the matmul half of the sort-free dictionary build used by
``parallel.sharded.encode_step_single`` for planner-bounded columns
(``value_bound`` <= 2^13: the gcd-stride/affine offsets of the cfg2
shape).

A value v < value_bound decomposes as ``v = hi*64 + lo6``.  Given the
per-column rank table RT (value -> ascending-unique index, from the
histogram pass), each row's rank is the bilinear form

    rank_r = H[r] @ RT2d @ L[r]^T,     RT2d = RT.reshape(nhi, 64)

with H/L the one-hot matrices of hi/lo6.  The XLA formulation
materialises H (N x nhi) and M = H @ RT2d (N x 64) in HBM — ~24 MB per
64Ki-row column, which makes it memory-bound (measured 2.6 ms vs the
production sort kernel's 1.8 at the 16-col probe shape).  This kernel
keeps every intermediate in VMEM: each grid step loads a TILE of raw
values, builds H/L on the VPU, does one small matmul on the MXU, and
writes only the TILE of int32 ranks — one HBM read of the values, one
write of the ranks, nothing in between.

Exactness: TPU matmuls at DEFAULT precision compute in bf16 passes, so
rank-table entries (< 8192) would round to multiples of 32.  The table
therefore splits into two bf16-EXACT planes ``RT = RThi*128 + RTlo``
(both < 128; one-hot H is 0/1, also exact) and the kernel does one
``H @ [RThi | RTlo]`` matmul with f32 accumulation, recombining the
planes on the VPU — exact at the MXU's fastest precision, no
HIGHEST-precision multi-pass fallback needed.

Masking: rows past the valid count must rank 0.  Callers pre-mask them
to the sentinel ``nhi*64`` (any value with hi >= nhi): its H row is all
zero, so M and the rank come out 0 — no count plumbing into the kernel.

``interpret=True`` runs the Pallas interpreter on any backend (how the
CPU CI exercises this file, same convention as ops.pallas_bitpack).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

S_LO = 64  # lo radix; nhi = padded value_bound / 64

# Values per grid step: R lane-rows of 128 values.  Layout is the whole
# game on TPU: values stay on the LANE dimension end to end (a (TILE, 1)
# values-on-sublanes layout measured 4x SLOWER than the sort it was
# meant to beat — 127 of 128 lanes idle and the physical array padded
# 128-wide), bins live on sublanes, and the per-row one-hot matmul runs
# TRANSPOSED: M^T = cat^T @ H^T with H^T (nhi x 128) built by comparing
# a broadcast lane vector against a sublane iota.
ROW_LANES = 128
ROWS_PER_STEP = 16


def _rank_kernel(lo_ref, rtt_ref, out_ref, *, nhi: int):
    """lo_ref (1, R, 128) uint32, rtt_ref (1, 128, nhi) bf16 (transposed
    split-plane rank table [RThi | RTlo]^T) -> out_ref (1, R, 128) int32
    ranks (0 for sentinel-masked values)."""
    v = lo_ref[0]      # (R, 128) uint32
    catT = rtt_ref[0]  # (128, nhi) bf16, rows 0..63 = RThi, 64.. = RTlo
    rows = v.shape[0]
    bins = jax.lax.broadcasted_iota(jnp.int32, (nhi, ROW_LANES), 0)
    lbins = jax.lax.broadcasted_iota(jnp.int32, (S_LO, ROW_LANES), 0)
    out = []
    for r in range(rows):
        vr = v[r:r + 1]  # (1, 128) uint32 — one lane vector
        hi = (vr >> jnp.uint32(6)).astype(jnp.int32)
        lo6 = (vr & jnp.uint32(S_LO - 1)).astype(jnp.int32)
        HT = (bins == hi).astype(jnp.bfloat16)        # (nhi, 128)
        MT = jnp.dot(catT, HT,
                     preferred_element_type=jnp.float32)  # (128, 128)
        LT = (lbins == lo6).astype(jnp.float32)       # (64, 128)
        rank = jnp.sum((MT[:S_LO] * 128.0 + MT[S_LO:]) * LT,
                       axis=0, keepdims=True)         # (1, 128)
        out.append(rank.astype(jnp.int32))
    out_ref[0] = jnp.concatenate(out, axis=0)


def presence_to_dict(counts: jax.Array, nhi: int):
    """The ONE definition of the histogram->dictionary step shared by the
    production path (parallel.sharded._encode_step_single_matmul) and the
    prototype tool: per column, (nhi, 64) bin counts -> (rank table
    (nhi, 64) int32, ascending-unique dictionary ulo (nhi*64,) uint32
    padded with 0xFFFFFFFF, unique count k).  One tiny nhi*64-bin sort
    per column instead of an N-row one."""
    vb = nhi * S_LO

    def one(cnt):
        present = (cnt > 0).reshape(-1)
        k = jnp.sum(present.astype(jnp.int32))
        rt = (jnp.cumsum(present.astype(jnp.int32)) - 1).reshape(nhi, S_LO)
        bins = jnp.arange(vb, dtype=jnp.uint32)
        ulo = jnp.sort(jnp.where(present, bins, jnp.uint32(0xFFFFFFFF)))
        return rt, ulo, k

    return jax.vmap(one)(counts)


def _hist_kernel(lo_ref, out_ref, *, nhi: int):
    """lo_ref (1, R, 128) uint32 -> accumulate the (nhi, 64) bin-count
    matrix over every grid step of the column (out block revisited across
    the row-tile axis; zero-initialised on its first step).  One
    contract-on-lanes matmul per lane row: counts += HT . LT^T."""
    @pl.when(pl.program_id(1) == 0)
    def _init():
        out_ref[0] = jnp.zeros((nhi, S_LO), jnp.float32)

    v = lo_ref[0]  # (R, 128) uint32
    rows = v.shape[0]
    bins = jax.lax.broadcasted_iota(jnp.int32, (nhi, ROW_LANES), 0)
    lbins = jax.lax.broadcasted_iota(jnp.int32, (S_LO, ROW_LANES), 0)
    acc = jnp.zeros((nhi, S_LO), jnp.float32)
    for r in range(rows):
        vr = v[r:r + 1]
        hi = (vr >> jnp.uint32(6)).astype(jnp.int32)
        lo6 = (vr & jnp.uint32(S_LO - 1)).astype(jnp.int32)
        HT = (bins == hi).astype(jnp.bfloat16)   # (nhi, 128)
        LT = (lbins == lo6).astype(jnp.bfloat16)  # (64, 128)
        acc = acc + jax.lax.dot_general(
            HT, LT, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
    out_ref[0] += acc


def hist_pages_core(lo_masked: jax.Array, nhi: int,
                    interpret: bool = False) -> jax.Array:
    """Traceable core: lo_masked (C, N) uint32 (invalid rows pre-masked to
    the sentinel nhi*64) -> (C, nhi, 64) f32 bin counts.  The counts are
    exact integers only while every bin stays below 2^24 (f32 mantissa;
    bf16 one-hot inputs, f32 accumulation) — beyond that, and after any
    cross-shard f32 psum of these histograms, only POSITIVITY is
    guaranteed (cnt > 0 survives rounding), which is all
    presence_to_dict consumes (ADVICE r4).  Constraints as
    :func:`rank_pages_core`."""
    C, N = lo_masked.shape
    if nhi > 128:
        raise ValueError(f"nhi={nhi} exceeds the 2^13 value-bound design")
    if N % ROW_LANES:
        raise ValueError(f"N={N} must be a multiple of {ROW_LANES}")
    rows_total = N // ROW_LANES
    r_step = math.gcd(rows_total, ROWS_PER_STEP)
    v3 = lo_masked.reshape(C, rows_total, ROW_LANES)
    return pl.pallas_call(
        functools.partial(_hist_kernel, nhi=nhi),
        out_shape=jax.ShapeDtypeStruct((C, nhi, S_LO), jnp.float32),
        grid=(C, rows_total // r_step),
        in_specs=[pl.BlockSpec((1, r_step, ROW_LANES), lambda c, t: (c, t, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, nhi, S_LO), lambda c, t: (c, 0, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(v3)


def rank_pages_core(lo_masked: jax.Array, rt: jax.Array,
                    interpret: bool = False) -> jax.Array:
    """Traceable core: lo_masked (C, N) uint32 (invalid rows pre-masked to
    the sentinel nhi*64), rt (C, nhi, 64) int32 rank tables -> (C, N)
    int32 ranks.  N must be a multiple of 128 (pad_bucket guarantees a
    power of two >= 256); nhi <= 128 (value_bound <= 2^13)."""
    C, N = lo_masked.shape
    nhi = rt.shape[1]
    if nhi > 128:
        raise ValueError(f"nhi={nhi} exceeds the 2^13 value-bound design")
    if N % ROW_LANES:
        raise ValueError(f"N={N} must be a multiple of {ROW_LANES}")
    # split-plane (< 128, bf16-exact) transposed table, built once in XLA
    cat = jnp.concatenate([rt // 128, rt % 128], axis=2)  # (C, nhi, 128)
    catT = jnp.swapaxes(cat, 1, 2).astype(jnp.bfloat16)   # (C, 128, nhi)
    rows_total = N // ROW_LANES
    r_step = math.gcd(rows_total, ROWS_PER_STEP)
    v3 = lo_masked.reshape(C, rows_total, ROW_LANES)
    ranks = pl.pallas_call(
        functools.partial(_rank_kernel, nhi=nhi),
        out_shape=jax.ShapeDtypeStruct((C, rows_total, ROW_LANES), jnp.int32),
        grid=(C, rows_total // r_step),
        in_specs=[
            pl.BlockSpec((1, r_step, ROW_LANES), lambda c, t: (c, t, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, ROW_LANES, nhi), lambda c, t: (c, 0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, r_step, ROW_LANES), lambda c, t: (c, t, 0),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(v3, catT)
    return ranks.reshape(C, N)
