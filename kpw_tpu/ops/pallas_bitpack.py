"""Pallas TPU kernel for parquet LSB-first bit-packing.

The XLA formulation in ``ops.packing`` materialises a (n, width) bit matrix
in HBM between the shift/mask step and the byte-fold reduce.  This kernel
keeps the whole pipeline in VMEM: each grid step loads a tile of dictionary
indices, expands bits on the VPU, and folds them into output bytes with one
small constant matmul on the MXU — one HBM read of the indices and one HBM
write of the packed bytes, nothing in between.

Layout.  A page of ``n`` values at bit ``width`` w packs value i's bit j at
overall bit position ``i*w + j`` (LSB-first bytes) —
``core.encodings.bitpack`` is the byte-exact oracle.  Group 8 consecutive
values: group g emits exactly w bytes (8 values x w bits), so a page
reshaped to (G, 8) (G = bucket/8) maps to (G, w) output bytes with no
cross-group carries.  Transposed to put G on the TPU lane dimension:

  v8t   (8, G)  uint32   v8t[i, g] = value 8g+i
  bits  (8w, G)          bits[i*w+j, g] = (v8t[i, g] >> j) & 1
  bytes (w, G)  = Wt @ bits   where Wt[m, p] = 2^(p%8) if p//8 == m else 0

The matmul is exact in float32 (partial sums <= 255).  The grid is
(pages, lane-tiles); lane tiles bound VMEM to ~1 MiB regardless of bucket.

Used by ``ops.packing.pack_pages_multi`` when running on a real TPU
(KPW_PALLAS=1 forces it, KPW_PALLAS=0 disables, KPW_PALLAS=interpret runs
the interpreter on any backend — how the CPU CI exercises this file).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Lane-dimension tile: 8 * 32 * LANE_TILE * 4 B of bit planes ~= 1 MiB at
# width 32 — comfortably inside VMEM with double buffering.
LANE_TILE = 1024


def _fold_matrix(width: int) -> jnp.ndarray:
    """(width, 8*width) f32: Wt[m, p] = 2^(p%8) iff byte p//8 == m."""
    p = jax.lax.broadcasted_iota(jnp.int32, (width, 8 * width), 1)
    m = jax.lax.broadcasted_iota(jnp.int32, (width, 8 * width), 0)
    weight = (jnp.int32(1) << (p % 8)).astype(jnp.float32)
    return jnp.where(p // 8 == m, weight, 0.0)


def _bitpack_kernel(v_ref, out_ref, *, width: int):
    """v_ref (1, 8, Gt) uint32 -> out_ref (1, width, Gt) f32 (byte values)."""
    v = v_ref[0]  # (8, Gt)
    gt = v.shape[1]
    shifts = jax.lax.broadcasted_iota(jnp.uint32, (8, width, 1), 1)
    bits = ((v[:, None, :] >> shifts) & jnp.uint32(1))  # (8, width, Gt)
    # Mosaic has no uint32->f32 cast; bits are 0/1 so int32 is lossless.
    bits_flat = bits.reshape(8 * width, gt).astype(jnp.int32).astype(jnp.float32)
    out_ref[0] = jnp.dot(_fold_matrix(width), bits_flat,
                         preferred_element_type=jnp.float32)


def bitpack_pages_core(pages: jax.Array, width: int,
                       interpret: bool = False) -> jax.Array:
    """Traceable core (callable inside an enclosing jit): (P, bucket) uint32,
    entries beyond each page's count already masked to zero -> (P,
    bucket*width//8) uint8, byte-equal to ``core.encodings.bitpack`` per
    page.  bucket must be a multiple of 8 (ops.packing.pad_bucket guarantees
    a power of two >= 256)."""
    P, bucket = pages.shape
    if bucket % 8:
        raise ValueError(f"bucket must be a multiple of 8, got {bucket}")
    G = bucket // 8
    # Lane tile must divide G exactly or trailing groups would never be
    # computed; gcd keeps full tiles for the power-of-two buckets pad_bucket
    # produces and stays correct for any multiple of 8.
    gt = math.gcd(G, LANE_TILE)
    v8t = pages.reshape(P, G, 8).transpose(0, 2, 1)  # (P, 8, G)

    bytes_f = pl.pallas_call(
        functools.partial(_bitpack_kernel, width=width),
        out_shape=jax.ShapeDtypeStruct((P, width, G), jnp.float32),
        grid=(P, G // gt),
        in_specs=[pl.BlockSpec((1, 8, gt), lambda p, g: (p, 0, g),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((1, width, gt), lambda p, g: (p, 0, g),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(v8t)

    # (P, w, G) byte planes -> (P, G, w) -> row-major byte stream per page.
    return bytes_f.astype(jnp.uint8).transpose(0, 2, 1).reshape(P, G * width)


@functools.partial(jax.jit, static_argnums=(1, 2))
def bitpack_pages_pallas(pages: jax.Array, width: int,
                         interpret: bool = False) -> jax.Array:
    return bitpack_pages_core(pages, width, interpret)
