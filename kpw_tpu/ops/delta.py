"""Device DELTA_BINARY_PACKED (SURVEY.md §7 step 5: "delta &
delta-length-byte-array" as per-column device kernels; BASELINE.md config 3).

The parquet delta format (core.encodings.delta_binary_packed_encode is the
byte oracle): blocks of 128 deltas, 4 miniblocks of 32, per-block zigzag
min-delta, per-miniblock bit widths, miniblocks packed LSB-first at their
own width.  The data-parallel work — ring-arithmetic deltas, signed block
minima, relative deltas, per-miniblock widths, and the bit-packing itself —
runs on device with static shapes:

- 64-bit ring arithmetic without device int64: values travel as (hi, lo)
  uint32 pairs; subtract-with-borrow and signed comparison via a sign-bit
  flip (the same key-splitting convention as ops.dictionary);
- widths are data-dependent per miniblock; each miniblock packs at its
  RUNTIME width through one branch-free shift-sum program writing into a
  fixed 256-byte slot (worst case: 32 values x 64 bits) — see
  ``_pack_mb_runtime_width`` for why a ``lax.switch`` over static widths
  is a vmap trap on TPU;
- the host assembles the stream in O(blocks): header varints, zigzag
  min-deltas, width bytes, and memcpy slices of the packed buffer.

Byte-identity with the numpy oracle is asserted by tests for int32 and
int64 across sign/wraparound edge cases.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.thrift import varint_bytes, zigzag

_BLOCK = 128
_MINI = 4
_MB = 32  # values per miniblock


def _sub64(ahi, alo, bhi, blo):
    """(a - b) mod 2^64 on (hi, lo) uint32 pairs."""
    lo = alo - blo
    borrow = (alo < blo).astype(jnp.uint32)
    hi = ahi - bhi - borrow
    return hi, lo


def _bit_width64(hi, lo):
    """bit_width of the unsigned 64-bit value (hi, lo): 0 for 0.  Pass
    ``hi=None`` when the hi plane is statically zero (single-plane ladder)."""
    def bw32(x):
        # 32 - clz(x) via float trick is inexact; use comparison ladder
        w = jnp.zeros(x.shape, jnp.int32)
        for b in range(32):
            w = jnp.where(x >= (jnp.uint32(1) << b), b + 1, w)
        return w

    if hi is None:
        return bw32(lo)
    return jnp.where(hi > 0, 32 + bw32(hi), bw32(lo))


def _pack_mb_runtime_width(hi, lo, w, max_bits: int = 64) -> jnp.ndarray:
    """LSB-first pack of 32 (hi, lo) values at RUNTIME width ``w`` into a
    fixed (4*max_bits,) uint8 slot (4*w bytes meaningful, rest zero) —
    branch-free.

    Replaces the original ``lax.switch`` over 65 static-width packers:
    under ``vmap`` (per-miniblock widths differ) XLA lowers a batched
    switch to computing EVERY branch and selecting, so each miniblock paid
    for all 65 packs — measured 35.5 ms for an 8-column 64Ki-value window
    on a v5e, ~30x the dictionary kernel per column, plus a combinatorial
    compile-time blowup.  Here each output byte is a masked shift-sum:
    value i occupies bit range [i*w, i*w+w) of the stream, so its
    contribution to byte b is ``(r_i >> (8b - i*w)) & 0xFF`` (or a left
    shift when the value starts mid-byte).  Different values' bits within
    one byte are DISJOINT, so integer summation equals bitwise OR and the
    (32 values x 4*max_bits bytes) grid needs no carries, no gathers, and
    no branches — one elementwise program for every width at once.

    ``max_bits`` is a STATIC budget on the runtime widths (w <= max_bits
    must hold for every miniblock — the caller derives it from host-known
    value range, see ``_delta_window``): the byte grid shrinks from the
    worst-case 256 columns to 4*max_bits, and when max_bits <= 32 the hi
    plane is statically zero so the 64-bit shift ladder collapses to the
    lo plane alone.  A violated budget silently truncates the stream, so
    budgets must come from a real bound, never a guess."""
    i = jnp.arange(_MB, dtype=jnp.int32)[:, None]  # value index
    b = jnp.arange(4 * max_bits, dtype=jnp.int32)[None, :]  # output byte index
    rel = 8 * b - i * w  # value-relative bit offset feeding byte b
    if max_bits <= 32:
        # hi plane statically zero: single-plane right shift, amounts < 32
        # for every cell that can be valid (rel < w <= 32; clamp shields
        # the masked-out cells from UB shift amounts)
        s_lo = jnp.clip(rel, 0, 31).astype(jnp.uint32)
        shr = lo[:, None] >> s_lo
    else:
        # 64-bit right shift by rel in [0, 64): piecewise over the planes
        s = jnp.clip(rel, 0, 63).astype(jnp.uint32)
        s_lo = jnp.minimum(s, 31)  # shift amounts must stay < 32 (XLA UB) --
        s_hi = jnp.where(s >= 32, s - 32, 0)
        # -- including inside unselected where-branches: at s_lo == 0 the raw
        # amount (32 - s_lo) would be 32, so clamp it before the mask selects
        up = jnp.where(s_lo > 0,
                       hi[:, None] << (32 - jnp.maximum(s_lo, 1)), 0)
        shr = jnp.where(s < 32,
                        (lo[:, None] >> s_lo) | up,
                        hi[:, None] >> s_hi)
    # left shift (value starts mid-byte): only -rel in (0, 8) matters
    t = jnp.clip(-rel, 0, 7).astype(jnp.uint32)
    shl = (lo[:, None] & 0xFF) << t
    c = jnp.where(rel >= 0, shr, shl) & jnp.uint32(0xFF)
    valid = (rel + 8 > 0) & (rel < w) & (w > 0)
    return jnp.sum(jnp.where(valid, c, 0), axis=0,
                   dtype=jnp.uint32).astype(jnp.uint8)


def _delta_window(vhi: jax.Array, vlo: jax.Array, n: jax.Array,
                  bit_size: int, max_bits: int | None = None):
    """Traceable core: DELTA_BINARY_PACKED device phase for one window of
    ``n`` values provided as (hi, lo) uint32 pairs padded to 1 + blocks*128
    entries.

    ``bit_size`` selects the ring: 64 works on (hi, lo) pairs, 32 on the lo
    plane alone (hi fixed at zero) — one kernel body for both.

    ``max_bits`` is a STATIC bound on every miniblock's bit width, i.e. on
    ``bit_width(delta - min_delta)``.  The caller derives it from the
    host-known value range: deltas lie in [-(vmax-vmin), vmax-vmin], so
    ``bit_length(2*(vmax-vmin))`` always works (``delta_bits_bucket``).
    The packed slots shrink from the worst-case 256 bytes to 4*max_bits
    and, when max_bits <= 32, the relative deltas are provably
    single-plane so the width scan and the pack drop the hi plane.  The
    output is byte-identical to the unbudgeted kernel wherever the bound
    holds; a violated bound silently truncates (same contract as
    ``encode_step_single(value_bound=...)``).

    Returns (min_hi, min_lo) per block (signed min-deltas), widths
    (blocks, 4) int32, and packed (blocks, 4, 4*max_bits) uint8 miniblock
    slots (each meaningful up to 4*width bytes; padding blocks width 0).
    """
    ring64 = bit_size == 64
    if max_bits is None:
        max_bits = bit_size
    blocks = (vhi.shape[0] - 1) // _BLOCK
    nd = n - 1
    if ring64:
        dhi, dlo = _sub64(vhi[1:], vlo[1:], vhi[:-1], vlo[:-1])  # ring deltas
    else:
        dlo = vlo[1:] - vlo[:-1]  # uint32 ring
        dhi = jnp.zeros_like(dlo)
    total = blocks * _BLOCK
    pos = jnp.arange(total, dtype=jnp.int32)
    valid = pos < nd
    dhi = dhi.reshape(blocks, _BLOCK)
    dlo = dlo.reshape(blocks, _BLOCK)
    vmask = valid.reshape(blocks, _BLOCK)
    f = jnp.uint32(0x8000_0000)
    ones = jnp.uint32(0xFFFFFFFF)

    def per_block(bhi, blo, bvalid):
        # signed min over the valid deltas as TWO vectorized reduces
        # (lexicographic on the sign-flipped hi plane, then the lo plane
        # among the hi-plane winners) — replaces a 128-step sequential
        # lax.scan that cost ~0.4 ms of the 8-column 64Ki-row probe.
        # Invalid slots lift to +inf; a fully-pad block keeps the scan
        # semantics' (bhi[0], blo[0]) so outputs stay bit-identical.
        any_v = bvalid[0]  # valid slots are a prefix of the window
        if ring64:
            kh = jnp.where(bvalid, bhi ^ f, ones)
            mkh = jnp.min(kh)
            kl = jnp.where(bvalid & (kh == mkh), blo, ones)
            mhi = jnp.where(any_v, mkh ^ f, bhi[0])
            mlo = jnp.where(any_v, jnp.min(kl), blo[0])
        else:
            kl = jnp.where(bvalid, blo ^ f, ones)
            mhi = jnp.zeros((), jnp.uint32)
            mlo = jnp.where(any_v, jnp.min(kl) ^ f, blo[0])
        if ring64:
            rhi, rlo = _sub64(bhi, blo, jnp.broadcast_to(mhi, bhi.shape),
                              jnp.broadcast_to(mlo, blo.shape))
        else:
            rhi, rlo = jnp.zeros_like(bhi), blo - mlo
        # pad (invalid) slots pack as zero, like the oracle's zero padding
        rlo = jnp.where(bvalid, rlo, 0)
        rlo_m = rlo.reshape(_MINI, _MB)
        if max_bits <= 32:
            rhi_m = jnp.zeros_like(rlo_m)  # provably zero under the budget
        else:
            rhi_m = jnp.where(bvalid, rhi, 0).reshape(_MINI, _MB)
        mb_valid = bvalid.reshape(_MINI, _MB)

        def per_mb(mhi_v, mlo_v, mv):
            any_valid = jnp.any(mv)
            if max_bits <= 32:
                w = jnp.max(jnp.where(mv, _bit_width64(None, mlo_v), 0))
            else:
                w = jnp.max(jnp.where(mv, _bit_width64(mhi_v, mlo_v), 0))
            w = jnp.where(any_valid, w, 0)
            packed = _pack_mb_runtime_width(mhi_v, mlo_v, w, max_bits)
            return w, packed

        ws, packs = jax.vmap(per_mb)(rhi_m, rlo_m, mb_valid)
        return mhi, mlo, ws, packs

    return jax.vmap(per_block)(dhi, dlo, vmask)


# Static width-budget buckets: one compiled program per bucket actually
# used; the grid cost is proportional to the bucket, so finer steps at the
# narrow end (near-sorted timestamps, string lengths) matter most.
_DELTA_BITS_BUCKETS = (8, 16, 24, 32, 48, 64)


def delta_bits_bucket(value_range: int, bit_size: int) -> int:
    """Smallest static width-budget bucket covering every possible
    miniblock width for a stream whose values span ``value_range`` =
    vmax - vmin (as Python ints — no ring overflow).  Any delta lies in
    [-range, range] and the packed relative deltas in [0, 2*range], so
    ``bit_length(2*range)`` bounds every width.  Ranges wide enough to
    wrap the signed ring fall back to the full ``bit_size`` budget."""
    if value_range < 0:
        raise ValueError("value_range must be >= 0")
    need = max((2 * value_range).bit_length(), 1)
    for b in _DELTA_BITS_BUCKETS:
        if need <= b <= bit_size:
            return b
    return bit_size


@functools.partial(jax.jit, static_argnums=(3, 4))
def delta_blocks_device(vhi: jax.Array, vlo: jax.Array, n: jax.Array,
                        bit_size: int, max_bits: int | None = None):
    """One full stream (see :func:`_delta_window`); jit keys bounded by the
    caller's power-of-two block padding."""
    return _delta_window(vhi, vlo, n, bit_size, max_bits)


@functools.partial(jax.jit, static_argnums=(5, 6, 7))
def delta_pages_multi(hi_all: jax.Array, lo_all: jax.Array,
                      stream_ids: jax.Array, starts: jax.Array,
                      counts: jax.Array, bucket: int, bit_size: int,
                      max_bits: int | None = None):
    """Batched per-page delta encode over windows of stacked value streams —
    the TPU backend's planner launches ONE of these per (bucket, bit_size,
    max_bits) group so a whole row group's delta pages cost one dispatch
    (ops.backend._DeltaPlanner), mirroring pack_pages_multi.

    ``hi_all``/``lo_all`` are (K, maxN) uint32 planes; each page encodes the
    window [start, start + bucket] of its stream (bucket a multiple of 128,
    ops.packing.pad_bucket guarantees it), masked to ``count`` values.
    ``max_bits`` is the static per-group width budget (every stream in the
    group must satisfy it — see :func:`delta_bits_bucket`).  Returns
    per-page stacked :func:`_delta_window` outputs.
    """
    padded_hi = jnp.pad(hi_all, ((0, 0), (0, bucket + 1)))
    padded_lo = jnp.pad(lo_all, ((0, 0), (0, bucket + 1)))

    def one(sid, start, count):
        whi = jax.lax.dynamic_slice(padded_hi, (sid, start), (1, bucket + 1))[0]
        wlo = jax.lax.dynamic_slice(padded_lo, (sid, start), (1, bucket + 1))[0]
        return _delta_window(whi, wlo, count, bit_size, max_bits)

    return jax.vmap(one)(stream_ids, starts, counts)


def assemble_delta_page(first_value: int, count: int, mh, ml, widths, packed,
                        bit_size: int, max_bits: int | None = None) -> bytes:
    """Host assembly of one page's DELTA_BINARY_PACKED stream from the
    device outputs (O(blocks)); byte-identical to the oracle.

    ``max_bits`` is the static width budget the device pack ran under: a
    miniblock width above it means the budget was violated and the packed
    plane was silently truncated on device — the host sees every width
    here anyway, so the check turns silent data corruption into a loud
    error (ADVICE r4)."""
    out = bytearray()
    out += varint_bytes(_BLOCK)
    out += varint_bytes(_MINI)
    out += varint_bytes(count)
    if count == 0:
        out += varint_bytes(0)
        return bytes(out)
    out += varint_bytes(zigzag(int(first_value)))
    if count == 1:
        return bytes(out)
    blocks = (count - 1 + _BLOCK - 1) // _BLOCK
    for b in range(blocks):
        md = int(ml[b]) if bit_size == 32 else (int(mh[b]) << 32) | int(ml[b])
        if md >= 1 << (bit_size - 1):
            md -= 1 << bit_size
        out += varint_bytes(zigzag(md))
        out += bytes(int(w) for w in widths[b])
        for m in range(_MINI):
            w = int(widths[b][m])
            if max_bits is not None and w > max_bits:
                raise ValueError(
                    f"delta miniblock width {w} exceeds the device pack's "
                    f"static budget max_bits={max_bits} (block {b}, "
                    f"miniblock {m}): the packed stream is truncated")
            if w:
                out += packed[b, m, : 4 * w].tobytes()
    return bytes(out)


def _split64(values: np.ndarray):
    a = np.ascontiguousarray(values)
    if a.dtype.itemsize == 8:
        u = a.view(np.uint64)
        return (u >> np.uint64(32)).astype(np.uint32), u.astype(np.uint32)
    u = a.view(np.uint32)
    return np.zeros_like(u), u


def delta_binary_packed_device(values: np.ndarray, bit_size: int = 64) -> bytes:
    """Full DELTA_BINARY_PACKED via the device kernel + O(blocks) host
    assembly.  Byte-identical to core.encodings.delta_binary_packed_encode."""
    itype = np.int64 if bit_size == 64 else np.int32
    v = np.ascontiguousarray(values, itype)
    n = len(v)
    if n <= 1:
        return assemble_delta_page(int(v[0]) if n else 0, n,
                                   None, None, None, None, bit_size)
    blocks = (n - 1 + _BLOCK - 1) // _BLOCK
    # pad the block count to a power of two so jit specializes on a bounded
    # set of shapes (invalid blocks mask to width-0 miniblocks)
    pad_blocks = 1 << max(0, (blocks - 1).bit_length())
    padded = np.zeros(1 + pad_blocks * _BLOCK, itype)
    padded[:n] = v
    hi, lo = _split64(padded)
    # host min/max (O(n), trivially cheap next to the encode) statically
    # bounds every miniblock width — the kernel's pack grid shrinks to it
    max_bits = delta_bits_bucket(int(v.max()) - int(v.min()), bit_size)
    mh, ml, widths, packed = jax.device_get(  # one bulk readback
        delta_blocks_device(jnp.asarray(hi), jnp.asarray(lo), jnp.int32(n),
                            bit_size, max_bits))
    return assemble_delta_page(int(v[0]), n, mh, ml, widths, packed, bit_size,
                               max_bits=max_bits)


def delta_length_byte_array_device(values) -> bytes:
    """DELTA_LENGTH_BYTE_ARRAY with the length vector delta-packed on
    device; the byte payload is a straight host concat."""
    from ..core.bytecol import lens_and_payload

    lens, payload = lens_and_payload(values)
    return delta_binary_packed_device(lens, 32) + payload
