"""BYTE_STREAM_SPLIT on the device — the byte-plane transpose as one jit.

The encoding is a pure data-movement transform: the K byte planes of N
K-byte values, concatenated (plane j holds byte j of every value in
order).  On device that is a (N, K) uint8 reshape + transpose, which XLA
lowers to a vectorized copy — no arithmetic, so the win over the native
host loop is purely bandwidth/overlap (the transpose rides the chip while
the host assembles other pages).

Byte-identity contract: output == kpw_tpu.core.encodings
.byte_stream_split_encode(values, pt) for values already in the column's
PLAIN dtype.  Inputs are padded to a power-of-two bucket (ops.packing
.pad_bucket) so the jit cache stays bounded like the delta kernels
(ops/delta.py); the pad tail is sliced off per plane on host.
"""

from __future__ import annotations

import functools

import numpy as np

import jax
import jax.numpy as jnp

from .packing import pad_bucket


@functools.partial(jax.jit, static_argnums=(1,))
def _bss_planes(flat_u8, width: int):
    """(pad_n * width,) uint8 value bytes -> (width, pad_n) byte planes."""
    return flat_u8.reshape(-1, width).T


def byte_stream_split_device(values: np.ndarray) -> bytes:
    """BYTE_STREAM_SPLIT body for ``values`` (already the column's PLAIN
    dtype — caller coerces, exactly like the native route), transposed on
    device.  Byte-identical to the numpy oracle."""
    v = np.ascontiguousarray(values)
    n, width = len(v), v.dtype.itemsize
    if n == 0:
        return b""
    pad_n = pad_bucket(n)
    flat = np.zeros(pad_n * width, np.uint8)
    flat[: n * width] = v.view(np.uint8).reshape(-1)
    planes = np.asarray(jax.device_get(_bss_planes(jnp.asarray(flat), width)))
    # drop the pad tail of every plane, keeping plane order (= the spec's
    # plane-major concatenation)
    return np.ascontiguousarray(planes[:, :n]).tobytes()
