"""TPU-native encoder kernels (JAX/XLA/Pallas).

This package is build-plan step 5 (SURVEY.md §7): the hot encode math that
parquet-mr runs record-at-a-time on the JVM (reference ParquetFile.java:59-62
-> ColumnWriter/page encoders) re-designed as batched, statically-shaped
device kernels:

- ``dictionary``: sort-based dictionary build (first-occurrence order) on
  device — replaces parquet-mr's per-record hash DictionaryValuesWriter.
- ``packing``: RLE/bit-pack hybrid page bodies — bit extraction + byte
  assembly as vectorized device ops.
- ``backend``: ``TpuChunkEncoder``, a drop-in for the CPU reference encoder
  at the EncoderBackend boundary, byte-identical output.

Everything is shape-static and jit-cached by (padded-size bucket, bit width)
so XLA compiles a small number of programs regardless of data.
"""

from .backend import TpuChunkEncoder  # noqa: F401
