"""NativeChunkEncoder — C++ host encode path at the pluggable boundary.

Same primitive-op boundary as the TPU backend (kpw_tpu/core/pages.py
``CpuChunkEncoder``), with dictionary build and RLE/bit-pack moved into the
native library (src/encode.cc).  Output is byte-identical to the numpy
oracle; anything the native path doesn't cover (strings, narrow dtypes,
missing .so) falls through to the superclass.

This is the fast single-host CPU path — the rebuild's counterpart of
parquet-mr's C++-less JVM encode stack reached from ParquetFile.java:59-62,
and the backend the auto-selector picks when the accelerator link is too
slow to pay for offload (kpw_tpu/runtime/writer.py).
"""

from __future__ import annotations

import struct

import numpy as np

from ..core import encodings as enc
from ..core.pages import CpuChunkEncoder, EncoderOptions
from ..core.schema import PhysicalType
from . import lib


class NativeChunkEncoder(CpuChunkEncoder):
    """Byte-identical C++ implementation of the chunk encoder primitives."""

    def __init__(self, options: EncoderOptions) -> None:
        super().__init__(options)
        self._lib = lib()

    def _native_ok(self, values, pt: int) -> bool:
        return (
            self._lib is not None
            and isinstance(values, np.ndarray)
            and values.dtype.kind in "iuf"
            and values.dtype.itemsize in (4, 8)
            and pt not in (PhysicalType.BOOLEAN, PhysicalType.BYTE_ARRAY,
                           PhysicalType.FIXED_LEN_BYTE_ARRAY)
        )

    def _dictionary_build(self, values, pt: int):
        if not self._native_ok(values, pt):
            return super()._dictionary_build(values, pt)
        key = values.view(np.uint32 if values.dtype.itemsize == 4 else np.uint64)
        d, idx = self._lib.dict_build(key)
        return d.view(values.dtype), idx

    def _try_dictionary(self, chunk):
        values = chunk.values
        pt = chunk.column.leaf.physical_type
        if not self._native_ok(values, pt):
            return super()._try_dictionary(chunk)
        # Largest k that would survive the rejection checks in encode():
        # the ratio bound and the dictionary-page byte budget.
        n = len(values)
        opts = self.options
        max_k = min(max(1, int(n * opts.max_dictionary_ratio)),
                    opts.dictionary_page_size_limit // values.dtype.itemsize)
        key = values.view(np.uint32 if values.dtype.itemsize == 4 else np.uint64)
        built = self._lib.dict_build(key, max_k=max_k)
        if built is None:
            return None  # proven infeasible; encode() falls back to plain/delta
        d, idx = built
        return d.view(values.dtype), idx

    def _indices_body(self, indices, va: int, vb: int, dict_size: int) -> bytes:
        L = self._lib
        if L is None or not isinstance(indices, np.ndarray):
            return super()._indices_body(indices, va, vb, dict_size)
        width = enc.bit_width(max(dict_size - 1, 0))
        return bytes([width]) + L.rle_hybrid(indices[va:vb], width)

    def _levels_body(self, levels: np.ndarray, max_level: int) -> bytes:
        L = self._lib
        if L is None:
            return super()._levels_body(levels, max_level)
        body = L.rle_hybrid(np.asarray(levels), enc.bit_width(max_level))
        return struct.pack("<I", len(body)) + body
