"""NativeChunkEncoder — C++ host encode path at the pluggable boundary.

Same primitive-op boundary as the TPU backend (kpw_tpu/core/pages.py
``CpuChunkEncoder``), with dictionary build and RLE/bit-pack moved into the
native library (src/encode.cc).  Output is byte-identical to the numpy
oracle; anything the native path doesn't cover (strings, narrow dtypes,
missing .so) falls through to the superclass.

This is the fast single-host CPU path — the rebuild's counterpart of
parquet-mr's C++-less JVM encode stack reached from ParquetFile.java:59-62,
and the backend the auto-selector picks when the accelerator link is too
slow to pay for offload (kpw_tpu/runtime/writer.py).
"""

from __future__ import annotations

import struct
import threading

import numpy as np

from ..core import encodings as enc
from ..core.bytecol import ByteColumn
from ..core.bytecol import lens_and_payload
from ..core.pages import CpuChunkEncoder, EncoderOptions, shared_assembly_pool
from ..core.schema import Codec, Encoding, PhysicalType
from . import assemble, lib

# compat alias: the shared host-assembly pool moved to core.pages so the
# split launch||assemble pipeline can use it without importing native
_shared_pool = shared_assembly_pool


class NativeChunkEncoder(CpuChunkEncoder):
    """Byte-identical C++ implementation of the chunk encoder primitives.

    encode_many / the launch||assemble split ride the superclass; this
    backend's hot primitives (dictionary build, RLE/bit-pack, delta,
    codecs) are GIL-releasing native calls, so _parallel_assembly_ok
    unlocks column-parallel page assembly across the shared pool — the
    intra-file counterpart of the reference's thread-per-file data
    parallelism (KafkaProtoParquetWriter.java:40-41)."""

    def __init__(self, options: EncoderOptions) -> None:
        super().__init__(options)
        self._lib = lib()
        self._asm = assemble() if options.native_assembly else None
        self._tl = threading.local()  # per-thread compression scratch

    def _parallel_assembly_ok(self) -> bool:
        return self._lib is not None

    def _native_assembler(self):
        """The nogil assemble_pages extension when this encoder's codec is
        covered by it, else None (Python page loops).  SNAPPY additionally
        requires the ctypes lib so the fallback path compresses through the
        same snappy_compress_parts object code (identical frames); ZSTD
        requires zstd on BOTH .so builds for the same reason.  Codecs the
        extension doesn't implement (gzip/brotli/lz4) always take the
        Python loops."""
        asm = self._asm
        if asm is None or not self.options.native_assembly:
            return None
        codec = self.options.codec
        if codec == Codec.UNCOMPRESSED:
            return asm
        if codec == Codec.SNAPPY and self._lib is not None:
            return asm
        if (codec == Codec.ZSTD and asm.HAS_ZSTD
                and self._lib is not None and self._lib.has_zstd):
            return asm
        return None

    def _page_stats_min_max(self, chunk, va: int, vb: int, pt: int):
        """ByteColumn page stats through the C++ lexicographic scan (the
        same kpw_bytes_min_max the chunk-level _stats_min_max override
        uses) instead of a per-page Python min/max over bytes objects."""
        v = chunk.values
        if self._lib is not None and isinstance(v, ByteColumn) and vb > va:
            sub = v[va:vb]
            mn, mx = self._lib.bytes_min_max(sub.data, sub.offsets)
            lo, hi = bytes(sub[mn]), bytes(sub[mx])
            return lo, hi, lo, hi
        return super()._page_stats_min_max(chunk, va, vb, pt)

    @staticmethod
    def _fixed_width_ok(values, pt: int) -> bool:
        """Shared eligibility shape test for fixed-width numeric fast paths
        (native primitives here, mesh-global dictionaries in
        parallel/mesh_encoder.py)."""
        return (
            isinstance(values, np.ndarray)
            and values.dtype.kind in "iuf"
            and values.dtype.itemsize in (4, 8)
            and pt not in (PhysicalType.BOOLEAN, PhysicalType.BYTE_ARRAY,
                           PhysicalType.FIXED_LEN_BYTE_ARRAY)
        )

    def _native_ok(self, values, pt: int) -> bool:
        return self._lib is not None and self._fixed_width_ok(values, pt)

    def _fixed_width_max_k(self, n: int, itemsize: int) -> int:
        """Largest dictionary size that survives encode()'s rejection
        checks (the ratio bound and the dictionary-page byte budget) for a
        fixed-width column — shared by the native and mesh early-aborts so
        they can't drift from encode()'s actual acceptance."""
        opts = self.options
        return min(max(1, int(n * opts.max_dictionary_ratio)),
                   opts.dictionary_page_size_limit // itemsize)

    def _bytes_native_ok(self, values, pt: int) -> bool:
        return (self._lib is not None
                and pt in (PhysicalType.BYTE_ARRAY,
                           PhysicalType.FIXED_LEN_BYTE_ARRAY)
                and isinstance(values, (list, ByteColumn)))

    @staticmethod
    def _bytes_parts(values) -> tuple[bytes, np.ndarray]:
        if not isinstance(values, ByteColumn):
            values = ByteColumn.from_list(values)
        return values.data, values.offsets  # zero-copy, absolute offsets

    def _bytes_dictionary(self, values, max_k: int | None):
        data, offsets = self._bytes_parts(values)
        built = self._lib.dict_build_bytes(data, offsets, max_k)
        if built is None:
            return None
        uniq_pos, idx = built
        if isinstance(values, ByteColumn):
            return values.take(uniq_pos), idx
        return [values[p] for p in uniq_pos], idx

    def _dictionary_build(self, values, pt: int):
        if self._bytes_native_ok(values, pt):
            return self._bytes_dictionary(values, None)
        if not self._native_ok(values, pt):
            return super()._dictionary_build(values, pt)
        key = values.view(np.uint32 if values.dtype.itemsize == 4 else np.uint64)
        d, idx = self._lib.dict_build(key)
        return d.view(values.dtype), idx

    def _try_dictionary(self, chunk):
        values = chunk.values
        pt = chunk.column.leaf.physical_type
        # a column with a bloom filter configured needs the exact distinct
        # set regardless of the dictionary verdict (core/index.py
        # population) — finishing the build is cheaper than a second
        # distinct pass, so the ratio early-abort is waived
        keep_distinct = self._bloom_wants_distinct(chunk)
        if self._bytes_native_ok(values, pt):
            # Early abort at the ratio bound (the byte-budget check needs the
            # built dictionary, so encode() still applies it afterwards).
            max_k = (None if keep_distinct else
                     max(1, int(len(values)
                                * self.options.max_dictionary_ratio)))
            return self._bytes_dictionary(values, max_k)
        if not self._native_ok(values, pt):
            return super()._try_dictionary(chunk)
        n = len(values)
        max_k = (n if keep_distinct
                 else self._fixed_width_max_k(n, values.dtype.itemsize))
        key = values.view(np.uint32 if values.dtype.itemsize == 4 else np.uint64)
        built = self._lib.dict_build(key, max_k=max_k)
        if built is None:
            return None  # proven infeasible; encode() falls back to plain/delta
        d, idx = built
        return d.view(values.dtype), idx

    def _values_body(self, values, pt: int, encoding: int) -> bytes:
        L = self._lib
        if L is not None and encoding == Encoding.DELTA_BINARY_PACKED:
            bit_size = 32 if pt == PhysicalType.INT32 else 64
            return L.delta_binary_packed(np.asarray(values), bit_size)
        if L is not None and encoding == Encoding.DELTA_LENGTH_BYTE_ARRAY:
            lens, payload = lens_and_payload(values)
            return L.delta_binary_packed(lens, 32) + payload
        if (L is not None and encoding == Encoding.BYTE_STREAM_SPLIT
                and pt in enc._PLAIN_DTYPES):
            # coerce to the column's PLAIN dtype first, exactly like the
            # oracle — the transpose must see the on-wire value bytes
            return L.byte_stream_split(
                np.ascontiguousarray(values, enc._PLAIN_DTYPES[pt]))
        return super()._values_body(values, pt, encoding)

    def _values_page_parts(self, chunk, va: int, vb: int, pt: int,
                           encoding: int) -> list:
        """DELTA_LENGTH_BYTE_ARRAY without materializing the concatenation:
        [tiny delta-of-lengths header, zero-copy payload view] — the codec
        streams the parts (page bytes unchanged)."""
        v = chunk.values
        if (self._lib is not None
                and encoding == Encoding.DELTA_LENGTH_BYTE_ARRAY
                and isinstance(v, ByteColumn)):
            o = v.offsets
            lens = np.diff(o[va:vb + 1])
            delta = self._lib.delta_binary_packed(lens, 32)
            payload = memoryview(v.data)[int(o[va]):int(o[vb])]
            return [delta, payload]
        return super()._values_page_parts(chunk, va, vb, pt, encoding)

    def _compress_parts(self, parts: list, body_len: int):
        """ZSTD and SNAPPY pages compress straight from the parts into
        per-thread scratch (no Python-side body concatenation, no zeroed
        bounce buffers, no compressed-bytes copy); other codecs take the
        base path."""
        opts = self.options
        if (self._lib is not None and opts.codec == Codec.ZSTD
                and self._lib.has_zstd):
            level = 3 if opts.compression_level is None else opts.compression_level
            res = self._lib.zstd_compress_parts(
                parts, level, getattr(self._tl, "zscratch", None))
            if res is not None:
                arr, n = res
                self._tl.zscratch = arr  # reuse; consumer copies immediately
                return memoryview(arr)[:n], n
        if self._lib is not None and opts.codec == Codec.SNAPPY:
            arr, n = self._lib.snappy_compress_parts(
                parts, getattr(self._tl, "sscratch", None))
            self._tl.sscratch = arr  # reuse; consumer copies immediately
            return memoryview(arr)[:n], n
        return super()._compress_parts(parts, body_len)

    def _stats_min_max(self, values, pt: int):
        if (self._lib is not None and isinstance(values, ByteColumn)
                and len(values)):
            mn, mx = self._lib.bytes_min_max(values.data, values.offsets)
            return values[mn], values[mx]
        return super()._stats_min_max(values, pt)

    def _plain_body(self, values, pt: int) -> bytes:
        if (self._lib is not None and isinstance(values, ByteColumn)
                and pt == PhysicalType.BYTE_ARRAY):
            return self._lib.byte_array_plain(values.data, values.offsets)
        return super()._plain_body(values, pt)

    def _indices_body(self, indices, va: int, vb: int, dict_size: int) -> bytes:
        L = self._lib
        if L is None or not isinstance(indices, np.ndarray):
            return super()._indices_body(indices, va, vb, dict_size)
        width = enc.bit_width(max(dict_size - 1, 0))
        return bytes([width]) + L.rle_hybrid(indices[va:vb], width)

    def _levels_body(self, levels: np.ndarray, max_level: int) -> bytes:
        L = self._lib
        if L is None:
            return super()._levels_body(levels, max_level)
        body = L.rle_hybrid(np.asarray(levels), enc.bit_width(max_level))
        return struct.pack("<I", len(body)) + body
