"""Native (C++) host library: codecs and byte-assembly hot paths.

The reference's only native code lives in its codec JNI deps (SURVEY.md §2.2
"Native-code accounting"); correspondingly this package holds the framework's
C++: a from-scratch Snappy block codec, a libzstd wrapper, and CRC32C.  Built
lazily with g++ on first use; all callers must tolerate ``lib() is None`` and
fall back to pure-python/ctypes paths (kpw_tpu.core.compression).
"""

from __future__ import annotations

_lib = None
_tried = False


def lib():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    try:
        from .build import load

        _lib = load()
    except Exception as e:
        import os
        import warnings

        if os.environ.get("KPW_TPU_NATIVE_REQUIRE"):
            raise
        warnings.warn(f"kpw_tpu native codec library unavailable ({e!r}); "
                      "falling back to ctypes/python codecs")
        _lib = None
    return _lib
