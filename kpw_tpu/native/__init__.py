"""Native (C++) host library: codecs and byte-assembly hot paths.

The reference's only native code lives in its codec JNI deps (SURVEY.md §2.2
"Native-code accounting"); correspondingly this package holds the framework's
C++: a from-scratch Snappy block codec, a libzstd wrapper, and CRC32C.  Built
lazily with g++ on first use; all callers must tolerate ``lib() is None`` and
fall back to pure-python/ctypes paths (kpw_tpu.core.compression).
"""

from __future__ import annotations

_lib = None
_tried = False
_pyshred = None
_pyshred_tried = False
_assemble = None
_assemble_tried = False


def lib():
    global _lib, _tried
    if _tried:
        return _lib
    _tried = True
    try:
        from .build import load

        _lib = load()
    except Exception as e:
        import os
        import warnings

        if os.environ.get("KPW_TPU_NATIVE_REQUIRE"):
            raise
        warnings.warn(f"kpw_tpu native codec library unavailable ({e!r}); "
                      "falling back to ctypes/python codecs")
        _lib = None
    return _lib


def pyshred():
    """The zero-copy CPython shred extension (src/pyshred.cc), or None —
    callers must fall back to the ctypes join path (NativeLib.proto_shred)."""
    global _pyshred, _pyshred_tried
    if _pyshred_tried:
        return _pyshred
    _pyshred_tried = True
    try:
        from .build import load_pyshred

        _pyshred = load_pyshred()
    except Exception as e:
        import os
        import warnings

        if os.environ.get("KPW_TPU_NATIVE_REQUIRE"):
            raise
        warnings.warn(f"kpw_tpu pyshred extension unavailable ({e!r}); "
                      "using the ctypes shred path")
        _pyshred = None
    return _pyshred


def assemble():
    """The nogil batch page-assembly extension (src/assemble.cc), or None —
    callers must fall back to the pure-Python page loop
    (kpw_tpu.core.pages.CpuChunkEncoder.encode)."""
    global _assemble, _assemble_tried
    if _assemble_tried:
        return _assemble
    _assemble_tried = True
    try:
        from .build import load_assemble

        _assemble = load_assemble()
    except Exception as e:
        import os
        import warnings

        if os.environ.get("KPW_TPU_NATIVE_REQUIRE"):
            raise
        warnings.warn(f"kpw_tpu assemble extension unavailable ({e!r}); "
                      "using the Python page-assembly loop")
        _assemble = None
    return _assemble
