// Batch protobuf wire-format shredder for NESTED schemas — repeated fields
// (packed and expanded), nested/repeated submessages, and enums — the
// Dremel-levels counterpart of shred.cc's flat decoder.  Together they give
// the native ingest path the reference's full data-model coverage: the
// reference funnels ANY Message subclass through one parse+shred path
// (KafkaProtoParquetWriter.java:671-684 parser.parseFrom +
// ParquetFile.java:97-99 ProtoWriteSupport), and with this file the native
// fast path does too, instead of only flat scalar messages.
//
// Semantics mirror kpw_tpu/models/proto_bridge.py's Python Dremel visitor
// byte-for-byte (the fallback and the oracle in tests):
//   - per-leaf outputs: values for PRESENT entries only, plus one
//     (def, rep) level pair per visit (value or null);
//   - repeated items after the first take rep level = depth of the nearest
//     repeated ancestor being iterated (Dremel), first item takes the
//     inherited r0;
//   - singular scalars are last-value-wins within one message instance;
//   - singular MESSAGE fields occurring twice in one instance require wire
//     merge semantics -> Python fallback (rare; parsers must merge);
//   - proto2 closed enums drop unknown values (they live in unknown
//     fields), proto3 open enums surface the raw number (the Python side
//     renders UNKNOWN_ENUM_{v} names, proto_bridge._emit_value);
//   - proto3 no-presence scalars emit their default when absent; proto2
//     required fields missing -> record error -> fallback.
//
// Any record this decoder cannot prove clean is reported by index and the
// whole batch re-parses in Python (exact per-record poison-pill policy).
//
// Wire-format reference: the public protobuf encoding spec (varint/fixed
// tags, packed repeated encoding, last-value-wins, unknown-field skipping).

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <vector>

#include "wire_common.h"

namespace {

using kpw_wire::read_varint;
using kpw_wire::utf8_ok;

// field kinds (mirrored in kpw_tpu/models/proto_bridge.py _WIRE_KINDS /
// _NESTED_KINDS; 0-8 shared with shred.cc)
enum Kind : uint8_t {
  K_VARINT64 = 0,
  K_VARINT32 = 1,
  K_SINT64 = 2,
  K_SINT32 = 3,
  K_FIXED64 = 4,
  K_FIXED32 = 5,
  K_BOOL = 6,
  K_SPAN = 7,
  K_SPAN_UTF8 = 8,
  K_MESSAGE = 9,
  K_ENUM = 10,  // int32 value slot; name rendering happens in Python
};

enum Flags : uint8_t {
  F_REQUIRED = 1,      // proto2 required: absence is a record parse error
  F_REPEATED = 2,      // Dremel repeated node
  F_DEF_INC = 4,       // present value adds 1 to def (OPTIONAL / REPEATED)
  F_EMIT_DEFAULT = 8,  // proto3 no-presence: absent -> emit default value
  F_CLOSED_ENUM = 16,  // proto2 enum: unknown numbers are dropped
};

inline int elem_size(uint8_t k) {
  switch (k) {
    case K_VARINT64:
    case K_SINT64:
    case K_FIXED64:
      return 8;
    case K_VARINT32:
    case K_SINT32:
    case K_FIXED32:
    case K_ENUM:
      return 4;
    case K_BOOL:
      return 1;
    default:
      return 0;  // spans
  }
}

struct LeafOut {
  std::vector<uint8_t> values;  // fixed-width payload (elem_size each)
  std::vector<int64_t> spos;    // span positions (span kinds)
  std::vector<int32_t> slen;    // span lengths
  std::vector<uint8_t> defs;    // one per visit (value or null)
  std::vector<uint8_t> reps;
};

struct Plan {
  int32_t n_nodes, n_leaves;
  const uint32_t* fnum;
  const uint8_t* kind;
  const uint8_t* flags;
  const int32_t* child_begin;
  const int32_t* child_end;
  const int32_t* leaf_idx;
  const int32_t* ftab;      // per message node: field number -> child node
  const int32_t* ftab_off;  // offset of node's table in ftab
  const int32_t* max_fn;    // table covers field numbers [0, max_fn]
  const int32_t* enum_vals;  // sorted valid numbers per closed enum node
  const int32_t* enum_off;
  const int32_t* enum_len;
  const int32_t* null_leaves;  // descendant leaves per message node
  const int32_t* null_off;
  const int32_t* null_len;
};

// per-(frame, child) parse state, preallocated as depth x max_children
struct ChildState {
  int32_t occ;       // accepted occurrences so far
  uint8_t seen;      // singular scalar pending?
  uint64_t pend;     // pending fixed value (raw bits)
  int64_t pend_pos;  // pending span
  int32_t pend_len;
};

struct Shredder {
  const Plan& plan;
  const uint8_t* buf;
  std::vector<LeafOut> leaves;
  std::vector<ChildState> scratch;  // depth-major frames
  int32_t max_children;

  Shredder(const Plan& p, const uint8_t* b, int64_t n_rec)
      : plan(p), buf(b), leaves(p.n_leaves) {
    max_children = 1;
    int depth_cap = 1;
    // schema depth bounds recursion depth (we only recurse into known
    // message children), so depth <= n_nodes is a safe scratch bound
    for (int32_t m = 0; m < p.n_nodes; m++) {
      int32_t c = p.child_end[m] - p.child_begin[m];
      if (c > max_children) max_children = c;
    }
    depth_cap = p.n_nodes + 1;
    scratch.resize(size_t(depth_cap) * max_children);
    for (auto& lf : leaves) {
      lf.defs.reserve(size_t(n_rec));
      lf.reps.reserve(size_t(n_rec));
    }
  }

  void emit_levels(LeafOut& lf, int d, int r) {
    lf.defs.push_back(uint8_t(d));
    lf.reps.push_back(uint8_t(r));
  }

  void emit_fixed(int32_t leaf, uint8_t k, uint64_t raw, int d, int r) {
    LeafOut& lf = leaves[leaf];
    int sz = elem_size(k);
    size_t at = lf.values.size();
    lf.values.resize(at + sz);
    std::memcpy(lf.values.data() + at, &raw, sz);  // little-endian hosts
    emit_levels(lf, d, r);
  }

  void emit_span(int32_t leaf, int64_t pos, int32_t len, int d, int r) {
    LeafOut& lf = leaves[leaf];
    lf.spos.push_back(pos);
    lf.slen.push_back(len);
    emit_levels(lf, d, r);
  }

  void emit_null(int32_t leaf, int d, int r) {
    emit_levels(leaves[leaf], d, r);
  }

  void emit_nulls_subtree(int32_t node, int d, int r) {
    const int32_t off = plan.null_off[node];
    const int32_t len = plan.null_len[node];
    for (int32_t i = 0; i < len; i++)
      emit_null(plan.null_leaves[off + i], d, r);
  }

  // one accepted scalar occurrence: emit (repeated) or stage (singular)
  void scalar_occurrence(ChildState& st, int32_t ch, uint8_t k, uint8_t fl,
                         uint64_t raw, int64_t pos, int32_t len, int r0,
                         int d0, int rep_depth) {
    if (fl & F_REPEATED) {
      int r = st.occ == 0 ? r0 : rep_depth + 1;
      int d = d0 + 1;
      if (k == K_SPAN || k == K_SPAN_UTF8)
        emit_span(plan.leaf_idx[ch], pos, len, d, r);
      else
        emit_fixed(plan.leaf_idx[ch], k, raw, d, r);
      st.occ++;
    } else {
      st.seen = 1;  // last value wins; emitted at frame end
      st.pend = raw;
      st.pend_pos = pos;
      st.pend_len = len;
    }
  }

  bool enum_accept(int32_t ch, uint8_t fl, uint64_t raw, int64_t* val) {
    int32_t v = int32_t(uint32_t(raw));  // low 32 bits, like the runtimes
    if (fl & F_CLOSED_ENUM) {
      const int32_t* t = plan.enum_vals + plan.enum_off[ch];
      int32_t lo = 0, hi = plan.enum_len[ch];
      while (lo < hi) {
        int32_t mid = (lo + hi) / 2;
        if (t[mid] < v)
          lo = mid + 1;
        else
          hi = mid;
      }
      if (lo >= plan.enum_len[ch] || t[lo] != v) return false;  // dropped
    }
    *val = v;
    return true;
  }

  // parse one message instance; false -> record takes the Python fallback
  bool parse(int32_t node, const uint8_t* p, const uint8_t* end, int r0,
             int d0, int rep_depth, int depth) {
    const int32_t cb = plan.child_begin[node];
    const int32_t ce = plan.child_end[node];
    ChildState* st = scratch.data() + size_t(depth) * max_children;
    std::memset(st, 0, sizeof(ChildState) * (ce - cb));
    const int32_t* table = plan.ftab + plan.ftab_off[node];
    const int32_t mfn = plan.max_fn[node];

    while (p < end) {
      uint64_t tag;
      if (!read_varint(p, end, &tag)) return false;
      uint32_t field = uint32_t(tag >> 3);
      uint32_t wire = uint32_t(tag & 7);
      if (field == 0) return false;
      int32_t ch = (field <= uint32_t(mfn)) ? table[field] : -1;
      if (ch < 0) {  // unknown field: skip by wire type
        uint64_t v;
        switch (wire) {
          case 0:
            if (!read_varint(p, end, &v)) return false;
            break;
          case 1:
            if (end - p < 8) return false;
            p += 8;
            break;
          case 2:
            if (!read_varint(p, end, &v) || uint64_t(end - p) < v)
              return false;
            p += v;
            break;
          case 5:
            if (end - p < 4) return false;
            p += 4;
            break;
          default:
            return false;  // groups / reserved
        }
        continue;
      }
      ChildState& cst = st[ch - cb];
      const uint8_t k = plan.kind[ch];
      const uint8_t fl = plan.flags[ch];

      if (k == K_MESSAGE) {
        uint64_t len;
        if (wire != 2 || !read_varint(p, end, &len) ||
            uint64_t(end - p) < len)
          return false;
        const uint8_t* sub_end = p + len;
        if (fl & F_REPEATED) {
          int r = cst.occ == 0 ? r0 : rep_depth + 1;
          cst.occ++;
          if (!parse(ch, p, sub_end, r, d0 + 1, rep_depth + 1, depth + 1))
            return false;
        } else {
          if (cst.occ > 0) return false;  // split singular message: merge
          cst.occ++;                      // semantics -> Python fallback
          int d1 = d0 + ((fl & F_DEF_INC) ? 1 : 0);
          if (!parse(ch, p, sub_end, r0, d1, rep_depth, depth + 1))
            return false;
        }
        p = sub_end;
        continue;
      }

      // scalar / enum / span
      const bool packable = (k != K_SPAN && k != K_SPAN_UTF8);
      if ((fl & F_REPEATED) && packable && wire == 2) {
        // packed run: each element is one occurrence
        uint64_t len;
        if (!read_varint(p, end, &len) || uint64_t(end - p) < len)
          return false;
        const uint8_t* q = p;
        const uint8_t* qend = p + len;
        while (q < qend) {
          uint64_t raw;
          switch (k) {
            case K_FIXED64:
              if (qend - q < 8) return false;
              std::memcpy(&raw, q, 8);
              q += 8;
              break;
            case K_FIXED32: {
              if (qend - q < 4) return false;
              uint32_t r32;
              std::memcpy(&r32, q, 4);
              raw = r32;
              q += 4;
              break;
            }
            default:
              if (!read_varint(q, qend, &raw)) return false;
          }
          if (k == K_SINT64)
            raw = uint64_t(int64_t(raw >> 1) ^ -int64_t(raw & 1));
          else if (k == K_SINT32) {
            uint32_t u = uint32_t(raw);
            raw = uint32_t(int32_t(u >> 1) ^ -int32_t(u & 1));
          } else if (k == K_BOOL)
            raw = raw ? 1 : 0;
          else if (k == K_ENUM) {
            int64_t v;
            if (!enum_accept(ch, fl, raw, &v)) continue;  // dropped value
            raw = uint64_t(uint32_t(int32_t(v)));
          }
          scalar_occurrence(cst, ch, k, fl, raw, 0, 0, r0, d0, rep_depth);
        }
        p = qend;
        continue;
      }

      uint64_t raw = 0;
      int64_t pos = 0;
      int32_t slen = 0;
      switch (k) {
        case K_VARINT64:
        case K_VARINT32:
        case K_SINT64:
        case K_SINT32:
        case K_BOOL:
        case K_ENUM: {
          if (wire != 0) return false;
          if (!read_varint(p, end, &raw)) return false;
          if (k == K_SINT64)
            raw = uint64_t(int64_t(raw >> 1) ^ -int64_t(raw & 1));
          else if (k == K_SINT32) {
            uint32_t u = uint32_t(raw);
            raw = uint32_t(int32_t(u >> 1) ^ -int32_t(u & 1));
          } else if (k == K_BOOL)
            raw = raw ? 1 : 0;
          else if (k == K_ENUM) {
            int64_t v;
            if (!enum_accept(ch, fl, raw, &v)) goto next_field;  // dropped
            raw = uint64_t(uint32_t(int32_t(v)));
          }
          break;
        }
        case K_FIXED64: {
          if (wire != 1 || end - p < 8) return false;
          std::memcpy(&raw, p, 8);
          p += 8;
          break;
        }
        case K_FIXED32: {
          if (wire != 5 || end - p < 4) return false;
          uint32_t r32;
          std::memcpy(&r32, p, 4);
          raw = r32;
          p += 4;
          break;
        }
        case K_SPAN:
        case K_SPAN_UTF8: {
          uint64_t len;
          if (wire != 2 || !read_varint(p, end, &len) ||
              uint64_t(end - p) < len)
            return false;
          if (k == K_SPAN_UTF8 && !utf8_ok(p, int64_t(len))) return false;
          pos = p - buf;
          slen = int32_t(len);
          p += len;
          break;
        }
        default:
          return false;
      }
      scalar_occurrence(cst, ch, k, fl, raw, pos, slen, r0, d0, rep_depth);
    next_field:;
    }

    // frame end: flush pending singulars, absence, required checks
    for (int32_t ch = cb; ch < ce; ch++) {
      ChildState& cst = st[ch - cb];
      const uint8_t k = plan.kind[ch];
      const uint8_t fl = plan.flags[ch];
      if (fl & F_REPEATED) {
        if (cst.occ == 0) {  // empty list
          if (k == K_MESSAGE)
            emit_nulls_subtree(ch, d0, r0);
          else
            emit_null(plan.leaf_idx[ch], d0, r0);
        }
      } else if (k == K_MESSAGE) {
        if (cst.occ == 0) {
          if (fl & F_REQUIRED) return false;  // missing required message
          emit_nulls_subtree(ch, d0, r0);
        }
      } else {
        if (cst.seen) {
          int d = d0 + ((fl & F_DEF_INC) ? 1 : 0);
          if (k == K_SPAN || k == K_SPAN_UTF8)
            emit_span(plan.leaf_idx[ch], cst.pend_pos, cst.pend_len, d, r0);
          else
            emit_fixed(plan.leaf_idx[ch], k, cst.pend, d, r0);
        } else if (fl & F_REQUIRED) {
          return false;  // missing required scalar
        } else if (fl & F_EMIT_DEFAULT) {
          // proto3 no-presence absent: emit the default (zeros / empty)
          if (k == K_SPAN || k == K_SPAN_UTF8)
            emit_span(plan.leaf_idx[ch], 0, 0, d0, r0);
          else
            emit_fixed(plan.leaf_idx[ch], k, 0, d0, r0);
        } else {
          emit_null(plan.leaf_idx[ch], d0, r0);
        }
      }
    }
    return true;
  }
};

}  // namespace

extern "C" {

struct KpwNestedOut {
  Shredder* sh;
};

// Decode n_rec serialized messages into Dremel-shredded per-leaf outputs.
// Returns -1 on success (*out set; free with kpw_nested_free) or the index
// of the first record needing the Python fallback (*out = nullptr).
int64_t kpw_proto_shred_nested(
    const uint8_t* buf, const int64_t* offs, int64_t n_rec, int32_t n_nodes,
    int32_t n_leaves, const uint32_t* fnum, const uint8_t* kind,
    const uint8_t* flags, const int32_t* child_begin,
    const int32_t* child_end, const int32_t* leaf_idx, const int32_t* ftab,
    const int32_t* ftab_off, const int32_t* max_fn, const int32_t* enum_vals,
    const int32_t* enum_off, const int32_t* enum_len,
    const int32_t* null_leaves, const int32_t* null_off,
    const int32_t* null_len, KpwNestedOut** out) {
  Plan plan{n_nodes,   n_leaves, fnum,     kind,     flags,
            child_begin, child_end, leaf_idx, ftab,     ftab_off,
            max_fn,    enum_vals, enum_off, enum_len, null_leaves,
            null_off,  null_len};
  auto* sh = new Shredder(plan, buf, n_rec);
  for (int64_t r = 0; r < n_rec; r++) {
    if (!sh->parse(0, buf + offs[r], buf + offs[r + 1], 0, 0, 0, 0)) {
      delete sh;
      *out = nullptr;
      return r;
    }
  }
  *out = new KpwNestedOut{sh};
  return -1;
}

int64_t kpw_nested_value_bytes(KpwNestedOut* o, int32_t leaf) {
  return int64_t(o->sh->leaves[leaf].values.size());
}

int64_t kpw_nested_nspans(KpwNestedOut* o, int32_t leaf) {
  return int64_t(o->sh->leaves[leaf].spos.size());
}

int64_t kpw_nested_nlevels(KpwNestedOut* o, int32_t leaf) {
  return int64_t(o->sh->leaves[leaf].defs.size());
}

const void* kpw_nested_values(KpwNestedOut* o, int32_t leaf) {
  return o->sh->leaves[leaf].values.data();
}

const int64_t* kpw_nested_spos(KpwNestedOut* o, int32_t leaf) {
  return o->sh->leaves[leaf].spos.data();
}

const int32_t* kpw_nested_slen(KpwNestedOut* o, int32_t leaf) {
  return o->sh->leaves[leaf].slen.data();
}

const uint8_t* kpw_nested_defs(KpwNestedOut* o, int32_t leaf) {
  return o->sh->leaves[leaf].defs.data();
}

const uint8_t* kpw_nested_reps(KpwNestedOut* o, int32_t leaf) {
  return o->sh->leaves[leaf].reps.data();
}

void kpw_nested_free(KpwNestedOut* o) {
  delete o->sh;
  delete o;
}

int32_t kpw_nested_n_leaves(KpwNestedOut* o) {
  return int32_t(o->sh->leaves.size());
}

// Batched output geometry: one int64 row of 4 per leaf —
// [value_bytes, n_spans, span_payload_bytes, n_levels].  The fused
// materialization path (pyshred.cc shred_nested_buf/nested_fill) sizes
// every output allocation from this table in ONE call instead of the
// 5-accessors-per-leaf ctypes round trips the NestedShredResult route
// pays with the GIL held.
void kpw_nested_sizes(KpwNestedOut* o, int64_t* out) {
  const auto& leaves = o->sh->leaves;
  for (size_t i = 0; i < leaves.size(); i++) {
    const LeafOut& lf = leaves[i];
    int64_t payload = 0;
    for (int32_t ln : lf.slen) payload += ln;
    out[4 * i + 0] = int64_t(lf.values.size());
    out[4 * i + 1] = int64_t(lf.spos.size());
    out[4 * i + 2] = payload;
    out[4 * i + 3] = int64_t(lf.defs.size());
  }
}

// Materialize one leaf into caller-allocated output buffers (any may be
// null to skip): fixed values memcpy'd, span payload gathered straight
// into its final ByteColumn payload with the int64 offset table built in
// the same pass, def/rep levels widened uint8 -> uint32 (the dtype the
// nogil page assembler's RLE ops consume — no Python-side astype copies).
// ``buf`` is re-supplied by the caller, so every span is bounds-checked
// against ``buf_len`` before the copy; returns 0 ok, 1 = span out of
// bounds (hostile/mismatched buffer: the caller must raise, not read).
int kpw_nested_fill_leaf(KpwNestedOut* o, int32_t leaf, const uint8_t* buf,
                         int64_t buf_len, void* values_out,
                         int64_t* offsets_out, uint8_t* payload_out,
                         uint32_t* defs_out, uint32_t* reps_out) {
  const LeafOut& lf = o->sh->leaves[leaf];
  if (values_out != nullptr && !lf.values.empty())
    std::memcpy(values_out, lf.values.data(), lf.values.size());
  if (payload_out != nullptr || offsets_out != nullptr) {
    int64_t at = 0;
    if (offsets_out != nullptr) offsets_out[0] = 0;
    for (size_t i = 0; i < lf.spos.size(); i++) {
      const int64_t pos = lf.spos[i];
      const int64_t len = lf.slen[i];
      if (pos < 0 || len < 0 || pos > buf_len - len) return 1;
      if (payload_out != nullptr && len > 0)
        std::memcpy(payload_out + at, buf + pos, size_t(len));
      at += len;
      if (offsets_out != nullptr) offsets_out[i + 1] = at;
    }
  }
  if (defs_out != nullptr)
    for (size_t i = 0; i < lf.defs.size(); i++) defs_out[i] = lf.defs[i];
  if (reps_out != nullptr)
    for (size_t i = 0; i < lf.reps.size(); i++) reps_out[i] = lf.reps[i];
  return 0;
}

}  // extern "C"
