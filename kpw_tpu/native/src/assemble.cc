// CPython extension: nogil batch page assembly from a lowered plan.
//
// PR 1 made EncodedChunk.parts the writev-style interface between the
// encoder and the sink; PR 6 moved the wire shred behind the nogil
// boundary (pyshred.cc).  This module does the same for the OTHER half of
// the host leg: the per-page assembly loop in CpuChunkEncoder.encode —
// the loop PR 1 measured as GIL-bound at 2 assembly threads (the pool was
// *slower* than one thread).  The Python side lowers a chunk's fully
// resolved page plan into flat int64 tables (pages + ops) over a tuple of
// buffers; this entry point then, with the GIL RELEASED:
//
//   * gathers each page's body parts (RAW ops) and/or RLE/bit-pack
//     encodes value-index and level streams in place (RLE ops,
//     kpw_rle_hybrid_u32 from encode.cc — the same object code the
//     ctypes path runs, so the streams cannot drift),
//   * optionally compresses the body (snappy / zstd via codecs.cc — the
//     same dispatch the ctypes scratch path uses, so frames are
//     byte-identical per host),
//   * optionally CRCs the on-wire body (standard CRC-32, gzip polynomial
//     0xEDB88320, PARQUET-1539 — bit-for-bit zlib.crc32),
//   * emits each page header from Python-provided thrift fragments
//     (prefix .. [uncompressed varint] 0x15 [compressed varint]
//     [0x15 [crc varint]] .. suffix),
//   * computes per-page min/max stats for fixed-width value slices (the
//     page-index pass that anti-scaled under the GIL: many ~20 us numpy
//     reductions thrash the GIL handoff at 2 threads).
//
// One call per column chunk returns the finished chunk buffer; the shared
// assembly pool (core/pages.py) runs one call per column, so columns
// finally shard across real cores.
//
// Contract (enforced before the GIL is released; fuzzed in tools/fuzz.py):
// malformed tables — out-of-range buffer indices, non-ascending or
// out-of-bounds ranges, bad widths/modes/kinds/flags — raise ValueError.
// The nogil loop never reads outside a validated range.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <cstring>
#include <vector>

extern "C" {
// encode.cc (compiled into this .so — same source as the ctypes library)
size_t kpw_rle_hybrid_cap(size_t n, int width);
int kpw_rle_hybrid_u32(const uint32_t* v, size_t n, int width, uint8_t* out,
                       size_t* out_len);
int kpw_rle_hybrid_from_runs_u32(const uint32_t* run_vals,
                                 const int32_t* run_lens, size_t n_runs,
                                 int width, uint8_t* out, size_t* out_len);
int kpw_byte_stream_split(const uint8_t* in, size_t n, size_t width,
                          uint8_t* out);
// codecs.cc
size_t kpw_snappy_max_compressed_length(size_t n);
int kpw_snappy_compress(const uint8_t* in, size_t n, uint8_t* out,
                        size_t* out_len);
#ifndef KPW_NO_ZSTD
size_t kpw_zstd_max_compressed_length(size_t n);
int kpw_zstd_compress(const uint8_t* in, size_t n, uint8_t* out,
                      size_t out_cap, size_t* out_len, int level);
#endif
}

namespace {

// -- table layout (mirrored by kpw_tpu/core/pages.py lowering) --------------
constexpr int kPageStride = 7;  // op_start, op_end, prefix, suffix, flags, va, vb
constexpr int kOpStride = 5;    // kind, buf, a, b, aux
constexpr int64_t kOpRaw = 0;   // bytes buffers[buf][a:b)
constexpr int64_t kOpRle = 1;   // u32 elements [a:b); aux = width | mode << 8
// RLE/bit-pack replay from a PRECOMPUTED run table (the device level
// planner's compact output, ops/levels.py): run values u32 in
// buffers[buf][a:b), run lengths i32 in buffers[aux >> 16][a:b);
// aux = width | mode << 8 | lens_buf << 16.  Byte-identical to
// core.encodings.rle_hybrid_from_runs (kpw_rle_hybrid_from_runs_u32,
// encode.cc) — the O(runs) host Python replay, moved behind the nogil
// boundary.
constexpr int64_t kOpRleRuns = 2;
// BYTE_ARRAY PLAIN assembly straight from the packed ByteColumn
// representation: values are elements [a:b) of the int64 offset table in
// buffers[aux >> 16] (absolute into the data buffer buffers[buf]); each
// emits a 4-byte LE length + the raw bytes — byte-identical to
// core.encodings.byte_array_plain_encode.  Offset CONTENT is snapshotted
// and bounds-checked at execution (it lives in a caller-mutable numpy
// array); a bad table raises ValueError, never an OOB read.
constexpr int64_t kOpBytesPlain = 3;
// BYTE_STREAM_SPLIT straight from the contiguous value buffer (ISSUE 16):
// elements [a:b) of buffers[buf], aux = value width in bytes (4 or 8),
// transposed into their byte planes inside the nogil call — byte-identical
// to core.encodings.byte_stream_split_encode via kpw_byte_stream_split
// (encode.cc, the same object code the ctypes path runs).
constexpr int64_t kOpBss = 4;
constexpr int64_t kModeBare = 0;
constexpr int64_t kModeWidthByte = 1;  // 1-byte bit width prefix (dict bodies)
constexpr int64_t kModeLen32 = 2;      // u32 LE length prefix (v1 level streams)
constexpr int64_t kFlagCrc = 1;
// stats dtype codes (0 = no native stats for this chunk)
enum StatsDtype { kStatsNone = 0, kStatsI32, kStatsI64, kStatsU32, kStatsU64,
                  kStatsF32, kStatsF64, kStatsU8 };
// out_mask values
constexpr uint8_t kStatUndefined = 0;   // empty page / all-NaN
constexpr uint8_t kStatDefined = 1;
constexpr uint8_t kStatAmbiguousZero = 2;  // +-0.0 tie: caller re-derives

// -- CRC-32 (gzip polynomial, reflected — zlib.crc32 semantics) -------------
struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++)
        c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};

inline uint32_t crc32_update(uint32_t crc, const uint8_t* p, size_t n) {
  static const Crc32Table table;
  crc = ~crc;
  for (size_t i = 0; i < n; i++)
    crc = table.t[(crc ^ p[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

// -- thrift compact varints -------------------------------------------------
inline void emit_varint(std::vector<uint8_t>& out, uint32_t v) {
  while (v >= 0x80) {
    out.push_back(static_cast<uint8_t>(v) | 0x80);
    v >>= 7;
  }
  out.push_back(static_cast<uint8_t>(v));
}

inline void emit_zigzag_i32(std::vector<uint8_t>& out, int32_t v) {
  emit_varint(out, (static_cast<uint32_t>(v) << 1)
                       ^ static_cast<uint32_t>(v >> 31));
}

// -- per-page min/max over a fixed-width value slice ------------------------
template <typename T>
uint8_t stats_int(const uint8_t* base, int64_t va, int64_t vb, uint8_t* lo_out,
                  uint8_t* hi_out) {
  if (vb <= va) return kStatUndefined;
  const T* v = reinterpret_cast<const T*>(base);
  T lo = v[va], hi = v[va];
  for (int64_t i = va + 1; i < vb; i++) {
    T x = v[i];
    if (x < lo) lo = x;
    if (x > hi) hi = x;
  }
  std::memcpy(lo_out, &lo, sizeof(T));
  std::memcpy(hi_out, &hi, sizeof(T));
  return kStatDefined;
}

template <typename T>
uint8_t stats_float(const uint8_t* base, int64_t va, int64_t vb,
                    uint8_t* lo_out, uint8_t* hi_out) {
  const T* v = reinterpret_cast<const T*>(base);
  bool any = false, zero_pos = false, zero_neg = false;
  T lo = T(0), hi = T(0);
  for (int64_t i = va; i < vb; i++) {
    T x = v[i];
    if (x != x) continue;  // NaN: the oracle masks them out
    if (x == T(0)) {
      // record both signed zeros: if min or max lands on 0.0 with both
      // signs present, numpy's SIMD lane order decides which sign wins —
      // report ambiguous and let the caller run the numpy oracle
      uint8_t top;
      std::memcpy(&top, reinterpret_cast<const uint8_t*>(&x) + sizeof(T) - 1,
                  1);
      (top & 0x80 ? zero_neg : zero_pos) = true;
    }
    if (!any) {
      lo = hi = x;
      any = true;
    } else {
      if (x < lo) lo = x;
      if (x > hi) hi = x;
    }
  }
  if (!any) return kStatUndefined;
  if ((lo == T(0) || hi == T(0)) && zero_pos && zero_neg)
    return kStatAmbiguousZero;
  std::memcpy(lo_out, &lo, sizeof(T));
  std::memcpy(hi_out, &hi, sizeof(T));
  return kStatDefined;
}

struct BufferSet {
  std::vector<Py_buffer> views;
  ~BufferSet() {
    for (auto& v : views) PyBuffer_Release(&v);
  }
  bool get(PyObject* obj, int flags = PyBUF_SIMPLE) {
    Py_buffer v;
    if (PyObject_GetBuffer(obj, &v, flags) != 0) return false;
    views.push_back(v);
    return true;
  }
};

bool fail_value(const char* msg) {
  PyErr_SetString(PyExc_ValueError, msg);
  return false;
}

// assemble_pages(buffers: tuple, page_tab, op_tab, codec, level,
//                values_or_None, stats_dtype, out_meta, out_stats_or_None,
//                out_mask_or_None) -> bytes
//
// page_tab: int64 (n_pages, 7); op_tab: int64 (n_ops, 5);
// out_meta: writable int64 (n_pages, 3) — [uncompressed_body_len,
// compressed_body_len, header_len] per page; out_stats: writable
// (n_pages, 2) of the values dtype; out_mask: writable uint8 (n_pages,).
PyObject* py_assemble_pages(PyObject*, PyObject* args) {
  PyObject *bufs_t, *pages_o, *ops_o, *values_o, *meta_o, *stats_o, *mask_o;
  int codec, level, sdt;
  if (!PyArg_ParseTuple(args, "O!OOiiOiOOO", &PyTuple_Type, &bufs_t, &pages_o,
                        &ops_o, &codec, &level, &values_o, &sdt, &meta_o,
                        &stats_o, &mask_o))
    return nullptr;

  const Py_ssize_t n_bufs = PyTuple_GET_SIZE(bufs_t);
  BufferSet bufs;
  for (Py_ssize_t i = 0; i < n_bufs; i++)
    if (!bufs.get(PyTuple_GET_ITEM(bufs_t, i))) return nullptr;

  BufferSet tabs;
  if (!tabs.get(pages_o) || !tabs.get(ops_o)) return nullptr;
  const Py_buffer& pv = tabs.views[0];
  const Py_buffer& ov = tabs.views[1];
  if (pv.len % (8 * kPageStride) != 0 || ov.len % (8 * kOpStride) != 0)
    return fail_value("page/op tables must be int64 with full rows"), nullptr;
  const int64_t* pages = static_cast<const int64_t*>(pv.buf);
  const int64_t* ops = static_cast<const int64_t*>(ov.buf);
  const int64_t n_pages = pv.len / (8 * kPageStride);
  const int64_t n_ops = ov.len / (8 * kOpStride);

  // Snapshot both tables BEFORE validation: the GIL is released during
  // assembly, so a concurrent Python thread could mutate the caller's
  // numpy arrays between the bounds checks and their use — validate and
  // execute against this immutable copy so "never reads outside a
  // validated range" holds unconditionally.  (Buffer CONTENT mutation
  // can still corrupt output bytes, but never memory safety: every
  // bound comes from the snapshot and Py_buffer pins the allocations.)
  std::vector<int64_t> page_snap, op_snap;
  try {
    page_snap.assign(pages, pages + n_pages * kPageStride);
    op_snap.assign(ops, ops + n_ops * kOpStride);
  } catch (const std::bad_alloc&) {
    return PyErr_NoMemory();
  }
  pages = page_snap.data();
  ops = op_snap.data();

#ifndef KPW_NO_ZSTD
  const bool zstd_ok = true;
#else
  const bool zstd_ok = false;
#endif
  if (!(codec == 0 || codec == 1 || (codec == 6 && zstd_ok)))
    return fail_value("unsupported codec for native assembly"), nullptr;

  // values buffer for native stats
  const uint8_t* vbase = nullptr;
  int64_t n_values = 0;
  size_t vsize = 0;
  switch (sdt) {
    case kStatsNone: break;
    case kStatsI32: case kStatsU32: case kStatsF32: vsize = 4; break;
    case kStatsI64: case kStatsU64: case kStatsF64: vsize = 8; break;
    case kStatsU8: vsize = 1; break;
    default: return fail_value("unknown stats dtype code"), nullptr;
  }
  BufferSet vbufs;
  if (sdt != kStatsNone) {
    if (values_o == Py_None)
      return fail_value("stats dtype set but values buffer is None"), nullptr;
    if (!vbufs.get(values_o)) return nullptr;
    vbase = static_cast<const uint8_t*>(vbufs.views[0].buf);
    n_values = vbufs.views[0].len / static_cast<int64_t>(vsize);
  }

  // writable outputs
  BufferSet outs;
  if (!outs.get(meta_o, PyBUF_WRITABLE)) return nullptr;
  if (outs.views[0].len != n_pages * 3 * 8)
    return fail_value("out_meta must be int64 (n_pages, 3)"), nullptr;
  int64_t* out_meta = static_cast<int64_t*>(outs.views[0].buf);
  uint8_t* out_stats = nullptr;
  uint8_t* out_mask = nullptr;
  if (sdt != kStatsNone) {
    if (stats_o == Py_None || mask_o == Py_None)
      return fail_value("stats dtype set but out_stats/out_mask is None"),
             nullptr;
    if (!outs.get(stats_o, PyBUF_WRITABLE) ||
        !outs.get(mask_o, PyBUF_WRITABLE))
      return nullptr;
    if (outs.views[1].len != n_pages * 2 * static_cast<int64_t>(vsize))
      return fail_value("out_stats must be (n_pages, 2) of the values dtype"),
             nullptr;
    if (outs.views[2].len != n_pages)
      return fail_value("out_mask must be uint8 (n_pages,)"), nullptr;
    out_stats = static_cast<uint8_t*>(outs.views[1].buf);
    out_mask = static_cast<uint8_t*>(outs.views[2].buf);
  }

  // -- validate every table entry BEFORE the GIL is released ---------------
  size_t cap = 0;  // worst-case output size (reserve hint only)
  for (int64_t p = 0; p < n_pages; p++) {
    const int64_t* pg = pages + p * kPageStride;
    const int64_t op_start = pg[0], op_end = pg[1];
    const int64_t prefix = pg[2], suffix = pg[3];
    const int64_t flags = pg[4], va = pg[5], vb = pg[6];
    if (op_start < 0 || op_end < op_start || op_end > n_ops)
      return fail_value("page op range out of bounds"), nullptr;
    if (prefix < 0 || prefix >= n_bufs || suffix < 0 || suffix >= n_bufs)
      return fail_value("page prefix/suffix buffer index out of range"),
             nullptr;
    if (flags & ~kFlagCrc)
      return fail_value("unknown page flags"), nullptr;
    if (sdt != kStatsNone && (va < 0 || vb < va || vb > n_values))
      return fail_value("page stats range out of values bounds"), nullptr;
    size_t body_cap = 0;
    for (int64_t o = op_start; o < op_end; o++) {
      const int64_t* op = ops + o * kOpStride;
      const int64_t kind = op[0], b_idx = op[1], a = op[2], b = op[3];
      const int64_t aux = op[4];
      if (b_idx < 0 || b_idx >= n_bufs)
        return fail_value("op buffer index out of range"), nullptr;
      const Py_buffer& view = bufs.views[b_idx];
      if (kind == kOpRaw) {
        if (a < 0 || b < a || b > view.len)
          return fail_value("raw op range out of buffer bounds"), nullptr;
        body_cap += static_cast<size_t>(b - a);
      } else if (kind == kOpRle) {
        const int64_t elems = view.len / 4;
        const int64_t width = aux & 0xFF, mode = (aux >> 8) & 0xFF;
        if (a < 0 || b < a || b > elems)
          return fail_value("rle op range out of buffer bounds"), nullptr;
        if (width < 0 || width > 32)
          return fail_value("rle width out of range"), nullptr;
        if (mode != kModeBare && mode != kModeWidthByte && mode != kModeLen32)
          return fail_value("unknown rle mode"), nullptr;
        if (aux >> 16)
          return fail_value("rle aux bits out of range"), nullptr;
        body_cap += kpw_rle_hybrid_cap(static_cast<size_t>(b - a),
                                       static_cast<int>(width)) + 5;
      } else if (kind == kOpRleRuns) {
        const int64_t elems = view.len / 4;
        const int64_t width = aux & 0xFF, mode = (aux >> 8) & 0xFF;
        const int64_t lens_buf = aux >> 16;
        if (a < 0 || b < a || b > elems)
          return fail_value("runs op range out of vals buffer bounds"),
                 nullptr;
        if (width < 1 || width > 32)
          return fail_value("runs width out of range"), nullptr;
        if (mode != kModeBare && mode != kModeWidthByte && mode != kModeLen32)
          return fail_value("unknown rle mode"), nullptr;
        if (lens_buf < 0 || lens_buf >= n_bufs)
          return fail_value("runs lens buffer index out of range"), nullptr;
        if (b > bufs.views[lens_buf].len / 4)
          return fail_value("runs op range out of lens buffer bounds"),
                 nullptr;
        // body size depends on run CONTENT (summed at execution from a
        // snapshot); contributes only the prefix bound here — the
        // emitted body is re-checked against the thrift i32 ceiling
        // after assembly
        body_cap += 5;
      } else if (kind == kOpBytesPlain) {
        const int64_t offs_buf = aux >> 16;
        if (aux & 0xFFFF)
          return fail_value("bytes-plain aux low bits must be zero"),
                 nullptr;
        if (offs_buf < 0 || offs_buf >= n_bufs)
          return fail_value("bytes-plain offsets buffer index out of range"),
                 nullptr;
        const int64_t offs_elems = bufs.views[offs_buf].len / 8;
        if (a < 0 || b < a || b + 1 > offs_elems)
          return fail_value("bytes-plain range out of offsets bounds"),
                 nullptr;
        // payload size depends on offset CONTENT (snapshotted + bounds-
        // checked at execution); length prefixes are bounded here
        body_cap += static_cast<size_t>(b - a) * 4;
      } else if (kind == kOpBss) {
        const int64_t width = aux;
        if (width != 4 && width != 8)
          return fail_value("bss op width must be 4 or 8"), nullptr;
        if (a < 0 || b < a || b > view.len / width)
          return fail_value("bss op range out of buffer bounds"), nullptr;
        body_cap += static_cast<size_t>(b - a) * width;
      } else {
        return fail_value("unknown op kind"), nullptr;
      }
    }
    if (body_cap > (1ull << 30))
      return fail_value("page body too large for a thrift i32 header"),
             nullptr;
    size_t comp_cap = body_cap;
    if (codec == 1) comp_cap = kpw_snappy_max_compressed_length(body_cap);
#ifndef KPW_NO_ZSTD
    if (codec == 6) comp_cap = kpw_zstd_max_compressed_length(body_cap);
#endif
    cap += static_cast<size_t>(bufs.views[prefix].len)
           + static_cast<size_t>(bufs.views[suffix].len) + 16
           + (comp_cap > body_cap ? comp_cap : body_cap);
  }

  std::vector<uint8_t> out;
  std::vector<uint8_t> body;      // per-page body scratch
  std::vector<uint8_t> comp;      // per-page compression scratch
  std::vector<uint8_t> rle;       // per-op rle scratch
  std::vector<uint32_t> run_vals; // per-op run-table snapshots (content is
  std::vector<int32_t> run_lens;  // caller-mutable while the GIL is down)
  std::vector<int64_t> offs_snap;
  bool oom = false;
  int codec_rc = 0;
  const char* op_err = nullptr;

  Py_BEGIN_ALLOW_THREADS try {
    out.reserve(cap);
    for (int64_t p = 0; p < n_pages && op_err == nullptr; p++) {
      const int64_t* pg = pages + p * kPageStride;
      const int64_t op_start = pg[0], op_end = pg[1];
      const Py_buffer& prefix = bufs.views[pg[2]];
      const Py_buffer& suffix = bufs.views[pg[3]];
      const bool want_crc = (pg[4] & kFlagCrc) != 0;

      // 1. body: gather RAW parts / RLE-encode streams into scratch
      body.clear();
      for (int64_t o = op_start; o < op_end && op_err == nullptr; o++) {
        const int64_t* op = ops + o * kOpStride;
        const Py_buffer& view = bufs.views[op[1]];
        const int64_t a = op[2], b = op[3];
        if (op[0] == kOpRaw) {
          const uint8_t* src = static_cast<const uint8_t*>(view.buf) + a;
          body.insert(body.end(), src, src + (b - a));
        } else if (op[0] == kOpRle) {
          const uint32_t* v = static_cast<const uint32_t*>(view.buf) + a;
          const size_t n = static_cast<size_t>(b - a);
          const int width = static_cast<int>(op[4] & 0xFF);
          const int64_t mode = (op[4] >> 8) & 0xFF;
          rle.resize(kpw_rle_hybrid_cap(n, width));
          size_t rle_len = 0;
          kpw_rle_hybrid_u32(v, n, width, rle.data(), &rle_len);
          if (mode == kModeWidthByte) {
            body.push_back(static_cast<uint8_t>(width));
          } else if (mode == kModeLen32) {
            uint32_t ln = static_cast<uint32_t>(rle_len);
            uint8_t le[4];
            std::memcpy(le, &ln, 4);
            body.insert(body.end(), le, le + 4);
          }
          body.insert(body.end(), rle.data(), rle.data() + rle_len);
        } else if (op[0] == kOpRleRuns) {
          // snapshot the run table first: the scratch is sized from the
          // summed lengths, and the caller's arrays are mutable while
          // the GIL is down — size and encode must see the same content
          const size_t n = static_cast<size_t>(b - a);
          const int width = static_cast<int>(op[4] & 0xFF);
          const int64_t mode = (op[4] >> 8) & 0xFF;
          const Py_buffer& lview = bufs.views[op[4] >> 16];
          const uint32_t* v = static_cast<const uint32_t*>(view.buf) + a;
          const int32_t* l = static_cast<const int32_t*>(lview.buf) + a;
          run_vals.assign(v, v + n);
          run_lens.resize(n);
          uint64_t total = 0;
          for (size_t i = 0; i < n; i++) {
            const int32_t rl = l[i] > 0 ? l[i] : 0;
            run_lens[i] = rl;
            total += static_cast<uint64_t>(rl);
          }
          // bound the SCRATCH, not just the emitted body: a hostile run
          // table summing just under 2^30 values at width 32 would
          // otherwise drive a ~4.3 GiB transient allocation before the
          // post-encode body check could reject the page
          if (total > (1ull << 30) ||
              kpw_rle_hybrid_cap(static_cast<size_t>(total), width) >
                  (1ull << 30)) {
            op_err = "runs op total length too large";
            break;
          }
          rle.resize(kpw_rle_hybrid_cap(static_cast<size_t>(total), width));
          size_t rle_len = 0;
          kpw_rle_hybrid_from_runs_u32(run_vals.data(), run_lens.data(), n,
                                       width, rle.data(), &rle_len);
          if (mode == kModeWidthByte) {
            body.push_back(static_cast<uint8_t>(width));
          } else if (mode == kModeLen32) {
            uint32_t ln = static_cast<uint32_t>(rle_len);
            uint8_t le[4];
            std::memcpy(le, &ln, 4);
            body.insert(body.end(), le, le + 4);
          }
          body.insert(body.end(), rle.data(), rle.data() + rle_len);
        } else if (op[0] == kOpBss) {
          const size_t n = static_cast<size_t>(b - a);
          const size_t width = static_cast<size_t>(op[4]);
          const size_t at = body.size();
          body.resize(at + n * width);
          kpw_byte_stream_split(
              static_cast<const uint8_t*>(view.buf) + a * width, n, width,
              body.data() + at);
        } else {  // kOpBytesPlain
          const size_t n = static_cast<size_t>(b - a);
          const Py_buffer& oview = bufs.views[op[4] >> 16];
          const int64_t* table = static_cast<const int64_t*>(oview.buf) + a;
          offs_snap.assign(table, table + n + 1);
          const int64_t data_len = view.len;
          for (size_t i = 0; i < n; i++) {
            const int64_t s = offs_snap[i], e = offs_snap[i + 1];
            if (s < 0 || e < s || e > data_len ||
                e - s > int64_t(0x7FFFFFFF)) {
              op_err = "bytes-plain offset table out of data bounds";
              break;
            }
            const uint32_t ln = static_cast<uint32_t>(e - s);
            uint8_t le[4];
            std::memcpy(le, &ln, 4);
            body.insert(body.end(), le, le + 4);
            const uint8_t* src = static_cast<const uint8_t*>(view.buf) + s;
            body.insert(body.end(), src, src + (e - s));
          }
        }
      }
      if (op_err != nullptr) break;
      if (body.size() > (1ull << 30)) {
        // content-sized ops (runs / bytes-plain) can only be bounded
        // here; the RAW/RLE ops were already bounded at validation
        op_err = "page body too large for a thrift i32 header";
        break;
      }
      const size_t body_len = body.size();

      // 2. compression (page body only; headers are never compressed)
      const uint8_t* wire = body.data();
      size_t wire_len = body_len;
      if (codec == 1) {
        comp.resize(kpw_snappy_max_compressed_length(body_len));
        size_t n = 0;
        codec_rc = kpw_snappy_compress(body.data(), body_len, comp.data(), &n);
        if (codec_rc != 0) break;
        wire = comp.data();
        wire_len = n;
      }
#ifndef KPW_NO_ZSTD
      else if (codec == 6) {
        comp.resize(kpw_zstd_max_compressed_length(body_len));
        size_t n = 0;
        codec_rc = kpw_zstd_compress(body.data(), body_len, comp.data(),
                                     comp.size(), &n, level);
        if (codec_rc != 0) break;
        wire = comp.data();
        wire_len = n;
      }
#endif

      // 3. header: prefix + uncomp varint + 0x15 + comp varint
      //    [+ 0x15 + crc varint] + suffix
      const size_t header_at = out.size();
      const uint8_t* pre = static_cast<const uint8_t*>(prefix.buf);
      out.insert(out.end(), pre, pre + prefix.len);
      emit_zigzag_i32(out, static_cast<int32_t>(body_len));
      out.push_back(0x15);
      emit_zigzag_i32(out, static_cast<int32_t>(wire_len));
      if (want_crc) {
        const uint32_t crc = crc32_update(0, wire, wire_len);
        out.push_back(0x15);
        emit_zigzag_i32(out, static_cast<int32_t>(crc));
      }
      const uint8_t* suf = static_cast<const uint8_t*>(suffix.buf);
      out.insert(out.end(), suf, suf + suffix.len);
      const size_t header_len = out.size() - header_at;

      // 4. body bytes onto the wire
      out.insert(out.end(), wire, wire + wire_len);

      int64_t* meta = out_meta + p * 3;
      meta[0] = static_cast<int64_t>(body_len);
      meta[1] = static_cast<int64_t>(wire_len);
      meta[2] = static_cast<int64_t>(header_len);

      // 5. per-page value stats
      if (sdt != kStatsNone) {
        const int64_t va = pg[5], vb = pg[6];
        uint8_t* lo = out_stats + p * 2 * vsize;
        uint8_t* hi = lo + vsize;
        uint8_t m = kStatUndefined;
        switch (sdt) {
          case kStatsI32: m = stats_int<int32_t>(vbase, va, vb, lo, hi); break;
          case kStatsI64: m = stats_int<int64_t>(vbase, va, vb, lo, hi); break;
          case kStatsU32: m = stats_int<uint32_t>(vbase, va, vb, lo, hi); break;
          case kStatsU64: m = stats_int<uint64_t>(vbase, va, vb, lo, hi); break;
          case kStatsU8: m = stats_int<uint8_t>(vbase, va, vb, lo, hi); break;
          case kStatsF32: m = stats_float<float>(vbase, va, vb, lo, hi); break;
          case kStatsF64: m = stats_float<double>(vbase, va, vb, lo, hi); break;
        }
        out_mask[p] = m;
      }
    }
  } catch (const std::bad_alloc&) {
    oom = true;
  }
  Py_END_ALLOW_THREADS

  if (oom) return PyErr_NoMemory();
  if (op_err != nullptr) return fail_value(op_err), nullptr;
  if (codec_rc != 0) {
    PyErr_Format(PyExc_RuntimeError, "native page compression failed rc=%d",
                 codec_rc);
    return nullptr;
  }
  return PyBytes_FromStringAndSize(reinterpret_cast<const char*>(out.data()),
                                   static_cast<Py_ssize_t>(out.size()));
}

PyMethodDef methods[] = {
    {"assemble_pages", py_assemble_pages, METH_VARARGS,
     "Gather/encode/compress/CRC a chunk's pages from a lowered plan, "
     "GIL released; returns the finished chunk bytes."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_kpw_assemble",
                         "nogil batch page assembly", -1, methods,
                         nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__kpw_assemble(void) {
  PyObject* m = PyModule_Create(&moduledef);
  if (m == nullptr) return nullptr;
#ifndef KPW_NO_ZSTD
  PyModule_AddIntConstant(m, "HAS_ZSTD", 1);
#else
  PyModule_AddIntConstant(m, "HAS_ZSTD", 0);
#endif
  // op-kind generation: 4 = RAW/RLE + the nested-pipeline additions
  // (RLE-from-runs, bytes-plain); 5 adds BYTE_STREAM_SPLIT (kOpBss).
  // The Python lowering getattr-gates on this, so a stale cached .so
  // silently keeps the old lowering instead of emitting ops it cannot
  // execute.
  PyModule_AddIntConstant(m, "OP_KINDS", 5);
  return m;
}
