// Parquet encoding primitives: dictionary build + RLE/bit-pack hybrid.
//
// Native host-side counterparts of kpw_tpu/core/encodings.py — the hot CPU
// encode path (the reference's equivalent hot path is parquet-mr's
// ColumnWriter/ValuesWriter stack reached from ParquetFile.java:59-62).
// Byte-for-byte identical to the numpy oracle: dictionary order is ascending
// *bit pattern* (floats/ints viewed unsigned), and the hybrid stream applies
// the same long-run mass heuristic and run segmentation.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <type_traits>
#include <vector>

#if defined(__AVX512BW__) || defined(__AVX512DQ__)
#include <immintrin.h>
#endif

namespace {

// Fused column stats for the affine dictionary planner: min, max, and the
// gcd of value differences in ONE memory pass.  gcd of pairwise
// differences is invariant to the base point (min and v[0] are both in
// the set), so gcd accumulates against v[0] without knowing min yet;
// once the gcd collapses to 1 the reduction is skipped for the rest of
// the scan.  gcd_out = gcd{v - min} (0 for a constant column).
// Widen to uint64 via sign-extension for signed T: modular uint64
// subtraction then yields the exact absolute difference (|diff| < 2^64).
template <typename T>
inline uint64_t stats_widen(T x) {
  if (std::is_signed<T>::value)
    return static_cast<uint64_t>(static_cast<int64_t>(x));
  return static_cast<uint64_t>(x);
}

template <typename T>
void int_stats(const T* v, size_t n, T* mn_out, T* mx_out,
               uint64_t* gcd_out) {
  T mn = v[0], mx = v[0];
  uint64_t g = 0;
  const T base = v[0];
  const uint64_t ub = stats_widen(base);
  // The gcd stabilizes after a few elements; from then on each element
  // only needs a divisibility CHECK, done divisionless (Granlund-
  // Montgomery): with g = g_odd << s, d % g == 0 iff the low s bits of d
  // are zero and (d >> s) * inv(g_odd) <= ~0 / g_odd.  A per-element
  // std::gcd (one 64-bit modulo) measured 4.5x slower than numpy's
  // reduction; this check is a multiply + compare.
  uint64_t inv = 0, lim = 0, low_mask = 0;
  int s = 0;
  auto set_magic = [&]() {
    uint64_t go = g;
    s = 0;
    while ((go & 1) == 0) {
      go >>= 1;
      ++s;
    }
    uint64_t x = go;  // Newton: inverse mod 2^64 of odd go (5 rounds)
    for (int it = 0; it < 5; ++it) x *= 2 - go * x;
    inv = x;
    lim = ~0ull / go;
    low_mask = (s == 0) ? 0 : ((1ull << s) - 1);
  };
  for (size_t i = 0; i < n; ++i) {
    const T x = v[i];
    if (x < mn) mn = x;
    if (x > mx) mx = x;
    if (g == 1) continue;
    const uint64_t ux = stats_widen(x);
    const uint64_t d = x >= base ? ux - ub : ub - ux;
    if (g == 0) {
      if (d != 0) {
        g = d;
        set_magic();
      }
      continue;
    }
    if ((d & low_mask) == 0 && (d >> s) * inv <= lim)
      continue;  // divisible: gcd unchanged
    g = std::gcd(g, d);
    if (g > 1) set_magic();
  }
  *mn_out = mn;
  *mx_out = mx;
  *gcd_out = g;
}

inline size_t varint(uint64_t v, uint8_t* out) {
  size_t i = 0;
  while (v >= 0x80) {
    out[i++] = static_cast<uint8_t>(v) | 0x80;
    v >>= 7;
  }
  out[i++] = static_cast<uint8_t>(v);
  return i;
}

// LSB-first parquet bit layout: bit j of value i lands at overall bit
// position i*width + j.  width <= 32, so acc never exceeds 7+32 bits.
inline uint8_t* bitpack_stream(const uint32_t* v, size_t n, int width,
                               uint8_t* op) {
  if (width <= 16 && n >= 8) {
    // Branchless whole-group path: an 8-value group is exactly `width`
    // bytes; 8*width <= 128 bits fits one accumulator, stored via a 16-byte
    // overwrite (successive groups overwrite the slack).  The combine is a
    // TREE, not a serial fold: the old 8-deep (acc << w) | p[i] chain left
    // the core idle on the carry dependency (~3 cycles/value); pairs ->
    // quads -> halves is depth 3 with 4-way ILP, and the quad combines
    // stay in uint64 (4 * 16 = 64 bits), entering __int128 only once.
    const size_t groups = n / 8;
    for (size_t g = 0; g < groups; ++g) {
      const uint32_t* p = v + g * 8;
      const uint64_t a01 = p[0] | (static_cast<uint64_t>(p[1]) << width);
      const uint64_t a23 = p[2] | (static_cast<uint64_t>(p[3]) << width);
      const uint64_t a45 = p[4] | (static_cast<uint64_t>(p[5]) << width);
      const uint64_t a67 = p[6] | (static_cast<uint64_t>(p[7]) << width);
      const uint64_t a03 = a01 | (a23 << (2 * width));
      const uint64_t a47 = a45 | (a67 << (2 * width));
      const unsigned __int128 acc =
          a03 | (static_cast<unsigned __int128>(a47) << (4 * width));
      std::memcpy(op, &acc, 16);
      op += width;
    }
    v += groups * 8;
    n -= groups * 8;
  }
  uint64_t acc = 0;
  int nbits = 0;
  for (size_t i = 0; i < n; ++i) {
    acc |= static_cast<uint64_t>(v[i]) << nbits;
    nbits += width;
    while (nbits >= 8) {
      *op++ = static_cast<uint8_t>(acc);
      acc >>= 8;
      nbits -= 8;
    }
  }
  if (nbits) *op++ = static_cast<uint8_t>(acc);
  return op;
}

inline uint64_t mix(uint64_t h) {
  h ^= h >> 33;
  h *= 0xFF51AFD7ED558CCDull;
  h ^= h >> 33;
  return h;
}

// Bounded-range path: when max-min is small, a direct rank table beats the
// hash (no probing, no sort; ranks fall out of the prefix sum — same trick
// as the sort-free device builder in kpw_tpu/ops/dictionary.py).
template <typename K>
int dict_build_range(const K* vals, size_t n, K* dict_out, uint32_t* idx_out,
                     uint32_t max_k, uint32_t* k_out) {
  // NOTE (measured, do not "fuse" these passes): the separate min/max
  // loop auto-vectorizes to AVX-512 min/max and runs at memory bandwidth;
  // a fused minmax+bitmap-fill single pass measured ~2x SLOWER — the
  // early-exit branch blocks vectorization, and on low-cardinality
  // columns every presence |= is a serial RMW chain on one hot word.
  K lo = vals[0], hi = vals[0];
  for (size_t i = 1; i < n; ++i) {
    const K v = vals[i];
    if (v < lo) lo = v;
    if (v > hi) hi = v;
  }
  uint64_t limit = 4 * static_cast<uint64_t>(n);
  if (limit > (1u << 22)) limit = 1u << 22;
  // Compare the span before +1: hi-lo can be UINT64_MAX (e.g. int64 keys 0
  // and -1), where +1 would wrap range to 0 and pass the guard.
  const uint64_t span = static_cast<uint64_t>(hi - lo);
  if (span >= limit) return -1;  // not range-suitable; caller tries hash
  const uint64_t range = span + 1;
  std::vector<uint32_t> table(range, 0);
  for (size_t i = 0; i < n; ++i) table[static_cast<uint64_t>(vals[i] - lo)] = 1;
  uint32_t k = 0;
  for (uint64_t d = 0; d < range; ++d) {
    const uint32_t present = table[d];
    table[d] = k;
    if (present) {
      if (k >= max_k) return 1;  // dictionary infeasible: abort early
      dict_out[k++] = lo + static_cast<K>(d);
    }
  }
  for (size_t i = 0; i < n; ++i)
    idx_out[i] = table[static_cast<uint64_t>(vals[i] - lo)];
  *k_out = k;
  return 0;
}

// Quantized-decimal double path: when every 64-bit key, VIEWED as a
// double, is a finite non-negative multiple of 1/scale for some scale in
// {1, 10, 100, 1000, 10000} — verified by BITWISE reconstruction of every
// element — the dictionary builds on the small integer quotients via a
// range table instead of the hash (fare/tip/distance columns quantized to
// cents or hundredths are the float-heavy case in taxi-like data; the
// hash pays an L2 miss per probe, the quotient table is L1-resident).
// Sound for ANY input: passing the bitwise check proves the keys are bit
// patterns of non-negative doubles, and for those uint64 ascending ==
// double ascending, so the output order contract (ascending bit pattern)
// is unchanged; quotients are distinct iff the doubles are (l/scale
// reproduces each v bitwise, so the map is a verified bijection).
// Returns -1 when no scale fits (caller falls back to the hash).
int dict_build_f64_scaled(const uint64_t* vals, size_t n, uint64_t* dict_out,
                          uint32_t* idx_out, uint32_t max_k, uint32_t* k_out) {
  const double* dv = reinterpret_cast<const double*>(vals);
  uint64_t limit = 4 * static_cast<uint64_t>(n);
  if (limit > (1u << 22)) limit = 1u << 22;
  static const double kScales[] = {1.0, 10.0, 100.0, 1000.0, 10000.0};
  std::vector<uint32_t> q(n);  // verified quotients, reused across scales
  for (const double scale : kScales) {
    uint32_t lo = UINT32_MAX, hi = 0;
    bool ok = true;
    // Chunked with a branch-free body so a wrong scale wastes at most one
    // chunk; the SIMD form below does 8 doubles per iteration (the scalar
    // early-exit loop cost nearly as much as the hash it replaces).
    // Rounding nuance: the lanes use round-to-nearest where the scalar
    // tail truncates d+0.5 — safe, because acceptance is per element and
    // EVERY accepted element independently passes the bitwise
    // reconstruction check; any verified scale yields the identical
    // dictionary (the sorted unique bit patterns).
    constexpr size_t CH = 4096;
#ifdef __AVX512DQ__
    const __m512d vscale = _mm512_set1_pd(scale);
    const __m512d vzero = _mm512_set1_pd(0.0);
    const __m512d vlim = _mm512_set1_pd(2147483648.0);
#endif
    for (size_t base = 0; base < n; base += CH) {
      const size_t m = std::min(CH, n - base);
      uint64_t bad = 0;
      uint32_t clo = UINT32_MAX, chi = 0;
      size_t i = 0;
#ifdef __AVX512DQ__
      __m512i vlo = _mm512_set1_epi64(INT64_MAX);
      __m512i vhi = _mm512_setzero_si512();
      for (; i + 8 <= m; i += 8) {
        const __m512d v = _mm512_loadu_pd(dv + base + i);
        const __m512d d = _mm512_mul_pd(v, vscale);
        const __mmask8 in =
            _mm512_cmp_pd_mask(d, vzero, _CMP_GE_OQ) &
            _mm512_cmp_pd_mask(d, vlim, _CMP_LT_OQ);
        // out-of-range lanes clamp to 0 so the convert stays defined
        const __m512d ds = _mm512_maskz_mov_pd(in, d);
        const __m512i l = _mm512_cvtpd_epi64(ds);  // round-to-nearest
        const __m512d r = _mm512_div_pd(_mm512_cvtepi64_pd(l), vscale);
        const __mmask8 neq = _mm512_cmpneq_epu64_mask(
            _mm512_castpd_si512(r),
            _mm512_loadu_si512(reinterpret_cast<const void*>(vals + base + i)));
        bad |= static_cast<uint64_t>(neq) |
               static_cast<uint64_t>(static_cast<uint8_t>(~in));
        if (bad) break;
        vlo = _mm512_min_epi64(vlo, l);
        vhi = _mm512_max_epi64(vhi, l);
        _mm256_storeu_si256(reinterpret_cast<__m256i*>(q.data() + base + i),
                            _mm512_cvtepi64_epi32(l));
      }
      if (!bad) {
        alignas(64) int64_t tmp[8];
        _mm512_store_si512(reinterpret_cast<void*>(tmp), vlo);
        for (int t = 0; t < 8; ++t)
          if (tmp[t] < static_cast<int64_t>(clo))
            clo = static_cast<uint32_t>(tmp[t]);
        _mm512_store_si512(reinterpret_cast<void*>(tmp), vhi);
        for (int t = 0; t < 8; ++t)
          if (tmp[t] > static_cast<int64_t>(chi))
            chi = static_cast<uint32_t>(tmp[t]);
      }
#endif
      for (; i < m && !bad; ++i) {
        const double d = dv[base + i] * scale;
        // quotients beyond 2^31 can't pass the span guard anyway; the
        // clamp keeps the int cast defined for out-of-range inputs
        const bool in = (d >= 0.0) & (d < 2147483648.0);
        const double ds = in ? d : 0.0;
        const int64_t l = static_cast<int64_t>(ds + 0.5);
        const double r = static_cast<double>(l) / scale;
        uint64_t rb;
        std::memcpy(&rb, &r, 8);
        bad |= static_cast<uint64_t>(rb != vals[base + i]) | !in;
        const uint32_t lu = static_cast<uint32_t>(l);
        q[base + i] = lu;
        clo = lu < clo ? lu : clo;
        chi = lu > chi ? lu : chi;
      }
      if (bad) {
        ok = false;
        break;
      }
      lo = clo < lo ? clo : lo;
      hi = chi > hi ? chi : hi;
    }
    if (!ok) continue;
    const uint64_t span = static_cast<uint64_t>(hi - lo);
    if (span >= limit) return -1;  // verified but too wide for a table
    const uint64_t range = span + 1;
    std::vector<uint32_t> table(range, 0);
    for (size_t i = 0; i < n; ++i) table[q[i] - lo] = 1;
    uint32_t k = 0;
    for (uint64_t d = 0; d < range; ++d) {
      const uint32_t present = table[d];
      table[d] = k;
      if (present) {
        if (k >= max_k) return 1;  // dictionary infeasible: abort early
        const double u =
            static_cast<double>(lo + static_cast<uint32_t>(d)) / scale;
        std::memcpy(&dict_out[k++], &u, 8);
      }
    }
    for (size_t i = 0; i < n; ++i) idx_out[i] = table[q[i] - lo];
    *k_out = k;
    return 0;
  }
  return -1;
}

inline int scaled_probe(const uint32_t*, size_t, uint32_t*, uint32_t*,
                        uint32_t, uint32_t*) {
  return -1;  // 32-bit keys: no double interpretation
}
inline int scaled_probe(const uint64_t* vals, size_t n, uint64_t* dict_out,
                        uint32_t* idx_out, uint32_t max_k, uint32_t* k_out) {
  return dict_build_f64_scaled(vals, n, dict_out, idx_out, max_k, k_out);
}

template <typename K>
int dict_build(const K* vals, size_t n, K* dict_out, uint32_t* idx_out,
               uint32_t max_k, uint32_t* k_out) {
  if (n) {
    int rc = dict_build_range(vals, n, dict_out, idx_out, max_k, k_out);
    if (rc >= 0) return rc;
    rc = scaled_probe(vals, n, dict_out, idx_out, max_k, k_out);
    if (rc >= 0) return rc;
  }
  // Adaptive open addressing: start small (low-cardinality columns never
  // touch a big table) and rehash at 50% load; rehashing only moves the
  // unique set, so total cost stays O(n + k).
  size_t cap = 1024;
  if (n >= 8192) {
    // Strided-sample cardinality probe — purely a table SIZING hint
    // (insertion order, output, and the max_k abort point are unchanged,
    // so backend byte-identity holds).  Near-unique columns are the
    // expensive case: they either abort at max_k or complete at large k,
    // and either way the 1024-start rehash cascade moves every survivor
    // log2(k/1024) times.  8192-slot fingerprint set on the stack; rare
    // fingerprint collisions only under-size, which the grow path absorbs.
    constexpr size_t kSample = 4096;
    const size_t stride = n / kSample;
    uint64_t fp[2 * kSample];
    std::memset(fp, 0, sizeof(fp));
    size_t sample_k = 0;
    for (size_t i = 0; i < kSample; ++i) {
      const uint64_t h =
          mix(static_cast<uint64_t>(vals[i * stride])) | 1;
      size_t s = h & (2 * kSample - 1);
      while (fp[s] && fp[s] != h) s = (s + 1) & (2 * kSample - 1);
      if (!fp[s]) {
        fp[s] = h;
        ++sample_k;
      }
    }
    size_t want = cap;
    if (sample_k > kSample * 9 / 10) {
      // near-unique: size past the abort bound so no grow ever fires
      want = 2 * (static_cast<size_t>(max_k) + 2);
    } else if (sample_k > 256) {
      // mid-cardinality: the sample floor is a lower bound on k
      want = 8 * sample_k;
    }
    if (want > (1u << 26)) want = 1u << 26;
    while (cap < want) cap <<= 1;
  }
  // One entry array, not parallel key/id arrays: a probe touches ONE cache
  // line instead of two (the second line was a guaranteed extra miss on
  // the 64-bit float-bit-pattern columns, the hash path's main customer).
  struct Entry {
    K key;
    uint32_t id;
  };
  const Entry kEmpty{K(), UINT32_MAX};
  std::vector<Entry> tab(cap, kEmpty);
  std::vector<K> uniq;
  uniq.reserve(1024);
  size_t mask = cap - 1;
  auto grow = [&]() {
    cap <<= 1;
    mask = cap - 1;
    tab.assign(cap, kEmpty);
    for (uint32_t id = 0; id < uniq.size(); ++id) {
      size_t s = static_cast<size_t>(mix(static_cast<uint64_t>(uniq[id]))) & mask;
      while (tab[s].id != UINT32_MAX) s = (s + 1) & mask;
      tab[s] = Entry{uniq[id], id};
    }
  };
  uint32_t gen = 0;  // bumped by grow(); invalidates precomputed slots
  auto grow_gen = [&]() {
    grow();
    ++gen;
  };
  // resolve one value starting at slot s; returns 1 iff dictionary
  // infeasible.  Output is independent of processing order: the final
  // dictionary is the SORTED unique set and indices are remapped through
  // the rank permutation below, so discovery ids never leak out.
  auto resolve = [&](const K val, size_t s, size_t i) -> int {
    for (;;) {
      const Entry e = tab[s];
      if (e.id == UINT32_MAX) {
        tab[s] = Entry{val, static_cast<uint32_t>(uniq.size())};
        idx_out[i] = static_cast<uint32_t>(uniq.size());
        uniq.push_back(val);
        if (uniq.size() > max_k) return 1;  // dictionary infeasible
        if (2 * uniq.size() >= cap) grow_gen();
        return 0;
      }
      if (e.key == val) {
        idx_out[i] = e.id;
        return 0;
      }
      s = (s + 1) & mask;
    }
  };
  // 4-way interleaved probing: hash four values up front and prefetch
  // their slots so the mix() latency and the dependent table loads of
  // consecutive values overlap instead of serializing (~2x on
  // medium-cardinality 64-bit keys, e.g. float bit patterns).
  size_t i = 0;
  for (; i + 4 <= n; i += 4) {
    const uint32_t g0 = gen;
    size_t s0 = static_cast<size_t>(mix(static_cast<uint64_t>(vals[i]))) & mask;
    size_t s1 = static_cast<size_t>(mix(static_cast<uint64_t>(vals[i + 1]))) & mask;
    size_t s2 = static_cast<size_t>(mix(static_cast<uint64_t>(vals[i + 2]))) & mask;
    size_t s3 = static_cast<size_t>(mix(static_cast<uint64_t>(vals[i + 3]))) & mask;
    __builtin_prefetch(&tab[s0]);
    __builtin_prefetch(&tab[s1]);
    __builtin_prefetch(&tab[s2]);
    __builtin_prefetch(&tab[s3]);
    // a grow() mid-block stales the remaining precomputed slots (mask
    // changed) — recompute those from the value
    if (resolve(vals[i], s0, i)) return 1;
    if (gen != g0)
      s1 = static_cast<size_t>(mix(static_cast<uint64_t>(vals[i + 1]))) & mask;
    if (resolve(vals[i + 1], s1, i + 1)) return 1;
    if (gen != g0)
      s2 = static_cast<size_t>(mix(static_cast<uint64_t>(vals[i + 2]))) & mask;
    if (resolve(vals[i + 2], s2, i + 2)) return 1;
    if (gen != g0)
      s3 = static_cast<size_t>(mix(static_cast<uint64_t>(vals[i + 3]))) & mask;
    if (resolve(vals[i + 3], s3, i + 3)) return 1;
  }
  for (; i < n; ++i) {
    const size_t s =
        static_cast<size_t>(mix(static_cast<uint64_t>(vals[i]))) & mask;
    if (resolve(vals[i], s, i)) return 1;
  }
  // Canonical ascending order: sort the (small) unique set, then remap the
  // discovery-order ids through the rank permutation in one linear pass.
  const size_t k = uniq.size();
  std::vector<uint32_t> order(k);
  for (uint32_t x = 0; x < k; ++x) order[x] = x;
  std::sort(order.begin(), order.end(),
            [&](uint32_t a, uint32_t b) { return uniq[a] < uniq[b]; });
  std::vector<uint32_t> rank(k);
  for (uint32_t r = 0; r < k; ++r) {
    rank[order[r]] = r;
    dict_out[r] = uniq[order[r]];
  }
  for (size_t i = 0; i < n; ++i) idx_out[i] = rank[idx_out[i]];
  *k_out = static_cast<uint32_t>(k);
  return 0;
}

// 64-bit-wide variant of bitpack_stream for delta miniblocks (widths up to
// 64); acc holds at most 7+64 bits.
inline uint8_t* bitpack_stream64(const uint64_t* v, size_t n, int width,
                                 uint8_t* op) {
  unsigned __int128 acc = 0;
  int nbits = 0;
  for (size_t i = 0; i < n; ++i) {
    acc |= static_cast<unsigned __int128>(v[i]) << nbits;
    nbits += width;
    while (nbits >= 8) {
      *op++ = static_cast<uint8_t>(acc);
      acc >>= 8;
      nbits -= 8;
    }
  }
  if (nbits) *op++ = static_cast<uint8_t>(acc);
  return op;
}

inline uint64_t zigzag64(int64_t x) {
  return (static_cast<uint64_t>(x) << 1) ^ static_cast<uint64_t>(x >> 63);
}

// DELTA_BINARY_PACKED (core.encodings.delta_binary_packed_encode oracle):
// block 128, 4 miniblocks of 32; ring arithmetic in the value width (I).
template <typename I, typename U>
int delta_bp(const I* v, size_t n, uint8_t* out, size_t* out_len) {
  constexpr int kBlock = 128, kMini = 4, kMB = 32;
  uint8_t* op = out;
  op += varint(kBlock, op);
  op += varint(kMini, op);
  op += varint(n, op);
  if (n == 0) {
    op += varint(0, op);
    *out_len = static_cast<size_t>(op - out);
    return 0;
  }
  op += varint(zigzag64(static_cast<int64_t>(v[0])), op);
  if (n == 1) {
    *out_len = static_cast<size_t>(op - out);
    return 0;
  }
  const size_t nd = n - 1;
  std::vector<I> deltas(nd);
  for (size_t i = 0; i < nd; ++i)
    deltas[i] = static_cast<I>(static_cast<U>(v[i + 1]) - static_cast<U>(v[i]));
  uint64_t rel[kBlock];
  for (size_t pos = 0; pos < nd; pos += kBlock) {
    const size_t m = std::min(static_cast<size_t>(kBlock), nd - pos);
    I min_delta = deltas[pos];
    for (size_t i = 1; i < m; ++i)
      if (deltas[pos + i] < min_delta) min_delta = deltas[pos + i];
    op += varint(zigzag64(static_cast<int64_t>(min_delta)), op);
    for (size_t i = 0; i < m; ++i)
      rel[i] = static_cast<U>(static_cast<U>(deltas[pos + i]) -
                              static_cast<U>(min_delta));
    for (size_t i = m; i < kBlock; ++i) rel[i] = 0;
    uint8_t* widths = op;
    op += kMini;
    for (int mb = 0; mb < kMini; ++mb) {
      const size_t a = static_cast<size_t>(mb) * kMB;
      if (a >= m) {  // miniblock entirely past the data: width 0, no bytes
        widths[mb] = 0;
        continue;
      }
      uint64_t mx = 0;
      for (size_t i = a; i < a + kMB; ++i)
        if (rel[i] > mx) mx = rel[i];
      const int w = mx ? 64 - __builtin_clzll(mx) : 0;
      widths[mb] = static_cast<uint8_t>(w);
      if (w) op = bitpack_stream64(rel + a, kMB, w, op);
    }
  }
  *out_len = static_cast<size_t>(op - out);
  return 0;
}

// Byte-array (string) dictionary: open-addressing over (offset, len) views
// into the caller's concatenated buffer, then a lexicographic sort of the
// unique set — the same order as python bytes comparison (memcmp on the
// common prefix, shorter-is-smaller tie-break), so output matches the
// numpy/python oracle (core.encodings.dictionary_build) byte for byte.
struct BytesView {
  const uint8_t* p;
  int64_t len;
};

inline bool view_eq(const BytesView& a, const BytesView& b) {
  return a.len == b.len && std::memcmp(a.p, b.p, static_cast<size_t>(a.len)) == 0;
}

inline bool view_lt(const BytesView& a, const BytesView& b) {
  const size_t m = static_cast<size_t>(a.len < b.len ? a.len : b.len);
  const int c = std::memcmp(a.p, b.p, m);
  if (c) return c < 0;
  return a.len < b.len;
}

inline uint64_t hash_bytes(const uint8_t* p, int64_t len,
                           const uint8_t* hard_end) {
  // Word-at-a-time FNV-style fold: one multiply per 8 bytes instead of
  // per byte (typical string-column values are 4-40 B, so this is the
  // dict_build_bytes hot spot).  The tail reads a full (unaligned) word
  // and masks — a fixed-size load the compiler inlines, unlike a
  // variable-length memcpy (measured 2x slower) — except within the last
  // 8 bytes before ``hard_end`` (the packed column buffer's end), where a
  // byte loop avoids the over-read.  Only the table layout depends on the
  // hash; the emitted dictionary/indices are sorted + rank-remapped, so
  // changing it cannot change output bytes.
  uint64_t h = 0xCBF29CE484222325ull ^ static_cast<uint64_t>(len);
  while (len >= 8) {
    uint64_t w;
    std::memcpy(&w, p, 8);
    h = (h ^ w) * 0x100000001B3ull;
    h = (h << 31) | (h >> 33);
    p += 8;
    len -= 8;
  }
  if (len > 0) {
    uint64_t w;
#if defined(__BYTE_ORDER__) && __BYTE_ORDER__ == __ORDER_LITTLE_ENDIAN__
    // the mask keeps the value's own (low-address) bytes only on
    // little-endian; big-endian takes the bytewise path so equal strings
    // hash equally regardless of where they sit in the buffer
    if (p + 8 <= hard_end) {
      std::memcpy(&w, p, 8);  // fixed-size: one unaligned load
      w &= (~0ull) >> (8 * (8 - len));
    } else
#endif
    {
      w = 0;
      for (int64_t i = 0; i < len; ++i)
        w |= static_cast<uint64_t>(p[i]) << (8 * i);
    }
    h = (h ^ w) * 0x100000001B3ull;
  }
  return mix(h);
}

int dict_build_bytes(const uint8_t* data, const int64_t* offsets, size_t n,
                     int64_t* uniq_pos_out, uint32_t* idx_out, uint32_t max_k,
                     uint32_t* k_out) {
  const uint8_t* hard_end = data + (n ? offsets[n] : 0);
  size_t cap = 1024;
  std::vector<uint32_t> ids(cap, UINT32_MAX);
  std::vector<BytesView> uniq;
  std::vector<int64_t> first_pos;
  uniq.reserve(1024);
  first_pos.reserve(1024);
  size_t mask = cap - 1;
  auto grow = [&]() {
    cap <<= 1;
    mask = cap - 1;
    ids.assign(cap, UINT32_MAX);
    for (uint32_t id = 0; id < uniq.size(); ++id) {
      size_t s =
          static_cast<size_t>(hash_bytes(uniq[id].p, uniq[id].len, hard_end)) &
          mask;
      while (ids[s] != UINT32_MAX) s = (s + 1) & mask;
      ids[s] = id;
    }
  };
  for (size_t i = 0; i < n; ++i) {
    const BytesView v{data + offsets[i], offsets[i + 1] - offsets[i]};
    size_t s = static_cast<size_t>(hash_bytes(v.p, v.len, hard_end)) & mask;
    for (;;) {
      const uint32_t id = ids[s];
      if (id == UINT32_MAX) {
        ids[s] = static_cast<uint32_t>(uniq.size());
        idx_out[i] = static_cast<uint32_t>(uniq.size());
        uniq.push_back(v);
        first_pos.push_back(static_cast<int64_t>(i));
        if (uniq.size() > max_k) return 1;  // dictionary infeasible
        if (2 * uniq.size() >= cap) grow();
        break;
      }
      if (view_eq(uniq[id], v)) {
        idx_out[i] = id;
        break;
      }
      s = (s + 1) & mask;
    }
  }
  const size_t k = uniq.size();
  std::vector<uint32_t> order(k);
  for (uint32_t x = 0; x < k; ++x) order[x] = x;
  std::sort(order.begin(), order.end(),
            [&](uint32_t a, uint32_t b) { return view_lt(uniq[a], uniq[b]); });
  std::vector<uint32_t> rank(k);
  for (uint32_t r = 0; r < k; ++r) {
    rank[order[r]] = r;
    uniq_pos_out[r] = first_pos[order[r]];
  }
  for (size_t i = 0; i < n; ++i) idx_out[i] = rank[idx_out[i]];
  *k_out = static_cast<uint32_t>(k);
  return 0;
}

}  // namespace

extern "C" {

int kpw_dict_build_u32(const uint32_t* vals, size_t n, uint32_t* dict_out,
                       uint32_t* idx_out, uint32_t max_k, uint32_t* k_out) {
  return dict_build(vals, n, dict_out, idx_out, max_k, k_out);
}

// Fused min/max/gcd column stats (the affine dictionary planner's one
// host pass over the raw values; see int_stats above).  min/max are
// returned widened: int64 slots for signed, uint64 for unsigned.
void kpw_int_stats_i64(const int64_t* v, size_t n, int64_t* mn, int64_t* mx,
                       uint64_t* g) {
  int_stats(v, n, mn, mx, g);
}

void kpw_int_stats_i32(const int32_t* v, size_t n, int64_t* mn, int64_t* mx,
                       uint64_t* g) {
  int32_t m1, m2;
  int_stats(v, n, &m1, &m2, g);
  *mn = m1;
  *mx = m2;
}

void kpw_int_stats_u64(const uint64_t* v, size_t n, uint64_t* mn,
                       uint64_t* mx, uint64_t* g) {
  int_stats(v, n, mn, mx, g);
}

void kpw_int_stats_u32(const uint32_t* v, size_t n, uint64_t* mn,
                       uint64_t* mx, uint64_t* g) {
  uint32_t m1, m2;
  int_stats(v, n, &m1, &m2, g);
  *mn = m1;
  *mx = m2;
}

int kpw_dict_build_u64(const uint64_t* vals, size_t n, uint64_t* dict_out,
                       uint32_t* idx_out, uint32_t max_k, uint32_t* k_out) {
  return dict_build(vals, n, dict_out, idx_out, max_k, k_out);
}

// Output bound: 4 header varints (<=10 B each) + per 128-delta block one
// min-delta varint (<=10 B) + 4 width bytes + 4 miniblocks of 32 values at
// <=64 bits (256 B each).
size_t kpw_delta_bp_cap(size_t n) {
  return 64 + ((n + 127) / 128) * (14 + 4 * 256);
}

int kpw_delta_bp32(const int32_t* v, size_t n, uint8_t* out, size_t* out_len) {
  return delta_bp<int32_t, uint32_t>(v, n, out, out_len);
}

int kpw_delta_bp64(const int64_t* v, size_t n, uint8_t* out, size_t* out_len) {
  return delta_bp<int64_t, uint64_t>(v, n, out, out_len);
}

// Lexicographic min/max of a byte-array column (column statistics) — one
// memcmp pass instead of two python iterations.
void kpw_bytes_min_max(const uint8_t* data, const int64_t* offsets, size_t n,
                       size_t* min_idx, size_t* max_idx) {
  size_t mn = 0, mx = 0;
  if (n == 0) {  // keep the C entry point n==0-safe (no offsets[1] read)
    *min_idx = *max_idx = 0;
    return;
  }
  // first-byte pruning: only values whose first byte ties the current
  // min/max first byte need a full lexicographic compare — on realistic
  // string columns this skips the memcmp for almost every row
  int mn_first = (offsets[1] > offsets[0]) ? data[offsets[0]] : -1;
  int mx_first = mn_first;
  for (size_t i = 1; i < n; ++i) {
    const int64_t off = offsets[i];
    const int64_t len = offsets[i + 1] - off;
    const int first = len > 0 ? data[off] : -1;
    if (first > mn_first && first < mx_first) continue;
    const BytesView v{data + off, len};
    if (first <= mn_first) {
      const BytesView m{data + offsets[mn], offsets[mn + 1] - offsets[mn]};
      if (view_lt(v, m)) { mn = i; mn_first = first; }
    }
    if (first >= mx_first) {
      const BytesView M{data + offsets[mx], offsets[mx + 1] - offsets[mx]};
      if (view_lt(M, v)) { mx = i; mx_first = first; }
    }
  }
  *min_idx = mn;
  *max_idx = mx;
}

int kpw_dict_build_bytes(const uint8_t* data, const int64_t* offsets, size_t n,
                         int64_t* uniq_pos_out, uint32_t* idx_out,
                         uint32_t max_k, uint32_t* k_out) {
  return dict_build_bytes(data, offsets, n, uniq_pos_out, idx_out, max_k, k_out);
}

// Worst-case output bound for the hybrid stream: each 8-value group costs at
// most a 5-byte varint header plus `width` packed bytes; RLE runs are
// strictly smaller per value.
size_t kpw_rle_hybrid_cap(size_t n, int width) {
  return 64 + ((n + 7) / 8) * (5 + static_cast<size_t>(width));
}

int kpw_rle_hybrid_u32(const uint32_t* v, size_t n, int width, uint8_t* out,
                       size_t* out_len) {
  uint8_t* op = out;
  if (n == 0) {
    *out_len = 0;
    return 0;
  }
  if (width == 0) {  // single possible value: one RLE run, no value bytes
    op += varint(static_cast<uint64_t>(n) << 1, op);
    *out_len = static_cast<size_t>(op - out);
    return 0;
  }
  // Long-run mass decides pure-bitpack vs mixed (mirrors the numpy oracle).
  //
  // The scalar run scan below mispredicts on every short run, which makes
  // it the dominant cost on random low-cardinality data — exactly the data
  // that has NO long runs.  So first answer "is there any run of >= 8 equal
  // values?" branchlessly: build a bitmap of adjacent-equal pairs (a value
  // run of length L is L-1 consecutive set bits) and AND seven shifted
  // copies over a 128-bit window so cross-word runs are seen.  Only when a
  // long run exists (runny data, where the scalar scan is cheap — few run
  // boundaries) does the exact mass computation run.
  uint64_t long_mass = 0;
  bool any_long = false;
  {
    // rolling two-word window: test starts in `prev` with `cur` appended
    // so cross-word runs are seen; early-exits on the first hit (an
    // all-equal column is detected after ~two words), no allocation
    const size_t pairs = n - 1;
    const size_t words = (pairs + 63) / 64;
    auto window_hit = [](uint64_t low, uint64_t high) -> bool {
      const unsigned __int128 x =
          static_cast<unsigned __int128>(low) |
          (static_cast<unsigned __int128>(high) << 64);
      unsigned __int128 t = x;
      for (int s = 1; s <= 6; ++s) t &= x >> s;
      return static_cast<uint64_t>(t) != 0;  // a 7-pair start in `low`
    };
    uint64_t prev = 0;
    for (size_t w = 0; w < words; ++w) {
      const size_t base = w * 64;
      const size_t m = std::min<size_t>(64, pairs - base);
      uint64_t bits = 0;
#ifdef __AVX512BW__
      if (m == 64) {
        // 16 adjacent-equal pairs per mask compare: the pair bitmap falls
        // straight out of _mm512_cmpeq_epi32_mask on (v[i], v[i+1]) lanes
        // — the scalar loop below was the detector's whole cost on
        // run-free data (the common cfg2 shape).
        for (int q = 0; q < 4; ++q) {
          const __m512i a =
              _mm512_loadu_si512(reinterpret_cast<const void*>(v + base + 16 * q));
          const __m512i b = _mm512_loadu_si512(
              reinterpret_cast<const void*>(v + base + 16 * q + 1));
          bits |= static_cast<uint64_t>(_mm512_cmpeq_epi32_mask(a, b))
                  << (16 * q);
        }
      } else
#endif
      {
        for (size_t b = 0; b < m; ++b)
          bits |= static_cast<uint64_t>(v[base + b] == v[base + b + 1]) << b;
      }
      if (w > 0 && window_hit(prev, bits)) {
        any_long = true;
        break;
      }
      prev = bits;
    }
    if (!any_long && words > 0 && window_hit(prev, 0)) any_long = true;
  }
  if (any_long) {
    for (size_t i = 0; i < n;) {
      size_t j = i + 1;
      while (j < n && v[j] == v[i]) ++j;
      if (j - i >= 8) long_mass += j - i;
      i = j;
    }
  }
  uint64_t thresh = n / 10;
  if (thresh < 8) thresh = 8;
  if (long_mass < thresh) {
    const size_t groups = (n + 7) / 8;
    op += varint((static_cast<uint64_t>(groups) << 1) | 1, op);
    const size_t full = n & ~static_cast<size_t>(7);
    op = bitpack_stream(v, full, width, op);
    if (n != full) {
      uint32_t tail[8] = {0};
      std::memcpy(tail, v + full, (n - full) * sizeof(uint32_t));
      op = bitpack_stream(tail, 8, width, op);
    }
    *out_len = static_cast<size_t>(op - out);
    return 0;
  }
  const int nbytes = (width + 7) / 8;
  std::vector<uint32_t> buf;
  buf.reserve(4096);
  auto flush = [&]() {
    if (buf.empty()) return;
    const size_t groups = (buf.size() + 7) / 8;
    buf.resize(groups * 8, 0);
    op += varint((static_cast<uint64_t>(groups) << 1) | 1, op);
    op = bitpack_stream(buf.data(), buf.size(), width, op);
    buf.clear();
  };
  for (size_t i = 0; i < n;) {
    const uint32_t rv = v[i];
    size_t j = i + 1;
    while (j < n && v[j] == rv) ++j;
    size_t rl = j - i;
    i = j;
    if (buf.size() % 8) {  // top up the open 8-value group first
      const size_t take = std::min(8 - buf.size() % 8, rl);
      buf.insert(buf.end(), take, rv);
      rl -= take;
    }
    if (rl >= 8) {
      flush();
      op += varint(static_cast<uint64_t>(rl) << 1, op);
      for (int b = 0; b < nbytes; ++b) *op++ = static_cast<uint8_t>(rv >> (8 * b));
      rl = 0;
    }
    if (rl) buf.insert(buf.end(), rl, rv);
  }
  flush();
  *out_len = static_cast<size_t>(op - out);
  return 0;
}

// Mixed RLE/bit-pack assembly driven from a precomputed run list — the C
// twin of kpw_tpu.core.encodings.rle_hybrid_from_runs (byte-identical by
// construction: same top-up / flush / RLE-threshold walk), so a device
// run-scan (ops/levels.py) can hand its compact run table STRAIGHT to the
// nogil page assembler instead of replaying the runs through a Python
// loop.  ``out`` needs kpw_rle_hybrid_cap(sum(run_lens), width) bytes;
// non-positive run lengths are skipped (padded device slots).
int kpw_rle_hybrid_from_runs_u32(const uint32_t* run_vals,
                                 const int32_t* run_lens, size_t n_runs,
                                 int width, uint8_t* out, size_t* out_len) {
  uint8_t* op = out;
  if (width == 0) {  // single possible value: one RLE run, no value bytes
    uint64_t total = 0;
    for (size_t r = 0; r < n_runs; r++)
      if (run_lens[r] > 0) total += static_cast<uint64_t>(run_lens[r]);
    if (total) op += varint(total << 1, op);
    *out_len = static_cast<size_t>(op - out);
    return 0;
  }
  const int nbytes = (width + 7) / 8;
  std::vector<uint32_t> buf;
  buf.reserve(4096);
  auto flush = [&]() {
    if (buf.empty()) return;
    const size_t groups = (buf.size() + 7) / 8;
    buf.resize(groups * 8, 0);
    op += varint((static_cast<uint64_t>(groups) << 1) | 1, op);
    op = bitpack_stream(buf.data(), buf.size(), width, op);
    buf.clear();
  };
  for (size_t r = 0; r < n_runs; r++) {
    if (run_lens[r] <= 0) continue;
    const uint32_t rv = run_vals[r];
    size_t rl = static_cast<size_t>(run_lens[r]);
    if (buf.size() % 8) {  // top up the open 8-value group first
      const size_t take = std::min(8 - buf.size() % 8, rl);
      buf.insert(buf.end(), take, rv);
      rl -= take;
    }
    if (rl >= 8) {
      flush();
      op += varint(static_cast<uint64_t>(rl) << 1, op);
      for (int b = 0; b < nbytes; ++b)
        *op++ = static_cast<uint8_t>(rv >> (8 * b));
      rl = 0;
    }
    if (rl) buf.insert(buf.end(), rl, rv);
  }
  flush();
  *out_len = static_cast<size_t>(op - out);
  return 0;
}

// BYTE_STREAM_SPLIT (ISSUE 16): scatter the K byte planes of n K-byte
// values — plane j collects byte j of every value in order.  Output is
// exactly n*width bytes (same count as PLAIN; the win is that grouped
// same-significance bytes compress far better).  The C twin of
// kpw_tpu.core.encodings.byte_stream_split_encode and the object code the
// nogil assembler's kOpBss op shares (both .so builds compile this file).
int kpw_byte_stream_split(const uint8_t* in, size_t n, size_t width,
                          uint8_t* out) {
  if (width == 0) return 1;
  // plane-major walk: each output plane is a sequential write while the
  // strided reads stay within one cache line per value — measurably
  // faster than value-major scatter for the 4/8-byte widths used here
  for (size_t w = 0; w < width; w++) {
    uint8_t* op = out + w * n;
    const uint8_t* ip = in + w;
    for (size_t i = 0; i < n; i++) op[i] = ip[i * width];
  }
  return 0;
}

}  // extern "C"
