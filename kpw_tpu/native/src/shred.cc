// Batch protobuf wire-format shredder for FLAT schemas (top-level scalar
// leaves only) — the C++ counterpart of the Python per-record proto parse +
// columnarize the reference funnels every record through
// (KafkaProtoParquetWriter.java:270 parser.parseFrom + ParquetFile.java:59-62
// ProtoWriteSupport shredding).  One call decodes a whole poll batch of
// serialized messages straight into columnar buffers, skipping Python
// message objects entirely.
//
// Scope: flat messages (no repeated / message / group / enum fields); the
// Python planner (kpw_tpu/models/proto_bridge.py) only engages this path
// when the schema qualifies and falls back to the exact Python semantics
// otherwise, including per-record error policy — any record this decoder
// cannot prove clean (wire-type mismatch, truncated varint, missing proto2
// required field, invalid UTF-8 in a validated string) is reported by index
// and the batch is re-parsed in Python.
//
// Wire-format reference: the public protobuf encoding spec
// (varint / fixed64 / length-delimited / fixed32 tags, last-value-wins
// scalar merge, unknown-field skipping).

#include <cstdint>
#include <cstring>
#include <vector>

#include "wire_common.h"

namespace {

// field kinds (mirrored in kpw_tpu/models/proto_bridge.py _WIRE_KINDS)
enum Kind : uint8_t {
  K_VARINT64 = 0,   // int64 / uint64 -> int64 slot (raw two's complement)
  K_VARINT32 = 1,   // int32 / uint32 -> int32 slot (low 32 bits)
  K_SINT64 = 2,     // zigzag -> int64
  K_SINT32 = 3,     // zigzag -> int32
  K_FIXED64 = 4,    // fixed64 / sfixed64 / double -> 8-byte slot
  K_FIXED32 = 5,    // fixed32 / sfixed32 / float -> 4-byte slot
  K_BOOL = 6,       // varint != 0 -> uint8 slot
  K_SPAN = 7,       // bytes / string: (pos, len) into the payload buffer
  K_SPAN_UTF8 = 8,  // string with UTF-8 validation (proto3 semantics)
};

enum Flags : uint8_t {
  F_REQUIRED = 1,  // proto2 required: absence is a record parse error
};

using kpw_wire::read_varint;
using kpw_wire::utf8_ok;

// record sources for the shared decode core: one contiguous buffer with an
// offsets table (the ctypes join path), or an iovec of per-record pointers
// (the zero-copy C-extension path, native/src/pyshred.cc — payload bytes
// objects are read in place, no join).  Span positions are relative to the
// source's per-record base so each path's gather knows how to resolve them.
struct ContigSrc {
  const uint8_t* buf;
  const int64_t* offs;
  inline void rec(int64_t r, const uint8_t** p, const uint8_t** end,
                  const uint8_t** base) const {
    *p = buf + offs[r];
    *end = buf + offs[r + 1];
    *base = buf;  // global positions, resolved by kpw_gather_spans
  }
};

struct IovSrc {
  const uint8_t* const* ptrs;
  const int64_t* lens;
  inline void rec(int64_t r, const uint8_t** p, const uint8_t** end,
                  const uint8_t** base) const {
    *p = ptrs[r];
    *end = ptrs[r] + lens[r];
    *base = ptrs[r];  // in-record positions, resolved with the record index
  }
};

// Decode n_rec serialized messages into per-field columnar outputs.
//
//   out_vals[f]: fixed-width target (n_rec slots of 1/4/8 bytes per Kind),
//                pre-zeroed by the caller (absent no-presence fields keep
//                proto defaults); NULL for span kinds.
//   out_pos[f]/out_len[f]: span targets (pos pre-filled with 0, len with 0 —
//                absent spans read back as empty); NULL for fixed kinds.
//   out_pres[f]: presence byte per record (pre-zeroed) or NULL when the
//                caller does not need presence (proto3 no-presence fields).
//
// Returns -1 on success, or the index of the first record that must take
// the Python fallback path (parse error / semantics this decoder does not
// model).  Outputs for preceding records are valid; the caller discards the
// batch on any error and re-parses in Python (errors are rare: poison
// pills).
template <typename Src>
int64_t shred_impl(const Src& src, int64_t n_rec, int32_t n_fields,
                   const uint32_t* fnum, const uint8_t* kind,
                   const uint8_t* flags, void* const* out_vals,
                   int64_t* const* out_pos, int32_t* const* out_len,
                   uint8_t* const* out_pres) {
  // direct-address field-number -> plan index table
  uint32_t max_fn = 0;
  for (int32_t f = 0; f < n_fields; f++)
    if (fnum[f] > max_fn) max_fn = fnum[f];
  if (max_fn > 65535) return -2;  // planner bug; never emitted for sane protos
  std::vector<int16_t> table(max_fn + 1, -1);
  for (int32_t f = 0; f < n_fields; f++) table[fnum[f]] = int16_t(f);

  bool any_required = false;
  for (int32_t f = 0; f < n_fields; f++)
    if (flags[f] & F_REQUIRED) any_required = true;
  std::vector<uint8_t> seen(any_required ? n_fields : 0);

  for (int64_t r = 0; r < n_rec; r++) {
    const uint8_t* p;
    const uint8_t* end;
    const uint8_t* base;
    src.rec(r, &p, &end, &base);
    if (any_required) std::memset(seen.data(), 0, seen.size());
    while (p < end) {
      uint64_t tag;
      if (!read_varint(p, end, &tag)) return r;
      uint32_t field = uint32_t(tag >> 3);
      uint32_t wire = uint32_t(tag & 7);
      if (field == 0) return r;  // invalid field number
      int16_t f = (field <= max_fn) ? table[field] : -1;
      if (f < 0) {
        // unknown field: skip by wire type (groups -> fallback)
        uint64_t v;
        switch (wire) {
          case 0:
            if (!read_varint(p, end, &v)) return r;
            break;
          case 1:
            if (end - p < 8) return r;
            p += 8;
            break;
          case 2:
            if (!read_varint(p, end, &v) || uint64_t(end - p) < v) return r;
            p += v;
            break;
          case 5:
            if (end - p < 4) return r;
            p += 4;
            break;
          default:
            return r;  // groups / reserved wire types
        }
        continue;
      }
      uint8_t k = kind[f];
      uint64_t v;
      switch (k) {
        case K_VARINT64:
        case K_VARINT32:
        case K_SINT64:
        case K_SINT32:
        case K_BOOL: {
          if (wire != 0) return r;  // mismatch: Python models the semantics
          if (!read_varint(p, end, &v)) return r;
          if (k == K_SINT64)
            reinterpret_cast<int64_t*>(out_vals[f])[r] =
                int64_t(v >> 1) ^ -int64_t(v & 1);
          else if (k == K_SINT32) {
            uint32_t u = uint32_t(v);
            reinterpret_cast<int32_t*>(out_vals[f])[r] =
                int32_t(u >> 1) ^ -int32_t(u & 1);
          } else if (k == K_VARINT64)
            reinterpret_cast<int64_t*>(out_vals[f])[r] = int64_t(v);
          else if (k == K_VARINT32)
            reinterpret_cast<int32_t*>(out_vals[f])[r] = int32_t(uint32_t(v));
          else
            reinterpret_cast<uint8_t*>(out_vals[f])[r] = v ? 1 : 0;
          break;
        }
        case K_FIXED64: {
          if (wire != 1 || end - p < 8) return r;
          std::memcpy(reinterpret_cast<uint8_t*>(out_vals[f]) + r * 8, p, 8);
          p += 8;
          break;
        }
        case K_FIXED32: {
          if (wire != 5 || end - p < 4) return r;
          std::memcpy(reinterpret_cast<uint8_t*>(out_vals[f]) + r * 4, p, 4);
          p += 4;
          break;
        }
        case K_SPAN:
        case K_SPAN_UTF8: {
          if (wire != 2) return r;
          if (!read_varint(p, end, &v) || uint64_t(end - p) < v) return r;
          if (k == K_SPAN_UTF8 && !utf8_ok(p, int64_t(v))) return r;
          out_pos[f][r] = p - base;
          out_len[f][r] = int32_t(v);
          p += v;
          break;
        }
        default:
          return r;
      }
      if (out_pres[f]) out_pres[f][r] = 1;
      if (any_required) seen[f] = 1;
    }
    if (any_required)
      for (int32_t f = 0; f < n_fields; f++)
        if ((flags[f] & F_REQUIRED) && !seen[f]) return r;  // missing required
  }
  return -1;
}

}  // namespace

extern "C" {

int64_t kpw_proto_shred(const uint8_t* buf, const int64_t* offs,
                        int64_t n_rec, int32_t n_fields,
                        const uint32_t* fnum, const uint8_t* kind,
                        const uint8_t* flags, void* const* out_vals,
                        int64_t* const* out_pos, int32_t* const* out_len,
                        uint8_t* const* out_pres) {
  return shred_impl(ContigSrc{buf, offs}, n_rec, n_fields, fnum, kind, flags,
                    out_vals, out_pos, out_len, out_pres);
}

// iovec variant: record r lives at [ptrs[r], ptrs[r] + lens[r]); span
// positions come back RELATIVE TO THE RECORD (resolve with
// kpw_gather_spans_iov).  The zero-copy entry used by the C extension.
int64_t kpw_proto_shred_iov(const uint8_t* const* ptrs, const int64_t* lens,
                            int64_t n_rec, int32_t n_fields,
                            const uint32_t* fnum, const uint8_t* kind,
                            const uint8_t* flags, void* const* out_vals,
                            int64_t* const* out_pos, int32_t* const* out_len,
                            uint8_t* const* out_pres) {
  return shred_impl(IovSrc{ptrs, lens}, n_rec, n_fields, fnum, kind, flags,
                    out_vals, out_pos, out_len, out_pres);
}

// Gather n spans (pos[i], len[i]) out of `src` back to back into `out`
// (caller sizes `out` as sum(len)).  The string-column assembly step after
// kpw_proto_shred.
void kpw_gather_spans(const uint8_t* src, const int64_t* pos,
                      const int32_t* len, int64_t n, uint8_t* out) {
  for (int64_t i = 0; i < n; i++) {
    std::memcpy(out, src + pos[i], size_t(len[i]));
    out += len[i];
  }
}

// iovec gather: span i is (pos[i], len[i]) within record rec_idx[i].
void kpw_gather_spans_iov(const uint8_t* const* ptrs, const int32_t* rec_idx,
                          const int64_t* pos, const int32_t* len, int64_t n,
                          uint8_t* out) {
  for (int64_t i = 0; i < n; i++) {
    std::memcpy(out, ptrs[rec_idx[i]] + pos[i], size_t(len[i]));
    out += len[i];
  }
}

}  // extern "C"
