// Shared protobuf wire-format parsing primitives for the flat (shred.cc)
// and nested (shred_nested.cc) batch shredders.  ONE definition for the
// security-sensitive pieces — varint bounds handling and strict UTF-8
// validation (overlong / surrogate / out-of-range rejection, proto3 string
// semantics) — so the two decode paths can never diverge on the same input.
#ifndef KPW_WIRE_COMMON_H_
#define KPW_WIRE_COMMON_H_

#include <cstdint>

namespace kpw_wire {

inline bool read_varint(const uint8_t*& p, const uint8_t* end,
                        uint64_t* out) {
  uint64_t v = 0;
  int shift = 0;
  while (p < end && shift < 64) {
    uint8_t b = *p++;
    v |= uint64_t(b & 0x7f) << shift;
    if (!(b & 0x80)) {
      *out = v;
      return true;
    }
    shift += 7;
  }
  return false;  // truncated or > 10 bytes
}

inline bool utf8_ok(const uint8_t* s, int64_t n) {
  int64_t i = 0;
  while (i < n) {
    uint8_t c = s[i];
    if (c < 0x80) {
      i++;
      continue;
    }
    int extra;
    uint32_t cp;
    if ((c & 0xe0) == 0xc0) {
      extra = 1;
      cp = c & 0x1f;
    } else if ((c & 0xf0) == 0xe0) {
      extra = 2;
      cp = c & 0x0f;
    } else if ((c & 0xf8) == 0xf0) {
      extra = 3;
      cp = c & 0x07;
    } else {
      return false;
    }
    if (i + extra >= n) return false;
    for (int k = 1; k <= extra; k++) {
      uint8_t cc = s[i + k];
      if ((cc & 0xc0) != 0x80) return false;
      cp = (cp << 6) | (cc & 0x3f);
    }
    // overlong / surrogate / out-of-range rejection
    if (extra == 1 && cp < 0x80) return false;
    if (extra == 2 && (cp < 0x800 || (cp >= 0xd800 && cp <= 0xdfff)))
      return false;
    if (extra == 3 && (cp < 0x10000 || cp > 0x10ffff)) return false;
    i += 1 + extra;
  }
  return true;
}

}  // namespace kpw_wire

#endif  // KPW_WIRE_COMMON_H_
