// kpw_tpu native host library: page codecs + byte-assembly hot paths.
//
// The reference system's only native code is the codec layer reached through
// parquet-mr (snappy-java JNI, zlib, libhadoop CRC — SURVEY.md §2.2
// "Native-code accounting").  This file is the rebuild's equivalent:
//   * Snappy block-format compressor/decompressor.  The wire format follows
//     the public format description; the compressor's internal heuristics
//     (the 0x1e35a7bd hash multiplier, the skip>>5 match-skipping schedule,
//     the emit_literal/emit_copy decomposition) follow the algorithm of
//     upstream google/snappy (BSD-licensed) — credit where due; output is
//     cross-validated against libsnappy in tests/test_native.py,
//   * ZSTD via the system libzstd (zstd.h),
//   * CRC32C (Castagnoli, table-driven), parquet page checksum polynomial,
//   * BYTE_ARRAY PLAIN assembly (length-prefix interleaving) for the string
//     hot path.
//
// Exposed as a plain C ABI for ctypes.

#include <cstdint>
#include <cstring>
#include <cstdlib>

#ifndef KPW_NO_ZSTD
#include <zstd.h>
#endif
#include <dlfcn.h>  // snappy + zstd runtime dispatch (glibc>=2.34: in libc)
#include <mutex>
#include <vector>

namespace {

// ---------------------------------------------------------------------------
// varint32
// ---------------------------------------------------------------------------

inline size_t varint_encode(uint32_t v, uint8_t* out) {
  size_t i = 0;
  while (v >= 0x80) {
    out[i++] = static_cast<uint8_t>(v) | 0x80;
    v >>= 7;
  }
  out[i++] = static_cast<uint8_t>(v);
  return i;
}

inline int varint_decode(const uint8_t* in, size_t n, uint32_t* v) {
  uint32_t result = 0;
  int shift = 0;
  for (size_t i = 0; i < n && i < 5; i++) {
    result |= static_cast<uint32_t>(in[i] & 0x7F) << shift;
    if (!(in[i] & 0x80)) {
      *v = result;
      return static_cast<int>(i) + 1;
    }
    shift += 7;
  }
  return -1;
}

// ---------------------------------------------------------------------------
// Snappy block format
// ---------------------------------------------------------------------------

constexpr size_t kBlockSize = 1 << 16;  // compress in 64 KiB fragments
constexpr int kHashBits = 14;
constexpr size_t kHashSize = 1 << kHashBits;

inline uint32_t load32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

inline uint64_t load64(const uint8_t* p) {
  uint64_t v;
  std::memcpy(&v, p, 8);
  return v;
}

inline uint32_t hash4(uint32_t v) {
  return (v * 0x1e35a7bdu) >> (32 - kHashBits);
}

// Emit a literal run [lit, lit+len)
inline uint8_t* emit_literal(uint8_t* op, const uint8_t* lit, size_t len) {
  if (len == 0) return op;
  size_t n = len - 1;
  if (n < 60) {
    *op++ = static_cast<uint8_t>(n << 2);
  } else if (n < (1u << 8)) {
    *op++ = 60 << 2;
    *op++ = static_cast<uint8_t>(n);
  } else if (n < (1u << 16)) {
    *op++ = 61 << 2;
    *op++ = static_cast<uint8_t>(n);
    *op++ = static_cast<uint8_t>(n >> 8);
  } else if (n < (1u << 24)) {
    *op++ = 62 << 2;
    *op++ = static_cast<uint8_t>(n);
    *op++ = static_cast<uint8_t>(n >> 8);
    *op++ = static_cast<uint8_t>(n >> 16);
  } else {
    *op++ = 63 << 2;
    *op++ = static_cast<uint8_t>(n);
    *op++ = static_cast<uint8_t>(n >> 8);
    *op++ = static_cast<uint8_t>(n >> 16);
    *op++ = static_cast<uint8_t>(n >> 24);
  }
  std::memcpy(op, lit, len);
  return op + len;
}

// Emit one copy element (len <= 64, offset < 65536)
inline uint8_t* emit_copy_upto64(uint8_t* op, size_t offset, size_t len) {
  if (len < 12 && offset < 2048) {
    // copy with 1-byte offset: tag 01
    *op++ = static_cast<uint8_t>(((offset >> 8) << 5) | ((len - 4) << 2) | 1);
    *op++ = static_cast<uint8_t>(offset);
  } else {
    // copy with 2-byte offset: tag 10
    *op++ = static_cast<uint8_t>(((len - 1) << 2) | 2);
    *op++ = static_cast<uint8_t>(offset);
    *op++ = static_cast<uint8_t>(offset >> 8);
  }
  return op;
}

inline uint8_t* emit_copy(uint8_t* op, size_t offset, size_t len) {
  // Long matches: emit 64-byte copies, keep remainder >= 4
  while (len >= 68) {
    op = emit_copy_upto64(op, offset, 64);
    len -= 64;
  }
  if (len > 64) {
    op = emit_copy_upto64(op, offset, 60);
    len -= 60;
  }
  return emit_copy_upto64(op, offset, len);
}

// Compress one fragment (<= 64 KiB); offsets are fragment-relative.
uint8_t* compress_fragment(const uint8_t* input, size_t n, uint8_t* op,
                           uint16_t* table) {
  std::memset(table, 0, kHashSize * sizeof(uint16_t));
  const uint8_t* ip = input;
  const uint8_t* ip_end = input + n;
  const uint8_t* next_emit = input;
  if (n >= 15) {
    const uint8_t* ip_limit = input + n - 15;
    ip++;  // first byte can never be a match target
    while (ip < ip_limit) {
      // find a match, skipping ahead faster the longer we go without one
      uint32_t skip = 32;
      const uint8_t* next_ip = ip;
      const uint8_t* candidate;
      do {
        ip = next_ip;
        uint32_t h = hash4(load32(ip));
        next_ip = ip + (skip++ >> 5);
        if (next_ip > ip_limit) goto emit_remainder;
        candidate = input + table[h];
        table[h] = static_cast<uint16_t>(ip - input);
      } while (load32(candidate) != load32(ip) || candidate >= ip);

      op = emit_literal(op, next_emit, ip - next_emit);

      // extend the match and emit copies; chain adjacent matches
      do {
        const uint8_t* base = ip;
        size_t matched = 4;
        ip += 4;
        candidate += 4;
        while (ip + 8 <= ip_end && load64(candidate) == load64(ip)) {
          ip += 8;
          candidate += 8;
          matched += 8;
        }
        while (ip < ip_end && *candidate == *ip) {
          ip++;
          candidate++;
          matched++;
        }
        op = emit_copy(op, base - (candidate - matched), matched);
        next_emit = ip;
        if (ip >= ip_limit) goto emit_remainder;
        // refresh hash entries around the match end
        uint32_t cur = load32(ip);
        table[hash4(load32(ip - 1))] = static_cast<uint16_t>(ip - 1 - input);
        uint32_t h = hash4(cur);
        candidate = input + table[h];
        table[h] = static_cast<uint16_t>(ip - input);
        if (load32(candidate) != cur || candidate >= ip) break;
      } while (true);
      ip++;
    }
  }
emit_remainder:
  if (next_emit < ip_end) {
    op = emit_literal(op, next_emit, ip_end - next_emit);
  }
  return op;
}

}  // namespace

// Runtime dispatch to the system libsnappy when present (same pattern as
// zdl:: for zstd): its compressor is ~2x our from-scratch one on page data
// (measured 4.0 vs 2.0 GB/s on this host), and both emit valid snappy
// streams.  The dispatch lives INSIDE kpw_snappy_compress so every caller
// (native encoder, cpu oracle path via core.compression) picks the same
// implementation — backend byte-identity holds per host.  Opt out with
// KPW_SNAPPY_LIB="" (empty) or point KPW_SNAPPY_LIB at a specific .so;
// decompression and the internal compressor remain available either way
// (tests cross-validate the two).
namespace sdl {
typedef int (*raw_compress_t)(const char*, size_t, char*, size_t*);
typedef size_t (*max_len_t)(size_t);

struct Api {
  raw_compress_t compress = nullptr;  // null = internal compressor
  max_len_t max_len = nullptr;
};

static Api g_api;
static std::once_flag g_once;

static void init_api() {
  const char* path = getenv("KPW_SNAPPY_LIB");
  if (path != nullptr && path[0] == '\0') return;  // explicit opt-out
  void* h = dlopen(path != nullptr ? path : "libsnappy.so.1",
                   RTLD_LAZY | RTLD_LOCAL);
  if (h == nullptr) return;
  Api a;
  a.compress = (raw_compress_t)dlsym(h, "snappy_compress");
  a.max_len = (max_len_t)dlsym(h, "snappy_max_compressed_length");
  if (a.compress != nullptr && a.max_len != nullptr)
    g_api = a;
  else
    dlclose(h);
}

static const Api& api() {
  std::call_once(g_once, init_api);
  return g_api;
}
}  // namespace sdl

extern "C" {

size_t kpw_snappy_max_compressed_length(size_t n) {
  const sdl::Api& s = sdl::api();
  if (s.max_len != nullptr) {
    size_t m = s.max_len(n);
    size_t ours = 32 + n + n / 6;
    return m > ours ? m : ours;
  }
  return 32 + n + n / 6;
}

int kpw_snappy_compress(const uint8_t* in, size_t n, uint8_t* out,
                        size_t* out_len) {
  if (n > 0xFFFFFFFFull) return -1;
  const sdl::Api& s = sdl::api();
  if (s.compress != nullptr) {
    *out_len = kpw_snappy_max_compressed_length(n);
    return s.compress(reinterpret_cast<const char*>(in), n,
                      reinterpret_cast<char*>(out), out_len) == 0 ? 0 : -3;
  }
  uint8_t* op = out;
  op += varint_encode(static_cast<uint32_t>(n), op);
  uint16_t* table =
      static_cast<uint16_t*>(std::malloc(kHashSize * sizeof(uint16_t)));
  if (!table) return -2;
  for (size_t pos = 0; pos < n; pos += kBlockSize) {
    size_t frag = n - pos < kBlockSize ? n - pos : kBlockSize;
    op = compress_fragment(in + pos, frag, op, table);
  }
  if (n == 0) {
    // nothing beyond the length varint
  }
  std::free(table);
  *out_len = static_cast<size_t>(op - out);
  return 0;
}

// Parts-based snappy page compression (mirrors kpw_zstd_compress_parts):
// the page body's discontiguous parts are concatenated in C into
// thread-local scratch (snappy's one-shot API needs contiguous input) and
// compressed straight into the caller's scratch — no Python-side join, no
// zeroed bounce buffers, no compressed-bytes copy.
int kpw_snappy_compress_parts(const void* const* parts, const size_t* lens,
                              int n_parts, uint8_t* out, size_t out_cap,
                              size_t* out_len) {
  size_t total = 0;
  for (int i = 0; i < n_parts; i++) total += lens[i];
  static thread_local std::vector<uint8_t> scratch;
  if (scratch.size() < total) scratch.resize(total);
  uint8_t* p = scratch.data();
  for (int i = 0; i < n_parts; i++) {
    std::memcpy(p, parts[i], lens[i]);
    p += lens[i];
  }
  if (out_cap < kpw_snappy_max_compressed_length(total)) return -4;
  *out_len = out_cap;
  return kpw_snappy_compress(scratch.data(), total, out, out_len);
}

int kpw_snappy_uncompressed_length(const uint8_t* in, size_t n,
                                   size_t* result) {
  uint32_t v;
  int used = varint_decode(in, n, &v);
  if (used < 0) return -1;
  *result = v;
  return 0;
}

int kpw_snappy_uncompress(const uint8_t* in, size_t n, uint8_t* out,
                          size_t out_cap, size_t* out_len) {
  uint32_t total;
  int used = varint_decode(in, n, &total);
  if (used < 0 || total > out_cap) return -1;
  const uint8_t* ip = in + used;
  const uint8_t* ip_end = in + n;
  uint8_t* op = out;
  uint8_t* op_end = out + total;
  while (ip < ip_end && op < op_end) {
    uint8_t tag = *ip++;
    uint32_t entry = tag >> 2;
    switch (tag & 3) {
      case 0: {  // literal
        size_t len;
        if (entry < 60) {
          len = entry + 1;
        } else {
          size_t extra = entry - 59;  // 1..4 bytes
          if (ip + extra > ip_end) return -2;
          uint32_t l = 0;
          for (size_t i = 0; i < extra; i++) l |= static_cast<uint32_t>(ip[i]) << (8 * i);
          ip += extra;
          len = static_cast<size_t>(l) + 1;
        }
        if (ip + len > ip_end || op + len > op_end) return -3;
        std::memcpy(op, ip, len);
        ip += len;
        op += len;
        break;
      }
      case 1: {  // copy, 1-byte offset
        if (ip >= ip_end) return -4;
        size_t len = ((entry >> 0) & 0x7) + 4;
        size_t offset = ((entry >> 3) << 8) | *ip++;
        if (offset == 0 || offset > static_cast<size_t>(op - out) ||
            op + len > op_end)
          return -5;
        const uint8_t* src = op - offset;
        for (size_t i = 0; i < len; i++) op[i] = src[i];
        op += len;
        break;
      }
      case 2: {  // copy, 2-byte offset
        if (ip + 2 > ip_end) return -6;
        size_t len = entry + 1;
        size_t offset = ip[0] | (static_cast<size_t>(ip[1]) << 8);
        ip += 2;
        if (offset == 0 || offset > static_cast<size_t>(op - out) ||
            op + len > op_end)
          return -7;
        const uint8_t* src = op - offset;
        for (size_t i = 0; i < len; i++) op[i] = src[i];
        op += len;
        break;
      }
      case 3: {  // copy, 4-byte offset
        if (ip + 4 > ip_end) return -8;
        size_t len = entry + 1;
        size_t offset = ip[0] | (static_cast<size_t>(ip[1]) << 8) |
                        (static_cast<size_t>(ip[2]) << 16) |
                        (static_cast<size_t>(ip[3]) << 24);
        ip += 4;
        if (offset == 0 || offset > static_cast<size_t>(op - out) ||
            op + len > op_end)
          return -9;
        const uint8_t* src = op - offset;
        for (size_t i = 0; i < len; i++) op[i] = src[i];
        op += len;
        break;
      }
    }
  }
  if (op != op_end) return -10;
  *out_len = total;
  return 0;
}

// ---------------------------------------------------------------------------
// ZSTD via system libzstd
// ---------------------------------------------------------------------------

#ifndef KPW_NO_ZSTD
// ---------------------------------------------------------------------------
// Runtime zstd dispatch: the public ZSTD_* API is version-stable, and the
// Python environment often ships a newer, faster libzstd inside the
// `zstandard` extension than the distro's (1.5.7 vs 1.5.4 here, ~1.5x
// compression throughput).  When KPW_ZSTD_LIB names a loadable library that
// exports the needed symbols, use it; otherwise fall back to the libzstd we
// linked against.  RTLD_LAZY: the donor .so may be a Python extension whose
// *other* symbols only resolve inside the interpreter.
// ---------------------------------------------------------------------------
namespace zdl {
typedef size_t (*compressBound_t)(size_t);
typedef ZSTD_CCtx* (*createCCtx_t)(void);
typedef size_t (*freeCCtx_t)(ZSTD_CCtx*);
typedef size_t (*cctxReset_t)(ZSTD_CCtx*, ZSTD_ResetDirective);
typedef size_t (*cctxSetParameter_t)(ZSTD_CCtx*, ZSTD_cParameter, int);
typedef size_t (*cctxSetPledged_t)(ZSTD_CCtx*, unsigned long long);
typedef size_t (*compressStream2_t)(ZSTD_CCtx*, ZSTD_outBuffer*, ZSTD_inBuffer*, ZSTD_EndDirective);
typedef unsigned (*isError_t)(size_t);
typedef unsigned long long (*getFrameContentSize_t)(const void*, size_t);
typedef size_t (*decompress_t)(void*, size_t, const void*, size_t);
typedef size_t (*oneshot_t)(void*, size_t, const void*, size_t, int);

struct Api {
  oneshot_t oneshot = ZSTD_compress;
  compressBound_t compressBound = ZSTD_compressBound;
  createCCtx_t createCCtx = ZSTD_createCCtx;
  freeCCtx_t freeCCtx = ZSTD_freeCCtx;
  cctxReset_t cctxReset = ZSTD_CCtx_reset;
  cctxSetParameter_t cctxSetParameter = ZSTD_CCtx_setParameter;
  cctxSetPledged_t cctxSetPledged = ZSTD_CCtx_setPledgedSrcSize;
  compressStream2_t compressStream2 = ZSTD_compressStream2;
  isError_t isError = ZSTD_isError;
  getFrameContentSize_t getFrameContentSize = ZSTD_getFrameContentSize;
  decompress_t decompress = ZSTD_decompress;
};

static Api g_api;
static std::once_flag g_once;

static void init_api() {
  const char* path = getenv("KPW_ZSTD_LIB");
  if (path == nullptr || path[0] == '\0') return;
  void* h = dlopen(path, RTLD_LAZY | RTLD_LOCAL);
  if (h == nullptr) return;
  Api a;
  bool ok = true;
  auto resolve = [&](const char* name) -> void* {
    void* p = dlsym(h, name);
    if (p == nullptr) ok = false;
    return p;
  };
  a.compressBound = (compressBound_t)resolve("ZSTD_compressBound");
  a.createCCtx = (createCCtx_t)resolve("ZSTD_createCCtx");
  a.freeCCtx = (freeCCtx_t)resolve("ZSTD_freeCCtx");
  a.cctxReset = (cctxReset_t)resolve("ZSTD_CCtx_reset");
  a.cctxSetParameter = (cctxSetParameter_t)resolve("ZSTD_CCtx_setParameter");
  a.cctxSetPledged = (cctxSetPledged_t)resolve("ZSTD_CCtx_setPledgedSrcSize");
  a.compressStream2 = (compressStream2_t)resolve("ZSTD_compressStream2");
  a.isError = (isError_t)resolve("ZSTD_isError");
  a.getFrameContentSize = (getFrameContentSize_t)resolve("ZSTD_getFrameContentSize");
  a.decompress = (decompress_t)resolve("ZSTD_decompress");
  a.oneshot = (oneshot_t)resolve("ZSTD_compress");
  if (ok) g_api = a; else dlclose(h);
}

static const Api& api() {
  std::call_once(g_once, init_api);
  return g_api;
}
}  // namespace zdl

size_t kpw_zstd_max_compressed_length(size_t n) { return zdl::api().compressBound(n); }

int kpw_zstd_compress_parts(const uint8_t* const* parts, const size_t* lens,
                            int n_parts, uint8_t* out, size_t out_cap,
                            size_t* out_len, int level);

int kpw_zstd_compress(const uint8_t* in, size_t n, uint8_t* out,
                      size_t out_cap, size_t* out_len, int level) {
  // one implementation: the streaming parts path with a single part (same
  // frame bytes — pledged content size keeps headers identical) and one
  // shared thread-local context per thread.
  return kpw_zstd_compress_parts(&in, &n, 1, out, out_cap, out_len, level);
}

// Compress several discontiguous input parts as ONE zstd frame (streaming
// API) — the page-assembly hot path hands [levels blob, delta header,
// string payload] without pre-concatenating them into a scratch buffer.
// Byte-compatibility note: the frame differs from ZSTD_compress output only
// in header flags (no content-size field); parquet stores the uncompressed
// size in the page header, and every decompressor (ours included) streams.
int kpw_zstd_compress_parts(const uint8_t* const* parts, const size_t* lens,
                            int n_parts, uint8_t* out, size_t out_cap,
                            size_t* out_len, int level) {
  const zdl::Api& z = zdl::api();
  struct CtxHolder {
    ZSTD_CCtx* ctx = zdl::api().createCCtx();
    ~CtxHolder() { zdl::api().freeCCtx(ctx); }
  };
  static thread_local CtxHolder holder;
  if (holder.ctx == nullptr) holder.ctx = z.createCCtx();  // retry after OOM
  if (holder.ctx == nullptr) {
    // stateless fallback: concatenate (if needed) and one-shot compress —
    // survivable degraded mode instead of poisoning the file
    unsigned long long total = 0;
    for (int i = 0; i < n_parts; i++) total += lens[i];
    const uint8_t* src = n_parts == 1 ? parts[0] : nullptr;
    uint8_t* tmp = nullptr;
    if (src == nullptr) {
      tmp = static_cast<uint8_t*>(std::malloc(total ? total : 1));
      if (tmp == nullptr) return -2;
      size_t off = 0;
      for (int i = 0; i < n_parts; i++) {
        std::memcpy(tmp + off, parts[i], lens[i]);
        off += lens[i];
      }
      src = tmp;
    }
    size_t rc = z.oneshot(out, out_cap, src, total, level);
    std::free(tmp);
    if (z.isError(rc)) return -1;
    *out_len = rc;
    return 0;
  }
  ZSTD_CCtx* c = holder.ctx;
  z.cctxReset(c, ZSTD_reset_session_only);
  if (z.isError(z.cctxSetParameter(c, ZSTD_c_compressionLevel, level)))
    return -3;
  unsigned long long total = 0;
  for (int i = 0; i < n_parts; i++) total += lens[i];
  // keep the frame identical to the one-shot API: record the content size
  z.cctxSetPledged(c, total);
  ZSTD_outBuffer ob{out, out_cap, 0};
  for (int i = 0; i < n_parts; i++) {
    ZSTD_inBuffer ib{parts[i], lens[i], 0};
    ZSTD_EndDirective mode = (i == n_parts - 1) ? ZSTD_e_end : ZSTD_e_continue;
    while (true) {
      size_t rc = z.compressStream2(c, &ob, &ib, mode);
      if (z.isError(rc)) return -1;
      if (mode == ZSTD_e_end ? rc == 0 : ib.pos == ib.size) break;
      if (ob.pos == ob.size) return -4;  // out_cap too small (caller bug)
    }
  }
  *out_len = ob.pos;
  return 0;
}

int kpw_zstd_uncompressed_length(const uint8_t* in, size_t n, size_t* result) {
  unsigned long long sz = zdl::api().getFrameContentSize(in, n);
  if (sz == ZSTD_CONTENTSIZE_ERROR || sz == ZSTD_CONTENTSIZE_UNKNOWN) return -1;
  *result = static_cast<size_t>(sz);
  return 0;
}

int kpw_zstd_uncompress(const uint8_t* in, size_t n, uint8_t* out,
                        size_t out_cap, size_t* out_len) {
  const zdl::Api& z = zdl::api();
  size_t rc = z.decompress(out, out_cap, in, n);
  if (z.isError(rc)) return -1;
  *out_len = rc;
  return 0;
}
#endif

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli), bit-reflected, table-driven
// ---------------------------------------------------------------------------

static uint32_t crc32c_table[8][256];
static bool crc32c_init_done = false;

static void crc32c_init() {
  const uint32_t poly = 0x82F63B78u;  // reflected 0x1EDC6F41
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++)
      crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    crc32c_table[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int s = 1; s < 8; s++)
      crc32c_table[s][i] =
          (crc32c_table[s - 1][i] >> 8) ^ crc32c_table[0][crc32c_table[s - 1][i] & 0xFF];
  crc32c_init_done = true;
}

uint32_t kpw_crc32c(const uint8_t* data, size_t n, uint32_t crc) {
  if (!crc32c_init_done) crc32c_init();
  crc = ~crc;
  while (n >= 8) {
    crc ^= load32(data);
    uint32_t hi = load32(data + 4);
    crc = crc32c_table[7][crc & 0xFF] ^ crc32c_table[6][(crc >> 8) & 0xFF] ^
          crc32c_table[5][(crc >> 16) & 0xFF] ^ crc32c_table[4][crc >> 24] ^
          crc32c_table[3][hi & 0xFF] ^ crc32c_table[2][(hi >> 8) & 0xFF] ^
          crc32c_table[1][(hi >> 16) & 0xFF] ^ crc32c_table[0][hi >> 24];
    data += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ crc32c_table[0][(crc ^ *data++) & 0xFF];
  return ~crc;
}

// ---------------------------------------------------------------------------
// BYTE_ARRAY PLAIN assembly: interleave 4-byte LE lengths with value bytes.
// data: concatenated values; offsets: count+1 int64 prefix offsets.
// out must have (offsets[count]-offsets[0]) + 4*count bytes.
// ---------------------------------------------------------------------------

void kpw_byte_array_plain(const uint8_t* data, const int64_t* offsets,
                          size_t count, uint8_t* out) {
  size_t pos = 0;
  for (size_t i = 0; i < count; i++) {
    uint32_t len = static_cast<uint32_t>(offsets[i + 1] - offsets[i]);
    std::memcpy(out + pos, &len, 4);
    pos += 4;
    std::memcpy(out + pos, data + offsets[i], len);
    pos += len;
  }
}

// Gather variable-length dictionary entries by index (host-side string
// dictionary materialization for the TPU path).
void kpw_byte_array_gather(const uint8_t* dict_data, const int64_t* dict_offsets,
                           const int32_t* indices, size_t count, uint8_t* out) {
  size_t pos = 0;
  for (size_t i = 0; i < count; i++) {
    int32_t idx = indices[i];
    int64_t start = dict_offsets[idx];
    uint32_t len = static_cast<uint32_t>(dict_offsets[idx + 1] - start);
    std::memcpy(out + pos, &len, 4);
    pos += 4;
    std::memcpy(out + pos, dict_data + start, len);
    pos += len;
  }
}

}  // extern "C"
