// CPython extension: zero-copy batch shred entry points.
//
// The ctypes path (build.py NativeLib.proto_shred) needs the poll batch
// joined into ONE contiguous buffer (b"".join + np.fromiter lengths) before
// the decoder can run — ~35 ms per 300k records of pure copy/iteration on
// the streaming hot path (the reference's equivalent cost is zero: its
// parser reads each record's byte[] in place, KafkaProtoParquetWriter.java:
// 270).  This module reads the payload list IN PLACE instead:
// PyBytes_AS_STRING pointers feed kpw_proto_shred_iov (shred.cc) directly,
// and string columns gather straight into a freshly-allocated bytes object
// (one copy total, into the final column payload).
//
// Compiled as _kpw_pyshred.so together with shred.cc (same source, no
// logic duplication); loaded via importlib ExtensionFileLoader (build.py
// pyshred()).  The GIL is released around the decode and gather loops —
// pointers stay valid because the payload list (and its bytes items) are
// owned by the calling frame for the duration.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <vector>

extern "C" {
int64_t kpw_proto_shred_iov(const uint8_t* const* ptrs, const int64_t* lens,
                            int64_t n_rec, int32_t n_fields,
                            const uint32_t* fnum, const uint8_t* kind,
                            const uint8_t* flags, void* const* out_vals,
                            int64_t* const* out_pos, int32_t* const* out_len,
                            uint8_t* const* out_pres);
void kpw_gather_spans_iov(const uint8_t* const* ptrs, const int32_t* rec_idx,
                          const int64_t* pos, const int32_t* len, int64_t n,
                          uint8_t* out);
int64_t kpw_proto_shred(const uint8_t* buf, const int64_t* offs,
                        int64_t n_rec, int32_t n_fields, const uint32_t* fnum,
                        const uint8_t* kind, const uint8_t* flags,
                        void* const* out_vals, int64_t* const* out_pos,
                        int32_t* const* out_len, uint8_t* const* out_pres);
void kpw_gather_spans(const uint8_t* src, const int64_t* pos,
                      const int32_t* len, int64_t n, uint8_t* out);
}

namespace {

// payload list -> per-record pointers/lengths, zero copy.  false = a
// non-bytes element (caller falls back to the ctypes path); TypeError set.
bool collect_iov(PyObject* payloads, std::vector<const uint8_t*>& ptrs,
                 std::vector<int64_t>& lens, int64_t* total) {
  Py_ssize_t n = PyList_GET_SIZE(payloads);
  ptrs.resize(n);
  lens.resize(n);
  int64_t t = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PyList_GET_ITEM(payloads, i);
    if (!PyBytes_Check(it)) {
      PyErr_SetString(PyExc_TypeError, "payloads must all be bytes");
      return false;
    }
    ptrs[i] = reinterpret_cast<const uint8_t*>(PyBytes_AS_STRING(it));
    lens[i] = PyBytes_GET_SIZE(it);
    t += lens[i];
  }
  *total = t;
  return true;
}

struct BufferSet {
  std::vector<Py_buffer> views;
  ~BufferSet() {
    for (auto& v : views) PyBuffer_Release(&v);
  }
  // None -> nullptr; else writable buffer pointer
  bool get(PyObject* obj, void** out, int flags = PyBUF_WRITABLE) {
    if (obj == Py_None) {
      *out = nullptr;
      return true;
    }
    Py_buffer v;
    if (PyObject_GetBuffer(obj, &v, flags) != 0) return false;
    views.push_back(v);
    *out = v.buf;
    return true;
  }
};

PyObject* py_shred_flat(PyObject*, PyObject* args) {
  PyObject *payloads, *fnum_o, *kinds_o, *flags_o;
  PyObject *vals_t, *pos_t, *len_t, *pres_t;
  if (!PyArg_ParseTuple(args, "O!OOOO!O!O!O!", &PyList_Type, &payloads,
                        &fnum_o, &kinds_o, &flags_o, &PyTuple_Type, &vals_t,
                        &PyTuple_Type, &pos_t, &PyTuple_Type, &len_t,
                        &PyTuple_Type, &pres_t))
    return nullptr;
  std::vector<const uint8_t*> ptrs;
  std::vector<int64_t> lens;
  int64_t total;
  if (!collect_iov(payloads, ptrs, lens, &total)) return nullptr;

  BufferSet bufs;
  void *fnum_p, *kinds_p, *flags_p;
  if (!bufs.get(fnum_o, &fnum_p, PyBUF_SIMPLE) ||
      !bufs.get(kinds_o, &kinds_p, PyBUF_SIMPLE) ||
      !bufs.get(flags_o, &flags_p, PyBUF_SIMPLE))
    return nullptr;
  Py_ssize_t nf = PyTuple_GET_SIZE(vals_t);
  if (PyTuple_GET_SIZE(pos_t) != nf || PyTuple_GET_SIZE(len_t) != nf ||
      PyTuple_GET_SIZE(pres_t) != nf) {
    PyErr_SetString(PyExc_ValueError, "output tuples must align");
    return nullptr;
  }
  std::vector<void*> vals(nf);
  std::vector<int64_t*> pos(nf);
  std::vector<int32_t*> lenp(nf);
  std::vector<uint8_t*> pres(nf);
  for (Py_ssize_t f = 0; f < nf; f++) {
    void *a, *b, *c, *d;
    if (!bufs.get(PyTuple_GET_ITEM(vals_t, f), &a) ||
        !bufs.get(PyTuple_GET_ITEM(pos_t, f), &b) ||
        !bufs.get(PyTuple_GET_ITEM(len_t, f), &c) ||
        !bufs.get(PyTuple_GET_ITEM(pres_t, f), &d))
      return nullptr;
    vals[f] = a;
    pos[f] = static_cast<int64_t*>(b);
    lenp[f] = static_cast<int32_t*>(c);
    pres[f] = static_cast<uint8_t*>(d);
  }
  int64_t rc;
  Py_BEGIN_ALLOW_THREADS
  rc = kpw_proto_shred_iov(ptrs.data(), lens.data(), ptrs.size(),
                           int32_t(nf),
                           static_cast<const uint32_t*>(fnum_p),
                           static_cast<const uint8_t*>(kinds_p),
                           static_cast<const uint8_t*>(flags_p),
                           vals.data(), pos.data(), lenp.data(),
                           pres.data());
  Py_END_ALLOW_THREADS
  return Py_BuildValue("LL", static_cast<long long>(rc),
                       static_cast<long long>(total));
}

// gather_iov(payloads, rec_idx i32 buffer, pos i64 buffer, len i32 buffer)
// -> bytes (the concatenated span payload, allocated here so ByteColumn
// gets a real bytes object with exactly one copy)
PyObject* py_gather_iov(PyObject*, PyObject* args) {
  PyObject *payloads, *idx_o, *pos_o, *len_o;
  if (!PyArg_ParseTuple(args, "O!OOO", &PyList_Type, &payloads, &idx_o,
                        &pos_o, &len_o))
    return nullptr;
  std::vector<const uint8_t*> ptrs;
  std::vector<int64_t> lens;
  int64_t total_payload;
  if (!collect_iov(payloads, ptrs, lens, &total_payload)) return nullptr;
  // idx/pos/len are mandatory here (BufferSet maps None to nullptr for the
  // shred entry points' optional outputs; a None in THIS call would shift
  // views[] and size the span count from the wrong buffer — over-read)
  if (idx_o == Py_None || pos_o == Py_None || len_o == Py_None) {
    PyErr_SetString(PyExc_TypeError,
                    "gather_iov: rec_idx/pos/len buffers must not be None");
    return nullptr;
  }
  BufferSet bufs;
  void *idx_p, *pos_p, *len_p;
  if (!bufs.get(idx_o, &idx_p, PyBUF_SIMPLE) ||
      !bufs.get(pos_o, &pos_p, PyBUF_SIMPLE) ||
      !bufs.get(len_o, &len_p, PyBUF_SIMPLE))
    return nullptr;
  // span count from the len buffer's OWN view (views[2]), not positional
  // assumption on views[0]
  Py_ssize_t n = bufs.views[2].len / sizeof(int32_t);
  const int32_t* ln = static_cast<const int32_t*>(len_p);
  int64_t out_len = 0;
  for (Py_ssize_t i = 0; i < n; i++) out_len += ln[i];
  PyObject* out = PyBytes_FromStringAndSize(nullptr, out_len);
  if (out == nullptr) return nullptr;
  uint8_t* dst = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
  Py_BEGIN_ALLOW_THREADS
  kpw_gather_spans_iov(ptrs.data(), static_cast<const int32_t*>(idx_p),
                       static_cast<const int64_t*>(pos_p), ln, n, dst);
  Py_END_ALLOW_THREADS
  return out;
}

// shred_flat_buf(buf, offs i64 buffer (n_rec+1, ascending; offs[0] may be
// nonzero — a RecordBatch slice window), fnum, kinds, flags, vals_t,
// pos_t, len_t, pres_t) -> (rc, total).  The batch-native ingest entry:
// one contiguous fetch buffer goes to the decoder AS-IS (no per-record
// bytes objects, no join), GIL released around the decode like
// shred_flat — the ctypes route's Python-side marshalling per call was
// measurable GIL pressure against the encode pipeline thread.
PyObject* py_shred_flat_buf(PyObject*, PyObject* args) {
  PyObject *buf_o, *offs_o, *fnum_o, *kinds_o, *flags_o;
  PyObject *vals_t, *pos_t, *len_t, *pres_t;
  if (!PyArg_ParseTuple(args, "OOOOOO!O!O!O!", &buf_o, &offs_o, &fnum_o,
                        &kinds_o, &flags_o, &PyTuple_Type, &vals_t,
                        &PyTuple_Type, &pos_t, &PyTuple_Type, &len_t,
                        &PyTuple_Type, &pres_t))
    return nullptr;
  BufferSet bufs;
  void *buf_p, *offs_p, *fnum_p, *kinds_p, *flags_p;
  if (!bufs.get(buf_o, &buf_p, PyBUF_SIMPLE) ||
      !bufs.get(offs_o, &offs_p, PyBUF_SIMPLE) ||
      !bufs.get(fnum_o, &fnum_p, PyBUF_SIMPLE) ||
      !bufs.get(kinds_o, &kinds_p, PyBUF_SIMPLE) ||
      !bufs.get(flags_o, &flags_p, PyBUF_SIMPLE))
    return nullptr;
  // record count from the offsets buffer's own view (len n_rec + 1)
  Py_ssize_t n_rec = bufs.views[1].len / Py_ssize_t(sizeof(int64_t)) - 1;
  if (n_rec < 0) {
    PyErr_SetString(PyExc_ValueError, "offs must hold >= 1 int64");
    return nullptr;
  }
  const int64_t* offs = static_cast<const int64_t*>(offs_p);
  if (n_rec > 0 && (offs[0] < 0 ||
                    offs[n_rec] > int64_t(bufs.views[0].len))) {
    PyErr_SetString(PyExc_ValueError, "offs out of buffer bounds");
    return nullptr;
  }
  // full ascending walk, not just the end points: one malformed interior
  // offset would otherwise send the decoder out of buffer bounds
  for (Py_ssize_t i = 0; i < n_rec; i++) {
    if (offs[i + 1] < offs[i]) {
      PyErr_SetString(PyExc_ValueError, "offs must be ascending");
      return nullptr;
    }
  }
  Py_ssize_t nf = PyTuple_GET_SIZE(vals_t);
  if (PyTuple_GET_SIZE(pos_t) != nf || PyTuple_GET_SIZE(len_t) != nf ||
      PyTuple_GET_SIZE(pres_t) != nf) {
    PyErr_SetString(PyExc_ValueError, "output tuples must align");
    return nullptr;
  }
  std::vector<void*> vals(nf);
  std::vector<int64_t*> pos(nf);
  std::vector<int32_t*> lenp(nf);
  std::vector<uint8_t*> pres(nf);
  for (Py_ssize_t f = 0; f < nf; f++) {
    void *a, *b, *c, *d;
    if (!bufs.get(PyTuple_GET_ITEM(vals_t, f), &a) ||
        !bufs.get(PyTuple_GET_ITEM(pos_t, f), &b) ||
        !bufs.get(PyTuple_GET_ITEM(len_t, f), &c) ||
        !bufs.get(PyTuple_GET_ITEM(pres_t, f), &d))
      return nullptr;
    vals[f] = a;
    pos[f] = static_cast<int64_t*>(b);
    lenp[f] = static_cast<int32_t*>(c);
    pres[f] = static_cast<uint8_t*>(d);
  }
  int64_t total = n_rec > 0 ? offs[n_rec] - offs[0] : 0;
  int64_t rc;
  Py_BEGIN_ALLOW_THREADS
  rc = kpw_proto_shred(static_cast<const uint8_t*>(buf_p), offs, n_rec,
                       int32_t(nf), static_cast<const uint32_t*>(fnum_p),
                       static_cast<const uint8_t*>(kinds_p),
                       static_cast<const uint8_t*>(flags_p), vals.data(),
                       pos.data(), lenp.data(), pres.data());
  Py_END_ALLOW_THREADS
  return Py_BuildValue("LL", static_cast<long long>(rc),
                       static_cast<long long>(total));
}

// gather_buf(buf, pos i64 buffer, len i32 buffer) -> bytes: span
// concatenation out of ONE contiguous buffer (absolute positions, the
// shred_flat_buf counterpart of gather_iov), GIL released around the copy.
PyObject* py_gather_buf(PyObject*, PyObject* args) {
  PyObject *buf_o, *pos_o, *len_o;
  if (!PyArg_ParseTuple(args, "OOO", &buf_o, &pos_o, &len_o)) return nullptr;
  if (pos_o == Py_None || len_o == Py_None) {
    PyErr_SetString(PyExc_TypeError,
                    "gather_buf: pos/len buffers must not be None");
    return nullptr;
  }
  BufferSet bufs;
  void *buf_p, *pos_p, *len_p;
  if (!bufs.get(buf_o, &buf_p, PyBUF_SIMPLE) ||
      !bufs.get(pos_o, &pos_p, PyBUF_SIMPLE) ||
      !bufs.get(len_o, &len_p, PyBUF_SIMPLE))
    return nullptr;
  Py_ssize_t n = bufs.views[2].len / Py_ssize_t(sizeof(int32_t));
  const int32_t* ln = static_cast<const int32_t*>(len_p);
  int64_t out_len = 0;
  for (Py_ssize_t i = 0; i < n; i++) out_len += ln[i];
  PyObject* out = PyBytes_FromStringAndSize(nullptr, out_len);
  if (out == nullptr) return nullptr;
  uint8_t* dst = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
  Py_BEGIN_ALLOW_THREADS
  kpw_gather_spans(static_cast<const uint8_t*>(buf_p),
                   static_cast<const int64_t*>(pos_p), ln, n, dst);
  Py_END_ALLOW_THREADS
  return out;
}

PyMethodDef methods[] = {
    {"shred_flat", py_shred_flat, METH_VARARGS,
     "Zero-copy flat wire shred over a list of payload bytes."},
    {"gather_iov", py_gather_iov, METH_VARARGS,
     "Concatenate spans (rec_idx, pos, len) from payload bytes -> bytes."},
    {"shred_flat_buf", py_shred_flat_buf, METH_VARARGS,
     "Flat wire shred over one contiguous buffer + record offsets."},
    {"gather_buf", py_gather_buf, METH_VARARGS,
     "Concatenate spans (pos, len) from one contiguous buffer -> bytes."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_kpw_pyshred",
                         "zero-copy wire shred entry points", -1, methods,
                         nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__kpw_pyshred(void) {
  return PyModule_Create(&moduledef);
}
