// CPython extension: zero-copy batch shred entry points.
//
// The ctypes path (build.py NativeLib.proto_shred) needs the poll batch
// joined into ONE contiguous buffer (b"".join + np.fromiter lengths) before
// the decoder can run — ~35 ms per 300k records of pure copy/iteration on
// the streaming hot path (the reference's equivalent cost is zero: its
// parser reads each record's byte[] in place, KafkaProtoParquetWriter.java:
// 270).  This module reads the payload list IN PLACE instead:
// PyBytes_AS_STRING pointers feed kpw_proto_shred_iov (shred.cc) directly,
// and string columns gather straight into a freshly-allocated bytes object
// (one copy total, into the final column payload).
//
// Compiled as _kpw_pyshred.so together with shred.cc (same source, no
// logic duplication); loaded via importlib ExtensionFileLoader (build.py
// pyshred()).  The GIL is released around the decode and gather loops —
// pointers stay valid because the payload list (and its bytes items) are
// owned by the calling frame for the duration.

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdint>
#include <vector>

extern "C" {
int64_t kpw_proto_shred_iov(const uint8_t* const* ptrs, const int64_t* lens,
                            int64_t n_rec, int32_t n_fields,
                            const uint32_t* fnum, const uint8_t* kind,
                            const uint8_t* flags, void* const* out_vals,
                            int64_t* const* out_pos, int32_t* const* out_len,
                            uint8_t* const* out_pres);
void kpw_gather_spans_iov(const uint8_t* const* ptrs, const int32_t* rec_idx,
                          const int64_t* pos, const int32_t* len, int64_t n,
                          uint8_t* out);
int64_t kpw_proto_shred(const uint8_t* buf, const int64_t* offs,
                        int64_t n_rec, int32_t n_fields, const uint32_t* fnum,
                        const uint8_t* kind, const uint8_t* flags,
                        void* const* out_vals, int64_t* const* out_pos,
                        int32_t* const* out_len, uint8_t* const* out_pres);
void kpw_gather_spans(const uint8_t* src, const int64_t* pos,
                      const int32_t* len, int64_t n, uint8_t* out);
// shred_nested.cc (compiled into this .so — same source as the ctypes
// library, so the two decode paths cannot drift)
struct KpwNestedOut;
int64_t kpw_proto_shred_nested(
    const uint8_t* buf, const int64_t* offs, int64_t n_rec, int32_t n_nodes,
    int32_t n_leaves, const uint32_t* fnum, const uint8_t* kind,
    const uint8_t* flags, const int32_t* child_begin,
    const int32_t* child_end, const int32_t* leaf_idx, const int32_t* ftab,
    const int32_t* ftab_off, const int32_t* max_fn, const int32_t* enum_vals,
    const int32_t* enum_off, const int32_t* enum_len,
    const int32_t* null_leaves, const int32_t* null_off,
    const int32_t* null_len, KpwNestedOut** out);
void kpw_nested_free(KpwNestedOut* o);
int32_t kpw_nested_n_leaves(KpwNestedOut* o);
void kpw_nested_sizes(KpwNestedOut* o, int64_t* out);
int kpw_nested_fill_leaf(KpwNestedOut* o, int32_t leaf, const uint8_t* buf,
                         int64_t buf_len, void* values_out,
                         int64_t* offsets_out, uint8_t* payload_out,
                         uint32_t* defs_out, uint32_t* reps_out);
}

namespace {

// payload list -> per-record pointers/lengths, zero copy.  false = a
// non-bytes element (caller falls back to the ctypes path); TypeError set.
bool collect_iov(PyObject* payloads, std::vector<const uint8_t*>& ptrs,
                 std::vector<int64_t>& lens, int64_t* total) {
  Py_ssize_t n = PyList_GET_SIZE(payloads);
  ptrs.resize(n);
  lens.resize(n);
  int64_t t = 0;
  for (Py_ssize_t i = 0; i < n; i++) {
    PyObject* it = PyList_GET_ITEM(payloads, i);
    if (!PyBytes_Check(it)) {
      PyErr_SetString(PyExc_TypeError, "payloads must all be bytes");
      return false;
    }
    ptrs[i] = reinterpret_cast<const uint8_t*>(PyBytes_AS_STRING(it));
    lens[i] = PyBytes_GET_SIZE(it);
    t += lens[i];
  }
  *total = t;
  return true;
}

struct BufferSet {
  std::vector<Py_buffer> views;
  ~BufferSet() {
    for (auto& v : views) PyBuffer_Release(&v);
  }
  // None -> nullptr; else writable buffer pointer
  bool get(PyObject* obj, void** out, int flags = PyBUF_WRITABLE) {
    if (obj == Py_None) {
      *out = nullptr;
      return true;
    }
    Py_buffer v;
    if (PyObject_GetBuffer(obj, &v, flags) != 0) return false;
    views.push_back(v);
    *out = v.buf;
    return true;
  }
  // like get, but also reports the view's byte length (0 for None) — the
  // nested_fill geometry checks need pointer AND length per output
  bool get_sized(PyObject* obj, void** out, Py_ssize_t* len_out,
                 int flags = PyBUF_WRITABLE) {
    *len_out = 0;
    if (!get(obj, out, flags)) return false;
    if (*out != nullptr) *len_out = views.back().len;
    return true;
  }
};

PyObject* py_shred_flat(PyObject*, PyObject* args) {
  PyObject *payloads, *fnum_o, *kinds_o, *flags_o;
  PyObject *vals_t, *pos_t, *len_t, *pres_t;
  if (!PyArg_ParseTuple(args, "O!OOOO!O!O!O!", &PyList_Type, &payloads,
                        &fnum_o, &kinds_o, &flags_o, &PyTuple_Type, &vals_t,
                        &PyTuple_Type, &pos_t, &PyTuple_Type, &len_t,
                        &PyTuple_Type, &pres_t))
    return nullptr;
  std::vector<const uint8_t*> ptrs;
  std::vector<int64_t> lens;
  int64_t total;
  if (!collect_iov(payloads, ptrs, lens, &total)) return nullptr;

  BufferSet bufs;
  void *fnum_p, *kinds_p, *flags_p;
  if (!bufs.get(fnum_o, &fnum_p, PyBUF_SIMPLE) ||
      !bufs.get(kinds_o, &kinds_p, PyBUF_SIMPLE) ||
      !bufs.get(flags_o, &flags_p, PyBUF_SIMPLE))
    return nullptr;
  Py_ssize_t nf = PyTuple_GET_SIZE(vals_t);
  if (PyTuple_GET_SIZE(pos_t) != nf || PyTuple_GET_SIZE(len_t) != nf ||
      PyTuple_GET_SIZE(pres_t) != nf) {
    PyErr_SetString(PyExc_ValueError, "output tuples must align");
    return nullptr;
  }
  std::vector<void*> vals(nf);
  std::vector<int64_t*> pos(nf);
  std::vector<int32_t*> lenp(nf);
  std::vector<uint8_t*> pres(nf);
  for (Py_ssize_t f = 0; f < nf; f++) {
    void *a, *b, *c, *d;
    if (!bufs.get(PyTuple_GET_ITEM(vals_t, f), &a) ||
        !bufs.get(PyTuple_GET_ITEM(pos_t, f), &b) ||
        !bufs.get(PyTuple_GET_ITEM(len_t, f), &c) ||
        !bufs.get(PyTuple_GET_ITEM(pres_t, f), &d))
      return nullptr;
    vals[f] = a;
    pos[f] = static_cast<int64_t*>(b);
    lenp[f] = static_cast<int32_t*>(c);
    pres[f] = static_cast<uint8_t*>(d);
  }
  int64_t rc;
  Py_BEGIN_ALLOW_THREADS
  rc = kpw_proto_shred_iov(ptrs.data(), lens.data(), ptrs.size(),
                           int32_t(nf),
                           static_cast<const uint32_t*>(fnum_p),
                           static_cast<const uint8_t*>(kinds_p),
                           static_cast<const uint8_t*>(flags_p),
                           vals.data(), pos.data(), lenp.data(),
                           pres.data());
  Py_END_ALLOW_THREADS
  return Py_BuildValue("LL", static_cast<long long>(rc),
                       static_cast<long long>(total));
}

// gather_iov(payloads, rec_idx i32 buffer, pos i64 buffer, len i32 buffer)
// -> bytes (the concatenated span payload, allocated here so ByteColumn
// gets a real bytes object with exactly one copy)
PyObject* py_gather_iov(PyObject*, PyObject* args) {
  PyObject *payloads, *idx_o, *pos_o, *len_o;
  if (!PyArg_ParseTuple(args, "O!OOO", &PyList_Type, &payloads, &idx_o,
                        &pos_o, &len_o))
    return nullptr;
  std::vector<const uint8_t*> ptrs;
  std::vector<int64_t> lens;
  int64_t total_payload;
  if (!collect_iov(payloads, ptrs, lens, &total_payload)) return nullptr;
  // idx/pos/len are mandatory here (BufferSet maps None to nullptr for the
  // shred entry points' optional outputs; a None in THIS call would shift
  // views[] and size the span count from the wrong buffer — over-read)
  if (idx_o == Py_None || pos_o == Py_None || len_o == Py_None) {
    PyErr_SetString(PyExc_TypeError,
                    "gather_iov: rec_idx/pos/len buffers must not be None");
    return nullptr;
  }
  BufferSet bufs;
  void *idx_p, *pos_p, *len_p;
  if (!bufs.get(idx_o, &idx_p, PyBUF_SIMPLE) ||
      !bufs.get(pos_o, &pos_p, PyBUF_SIMPLE) ||
      !bufs.get(len_o, &len_p, PyBUF_SIMPLE))
    return nullptr;
  // span count from the len buffer's OWN view (views[2]), not positional
  // assumption on views[0]
  Py_ssize_t n = bufs.views[2].len / sizeof(int32_t);
  const int32_t* ln = static_cast<const int32_t*>(len_p);
  int64_t out_len = 0;
  for (Py_ssize_t i = 0; i < n; i++) out_len += ln[i];
  PyObject* out = PyBytes_FromStringAndSize(nullptr, out_len);
  if (out == nullptr) return nullptr;
  uint8_t* dst = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
  Py_BEGIN_ALLOW_THREADS
  kpw_gather_spans_iov(ptrs.data(), static_cast<const int32_t*>(idx_p),
                       static_cast<const int64_t*>(pos_p), ln, n, dst);
  Py_END_ALLOW_THREADS
  return out;
}

// shred_flat_buf(buf, offs i64 buffer (n_rec+1, ascending; offs[0] may be
// nonzero — a RecordBatch slice window), fnum, kinds, flags, vals_t,
// pos_t, len_t, pres_t) -> (rc, total).  The batch-native ingest entry:
// one contiguous fetch buffer goes to the decoder AS-IS (no per-record
// bytes objects, no join), GIL released around the decode like
// shred_flat — the ctypes route's Python-side marshalling per call was
// measurable GIL pressure against the encode pipeline thread.
PyObject* py_shred_flat_buf(PyObject*, PyObject* args) {
  PyObject *buf_o, *offs_o, *fnum_o, *kinds_o, *flags_o;
  PyObject *vals_t, *pos_t, *len_t, *pres_t;
  if (!PyArg_ParseTuple(args, "OOOOOO!O!O!O!", &buf_o, &offs_o, &fnum_o,
                        &kinds_o, &flags_o, &PyTuple_Type, &vals_t,
                        &PyTuple_Type, &pos_t, &PyTuple_Type, &len_t,
                        &PyTuple_Type, &pres_t))
    return nullptr;
  BufferSet bufs;
  void *buf_p, *offs_p, *fnum_p, *kinds_p, *flags_p;
  if (!bufs.get(buf_o, &buf_p, PyBUF_SIMPLE) ||
      !bufs.get(offs_o, &offs_p, PyBUF_SIMPLE) ||
      !bufs.get(fnum_o, &fnum_p, PyBUF_SIMPLE) ||
      !bufs.get(kinds_o, &kinds_p, PyBUF_SIMPLE) ||
      !bufs.get(flags_o, &flags_p, PyBUF_SIMPLE))
    return nullptr;
  // record count from the offsets buffer's own view (len n_rec + 1)
  Py_ssize_t n_rec = bufs.views[1].len / Py_ssize_t(sizeof(int64_t)) - 1;
  if (n_rec < 0) {
    PyErr_SetString(PyExc_ValueError, "offs must hold >= 1 int64");
    return nullptr;
  }
  const int64_t* offs = static_cast<const int64_t*>(offs_p);
  if (n_rec > 0 && (offs[0] < 0 ||
                    offs[n_rec] > int64_t(bufs.views[0].len))) {
    PyErr_SetString(PyExc_ValueError, "offs out of buffer bounds");
    return nullptr;
  }
  // full ascending walk, not just the end points: one malformed interior
  // offset would otherwise send the decoder out of buffer bounds
  for (Py_ssize_t i = 0; i < n_rec; i++) {
    if (offs[i + 1] < offs[i]) {
      PyErr_SetString(PyExc_ValueError, "offs must be ascending");
      return nullptr;
    }
  }
  Py_ssize_t nf = PyTuple_GET_SIZE(vals_t);
  if (PyTuple_GET_SIZE(pos_t) != nf || PyTuple_GET_SIZE(len_t) != nf ||
      PyTuple_GET_SIZE(pres_t) != nf) {
    PyErr_SetString(PyExc_ValueError, "output tuples must align");
    return nullptr;
  }
  std::vector<void*> vals(nf);
  std::vector<int64_t*> pos(nf);
  std::vector<int32_t*> lenp(nf);
  std::vector<uint8_t*> pres(nf);
  for (Py_ssize_t f = 0; f < nf; f++) {
    void *a, *b, *c, *d;
    if (!bufs.get(PyTuple_GET_ITEM(vals_t, f), &a) ||
        !bufs.get(PyTuple_GET_ITEM(pos_t, f), &b) ||
        !bufs.get(PyTuple_GET_ITEM(len_t, f), &c) ||
        !bufs.get(PyTuple_GET_ITEM(pres_t, f), &d))
      return nullptr;
    vals[f] = a;
    pos[f] = static_cast<int64_t*>(b);
    lenp[f] = static_cast<int32_t*>(c);
    pres[f] = static_cast<uint8_t*>(d);
  }
  int64_t total = n_rec > 0 ? offs[n_rec] - offs[0] : 0;
  int64_t rc;
  Py_BEGIN_ALLOW_THREADS
  rc = kpw_proto_shred(static_cast<const uint8_t*>(buf_p), offs, n_rec,
                       int32_t(nf), static_cast<const uint32_t*>(fnum_p),
                       static_cast<const uint8_t*>(kinds_p),
                       static_cast<const uint8_t*>(flags_p), vals.data(),
                       pos.data(), lenp.data(), pres.data());
  Py_END_ALLOW_THREADS
  return Py_BuildValue("LL", static_cast<long long>(rc),
                       static_cast<long long>(total));
}

// gather_buf(buf, pos i64 buffer, len i32 buffer) -> bytes: span
// concatenation out of ONE contiguous buffer (absolute positions, the
// shred_flat_buf counterpart of gather_iov), GIL released around the copy.
PyObject* py_gather_buf(PyObject*, PyObject* args) {
  PyObject *buf_o, *pos_o, *len_o;
  if (!PyArg_ParseTuple(args, "OOO", &buf_o, &pos_o, &len_o)) return nullptr;
  if (pos_o == Py_None || len_o == Py_None) {
    PyErr_SetString(PyExc_TypeError,
                    "gather_buf: pos/len buffers must not be None");
    return nullptr;
  }
  BufferSet bufs;
  void *buf_p, *pos_p, *len_p;
  if (!bufs.get(buf_o, &buf_p, PyBUF_SIMPLE) ||
      !bufs.get(pos_o, &pos_p, PyBUF_SIMPLE) ||
      !bufs.get(len_o, &len_p, PyBUF_SIMPLE))
    return nullptr;
  Py_ssize_t n = bufs.views[2].len / Py_ssize_t(sizeof(int32_t));
  const int32_t* ln = static_cast<const int32_t*>(len_p);
  int64_t out_len = 0;
  for (Py_ssize_t i = 0; i < n; i++) out_len += ln[i];
  PyObject* out = PyBytes_FromStringAndSize(nullptr, out_len);
  if (out == nullptr) return nullptr;
  uint8_t* dst = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(out));
  Py_BEGIN_ALLOW_THREADS
  kpw_gather_spans(static_cast<const uint8_t*>(buf_p),
                   static_cast<const int64_t*>(pos_p), ln, n, dst);
  Py_END_ALLOW_THREADS
  return out;
}

// -- fused nested shred ------------------------------------------------------
//
// The ctypes nested route (build.py NestedShredResult) pays 5 ctypes
// round trips per leaf with the GIL held, copies every output twice
// (C arena -> ctypes view -> numpy .copy()), widens levels u8->i32 in
// numpy, and gathers string payloads through create_string_buffer (a
// third copy).  These two entries replace all of it with TWO C calls
// per batch: decode (GIL released) returning an opaque handle plus the
// per-leaf geometry table, then one fill call (GIL released) that
// materializes every leaf straight into its FINAL representation —
// fixed values into numpy arrays, span payloads into freshly-allocated
// bytes objects with their int64 ByteColumn offset tables, def/rep
// levels widened to the uint32 the nogil page assembler's RLE ops
// consume.  One copy per output, zero per-leaf Python work.
//
// Contract: the PLAN buffers are trusted (built by proto_bridge
// _NestedPlan from the schema — same trust the ctypes route extends);
// the WIRE buffer and offset table are hostile and fully validated
// (ascending walk, bounds) before the decoder runs, and every span is
// re-checked against the buffer handed to nested_fill.

void nested_capsule_free(PyObject* cap) {
  auto* o = static_cast<KpwNestedOut*>(
      PyCapsule_GetPointer(cap, "kpw_nested_out"));
  if (o != nullptr) kpw_nested_free(o);
}

// shred_nested_buf(buf, offs, n_nodes, n_leaves, fnum, kind, flags,
//                  tabs: tuple of 12 int32 buffers)
//   -> (rc, capsule | None, sizes bytes | None)
// rc = -1 on success; else the first record index needing the Python
// fallback.  sizes = int64[n_leaves, 4]:
//   [value_bytes, n_spans, span_payload_bytes, n_levels] per leaf.
PyObject* py_shred_nested_buf(PyObject*, PyObject* args) {
  PyObject *buf_o, *offs_o, *fnum_o, *kind_o, *flags_o, *tabs_t;
  int n_nodes, n_leaves;
  if (!PyArg_ParseTuple(args, "OOiiOOOO!", &buf_o, &offs_o, &n_nodes,
                        &n_leaves, &fnum_o, &kind_o, &flags_o, &PyTuple_Type,
                        &tabs_t))
    return nullptr;
  if (n_nodes <= 0 || n_leaves <= 0) {
    PyErr_SetString(PyExc_ValueError, "n_nodes/n_leaves must be positive");
    return nullptr;
  }
  if (PyTuple_GET_SIZE(tabs_t) != 12) {
    PyErr_SetString(PyExc_ValueError, "plan tabs tuple must have 12 buffers");
    return nullptr;
  }
  BufferSet bufs;
  void *buf_p, *offs_p, *fnum_p, *kind_p, *flags_p;
  if (!bufs.get(buf_o, &buf_p, PyBUF_SIMPLE) ||
      !bufs.get(offs_o, &offs_p, PyBUF_SIMPLE) ||
      !bufs.get(fnum_o, &fnum_p, PyBUF_SIMPLE) ||
      !bufs.get(kind_o, &kind_p, PyBUF_SIMPLE) ||
      !bufs.get(flags_o, &flags_p, PyBUF_SIMPLE))
    return nullptr;
  // hostile-input validation, exactly shred_flat_buf's discipline
  Py_ssize_t n_rec = bufs.views[1].len / Py_ssize_t(sizeof(int64_t)) - 1;
  if (n_rec < 0) {
    PyErr_SetString(PyExc_ValueError, "offs must hold >= 1 int64");
    return nullptr;
  }
  const int64_t* offs = static_cast<const int64_t*>(offs_p);
  if (n_rec > 0 && (offs[0] < 0 ||
                    offs[n_rec] > int64_t(bufs.views[0].len))) {
    PyErr_SetString(PyExc_ValueError, "offs out of buffer bounds");
    return nullptr;
  }
  for (Py_ssize_t i = 0; i < n_rec; i++) {
    if (offs[i + 1] < offs[i]) {
      PyErr_SetString(PyExc_ValueError, "offs must be ascending");
      return nullptr;
    }
  }
  // plan-shape sanity: node-indexed tables must cover n_nodes entries
  // (content is trusted; a SHORT buffer would still be an OOB read)
  if (bufs.views[2].len < Py_ssize_t(n_nodes) * 4 ||
      bufs.views[3].len < n_nodes || bufs.views[4].len < n_nodes) {
    PyErr_SetString(PyExc_ValueError, "plan fnum/kind/flags too short");
    return nullptr;
  }
  const int32_t* tabs[12];
  // per-node int32 tables (every one indexed by node id except ftab /
  // enum_vals / null_leaves, whose minimum is 1 element)
  static const bool per_node[12] = {true, true, true, false, true, true,
                                    false, true, true, false, true, true};
  for (int t = 0; t < 12; t++) {
    void* p;
    if (!bufs.get(PyTuple_GET_ITEM(tabs_t, t), &p, PyBUF_SIMPLE))
      return nullptr;
    const Py_buffer& v = bufs.views[bufs.views.size() - 1];
    const Py_ssize_t need = (per_node[t] ? Py_ssize_t(n_nodes) : 1) * 4;
    if (v.len < need) {
      PyErr_SetString(PyExc_ValueError, "plan table too short");
      return nullptr;
    }
    tabs[t] = static_cast<const int32_t*>(p);
  }
  KpwNestedOut* out = nullptr;
  int64_t rc;
  Py_BEGIN_ALLOW_THREADS
  rc = kpw_proto_shred_nested(
      static_cast<const uint8_t*>(buf_p), offs, n_rec, n_nodes, n_leaves,
      static_cast<const uint32_t*>(fnum_p),
      static_cast<const uint8_t*>(kind_p),
      static_cast<const uint8_t*>(flags_p), tabs[0], tabs[1], tabs[2],
      tabs[3], tabs[4], tabs[5], tabs[6], tabs[7], tabs[8], tabs[9],
      tabs[10], tabs[11], &out);
  Py_END_ALLOW_THREADS
  if (rc >= 0)
    return Py_BuildValue("LOO", static_cast<long long>(rc), Py_None,
                         Py_None);
  PyObject* sizes = PyBytes_FromStringAndSize(
      nullptr, Py_ssize_t(n_leaves) * 4 * sizeof(int64_t));
  if (sizes == nullptr) {
    kpw_nested_free(out);
    return nullptr;
  }
  kpw_nested_sizes(out,
                   reinterpret_cast<int64_t*>(PyBytes_AS_STRING(sizes)));
  PyObject* cap = PyCapsule_New(out, "kpw_nested_out", nested_capsule_free);
  if (cap == nullptr) {
    Py_DECREF(sizes);
    kpw_nested_free(out);
    return nullptr;
  }
  PyObject* res = Py_BuildValue("LNN", -1LL, cap, sizes);
  return res;
}

// nested_fill(capsule, buf, values_t, offsets_t, defs_t, reps_t)
//   -> tuple of span payload bytes (None for non-span leaves)
// Per leaf: values_t = writable fixed-width array or None (must be None
// for span leaves — their payload is allocated HERE as bytes);
// offsets_t = writable int64 (n_spans + 1) array for span leaves, None
// otherwise; defs_t / reps_t = writable uint32 arrays or None.  All
// output geometry is validated against the decode's size table before
// the GIL is released.
PyObject* py_nested_fill(PyObject*, PyObject* args) {
  PyObject *cap, *buf_o, *vals_t, *offsets_t, *defs_t, *reps_t;
  if (!PyArg_ParseTuple(args, "OOO!O!O!O!", &cap, &buf_o, &PyTuple_Type,
                        &vals_t, &PyTuple_Type, &offsets_t, &PyTuple_Type,
                        &defs_t, &PyTuple_Type, &reps_t))
    return nullptr;
  auto* o = static_cast<KpwNestedOut*>(
      PyCapsule_GetPointer(cap, "kpw_nested_out"));
  if (o == nullptr) return nullptr;  // wrong/expired capsule: TypeError set
  const Py_ssize_t nl = PyTuple_GET_SIZE(vals_t);
  if (PyTuple_GET_SIZE(offsets_t) != nl || PyTuple_GET_SIZE(defs_t) != nl ||
      PyTuple_GET_SIZE(reps_t) != nl) {
    PyErr_SetString(PyExc_ValueError, "output tuples must align");
    return nullptr;
  }
  if (Py_ssize_t(kpw_nested_n_leaves(o)) != nl) {
    PyErr_SetString(PyExc_ValueError,
                    "output tuples do not match the handle's leaf count");
    return nullptr;
  }
  std::vector<int64_t> sizes(size_t(nl) * 4);
  kpw_nested_sizes(o, sizes.data());

  BufferSet bufs;
  void* buf_p;
  if (!bufs.get(buf_o, &buf_p, PyBUF_SIMPLE)) return nullptr;
  const int64_t buf_len = int64_t(bufs.views[0].len);

  std::vector<void*> vals(nl, nullptr);
  std::vector<int64_t*> offsets(nl, nullptr);
  std::vector<uint8_t*> payloads(nl, nullptr);
  std::vector<uint32_t*> defs(nl, nullptr);
  std::vector<uint32_t*> reps(nl, nullptr);
  PyObject* payload_objs = PyTuple_New(nl);
  if (payload_objs == nullptr) return nullptr;
  bool bad = false;
  const char* bad_msg = nullptr;
  for (Py_ssize_t f = 0; f < nl && !bad; f++) {
    const int64_t value_bytes = sizes[4 * f + 0];
    const int64_t n_spans = sizes[4 * f + 1];
    const int64_t payload_bytes = sizes[4 * f + 2];
    const int64_t n_levels = sizes[4 * f + 3];
    PyObject* off_o = PyTuple_GET_ITEM(offsets_t, f);
    const bool is_span = off_o != Py_None;
    void *vp, *op, *dp, *rp;
    Py_ssize_t vlen, olen, dlen, rlen;
    if (!bufs.get_sized(PyTuple_GET_ITEM(vals_t, f), &vp, &vlen) ||
        !bufs.get_sized(off_o, &op, &olen) ||
        !bufs.get_sized(PyTuple_GET_ITEM(defs_t, f), &dp, &dlen) ||
        !bufs.get_sized(PyTuple_GET_ITEM(reps_t, f), &rp, &rlen)) {
      Py_DECREF(payload_objs);
      return nullptr;
    }
    // geometry checks against the decode's own size table: a wrong
    // allocation must raise here, never write out of bounds nogil
    if (is_span) {
      if (vp != nullptr) {
        bad = true;
        bad_msg = "span leaves take no values buffer (payload is "
                  "allocated by nested_fill)";
        break;
      }
      if (olen != (n_spans + 1) * Py_ssize_t(sizeof(int64_t))) {
        bad = true;
        bad_msg = "offsets buffer length mismatch";
        break;
      }
      PyObject* pb = PyBytes_FromStringAndSize(nullptr,
                                               Py_ssize_t(payload_bytes));
      if (pb == nullptr) {
        Py_DECREF(payload_objs);
        return nullptr;
      }
      payloads[f] = reinterpret_cast<uint8_t*>(PyBytes_AS_STRING(pb));
      PyTuple_SET_ITEM(payload_objs, f, pb);
    } else {
      if (op != nullptr) {
        bad = true;
        bad_msg = "offsets buffer on a non-span leaf";
        break;
      }
      if (vp == nullptr ? value_bytes != 0
                        : vlen != Py_ssize_t(value_bytes)) {
        bad = true;
        bad_msg = "values buffer length mismatch";
        break;
      }
      Py_INCREF(Py_None);
      PyTuple_SET_ITEM(payload_objs, f, Py_None);
    }
    const Py_ssize_t lvl_len = n_levels * Py_ssize_t(sizeof(uint32_t));
    if ((dp != nullptr && dlen != lvl_len) ||
        (rp != nullptr && rlen != lvl_len)) {
      bad = true;
      bad_msg = "level buffer length mismatch";
      break;
    }
    vals[f] = vp;
    offsets[f] = static_cast<int64_t*>(op);
    defs[f] = static_cast<uint32_t*>(dp);
    reps[f] = static_cast<uint32_t*>(rp);
  }
  if (bad) {
    Py_DECREF(payload_objs);
    PyErr_SetString(PyExc_ValueError, bad_msg);
    return nullptr;
  }
  int rc = 0;
  Py_BEGIN_ALLOW_THREADS
  for (Py_ssize_t f = 0; f < nl && rc == 0; f++)
    rc = kpw_nested_fill_leaf(o, int32_t(f),
                              static_cast<const uint8_t*>(buf_p), buf_len,
                              vals[f], offsets[f], payloads[f], defs[f],
                              reps[f]);
  Py_END_ALLOW_THREADS
  if (rc != 0) {
    Py_DECREF(payload_objs);
    PyErr_SetString(PyExc_ValueError,
                    "span out of payload-buffer bounds (buffer does not "
                    "match the decoded batch)");
    return nullptr;
  }
  return payload_objs;
}

PyMethodDef methods[] = {
    {"shred_flat", py_shred_flat, METH_VARARGS,
     "Zero-copy flat wire shred over a list of payload bytes."},
    {"gather_iov", py_gather_iov, METH_VARARGS,
     "Concatenate spans (rec_idx, pos, len) from payload bytes -> bytes."},
    {"shred_flat_buf", py_shred_flat_buf, METH_VARARGS,
     "Flat wire shred over one contiguous buffer + record offsets."},
    {"gather_buf", py_gather_buf, METH_VARARGS,
     "Concatenate spans (pos, len) from one contiguous buffer -> bytes."},
    {"shred_nested_buf", py_shred_nested_buf, METH_VARARGS,
     "Nested wire shred over one contiguous buffer + record offsets, "
     "GIL released; returns (rc, handle, per-leaf size table)."},
    {"nested_fill", py_nested_fill, METH_VARARGS,
     "Materialize every leaf of a shred_nested_buf handle into final "
     "arrays/ByteColumn payloads in one GIL-released pass."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_kpw_pyshred",
                         "zero-copy wire shred entry points", -1, methods,
                         nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__kpw_pyshred(void) {
  return PyModule_Create(&moduledef);
}
