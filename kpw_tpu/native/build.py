"""Lazy g++ build + ctypes bindings for the native codec library.

Builds ``_kpw_native.so`` from ``src/codecs.cc`` on first use (cached next to
the source; rebuilt when the source mtime changes).  Falls back to a build
without zstd if libzstd is unlinkable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_SRC_DIR, "src", "codecs.cc")
_SO = os.path.join(_SRC_DIR, "_kpw_native.so")


def _build() -> str:
    if os.path.exists(_SO) and os.path.getmtime(_SO) >= os.path.getmtime(_SRC):
        return _SO
    base = ["g++", "-O3", "-fPIC", "-shared", "-std=c++17", "-o"]
    # build into a temp file then atomic-rename (parallel test runners)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_SRC_DIR)
    os.close(fd)
    try:
        try:
            subprocess.run(base + [tmp, _SRC, "-lzstd"], check=True,
                           capture_output=True)
        except subprocess.CalledProcessError:
            subprocess.run(base + [tmp, _SRC, "-DKPW_NO_ZSTD"], check=True,
                           capture_output=True)
        os.replace(tmp, _SO)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return _SO


class NativeLib:
    """bytes-in/bytes-out wrappers over the C ABI."""

    def __init__(self, cdll: ctypes.CDLL) -> None:
        self._c = cdll
        c_sz = ctypes.c_size_t
        c_p = ctypes.c_char_p
        cdll.kpw_snappy_max_compressed_length.restype = c_sz
        cdll.kpw_snappy_max_compressed_length.argtypes = [c_sz]
        cdll.kpw_snappy_compress.restype = ctypes.c_int
        cdll.kpw_snappy_compress.argtypes = [c_p, c_sz, c_p, ctypes.POINTER(c_sz)]
        cdll.kpw_snappy_uncompressed_length.restype = ctypes.c_int
        cdll.kpw_snappy_uncompressed_length.argtypes = [c_p, c_sz, ctypes.POINTER(c_sz)]
        cdll.kpw_snappy_uncompress.restype = ctypes.c_int
        cdll.kpw_snappy_uncompress.argtypes = [c_p, c_sz, c_p, c_sz, ctypes.POINTER(c_sz)]
        self.has_zstd = hasattr(cdll, "kpw_zstd_compress")
        if self.has_zstd:
            cdll.kpw_zstd_max_compressed_length.restype = c_sz
            cdll.kpw_zstd_max_compressed_length.argtypes = [c_sz]
            cdll.kpw_zstd_compress.restype = ctypes.c_int
            cdll.kpw_zstd_compress.argtypes = [c_p, c_sz, c_p, c_sz,
                                               ctypes.POINTER(c_sz), ctypes.c_int]
            cdll.kpw_zstd_uncompressed_length.restype = ctypes.c_int
            cdll.kpw_zstd_uncompressed_length.argtypes = [c_p, c_sz, ctypes.POINTER(c_sz)]
            cdll.kpw_zstd_uncompress.restype = ctypes.c_int
            cdll.kpw_zstd_uncompress.argtypes = [c_p, c_sz, c_p, c_sz, ctypes.POINTER(c_sz)]
        cdll.kpw_crc32c.restype = ctypes.c_uint32
        cdll.kpw_crc32c.argtypes = [c_p, c_sz, ctypes.c_uint32]
        cdll.kpw_byte_array_plain.restype = None
        cdll.kpw_byte_array_plain.argtypes = [
            c_p, ctypes.POINTER(ctypes.c_int64), c_sz, c_p]
        cdll.kpw_byte_array_gather.restype = None
        cdll.kpw_byte_array_gather.argtypes = [
            c_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), c_sz, c_p]

    # -- snappy ------------------------------------------------------------
    def snappy_compress(self, data: bytes) -> bytes:
        cap = self._c.kpw_snappy_max_compressed_length(len(data))
        out = ctypes.create_string_buffer(cap)
        out_len = ctypes.c_size_t(0)
        rc = self._c.kpw_snappy_compress(data, len(data), out, ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError(f"kpw_snappy_compress rc={rc}")
        return out.raw[: out_len.value]

    def snappy_decompress(self, data: bytes) -> bytes:
        size = ctypes.c_size_t(0)
        rc = self._c.kpw_snappy_uncompressed_length(data, len(data), ctypes.byref(size))
        if rc != 0:
            raise RuntimeError("invalid snappy stream")
        out = ctypes.create_string_buffer(max(size.value, 1))
        out_len = ctypes.c_size_t(0)
        rc = self._c.kpw_snappy_uncompress(data, len(data), out, size.value,
                                           ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError(f"kpw_snappy_uncompress rc={rc}")
        return out.raw[: out_len.value]

    # -- zstd --------------------------------------------------------------
    def zstd_compress(self, data: bytes, level: int = 3) -> bytes | None:
        if not self.has_zstd:
            return None
        cap = self._c.kpw_zstd_max_compressed_length(len(data))
        out = ctypes.create_string_buffer(cap)
        out_len = ctypes.c_size_t(0)
        rc = self._c.kpw_zstd_compress(data, len(data), out, cap,
                                       ctypes.byref(out_len), level)
        if rc != 0:
            raise RuntimeError("zstd compress failed")
        return out.raw[: out_len.value]

    def zstd_decompress(self, data: bytes) -> bytes | None:
        if not self.has_zstd:
            return None
        size = ctypes.c_size_t(0)
        rc = self._c.kpw_zstd_uncompressed_length(data, len(data), ctypes.byref(size))
        if rc != 0:
            raise RuntimeError("zstd: unknown content size")
        out = ctypes.create_string_buffer(max(size.value, 1))
        out_len = ctypes.c_size_t(0)
        rc = self._c.kpw_zstd_uncompress(data, len(data), out, size.value,
                                         ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError("zstd decompress failed")
        return out.raw[: out_len.value]

    # -- misc --------------------------------------------------------------
    def crc32c(self, data: bytes, crc: int = 0) -> int:
        return self._c.kpw_crc32c(data, len(data), crc)

    def byte_array_plain(self, data: bytes, offsets) -> bytes:
        import numpy as np

        offs = np.ascontiguousarray(offsets, np.int64)
        count = len(offs) - 1
        total = int(offs[-1] - offs[0]) + 4 * count
        out = ctypes.create_string_buffer(max(total, 1))
        self._c.kpw_byte_array_plain(
            data, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), count, out)
        return out.raw[:total]

    def byte_array_gather(self, dict_data: bytes, dict_offsets, indices) -> bytes:
        import numpy as np

        offs = np.ascontiguousarray(dict_offsets, np.int64)
        idx = np.ascontiguousarray(indices, np.int32)
        lens = offs[1:] - offs[:-1]
        total = int(lens[idx].sum()) + 4 * len(idx)
        out = ctypes.create_string_buffer(max(total, 1))
        self._c.kpw_byte_array_gather(
            dict_data, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(idx), out)
        return out.raw[:total]


def load() -> NativeLib:
    return NativeLib(ctypes.CDLL(_build()))
