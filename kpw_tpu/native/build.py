"""Lazy g++ build + ctypes bindings for the native codec library.

Builds ``_kpw_native.so`` from ``src/codecs.cc`` on first use (cached next to
the source; rebuilt when the source mtime changes).  Falls back to a build
without zstd if libzstd is unlinkable.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_SRCS = [os.path.join(_SRC_DIR, "src", "codecs.cc"),
         os.path.join(_SRC_DIR, "src", "encode.cc"),
         os.path.join(_SRC_DIR, "src", "shred.cc"),
         os.path.join(_SRC_DIR, "src", "shred_nested.cc")]


def _sanitize_mode() -> str:
    """Sanitizer build modes, selected by KPW_NATIVE_SANITIZE:

    * ``1`` / ``asan`` — ASan+UBSan: every native entry point — the wire
      shredders, codecs, thrift-adjacent buffer walks — compiles with
      -fsanitize=address,undefined so the fuzz harness (tools/fuzz.py)
      and the shred/verify test subsets run with out-of-bounds reads and
      UB trapping instead of silently reading garbage (the PR-6
      ``shred_flat_buf`` malformed-offset OOB class).
    * ``tsan`` — ThreadSanitizer: the GIL-released entries
      (``shred_flat_buf``/``gather_buf``/``assemble_pages``) genuinely
      run concurrently from multiple Python threads (PR 6/10), so a data
      race in the native code is a real race no Python-level tool can
      see; ``tools/sanitize.sh --tsan`` drives them concurrently via
      ``tools/tsan_stress.py``.

    Each mode caches under a distinct artifact name (``_san.so`` /
    ``_tsan.so``) so the normal build is never polluted; the host python
    is uninstrumented, so the runner (tools/sanitize.sh) must LD_PRELOAD
    the matching sanitizer runtime."""
    v = os.environ.get("KPW_NATIVE_SANITIZE", "")
    if v in ("1", "asan"):
        return "asan"
    if v == "tsan":
        return "tsan"
    return ""


_ASAN_FLAGS = ["-fsanitize=address,undefined", "-fno-sanitize-recover=all",
               "-fno-omit-frame-pointer", "-g", "-O1"]
_TSAN_FLAGS = ["-fsanitize=thread", "-fno-omit-frame-pointer", "-g", "-O1"]


def _san_flags() -> list:
    return list(_TSAN_FLAGS if _sanitize_mode() == "tsan" else _ASAN_FLAGS)


def _so_path(base: str) -> str:
    mode = _sanitize_mode()
    if mode == "asan":
        return base.replace(".so", "_san.so")
    if mode == "tsan":
        return base.replace(".so", "_tsan.so")
    return base


_SO = os.path.join(_SRC_DIR, "_kpw_native.so")


def _host_tag() -> str:
    """Identifies the CPU the cached .so was compiled for: -march=native
    output is ISA-specific, so a cache file that traveled to a different
    machine (wheel, NFS venv, Docker layer) must trigger a rebuild rather
    than a SIGILL at first call."""
    import platform

    tag = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("model name", "flags")):
                    tag += "|" + line.strip()
                    break
    except OSError:
        pass
    import hashlib

    return hashlib.sha256(tag.encode()).hexdigest()[:16]


_TAG = _SO + ".hosttag"


def _build() -> str:
    so = _so_path(_SO)
    tag = so + ".hosttag"
    if (os.path.exists(so)
            and all(os.path.getmtime(so) >= os.path.getmtime(s) for s in _SRCS)
            and os.path.exists(tag)
            and open(tag).read() == _host_tag()):
        return so
    # -march=native is a ~1.8x dictionary-build win; the host-tag check above
    # guarantees the cached binary only runs on the CPU family it was
    # compiled for.
    fast = ["-O3", "-march=native", "-funroll-loops"]
    plain = ["-O3"]
    if _sanitize_mode():
        # sanitized artifacts trade speed for trap-on-UB/OOB/races; one
        # flag level (plus the no-zstd fallback) keeps failure modes
        # obvious
        fast = plain = _san_flags()
    tail = ["-fPIC", "-shared", "-std=c++17", "-o"]
    # build into a temp file then atomic-rename (parallel test runners)
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_SRC_DIR)
    os.close(fd)
    try:
        last_err = b""
        for cflags, zstd in ((fast, True), (plain, True),
                             (fast, False), (plain, False)):
            args = (["g++"] + cflags + tail + [tmp] + list(_SRCS)
                    # -ldl in BOTH branches: the snappy runtime dispatch
                    # dlopens unconditionally (pre-2.34 glibc keeps dlopen
                    # in libdl; -shared would link with it undefined and
                    # ctypes.CDLL would fail at load)
                    + (["-lzstd", "-ldl"] if zstd
                       else ["-DKPW_NO_ZSTD", "-ldl"]))
            try:
                subprocess.run(args, check=True, capture_output=True)
                break
            except subprocess.CalledProcessError as e:
                last_err = e.stderr or b""
                continue
        else:
            raise RuntimeError(
                "native library build failed at every flag level:\n"
                + last_err.decode(errors="replace"))
        os.replace(tmp, so)
        with open(tag, "w") as f:
            f.write(_host_tag())
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return so


class NestedShredResult:
    """Owner of one kpw_proto_shred_nested output; numpy views are COPIES
    (the C++ arena is freed on close / GC)."""

    def __init__(self, cdll, handle) -> None:
        self._c = cdll
        self._h = handle

    def _copy(self, ptr, n, dtype):
        import numpy as np

        if n == 0 or not ptr:
            return np.zeros(0, dtype)
        return np.ctypeslib.as_array(
            ctypes.cast(ptr, ctypes.POINTER(ctypes.c_uint8)),
            shape=(n * np.dtype(dtype).itemsize,)).view(dtype).copy()

    def values(self, leaf: int, dtype):
        import numpy as np

        nbytes = self._c.kpw_nested_value_bytes(self._h, leaf)
        n = nbytes // np.dtype(dtype).itemsize
        return self._copy(self._c.kpw_nested_values(self._h, leaf), n, dtype)

    def spans(self, leaf: int):
        import numpy as np

        n = self._c.kpw_nested_nspans(self._h, leaf)
        return (self._copy(self._c.kpw_nested_spos(self._h, leaf), n, np.int64),
                self._copy(self._c.kpw_nested_slen(self._h, leaf), n, np.int32))

    def levels(self, leaf: int):
        import numpy as np

        n = self._c.kpw_nested_nlevels(self._h, leaf)
        return (self._copy(self._c.kpw_nested_defs(self._h, leaf), n, np.uint8),
                self._copy(self._c.kpw_nested_reps(self._h, leaf), n, np.uint8))

    def close(self) -> None:
        if self._h:
            self._c.kpw_nested_free(self._h)
            self._h = None

    def __del__(self) -> None:  # pragma: no cover - GC safety net
        try:
            self.close()
        # lint: swallowed-exceptions ok — __del__ runs at arbitrary GC
        # points (possibly interpreter teardown); raising here aborts the
        # process with an unraisable-exception warning, not a diagnosis
        except Exception:
            pass


class NativeLib:
    """bytes-in/bytes-out wrappers over the C ABI."""

    def __init__(self, cdll: ctypes.CDLL) -> None:
        self._c = cdll
        c_sz = ctypes.c_size_t
        c_p = ctypes.c_char_p
        for name, slot in (("kpw_int_stats_i64", ctypes.c_int64),
                           ("kpw_int_stats_i32", ctypes.c_int64),
                           ("kpw_int_stats_u64", ctypes.c_uint64),
                           ("kpw_int_stats_u32", ctypes.c_uint64)):
            fn = getattr(cdll, name)
            fn.restype = None
            fn.argtypes = [ctypes.c_void_p, c_sz, ctypes.POINTER(slot),
                           ctypes.POINTER(slot),
                           ctypes.POINTER(ctypes.c_uint64)]
        cdll.kpw_snappy_max_compressed_length.restype = c_sz
        cdll.kpw_snappy_max_compressed_length.argtypes = [c_sz]
        cdll.kpw_snappy_compress.restype = ctypes.c_int
        cdll.kpw_snappy_compress.argtypes = [c_p, c_sz, c_p, ctypes.POINTER(c_sz)]
        cdll.kpw_snappy_uncompressed_length.restype = ctypes.c_int
        cdll.kpw_snappy_uncompressed_length.argtypes = [c_p, c_sz, ctypes.POINTER(c_sz)]
        cdll.kpw_snappy_compress_parts.restype = ctypes.c_int
        cdll.kpw_snappy_compress_parts.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(c_sz),
            ctypes.c_int, c_p, c_sz, ctypes.POINTER(c_sz)]
        cdll.kpw_snappy_uncompress.restype = ctypes.c_int
        cdll.kpw_snappy_uncompress.argtypes = [c_p, c_sz, c_p, c_sz, ctypes.POINTER(c_sz)]
        self.has_zstd = hasattr(cdll, "kpw_zstd_compress")
        if self.has_zstd:
            cdll.kpw_zstd_max_compressed_length.restype = c_sz
            cdll.kpw_zstd_max_compressed_length.argtypes = [c_sz]
            cdll.kpw_zstd_compress.restype = ctypes.c_int
            cdll.kpw_zstd_compress.argtypes = [c_p, c_sz, c_p, c_sz,
                                               ctypes.POINTER(c_sz), ctypes.c_int]
            cdll.kpw_zstd_uncompressed_length.restype = ctypes.c_int
            cdll.kpw_zstd_uncompressed_length.argtypes = [c_p, c_sz, ctypes.POINTER(c_sz)]
            cdll.kpw_zstd_uncompress.restype = ctypes.c_int
            cdll.kpw_zstd_uncompress.argtypes = [c_p, c_sz, c_p, c_sz, ctypes.POINTER(c_sz)]
        cdll.kpw_crc32c.restype = ctypes.c_uint32
        cdll.kpw_crc32c.argtypes = [c_p, c_sz, ctypes.c_uint32]
        cdll.kpw_byte_array_plain.restype = None
        cdll.kpw_byte_array_plain.argtypes = [
            c_p, ctypes.POINTER(ctypes.c_int64), c_sz, c_p]
        cdll.kpw_byte_array_gather.restype = None
        cdll.kpw_byte_array_gather.argtypes = [
            c_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), c_sz, c_p]
        c_u32p = ctypes.POINTER(ctypes.c_uint32)
        c_u64p = ctypes.POINTER(ctypes.c_uint64)
        cdll.kpw_dict_build_u32.restype = ctypes.c_int
        cdll.kpw_dict_build_u32.argtypes = [
            c_u32p, c_sz, c_u32p, c_u32p, ctypes.c_uint32, c_u32p]
        cdll.kpw_dict_build_u64.restype = ctypes.c_int
        cdll.kpw_dict_build_u64.argtypes = [
            c_u64p, c_sz, c_u64p, c_u32p, ctypes.c_uint32, c_u32p]
        c_i64p = ctypes.POINTER(ctypes.c_int64)
        c_i32p = ctypes.POINTER(ctypes.c_int32)
        cdll.kpw_delta_bp_cap.restype = c_sz
        cdll.kpw_delta_bp_cap.argtypes = [c_sz]
        cdll.kpw_delta_bp32.restype = ctypes.c_int
        cdll.kpw_delta_bp32.argtypes = [c_i32p, c_sz, c_p, ctypes.POINTER(c_sz)]
        cdll.kpw_delta_bp64.restype = ctypes.c_int
        cdll.kpw_delta_bp64.argtypes = [c_i64p, c_sz, c_p, ctypes.POINTER(c_sz)]
        cdll.kpw_dict_build_bytes.restype = ctypes.c_int
        cdll.kpw_dict_build_bytes.argtypes = [
            c_p, c_i64p, c_sz, c_i64p, c_u32p, ctypes.c_uint32, c_u32p]
        cdll.kpw_bytes_min_max.restype = None
        cdll.kpw_bytes_min_max.argtypes = [c_p, c_i64p, c_sz,
                                           ctypes.POINTER(c_sz),
                                           ctypes.POINTER(c_sz)]
        cdll.kpw_rle_hybrid_cap.restype = c_sz
        cdll.kpw_rle_hybrid_cap.argtypes = [c_sz, ctypes.c_int]
        cdll.kpw_rle_hybrid_u32.restype = ctypes.c_int
        cdll.kpw_rle_hybrid_u32.argtypes = [
            c_u32p, c_sz, ctypes.c_int, c_p, ctypes.POINTER(c_sz)]
        cdll.kpw_byte_stream_split.restype = ctypes.c_int
        cdll.kpw_byte_stream_split.argtypes = [
            ctypes.c_void_p, c_sz, c_sz, c_p]
        if self.has_zstd:
            cdll.kpw_zstd_compress_parts.restype = ctypes.c_int
            cdll.kpw_zstd_compress_parts.argtypes = [
                ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(c_sz),
                ctypes.c_int, c_p, c_sz, ctypes.POINTER(c_sz), ctypes.c_int]
        c_vpp = ctypes.POINTER(ctypes.c_void_p)
        cdll.kpw_proto_shred.restype = ctypes.c_int64
        cdll.kpw_proto_shred.argtypes = [
            c_p, c_i64p, ctypes.c_int64, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint32), c_p, c_p,
            c_vpp, c_vpp, c_vpp, c_vpp]
        cdll.kpw_gather_spans.restype = None
        cdll.kpw_gather_spans.argtypes = [
            c_p, c_i64p, c_i32p, ctypes.c_int64, c_p]
        h_p = ctypes.c_void_p
        cdll.kpw_proto_shred_nested.restype = ctypes.c_int64
        cdll.kpw_proto_shred_nested.argtypes = (
            [c_p, c_i64p, ctypes.c_int64, ctypes.c_int32, ctypes.c_int32,
             c_u32p, c_p, c_p] + [c_i32p] * 12 + [ctypes.POINTER(h_p)])
        for name in ("kpw_nested_value_bytes", "kpw_nested_nspans",
                     "kpw_nested_nlevels"):
            fn = getattr(cdll, name)
            fn.restype = ctypes.c_int64
            fn.argtypes = [h_p, ctypes.c_int32]
        for name, rt in (("kpw_nested_values", ctypes.c_void_p),
                         ("kpw_nested_spos", ctypes.POINTER(ctypes.c_int64)),
                         ("kpw_nested_slen", ctypes.POINTER(ctypes.c_int32)),
                         ("kpw_nested_defs", ctypes.POINTER(ctypes.c_uint8)),
                         ("kpw_nested_reps", ctypes.POINTER(ctypes.c_uint8))):
            fn = getattr(cdll, name)
            fn.restype = rt
            fn.argtypes = [h_p, ctypes.c_int32]
        cdll.kpw_nested_free.restype = None
        cdll.kpw_nested_free.argtypes = [h_p]

    # -- snappy ------------------------------------------------------------
    def snappy_compress(self, data: bytes) -> bytes:
        cap = self._c.kpw_snappy_max_compressed_length(len(data))
        out = ctypes.create_string_buffer(cap)
        out_len = ctypes.c_size_t(0)
        rc = self._c.kpw_snappy_compress(data, len(data), out, ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError(f"kpw_snappy_compress rc={rc}")
        return out.raw[: out_len.value]

    def snappy_decompress(self, data: bytes) -> bytes:
        size = ctypes.c_size_t(0)
        rc = self._c.kpw_snappy_uncompressed_length(data, len(data), ctypes.byref(size))
        if rc != 0:
            raise RuntimeError("invalid snappy stream")
        out = ctypes.create_string_buffer(max(size.value, 1))
        out_len = ctypes.c_size_t(0)
        rc = self._c.kpw_snappy_uncompress(data, len(data), out, size.value,
                                           ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError(f"kpw_snappy_uncompress rc={rc}")
        return out.raw[: out_len.value]

    # -- zstd --------------------------------------------------------------
    def zstd_compress(self, data: bytes, level: int = 3) -> bytes | None:
        if not self.has_zstd:
            return None
        cap = self._c.kpw_zstd_max_compressed_length(len(data))
        out = ctypes.create_string_buffer(cap)
        out_len = ctypes.c_size_t(0)
        rc = self._c.kpw_zstd_compress(data, len(data), out, cap,
                                       ctypes.byref(out_len), level)
        if rc != 0:
            raise RuntimeError("zstd compress failed")
        return out.raw[: out_len.value]

    def snappy_compress_parts(self, parts: list, out=None):
        """Compress discontiguous parts (bytes / memoryview / ndarray) as
        one snappy stream into ``out`` (a uint8 ndarray scratch, grown as
        needed, NOT zeroed) — returns (out, n_written).  The caller slices
        ``memoryview(out)[:n]`` and must consume it before the next call
        reusing the same scratch.  Same contract as zstd_compress_parts."""
        import numpy as np

        n = len(parts)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_size_t * n)()
        keep = []  # keep frombuffer views alive through the call
        total = 0
        for i, p in enumerate(parts):
            if isinstance(p, bytes):
                ptrs[i] = ctypes.cast(ctypes.c_char_p(p), ctypes.c_void_p)
                lens[i] = len(p)
                total += len(p)
            else:
                a = np.frombuffer(p, np.uint8)
                keep.append(a)
                ptrs[i] = a.ctypes.data
                lens[i] = a.nbytes
                total += a.nbytes
        cap = self._c.kpw_snappy_max_compressed_length(total)
        if out is None or out.nbytes < cap:
            out = np.empty(cap, np.uint8)
        out_len = ctypes.c_size_t(0)
        rc = self._c.kpw_snappy_compress_parts(
            ptrs, lens, n, out.ctypes.data_as(ctypes.c_char_p), out.nbytes,
            ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError(f"kpw_snappy_compress_parts rc={rc}")
        return out, out_len.value

    def zstd_compress_parts(self, parts: list, level: int = 3, out=None):
        """Compress discontiguous parts (bytes / memoryview / ndarray) as
        one zstd frame into ``out`` (a uint8 ndarray scratch, grown as
        needed, NOT zeroed) — returns (out, n_written) or None without
        libzstd.  The caller slices ``memoryview(out)[:n]`` and must consume
        it before the next call reusing the same scratch."""
        if not self.has_zstd:
            return None
        import numpy as np

        n = len(parts)
        ptrs = (ctypes.c_void_p * n)()
        lens = (ctypes.c_size_t * n)()
        keep = []  # keep frombuffer views alive through the call
        total = 0
        for i, p in enumerate(parts):
            if isinstance(p, bytes):
                ptrs[i] = ctypes.cast(ctypes.c_char_p(p), ctypes.c_void_p)
                lens[i] = len(p)
                total += len(p)
            else:
                a = np.frombuffer(p, np.uint8)
                keep.append(a)
                ptrs[i] = a.ctypes.data
                lens[i] = a.nbytes
                total += a.nbytes
        cap = self._c.kpw_zstd_max_compressed_length(total)
        if out is None or out.nbytes < cap:
            out = np.empty(cap, np.uint8)
        out_len = ctypes.c_size_t(0)
        rc = self._c.kpw_zstd_compress_parts(
            ptrs, lens, n,
            out.ctypes.data_as(ctypes.c_char_p), out.nbytes,
            ctypes.byref(out_len), level)
        if rc != 0:
            raise RuntimeError(f"kpw_zstd_compress_parts rc={rc}")
        return out, out_len.value

    def zstd_decompress(self, data: bytes) -> bytes | None:
        if not self.has_zstd:
            return None
        size = ctypes.c_size_t(0)
        rc = self._c.kpw_zstd_uncompressed_length(data, len(data), ctypes.byref(size))
        if rc != 0:
            raise RuntimeError("zstd: unknown content size")
        out = ctypes.create_string_buffer(max(size.value, 1))
        out_len = ctypes.c_size_t(0)
        rc = self._c.kpw_zstd_uncompress(data, len(data), out, size.value,
                                         ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError("zstd decompress failed")
        return out.raw[: out_len.value]

    # -- misc --------------------------------------------------------------
    def crc32c(self, data: bytes, crc: int = 0) -> int:
        return self._c.kpw_crc32c(data, len(data), crc)

    def byte_array_plain(self, data: bytes, offsets) -> bytes:
        import numpy as np

        offs = np.ascontiguousarray(offsets, np.int64)
        count = len(offs) - 1
        total = int(offs[-1] - offs[0]) + 4 * count
        out = ctypes.create_string_buffer(max(total, 1))
        self._c.kpw_byte_array_plain(
            data, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), count, out)
        return out.raw[:total]

    def byte_array_gather(self, dict_data: bytes, dict_offsets, indices) -> bytes:
        import numpy as np

        offs = np.ascontiguousarray(dict_offsets, np.int64)
        idx = np.ascontiguousarray(indices, np.int32)
        lens = offs[1:] - offs[:-1]
        total = int(lens[idx].sum()) + 4 * len(idx)
        out = ctypes.create_string_buffer(max(total, 1))
        self._c.kpw_byte_array_gather(
            dict_data, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(idx), out)
        return out.raw[:total]


    # -- encoding primitives ----------------------------------------------
    def dict_build(self, keys, max_k: int | None = None):
        """Ascending bit-pattern dictionary + uint32 indices for a uint32 or
        uint64 key array (kpw_tpu.core.encodings.dictionary_build semantics).
        Returns None when the unique count exceeds ``max_k`` (early abort:
        the dictionary would be rejected anyway)."""
        import numpy as np

        arr = np.ascontiguousarray(keys)
        n = len(arr)
        cap = n if max_k is None else min(n, max_k)
        idx = np.empty(n, np.uint32)
        dict_out = np.empty(cap, arr.dtype)
        k = ctypes.c_uint32(0)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        if arr.dtype.itemsize == 8:
            u64p = ctypes.POINTER(ctypes.c_uint64)
            rc = self._c.kpw_dict_build_u64(
                arr.ctypes.data_as(u64p), n, dict_out.ctypes.data_as(u64p),
                idx.ctypes.data_as(u32p), cap, ctypes.byref(k))
        else:
            rc = self._c.kpw_dict_build_u32(
                arr.ctypes.data_as(u32p), n, dict_out.ctypes.data_as(u32p),
                idx.ctypes.data_as(u32p), cap, ctypes.byref(k))
        if rc == 1:
            return None
        if rc != 0:
            raise RuntimeError(f"kpw_dict_build rc={rc}")
        return dict_out[: k.value].copy(), idx

    def dict_build_bytes(self, data: bytes, offsets, max_k: int | None = None):
        """Byte-array dictionary over a concatenated buffer + int64 offsets
        (n+1 entries).  Returns (uniq_pos int64 (k,) — index of each unique
        value's first occurrence, in ascending lexicographic order — and
        idx uint32 (n,)), or None when uniques exceed ``max_k``."""
        import numpy as np

        offs = np.ascontiguousarray(offsets, np.int64)
        n = len(offs) - 1
        cap = n if max_k is None else min(n, max_k)
        uniq_pos = np.empty(max(cap, 1), np.int64)
        idx = np.empty(max(n, 1), np.uint32)
        k = ctypes.c_uint32(0)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        rc = self._c.kpw_dict_build_bytes(
            data, offs.ctypes.data_as(i64p), n,
            uniq_pos.ctypes.data_as(i64p), idx.ctypes.data_as(u32p),
            cap, ctypes.byref(k))
        if rc == 1:
            return None
        if rc != 0:
            raise RuntimeError(f"kpw_dict_build_bytes rc={rc}")
        return uniq_pos[: k.value].copy(), idx[:n]

    def int_stats(self, values) -> tuple[int, int, int] | None:
        """(min, max, gcd_of_offsets) of an int32/int64/uint32/uint64 array
        in one fused C++ pass (kpw_int_stats_*) — the affine dictionary
        planner's stats.  gcd is gcd{v - min} (0 for a constant column).
        Returns None for unsupported dtypes (caller falls back to numpy)."""
        import numpy as np

        v = np.ascontiguousarray(values)
        fn = {np.dtype(np.int64): ("kpw_int_stats_i64", ctypes.c_int64),
              np.dtype(np.int32): ("kpw_int_stats_i32", ctypes.c_int64),
              np.dtype(np.uint64): ("kpw_int_stats_u64", ctypes.c_uint64),
              np.dtype(np.uint32): ("kpw_int_stats_u32", ctypes.c_uint64),
              }.get(v.dtype)
        if fn is None or not len(v):
            return None
        name, slot = fn
        mn, mx, g = slot(0), slot(0), ctypes.c_uint64(0)
        getattr(self._c, name)(
            v.ctypes.data_as(ctypes.c_void_p), len(v),
            ctypes.byref(mn), ctypes.byref(mx), ctypes.byref(g))
        return mn.value, mx.value, g.value

    def bytes_min_max(self, data: bytes, offsets) -> tuple[int, int]:
        """(min_idx, max_idx) of the lexicographically smallest/largest
        value; offsets must have >= 2 entries (n >= 1)."""
        import numpy as np

        offs = np.ascontiguousarray(offsets, np.int64)
        mn = ctypes.c_size_t(0)
        mx = ctypes.c_size_t(0)
        self._c.kpw_bytes_min_max(
            data, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            len(offs) - 1, ctypes.byref(mn), ctypes.byref(mx))
        return mn.value, mx.value

    def delta_binary_packed(self, values, bit_size: int = 64) -> bytes:
        """DELTA_BINARY_PACKED stream, byte-identical to
        kpw_tpu.core.encodings.delta_binary_packed_encode."""
        import numpy as np

        itype = np.int64 if bit_size == 64 else np.int32
        v = np.ascontiguousarray(values, itype)
        cap = self._c.kpw_delta_bp_cap(len(v))
        out = ctypes.create_string_buffer(cap)
        out_len = ctypes.c_size_t(0)
        if bit_size == 64:
            rc = self._c.kpw_delta_bp64(
                v.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), len(v),
                out, ctypes.byref(out_len))
        else:
            rc = self._c.kpw_delta_bp32(
                v.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(v),
                out, ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError(f"kpw_delta_bp rc={rc}")
        return out.raw[: out_len.value]

    def byte_stream_split(self, values) -> bytes:
        """BYTE_STREAM_SPLIT byte-plane transpose, byte-identical to
        kpw_tpu.core.encodings.byte_stream_split_encode (``values`` must
        already be a fixed-width ndarray in the column's PLAIN dtype)."""
        import numpy as np

        v = np.ascontiguousarray(values)
        n, width = len(v), v.dtype.itemsize
        if n == 0:
            return b""
        out = ctypes.create_string_buffer(n * width)
        rc = self._c.kpw_byte_stream_split(
            v.ctypes.data_as(ctypes.c_void_p), n, width, out)
        if rc != 0:
            raise RuntimeError(f"kpw_byte_stream_split rc={rc}")
        return out.raw[: n * width]

    def proto_shred(self, buf: bytes, rec_offsets, n_fields: int,
                    fnum, kinds, flags, out_vals, out_pos, out_len,
                    out_pres) -> int:
        """Batch wire-format decode (kpw_proto_shred).  ``out_*`` are lists
        (len n_fields) of numpy arrays or None; returns the first failing
        record index, or -1 when the whole batch decoded clean."""
        import numpy as np

        offs = np.ascontiguousarray(rec_offsets, np.int64)
        n_rec = len(offs) - 1

        def ptr_array(arrs):
            a = (ctypes.c_void_p * n_fields)()
            for i, arr in enumerate(arrs):
                a[i] = arr.ctypes.data if arr is not None else None
            return ctypes.cast(a, ctypes.POINTER(ctypes.c_void_p))

        rc = self._c.kpw_proto_shred(
            buf, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n_rec, n_fields,
            np.ascontiguousarray(fnum, np.uint32).ctypes.data_as(
                ctypes.POINTER(ctypes.c_uint32)),
            bytes(np.ascontiguousarray(kinds, np.uint8)),
            bytes(np.ascontiguousarray(flags, np.uint8)),
            ptr_array(out_vals), ptr_array(out_pos), ptr_array(out_len),
            ptr_array(out_pres))
        if rc == -2:
            raise RuntimeError("kpw_proto_shred: field number table overflow")
        return rc

    def proto_shred_nested(self, buf: bytes, rec_offsets, plan):
        """Batch nested wire-format decode (kpw_proto_shred_nested).
        ``plan`` carries the node-table arrays (models.proto_bridge
        _NestedPlan).  Returns a :class:`NestedShredResult` on success or
        the failing record index (int) when the batch needs the Python
        fallback."""
        import numpy as np

        offs = np.ascontiguousarray(rec_offsets, np.int64)
        n_rec = len(offs) - 1
        i32p = ctypes.POINTER(ctypes.c_int32)
        i64p = ctypes.POINTER(ctypes.c_int64)
        u32p = ctypes.POINTER(ctypes.c_uint32)
        keep = []  # anchor temporaries across the C call

        def ip(a):
            arr = np.ascontiguousarray(a, np.int32)
            keep.append(arr)
            return arr.ctypes.data_as(i32p)

        fnum = np.ascontiguousarray(plan.fnum, np.uint32)
        keep.append(fnum)
        handle = ctypes.c_void_p()
        rc = self._c.kpw_proto_shred_nested(
            buf, offs.ctypes.data_as(i64p), n_rec,
            plan.n_nodes, plan.n_leaves,
            fnum.ctypes.data_as(u32p),
            bytes(np.ascontiguousarray(plan.kind, np.uint8)),
            bytes(np.ascontiguousarray(plan.flags, np.uint8)),
            ip(plan.child_begin), ip(plan.child_end), ip(plan.leaf_idx),
            ip(plan.ftab), ip(plan.ftab_off), ip(plan.max_fn),
            ip(plan.enum_vals), ip(plan.enum_off), ip(plan.enum_len),
            ip(plan.null_leaves), ip(plan.null_off), ip(plan.null_len),
            ctypes.byref(handle))
        del keep
        if rc >= 0:
            return int(rc)
        return NestedShredResult(self._c, handle)

    def gather_spans(self, src: bytes, pos, lens) -> bytes:
        """Concatenate spans (pos[i], lens[i]) of ``src`` — the string-column
        payload assembly after proto_shred."""
        import numpy as np

        p = np.ascontiguousarray(pos, np.int64)
        ln = np.ascontiguousarray(lens, np.int32)
        total = int(ln.sum(dtype=np.int64))
        out = ctypes.create_string_buffer(max(total, 1))
        self._c.kpw_gather_spans(
            src, p.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ln.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(p), out)
        return out.raw[:total]

    def rle_hybrid(self, values, width: int) -> bytes:
        """RLE/bit-pack hybrid stream, byte-identical to
        kpw_tpu.core.encodings.rle_hybrid_encode."""
        import numpy as np

        v = np.ascontiguousarray(values, np.uint32)
        cap = self._c.kpw_rle_hybrid_cap(len(v), width)
        out = ctypes.create_string_buffer(cap)
        out_len = ctypes.c_size_t(0)
        rc = self._c.kpw_rle_hybrid_u32(
            v.ctypes.data_as(ctypes.POINTER(ctypes.c_uint32)), len(v), width,
            out, ctypes.byref(out_len))
        if rc != 0:
            raise RuntimeError(f"kpw_rle_hybrid rc={rc}")
        return out.raw[: out_len.value]


def _prefer_bundled_zstd() -> None:
    """Point the native lib's runtime zstd dispatch (codecs.cc zdl::) at the
    newest libzstd in the environment: the `zstandard` package bundles a
    newer build than most distros (1.5.7 vs 1.5.4 here — ~1.5x compression
    throughput on the page hot path).  Respect an operator-set value; unset
    or unloadable paths fall back to the linked system libzstd inside the
    native lib itself."""
    if "KPW_ZSTD_LIB" in os.environ:
        return
    try:
        import glob

        import zstandard

        try:
            system_ver = ctypes.CDLL("libzstd.so.1").ZSTD_versionNumber()
        except (OSError, AttributeError):
            system_ver = 0
        cands = glob.glob(os.path.join(os.path.dirname(zstandard.__file__),
                                       "_cffi*.so"))
        for so in cands:
            try:
                if ctypes.CDLL(so).ZSTD_versionNumber() > system_ver:
                    os.environ["KPW_ZSTD_LIB"] = so
                    return
            except (OSError, AttributeError):
                continue
    except ImportError:
        pass


def load() -> NativeLib:
    _prefer_bundled_zstd()
    return NativeLib(ctypes.CDLL(_build()))


# -- zero-copy CPython shred extension --------------------------------------
# shred_nested.cc compiles into BOTH this .so and the ctypes library (same
# source, no logic duplication) — the fused nested entries
# (shred_nested_buf/nested_fill) and the ctypes NestedShredResult route
# decode with identical object code, so the two paths cannot drift.
_PYSHRED_SRCS = [os.path.join(_SRC_DIR, "src", "pyshred.cc"),
                 os.path.join(_SRC_DIR, "src", "shred.cc"),
                 os.path.join(_SRC_DIR, "src", "shred_nested.cc")]
_PYSHRED_SO = os.path.join(_SRC_DIR, "_kpw_pyshred.so")
_PYSHRED_TAG = _PYSHRED_SO + ".hosttag"


def _build_pyshred() -> str:
    """Compile the _kpw_pyshred extension (pyshred.cc + shred.cc — the
    decoder compiles into both .so files from the same source, so the two
    paths cannot drift).  Same cache/hosttag discipline as _build, and
    the same KPW_NATIVE_SANITIZE asan/tsan modes (distinct caches)."""
    so = _so_path(_PYSHRED_SO)
    tag = so + ".hosttag"
    if (os.path.exists(so)
            and all(os.path.getmtime(so) >= os.path.getmtime(s)
                    for s in _PYSHRED_SRCS)
            and os.path.exists(tag)
            and open(tag).read() == _host_tag()):
        return so
    import sysconfig

    inc = sysconfig.get_paths()["include"]
    fast = ["-O3", "-march=native", "-funroll-loops"]
    plain = ["-O3"]
    if _sanitize_mode():
        fast = plain = _san_flags()
    tail = ["-fPIC", "-shared", "-std=c++17", f"-I{inc}", "-o"]
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_SRC_DIR)
    os.close(fd)
    try:
        last_err = b""
        for cflags in (fast, plain):
            args = ["g++"] + cflags + tail + [tmp] + _PYSHRED_SRCS
            try:
                subprocess.run(args, check=True, capture_output=True)
                break
            except subprocess.CalledProcessError as e:
                last_err = e.stderr or b""
                continue
        else:
            raise RuntimeError("pyshred build failed:\n"
                               + last_err.decode(errors="replace"))
        os.replace(tmp, so)
        with open(tag, "w") as f:
            f.write(_host_tag())
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return so


def load_pyshred():
    import importlib.machinery
    import importlib.util

    path = _build_pyshred()
    loader = importlib.machinery.ExtensionFileLoader("_kpw_pyshred", path)
    spec = importlib.util.spec_from_loader("_kpw_pyshred", loader,
                                           origin=path)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod


# -- nogil batch page-assembly extension -------------------------------------
_ASSEMBLE_SRCS = [os.path.join(_SRC_DIR, "src", "assemble.cc"),
                  os.path.join(_SRC_DIR, "src", "encode.cc"),
                  os.path.join(_SRC_DIR, "src", "codecs.cc")]
_ASSEMBLE_SO = os.path.join(_SRC_DIR, "_kpw_assemble.so")


def _assemble_tag() -> str:
    """Cache tag for the assemble extension: the host tag PLUS the CPython
    ABI tag — unlike the ctypes-only .so files (pure C ABI), this one is
    compiled against Python.h, so loading a cached build from a different
    interpreter would be undefined behavior, not a graceful fallback."""
    import sys

    return f"{_host_tag()}:{sys.implementation.cache_tag}"


def _build_assemble() -> str:
    """Compile the _kpw_assemble extension (assemble.cc + encode.cc +
    codecs.cc — the RLE/bit-pack encoder and the page codecs compile into
    this .so from the same sources as the ctypes library, so the two paths
    cannot drift).  Same cache/hosttag discipline as _build including the
    no-zstd fallback chain, and the same KPW_NATIVE_SANITIZE asan/tsan
    modes (distinct caches); the tag additionally pins the Python ABI."""
    so = _so_path(_ASSEMBLE_SO)
    tag = so + ".hosttag"
    if (os.path.exists(so)
            and all(os.path.getmtime(so) >= os.path.getmtime(s)
                    for s in _ASSEMBLE_SRCS)
            and os.path.exists(tag)
            and open(tag).read() == _assemble_tag()):
        return so
    import sysconfig

    inc = sysconfig.get_paths()["include"]
    fast = ["-O3", "-march=native", "-funroll-loops"]
    plain = ["-O3"]
    if _sanitize_mode():
        fast = plain = _san_flags()
    tail = ["-fPIC", "-shared", "-std=c++17", f"-I{inc}", "-o"]
    fd, tmp = tempfile.mkstemp(suffix=".so", dir=_SRC_DIR)
    os.close(fd)
    try:
        last_err = b""
        for cflags, zstd in ((fast, True), (plain, True),
                             (fast, False), (plain, False)):
            args = (["g++"] + cflags + tail + [tmp] + _ASSEMBLE_SRCS
                    + (["-lzstd", "-ldl"] if zstd
                       else ["-DKPW_NO_ZSTD", "-ldl"]))
            try:
                subprocess.run(args, check=True, capture_output=True)
                break
            except subprocess.CalledProcessError as e:
                last_err = e.stderr or b""
                continue
        else:
            raise RuntimeError("assemble build failed:\n"
                               + last_err.decode(errors="replace"))
        os.replace(tmp, so)
        with open(tag, "w") as f:
            f.write(_assemble_tag())
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)
    return so


def load_assemble():
    import importlib.machinery
    import importlib.util

    path = _build_assemble()
    loader = importlib.machinery.ExtensionFileLoader("_kpw_assemble", path)
    spec = importlib.util.spec_from_loader("_kpw_assemble", loader,
                                           origin=path)
    mod = importlib.util.module_from_spec(spec)
    loader.exec_module(mod)
    return mod
