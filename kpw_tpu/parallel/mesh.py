"""Device-mesh helpers.

One logical axis, ``shard``: Kafka partitions are assigned round-robin to
mesh shards the way the reference assigns them to worker threads via the
shared consumer queue (KafkaProtoParquetWriter.java:175-179).  Multi-host
extends the same axis over DCN — JAX process boundaries play the role of
the reference's scale-out consumer-group instances (KPW.java:72-76).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(n_devices: int | None = None, axis: str = "shard") -> Mesh:
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)} "
                f"(set --xla_force_host_platform_device_count for CPU dry runs)")
        devices = devices[:n_devices]
    return Mesh(np.array(devices), (axis,))


def shard_spec(mesh: Mesh) -> NamedSharding:
    """Rows sharded over the mesh's first axis."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def partition_assignment(n_partitions: int, n_shards: int) -> list[list[int]]:
    """Round-robin Kafka-partition -> shard assignment (the mesh analog of
    threads polling a shared queue, KPW.java:93-94)."""
    out: list[list[int]] = [[] for _ in range(n_shards)]
    for p in range(n_partitions):
        out[p % n_shards].append(p)
    return out
