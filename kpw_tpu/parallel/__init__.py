"""Multi-chip parallelism (build-plan step 6, SURVEY.md §7).

The reference's parallelism is thread-level data parallelism over a shared
consumer queue plus scale-out via Kafka consumer groups (SURVEY.md §2.4,
KafkaProtoParquetWriter.java:40-41,72-76).  The TPU-native design is SPMD
over a ``jax.sharding.Mesh``:

- ``mesh``: device mesh helpers (one ``shard`` axis; partitions -> chips).
- ``dict_merge``: the north-star collective — when multiple Kafka partitions
  share a row group, each chip dictionary-encodes its shard locally and the
  global dictionary is merged with ``all_gather``/``psum`` over ICI
  (SURVEY.md §5 "Distributed communication backend").
- ``sharded``: the full sharded encode step (shard_map over rows) used by
  ``__graft_entry__.dryrun_multichip``.
"""

from .mesh import make_mesh  # noqa: F401
from .dict_merge import DictionaryOverflow, global_dictionary_encode  # noqa: F401
from .sharded import sharded_encode_step, sharded_encode_step_bounded  # noqa: F401
