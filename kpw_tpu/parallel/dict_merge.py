"""Global dictionary merge across mesh shards (the north-star collective).

Scenario (BASELINE.md config 4): 16 Kafka partitions land on 8 chips, all
writing one shared row group.  Each shard dictionary-encodes its rows
locally, then the shards agree on ONE global dictionary so the row group has
a single dictionary page.  The reference has no analog — parquet-mr builds
one hash map per file on one thread (SURVEY.md §2.4 "Collective ops: No").

Algorithm (all static shapes, runs under shard_map over the ``shard`` axis):

  1. per-shard sorted-unique of the local values (capped at ``cap``);
  2. ``all_gather`` the per-shard unique sets over ICI;
  3. merge: sort-unique the gathered sets -> the global dictionary in
     ascending key order (deterministic regardless of shard count);
  4. per-shard index lookup by a vectorized lexicographic binary search of
     each (hi, lo) value pair against the ascending dictionary — O(n log G)
     gathers instead of sorting dict+values together (plain searchsorted
     cannot compare 64-bit keys split into uint32 halves; a pairwise
     compare in the search body can);
  5. ``psum`` the per-shard row counts -> global row count for the footer,
     and an overflow flag if any shard exceeded ``cap``.

Keys are bit-pattern (hi, lo) uint32 pairs as in ops.dictionary, so int64 /
float64 columns need no device int64 support.
"""

from __future__ import annotations

import contextlib
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.dictionary import split_keys
from ..ops.packing import pad_bucket

AXIS = "shard"


class DictionaryOverflow(ValueError):
    """A shard's local cardinality exceeded the requested cap.  Distinct
    from other ValueErrors so callers falling back to plain encoding don't
    swallow real bugs (shape/sharding mismatches) as 'overflow'."""


def _local_unique(hi, lo, valid, cap: int, has_hi: bool = True,
                  method: str | None = None):
    """Sorted-unique of the valid (hi, lo) keys, padded to ``cap``.
    Returns (uhi, ulo, uvalid, k) with uniques in ascending key order.

    Invalid slots are lifted to the MAX key instead of carrying a validity
    sort key: they land at the tail (or merge into a real max-key run, where
    dedupe counts the key once — correct either way), and the valid region
    is exactly the first sum(valid) slots.  That makes the 32-bit case
    (``has_hi=False``, statically known zero hi plane) a SINGLE-operand
    ``jnp.sort`` — XLA's fast path, ~5x quicker on CPU than the variadic
    comparator sort, which round 1 paid three times over via lexsort."""
    n = lo.shape[0]
    big = jnp.uint32(0xFFFFFFFF)
    llo = jnp.where(valid, lo, big)
    if has_hi:
        shi, slo = jax.lax.sort((jnp.where(valid, hi, big), llo), num_keys=2)
    else:
        slo = jnp.sort(llo)
        shi = jnp.zeros_like(slo)
    sval = jnp.arange(n, dtype=jnp.int32) < jnp.sum(valid.astype(jnp.int32))
    same = (shi[1:] == shi[:-1]) & (slo[1:] == slo[:-1]) if has_hi else (
        slo[1:] == slo[:-1])
    same = jnp.concatenate([jnp.zeros((1,), bool), same])
    is_new = sval & ~same
    k = jnp.sum(is_new.astype(jnp.int32))
    rank = jnp.where(is_new, jnp.cumsum(is_new.astype(jnp.int32)) - 1, n)
    if (method or default_rank_method()) == "sortrank":
        # TPU: compact by one more (fast) sort on rank — scatters are as
        # slow as gathers on the vector units
        if n >= cap:
            _, chi, clo = jax.lax.sort((rank, shi, slo), num_keys=1)
            uhi, ulo = chi[:cap], clo[:cap]
        else:  # pad up so the slice below is well-defined
            pad = jnp.full(cap - n, n, jnp.int32)
            _, chi, clo = jax.lax.sort(
                (jnp.concatenate([rank, pad]),
                 jnp.concatenate([shi, jnp.zeros(cap - n, shi.dtype)]),
                 jnp.concatenate([slo, jnp.zeros(cap - n, slo.dtype)])),
                num_keys=1)
            uhi, ulo = chi[:cap], clo[:cap]
    else:
        # CPU: compact by scatter-drop (cheap there)
        rank = jnp.where(is_new, rank, cap)
        uhi = jnp.zeros(cap + 1, jnp.uint32).at[rank].set(shi, mode="drop")[:cap]
        ulo = jnp.zeros(cap + 1, jnp.uint32).at[rank].set(slo, mode="drop")[:cap]
    uvalid = jnp.arange(cap) < k
    return uhi, ulo, uvalid, k


def default_rank_method() -> str:
    """'search' (vectorized binary search, gather-bound) wins on CPU meshes
    where gathers are cheap and variadic sorts are 4-5x slower than the
    single-key fast path; 'sortrank' (two stable sorts + cumsum, zero
    gathers) wins on TPU where sorts run ~12 GB/s on the vector units but
    per-element gathers are catastrophic (measured 454 ms vs 1.4 ms per
    64x64k step on v5e)."""
    import jax as _jax

    return "search" if _jax.devices()[0].platform == "cpu" else "sortrank"


def _rank_against_dict(dhi, dlo, dvalid, vhi, vlo, vvalid, k=None,
                       has_hi: bool = True, method: str | None = None):
    """Index of each (vhi, vlo) key in the ascending dict (dhi, dlo).
    Values not present map to arbitrary indices (callers guarantee
    coverage); invalid value slots map to garbage and must be masked by the
    caller.  ``method`` picks the hardware-appropriate implementation (see
    :func:`default_rank_method`); both produce identical indices."""
    if method is None:
        method = default_rank_method()
    G = dhi.shape[0]
    # pads live past the valid prefix; lift them to the max key so the whole
    # array is ascending
    big = jnp.uint32(0xFFFFFFFF)
    dh = jnp.where(dvalid, dhi, big)
    dl = jnp.where(dvalid, dlo, big)

    if method == "sortrank":
        # Stable sort of [dict, values]: on ties the dict entry (earlier
        # concat index) sorts first, so a running count of dict entries
        # assigns every value its dictionary slot; a second stable sort by
        # original position unscrambles — no gathers or scatters anywhere.
        # Only the VALID dict prefix counts: lifted pads share the max key
        # with real max-key values and must not inflate their slots.
        kk = jnp.sum(dvalid.astype(jnp.int32)) if k is None else k
        n = vlo.shape[0]
        iota = jnp.arange(G + n, dtype=jnp.int32)
        cat_lo = jnp.concatenate([dl, vlo])
        if has_hi:
            cat_hi = jnp.concatenate([dh, vhi])
            _, _, pos = jax.lax.sort((cat_hi, cat_lo, iota), num_keys=2)
        else:
            _, pos = jax.lax.sort((cat_lo, iota), num_keys=1)
        slots = jnp.cumsum((pos < kk).astype(jnp.int32)) - 1
        _, unscrambled = jax.lax.sort((pos, slots), num_keys=1)
        return unscrambled[G:]

    # 'search': lexicographic binary search with early exit — the round
    # count tracks the dict's VALID cardinality ``k`` (when given), not its
    # padded capacity, so a 1k-entry dictionary in a 16k-slot gather costs
    # ~10 gather rounds, not 15.
    lo_b = jnp.zeros(vhi.shape, jnp.int32)
    upper = jnp.int32(G) if k is None else jnp.minimum(jnp.int32(G),
                                                       k.astype(jnp.int32))
    hi_b = jnp.broadcast_to(upper, vhi.shape).astype(jnp.int32)

    def cond(c):
        lo_bound, hi_bound = c
        return jnp.any(lo_bound < hi_bound)

    def body(c):
        lo_bound, hi_bound = c
        mid = (lo_bound + hi_bound) // 2
        ml = dl[mid]
        if has_hi:
            mh = dh[mid]
            lt = (mh < vhi) | ((mh == vhi) & (ml < vlo))  # dict[mid] < value
        else:
            lt = ml < vlo
        return (jnp.where(lt, mid + 1, lo_bound),
                jnp.where(lt, hi_bound, mid))

    lo_b, _ = jax.lax.while_loop(cond, body, (lo_b, hi_b))
    return lo_b  # leftmost index with dict >= value == the match slot


def _merge_kernel(hi, lo, count, cap: int, has_hi: bool = True):
    """shard_map body: per-shard local view -> (indices, gdict, gk, rows)."""
    n = lo.shape[0]
    valid = jnp.arange(n, dtype=jnp.int32) < count
    uhi, ulo, uvalid, k = _local_unique(hi, lo, valid, cap, has_hi=has_hi)
    overflow = jax.lax.psum((k > cap).astype(jnp.int32), AXIS)

    glo = jax.lax.all_gather(ulo, AXIS).reshape(-1)
    gvalid = jax.lax.all_gather(uvalid, AXIS).reshape(-1)
    if has_hi:
        ghi = jax.lax.all_gather(uhi, AXIS).reshape(-1)
    else:
        ghi = jnp.zeros_like(glo)  # one less ICI gather for 32-bit columns
    G = glo.shape[0]
    mhi, mlo, mvalid, gk = _local_unique(ghi, glo, gvalid, G, has_hi=has_hi)

    indices = _rank_against_dict(mhi, mlo, mvalid, hi, lo, valid, k=gk,
                                 has_hi=has_hi)
    rows = jax.lax.psum(count, AXIS)
    return (indices.astype(jnp.uint32), mhi, mlo, gk, rows, overflow)


@functools.partial(jax.jit, static_argnames=("mesh", "cap", "has_hi"))
def _phase_a_sharded(hi, lo, counts, *, mesh: Mesh, cap: int,
                     has_hi: bool = True):
    """Two-phase merge, phase A: per-shard local uniques (kept on device,
    sharded) + the psum-max of the local cardinalities.  No row-block
    gather happens here — the host reads back only (k_max, overflow) and
    picks the phase-B gather capacity ``pad_bucket(k_max)``, so the ICI
    payload is bounded by the actual cardinality instead of the padded
    per-shard row block (VERDICT r3 next #5)."""

    def kern(h, l, c):
        count = c[0]
        n = l.shape[0]
        valid = jnp.arange(n, dtype=jnp.int32) < count
        uhi, ulo, _, k = _local_unique(h, l, valid, cap, has_hi=has_hi)
        overflow = jax.lax.psum((k > cap).astype(jnp.int32), AXIS)
        k_max = jax.lax.pmax(k, AXIS)
        return uhi, ulo, k.reshape(1), k_max, overflow

    fn = jax.shard_map(
        kern, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(AXIS), P(AXIS), P(), P()),
        check_vma=False,
    )
    return fn(hi, lo, counts)


@functools.partial(jax.jit, static_argnames=("mesh", "cap2", "has_hi"))
def _phase_b_sharded(uhi, ulo, ks, hi, lo, counts, *, mesh: Mesh, cap2: int,
                     has_hi: bool = True):
    """Two-phase merge, phase B: re-slice each shard's (device-resident)
    unique block to the host-chosen ``cap2 = pad_bucket(k_max)``, gather
    THAT over ICI, merge, and rank the original rows — payload
    ``n_shards * cap2`` keys ∝ the real cardinality.  Shard validity
    travels as one i32 per shard (the gathered ``k`` vector) instead of a
    gathered bool plane."""

    def kern(uh, ul, kk, h, l, c):
        count = c[0]
        n = l.shape[0]
        valid = jnp.arange(n, dtype=jnp.int32) < count
        ul2 = jax.lax.slice(ul, (0,), (cap2,))
        glo = jax.lax.all_gather(ul2, AXIS).reshape(-1)
        gk = jax.lax.all_gather(kk, AXIS).reshape(-1)  # (n_shards,) i32
        gvalid = (jnp.arange(cap2, dtype=jnp.int32)[None, :]
                  < jnp.minimum(gk, cap2)[:, None]).reshape(-1)
        if has_hi:
            uh2 = jax.lax.slice(uh, (0,), (cap2,))
            ghi = jax.lax.all_gather(uh2, AXIS).reshape(-1)
        else:
            ghi = jnp.zeros_like(glo)
        G = glo.shape[0]
        mhi, mlo, mvalid, gkk = _local_unique(ghi, glo, gvalid, G,
                                              has_hi=has_hi)
        indices = _rank_against_dict(mhi, mlo, mvalid, h, l, valid, k=gkk,
                                     has_hi=has_hi)
        rows = jax.lax.psum(count, AXIS)
        return indices.astype(jnp.uint32), mhi, mlo, gkk, rows

    fn = jax.shard_map(
        kern, mesh=mesh,
        in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
        out_specs=(P(AXIS), P(), P(), P(), P()),
        check_vma=False,
    )
    return fn(uhi, ulo, ks, hi, lo, counts)


@functools.partial(jax.jit, static_argnames=("mesh", "cap", "has_hi"))
def _merge_sharded(hi, lo, counts, *, mesh: Mesh, cap: int,
                   has_hi: bool = True):
    sharded = P(AXIS)
    rep = P()
    fn = jax.shard_map(
        lambda h, l, c: _merge_kernel(h, l, c[0], cap, has_hi=has_hi),
        mesh=mesh,
        in_specs=(sharded, sharded, sharded),
        out_specs=(sharded, rep, rep, rep, rep, rep),
        # the merged dict is replicated by construction (computed from
        # all_gather'd data), but VMA can't see that through sort/scatter
        check_vma=False,
    )
    return fn(hi, lo, counts)


def global_dictionary_encode(values: np.ndarray, mesh: Mesh,
                             cap: int | None = 65536, dispatch_lock=None,
                             two_phase: bool | None = None,
                             stats_out: dict | None = None):
    """Encode ``values`` against a mesh-global dictionary.

    Rows are split evenly over the mesh's shards (the partitions->chips
    assignment); returns (dict_values ascending by bit pattern, indices)
    as host arrays.  Raises :class:`DictionaryOverflow` when a shard's
    local cardinality exceeds ``cap`` (caller should fall back to plain
    encoding, the same escape hatch parquet-mr uses for oversized
    dictionaries).  ``cap=None`` sizes the cap to the padded per-shard row
    block — a shard can never hold more uniques than rows, so overflow
    becomes impossible (the MeshChunkEncoder byte-identity guarantee).

    ``two_phase`` (default on; env ``KPW_MESH_TWO_PHASE=0`` disables)
    bounds the ICI payload by the data instead of the row block: phase A
    computes per-shard uniques on device and psum-maxes the local
    cardinalities; the host then re-gathers at ``pad_bucket(k_max)`` —
    so an 8-shard 128Ki-rows/shard row group with 5k-cardinality columns
    gathers ~8k keys per column over ICI, not ~1M (VERDICT r3 next #5).
    Output is identical either way (every shard's k <= k_max uniques
    survive the re-slice).  ``stats_out`` (a dict) accumulates
    ``ici_gathered_bytes`` / ``k_max`` / ``gather_cap`` for the payload
    accounting in the cfg4 bench artifact.

    ``dispatch_lock`` (any context manager, e.g. a ``threading.Lock``) is
    held only around the DEVICE section — transfers, the SPMD collective
    launch, and result materialization — the part where interleaved
    multi-device enqueue order across host threads is a deadlock class on
    real meshes.  Host-side key splitting, shard padding, and index
    reassembly run outside it, so concurrent writer workers overlap their
    host prep (VERDICT r2 weak #5)."""
    if two_phase is None:
        import os

        two_phase = os.environ.get("KPW_MESH_TWO_PHASE", "1") != "0"
    n_shards = mesh.devices.size
    n = len(values)
    rows_per = max((n + n_shards - 1) // n_shards, 1)  # even split over shards
    per = pad_bucket(rows_per)  # static per-shard block, padded
    if cap is None:
        cap = per
    hi, lo = split_keys(np.ascontiguousarray(values))
    hi_p = np.zeros(n_shards * per, np.uint32)
    lo_p = np.zeros(n_shards * per, np.uint32)
    counts = np.zeros(n_shards, np.int32)
    for s in range(n_shards):
        src_a = s * rows_per
        take = max(0, min(rows_per, n - src_a))
        if take:
            dst = slice(s * per, s * per + take)
            lo_p[dst] = lo[src_a : src_a + take]
            if hi is not None:
                hi_p[dst] = hi[src_a : src_a + take]
        counts[s] = take
    planes = 2 if hi is not None else 1
    shard_sharding = NamedSharding(mesh, P(AXIS))
    with dispatch_lock if dispatch_lock is not None else contextlib.nullcontext():
        hi_d = jax.device_put(hi_p, shard_sharding)
        lo_d = jax.device_put(lo_p, shard_sharding)
        cnt_d = jax.device_put(counts, shard_sharding)
        if two_phase:
            uhi_d, ulo_d, ks_d, k_max_d, overflow = _phase_a_sharded(
                hi_d, lo_d, cnt_d, mesh=mesh, cap=cap,
                has_hi=hi is not None)
            # ONE combined D2H fetch picks the gather capacity and checks
            # overflow — separate int() reads would each pay a transfer
            # round trip on high-latency links
            ovf_i, k_max = map(int, jax.device_get((overflow, k_max_d)))
            if ovf_i:
                raise DictionaryOverflow(
                    f"per-shard dictionary cardinality exceeded cap={cap}")
            cap2 = min(pad_bucket(max(k_max, 1)), cap)
            indices, mhi, mlo, gk, rows = _phase_b_sharded(
                uhi_d, ulo_d, ks_d, hi_d, lo_d, cnt_d, mesh=mesh,
                cap2=cap2, has_hi=hi is not None)
            if stats_out is not None:
                stats_out["ici_gathered_bytes"] = (
                    stats_out.get("ici_gathered_bytes", 0)
                    + n_shards * (cap2 * 4 * planes + 4))
                stats_out["k_max"] = max(stats_out.get("k_max", 0), k_max)
                stats_out["gather_cap"] = max(stats_out.get("gather_cap", 0),
                                              cap2)
                stats_out["columns"] = stats_out.get("columns", 0) + 1
        else:
            indices, mhi, mlo, gk, rows, overflow = _merge_sharded(
                hi_d, lo_d, cnt_d, mesh=mesh, cap=cap,
                has_hi=hi is not None)  # 32-bit dtypes: single-key sorts
            if stats_out is not None:
                stats_out["ici_gathered_bytes"] = (
                    stats_out.get("ici_gathered_bytes", 0)
                    + n_shards * cap * (4 * planes + 1))
                stats_out["gather_cap"] = cap
                stats_out["columns"] = stats_out.get("columns", 0) + 1
        # materialize INSIDE the lock: device->host gathers of sharded
        # arrays are multi-device operations too.  Overflow first — the
        # expected fallback path must not hold the lock for full-array
        # transfers whose results are discarded.
        if not two_phase and int(overflow):
            raise DictionaryOverflow(
                f"per-shard dictionary cardinality exceeded cap={cap}")
        gk_i = int(gk)
        rows_i = int(rows)
        mhi_np = np.asarray(mhi)
        mlo_np = np.asarray(mlo)
        idx_np = np.asarray(indices)
    gk = gk_i
    assert rows_i == n
    mhi_np = mhi_np[:gk].astype(np.uint64)
    mlo_np = mlo_np[:gk].astype(np.uint64)
    arr = np.ascontiguousarray(values)
    if arr.dtype.itemsize == 4:
        dict_values = mlo_np.astype(np.uint32).view(arr.dtype)
    else:
        dict_values = ((mhi_np << np.uint64(32)) | mlo_np).view(arr.dtype)
    # shards are contiguous row ranges; reassemble by stripping per-shard pad
    parts = [idx_np[s * per : s * per + int(counts[s])] for s in range(n_shards)]
    out_idx = np.concatenate(parts) if parts else np.zeros(0, np.uint32)
    return dict_values, out_idx
