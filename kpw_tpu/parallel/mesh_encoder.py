"""Mesh-global encoder backend: the multi-chip encode path, reachable from
the writer runtime.

The reference scales out with one consumer-group instance per host
(KafkaProtoParquetWriter.java:72-76), so records from different Kafka
partitions always land in different files.  The TPU-native design instead
lets partitions SHARE a row group (SURVEY §2.4 / BASELINE config 4): a row
batch is split over the chips of a ``jax.sharding.Mesh`` and every eligible
column's dictionary is built mesh-globally — per-shard sort-unique, an
``all_gather`` of the unique sets over ICI, a merged sort-unique, and a
sortrank of each shard's rows against the merged dictionary
(parallel/dict_merge.py).  One jitted SPMD program; XLA schedules the
collectives.

The merged dictionary is the ascending-bit-pattern unique set of ALL rows —
exactly what every single-chip builder produces — so with the default
(adaptive) shard capacity, files written through this backend are
byte-identical to the cpu/native/tpu backends (asserted in
tests/test_parallel.py; an explicit undersized ``cap`` trades identity for
ICI payload, see class docstring).  Page assembly, levels, non-dictionary
encodings, strings and compression ride the native host path unchanged.

Select with ``Builder.encoder_backend(MeshChunkEncoder(options))`` or the
string ``"mesh"`` (runtime/select.py); ``choose_backend()`` never picks it
automatically — sharing row groups across partitions is a topology decision,
not a link-speed one.
"""

from __future__ import annotations

import threading

import numpy as np

from ..native.encoder import NativeChunkEncoder
from .dict_merge import DictionaryOverflow, global_dictionary_encode
from .mesh import make_mesh

# One collective launch at a time, process-wide: multiple writer workers
# (thread_count > 1) each own a MeshChunkEncoder, and concurrent
# multi-device program dispatch from different host threads can interleave
# collective enqueue order across devices — a deadlock class on real
# meshes.  Passed INTO global_dictionary_encode so it covers only the
# device section (transfers + collective launch + materialization); each
# worker's host-side key splitting, shard padding, and index reassembly
# run outside it and overlap freely.
_DISPATCH_LOCK = threading.Lock()


class MeshChunkEncoder(NativeChunkEncoder):
    """Chunk encoder whose dictionary build runs mesh-globally on device.

    ``cap`` bounds each shard's local unique capacity (the all_gather
    payload is ``n_shards * cap`` keys).  The default (None) lets
    ``global_dictionary_encode`` size it to the padded per-shard row block
    — a shard can never hold more uniques than rows, so overflow is
    impossible and byte-identity with the host backends holds
    unconditionally.  Passing an explicit ``cap`` trades that guarantee
    for a smaller ICI payload: a column whose per-shard cardinality
    overflows it falls back to plain/delta (which the host backends may
    not do for the same column)."""

    def __init__(self, options, mesh=None, cap: int | None = None) -> None:
        super().__init__(options)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.cap = cap
        # Cumulative ICI payload accounting (filled by the two-phase merge:
        # gathered bytes, max cardinality, gather capacity, column count) —
        # read by the cfg4 bench artifact so the collective's cost is a
        # recorded number, not prose (VERDICT r3 next #5).
        self.ici_stats: dict = {}
        # String-dictionary merge accounting (per-shard host hash + sorted
        # union — VERDICT r3 next #7): exchanged payload bytes, global/local
        # cardinalities, wall time.
        self.string_stats: dict = {}
        # Per-column routing record (VERDICT r4 next #2): which merge each
        # dictionary column actually rode, with its ICI payload — read by
        # the cfg4 bench artifact's writer_route block.  Bounded: a shared
        # long-lived encoder appends one entry per dict column per row
        # group, so an unbounded list would leak on a streaming writer.
        import collections

        self.route_log: collections.deque = collections.deque(maxlen=512)
        # Workers can SHARE one encoder instance (runtime/writer.py passes
        # the same backend object to every worker): stats accumulate from
        # per-call local dicts under this lock, never by unlocked
        # read-modify-writes on the shared dicts.
        self._stats_lock = threading.Lock()

    def _merge_stats(self, col_stats: dict) -> None:
        with self._stats_lock:
            for k, v in col_stats.items():
                if k in ("k_max", "gather_cap", "bounded_nhi_max"):
                    self.ici_stats[k] = max(self.ici_stats.get(k, 0), v)
                else:  # byte/column counters sum
                    self.ici_stats[k] = self.ici_stats.get(k, 0) + v

    def _merge_string_stats(self, col_stats: dict) -> None:
        """string_stats counterpart of :meth:`_merge_stats` (ADVICE r5 #1):
        per-call locals merge under the lock — a shared multi-worker
        encoder must never read-modify-write the shared dict unlocked, or
        concurrent BYTE_ARRAY encodes drop counter updates."""
        with self._stats_lock:
            for k, v in col_stats.items():
                if k in ("k_global_max", "k_local_max"):
                    self.string_stats[k] = max(self.string_stats.get(k, 0), v)
                elif k == "merge_ms":
                    self.string_stats[k] = round(
                        self.string_stats.get(k, 0.0) + v, 3)
                else:  # column/byte counters sum
                    self.string_stats[k] = self.string_stats.get(k, 0) + v

    def _mesh_string_dictionary(self, values, max_k: int | None):
        """Byte-array dictionary built the way a real multi-host mesh
        would: each shard hashes ITS rows locally (the GIL-releasing C++
        hash, native/src/encode.cc), the shards' sorted unique sets merge
        by k-way union, and each shard's local indices remap through a
        per-shard lookup table.  Exactly the two-phase numeric merge's
        shape with the collective replaced by a host exchange — variable-
        length bytes don't belong on the ICI vector path, but only the
        per-shard UNIQUE payload crosses the wire, recorded in
        ``string_stats``.  Output (ascending bytes) is byte-identical to
        the single-hash native build (asserted in tests/test_parallel.py).

        Returns None on ratio overflow (counted in
        ``self.string_stats['overflow_columns']`` so callers can tell abort
        from ineligibility, mirroring ``_bytes_dictionary``'s contract).
        A shard whose LOCAL unique count already exceeds max_k aborts
        inside the C++ hash (local k is a lower bound on global k), and
        the k-way union bails as soon as the running merge crosses max_k —
        an overflowing column never pays a full Python-level merge."""
        import heapq
        import time as _time

        from ..core.bytecol import ByteColumn

        n_shards = self.mesh.devices.size
        if n_shards == 1:
            # nothing to merge on a 1-device mesh — the single C++ hash
            # build IS the per-shard step, with no remap/union overhead
            return self._bytes_dictionary(values, max_k)
        t0 = _time.perf_counter()
        if not isinstance(values, ByteColumn):
            values = ByteColumn.from_list(values)
        data, offsets = values.data, values.offsets
        n = len(values)
        rows_per = max((n + n_shards - 1) // n_shards, 1)
        shard_uniqs: list[list[bytes]] = []
        shard_idx: list = []
        bounds: list[tuple[int, int]] = []
        exchanged = 0
        overflow = False
        for s in range(n_shards):
            a = min(s * rows_per, n)
            b = min(a + rows_per, n)
            bounds.append((a, b))
            if b == a:
                shard_uniqs.append([])
                shard_idx.append(None)
                continue
            built = self._lib.dict_build_bytes(data, offsets[a:b + 1], max_k)
            if built is None:  # local k > max_k => global k > max_k
                overflow = True
                break
            uniq_pos, idx = built  # ascending lexicographic within the shard
            uniqs = values.take(uniq_pos + a)
            shard_uniqs.append(uniqs)
            shard_idx.append(idx)
            exchanged += sum(map(len, uniqs)) + 4 * len(uniqs)
        # k-way sorted union -> the global ascending dictionary (the oracle
        # order, core.encodings.dictionary_build)
        merged: list[bytes] = []
        if not overflow:
            for v in heapq.merge(*shard_uniqs):
                if not merged or v != merged[-1]:
                    merged.append(v)
                    if max_k is not None and len(merged) > max_k:
                        overflow = True
                        break
        gk = len(merged)
        # per-call local accumulation, merged under the stats lock at the
        # exits (ADVICE r5 #1) — the same protocol as the numeric routes'
        # _merge_stats, so a shared multi-worker encoder stays exact
        col_stats = {
            "columns": 1,
            "exchanged_payload_bytes": exchanged,
            "k_global_max": gk,
            "k_local_max": max([0] + [len(u) for u in shard_uniqs]),
        }
        if overflow:
            col_stats["overflow_columns"] = 1
            col_stats["merge_ms"] = round(
                (_time.perf_counter() - t0) * 1e3, 3)
            self._merge_string_stats(col_stats)
            return None  # ratio abort: encode() falls back like the oracle
        slot = {v: i for i, v in enumerate(merged)}
        out_idx = np.empty(n, np.uint32)
        for s, (a, b) in enumerate(bounds):
            if b == a:
                continue
            lut = np.fromiter((slot[v] for v in shard_uniqs[s]), np.uint32,
                              len(shard_uniqs[s]))
            out_idx[a:b] = lut[shard_idx[s][: b - a]]
        col_stats["merge_ms"] = round((_time.perf_counter() - t0) * 1e3, 3)
        self._merge_string_stats(col_stats)
        return merged, out_idx

    def _parallel_assembly_ok(self) -> bool:
        """Sequential page assembly, always: each eligible column launches
        a multi-device SPMD collective program from inside encode(), and
        concurrent multi-device dispatch from a host thread pool adds
        contention without parallelism (device work serializes on the same
        chips anyway) — so the native backend's column-threaded assembly
        is deliberately disabled."""
        return False

    def _try_dictionary(self, chunk):
        from ..core.bytecol import ByteColumn
        from ..core.schema import PhysicalType

        values = chunk.values
        pt = chunk.column.leaf.physical_type
        if (pt == PhysicalType.BYTE_ARRAY and self._lib is not None
                and isinstance(values, (list, ByteColumn)) and len(values)):
            # strings join the shared-row-group story too (VERDICT r3 next
            # #7): per-shard host hash + a sorted-union merge — the
            # DCN-side analog of the ICI key merge
            max_k = max(1, int(len(values)
                               * self.options.max_dictionary_ratio))
            if self._bloom_wants_distinct(chunk):
                # bloom population (core/index.py) needs the exact
                # distinct set whatever the dictionary verdict — and here
                # the completed merge is the MESH-GLOBAL set, so the
                # ratio abort is waived and the filter covers every
                # shard's values for free
                max_k = len(values)
            # returns None only on ratio overflow -> encode() falls back to
            # plain/delta, the same escape hatch as _bytes_dictionary
            return self._mesh_string_dictionary(values, max_k)
        if not (self._fixed_width_ok(values, pt) and len(values) > 0):
            # bool / exotic value containers ride the native host dictionary
            return super()._try_dictionary(chunk)
        max_k = self._fixed_width_max_k(len(values), values.dtype.itemsize)
        bounded = self._bounded_route(values)
        if bounded is not None:
            # globally-bounded column (VERDICT r4 next #2): the merge is
            # one constant-payload psum of per-shard histograms instead of
            # the cardinality-proportional unique-set gather.  The bound
            # comes from the planner's fused native min/max/gcd stats over
            # ALL rows, so it is globally valid across every shard, and
            # k <= value_bound <= 2^13 can never overflow a cap.
            vmin, stride, vb = bounded
            from .sharded import (bounded_global_dictionary_encode,
                                  bounded_psum_payload_bytes)

            col_stats: dict = {}
            d, idx = bounded_global_dictionary_encode(
                values, self.mesh, vmin=vmin, stride=stride, value_bound=vb,
                dispatch_lock=_DISPATCH_LOCK, stats_out=col_stats,
                trusted=True)  # vmin/stride/vb come from the fused stats
            self._merge_stats(col_stats)
            accepted = len(d) <= max_k
            self.route_log.append({
                "column": chunk.column.name, "route": "bounded-psum",
                "value_bound": vb, "stride": stride, "k": len(d),
                "accepted": accepted,  # False: encode() falls back to plain
                "ici_payload_bytes": bounded_psum_payload_bytes(vb)})
            if not accepted:
                return None  # encode() would reject it; skip wasted pages
            return d, idx
        col_stats = {}
        try:
            d, idx = global_dictionary_encode(values, self.mesh, cap=self.cap,
                                              dispatch_lock=_DISPATCH_LOCK,
                                              stats_out=col_stats)
        except DictionaryOverflow:
            self._merge_stats(col_stats)
            # the rejection is part of the routing record too: without it
            # the cfg4 writer_route block would list fewer columns than
            # the file has dict-eligible ones, with no indication why
            self.route_log.append({
                "column": chunk.column.name, "route": "two-phase-gather",
                "accepted": False, "overflow": True})
            return None  # per-shard cardinality overflow (explicit cap)
        self._merge_stats(col_stats)
        accepted = len(d) <= max_k
        self.route_log.append({
            "column": chunk.column.name, "route": "two-phase-gather",
            "k": len(d), "accepted": accepted,
            "ici_payload_bytes": col_stats.get("ici_gathered_bytes", 0)})
        if not accepted:
            return None  # encode() would reject it; skip the wasted pages
        return d, idx

    @staticmethod
    def _bounded_route(values) -> tuple[int, int, int] | None:
        """(vmin, stride, value_bound) when the planner's fused
        min/max/gcd stats prove the column's offsets fit the
        histogram-psum design bound (<= 2^13), else None.  ``vmin >= 0``
        is load-bearing: ascending offsets reconstruct to ascending
        bit-pattern dictionary order — identical to the gather merge and
        the host oracle — only for non-negative values (a negative int64's
        bit pattern sorts ABOVE the positives)."""
        from ..ops.dictionary import _int_stats, affine_stride
        from .sharded import _MATMUL_MAX_BOUND

        if values.dtype.kind not in "iu" or not len(values):
            return None
        vmin, vmax, g_all = _int_stats(values)
        if vmin < 0:
            return None
        span = vmax - vmin
        g = affine_stride(values, vmin, span, g_all, _MATMUL_MAX_BOUND)
        if g:
            return vmin, g, span // g + 1
        return None
