"""Mesh-global encoder backend: the multi-chip encode path, reachable from
the writer runtime.

The reference scales out with one consumer-group instance per host
(KafkaProtoParquetWriter.java:72-76), so records from different Kafka
partitions always land in different files.  The TPU-native design instead
lets partitions SHARE a row group (SURVEY §2.4 / BASELINE config 4): a row
batch is split over the chips of a ``jax.sharding.Mesh`` and every eligible
column's dictionary is built mesh-globally — per-shard sort-unique, an
``all_gather`` of the unique sets over ICI, a merged sort-unique, and a
sortrank of each shard's rows against the merged dictionary
(parallel/dict_merge.py).  One jitted SPMD program; XLA schedules the
collectives.

The merged dictionary is the ascending-bit-pattern unique set of ALL rows —
exactly what every single-chip builder produces — so with the default
(adaptive) shard capacity, files written through this backend are
byte-identical to the cpu/native/tpu backends (asserted in
tests/test_parallel.py; an explicit undersized ``cap`` trades identity for
ICI payload, see class docstring).  Page assembly, levels, non-dictionary
encodings, strings and compression ride the native host path unchanged.

Select with ``Builder.encoder_backend(MeshChunkEncoder(options))`` or the
string ``"mesh"`` (runtime/select.py); ``choose_backend()`` never picks it
automatically — sharing row groups across partitions is a topology decision,
not a link-speed one.
"""

from __future__ import annotations

import threading

from ..native.encoder import NativeChunkEncoder
from .dict_merge import DictionaryOverflow, global_dictionary_encode
from .mesh import make_mesh

# One collective launch at a time, process-wide: multiple writer workers
# (thread_count > 1) each own a MeshChunkEncoder, and concurrent
# multi-device program dispatch from different host threads can interleave
# collective enqueue order across devices — a deadlock class on real
# meshes.  Passed INTO global_dictionary_encode so it covers only the
# device section (transfers + collective launch + materialization); each
# worker's host-side key splitting, shard padding, and index reassembly
# run outside it and overlap freely.
_DISPATCH_LOCK = threading.Lock()


class MeshChunkEncoder(NativeChunkEncoder):
    """Chunk encoder whose dictionary build runs mesh-globally on device.

    ``cap`` bounds each shard's local unique capacity (the all_gather
    payload is ``n_shards * cap`` keys).  The default (None) lets
    ``global_dictionary_encode`` size it to the padded per-shard row block
    — a shard can never hold more uniques than rows, so overflow is
    impossible and byte-identity with the host backends holds
    unconditionally.  Passing an explicit ``cap`` trades that guarantee
    for a smaller ICI payload: a column whose per-shard cardinality
    overflows it falls back to plain/delta (which the host backends may
    not do for the same column)."""

    def __init__(self, options, mesh=None, cap: int | None = None) -> None:
        super().__init__(options)
        self.mesh = mesh if mesh is not None else make_mesh()
        self.cap = cap
        # Cumulative ICI payload accounting (filled by the two-phase merge:
        # gathered bytes, max cardinality, gather capacity, column count) —
        # read by the cfg4 bench artifact so the collective's cost is a
        # recorded number, not prose (VERDICT r3 next #5).
        self.ici_stats: dict = {}

    def encode_many(self, chunks, base_offset: int):
        """Sequential: each eligible column launches a multi-device SPMD
        collective program, and concurrent multi-device dispatch from a
        host thread pool adds contention without parallelism (device work
        serializes on the same chips anyway) — so the native backend's
        column-threaded encode_many is deliberately bypassed."""
        from ..core.pages import CpuChunkEncoder

        return CpuChunkEncoder.encode_many(self, chunks, base_offset)

    def _try_dictionary(self, chunk):
        values = chunk.values
        pt = chunk.column.leaf.physical_type
        if not (self._fixed_width_ok(values, pt) and len(values) > 0):
            # strings/bool ride the native host dictionary
            return super()._try_dictionary(chunk)
        max_k = self._fixed_width_max_k(len(values), values.dtype.itemsize)
        try:
            d, idx = global_dictionary_encode(values, self.mesh, cap=self.cap,
                                              dispatch_lock=_DISPATCH_LOCK,
                                              stats_out=self.ici_stats)
        except DictionaryOverflow:
            return None  # per-shard cardinality overflow (explicit cap)
        if len(d) > max_k:
            return None  # encode() would reject it; skip the wasted pages
        return d, idx
