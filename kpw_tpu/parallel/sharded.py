"""The full sharded encode step: data-parallel rows, collective dictionary.

This is the TPU-native equivalent of the reference's whole hot loop
(KafkaProtoParquetWriter.java:253-292) at multi-chip scale: a (columns, rows)
batch is sharded over the ``shard`` mesh axis (rows = records polled from the
shards' Kafka partitions), every column is dictionary-encoded against a
mesh-global dictionary (all_gather/psum over ICI, see dict_merge), and the
dictionary indices are bit-packed on device.  One jitted program; XLA
schedules the collectives.

``encode_step_single`` is the single-chip flagship forward step used by
``__graft_entry__.entry`` — identical math minus the collectives.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.packing import bitpack_device, packed_reorder
from .dict_merge import AXIS, _local_unique, _merge_kernel, _rank_against_dict


@functools.partial(jax.jit, static_argnames=("mesh", "cap", "width", "has_hi"))
def sharded_encode_step(hi, lo, counts, *, mesh: Mesh, cap: int = 4096,
                        width: int = 16, has_hi: bool = True):
    """One SPMD encode step.

    hi, lo: (C, N) uint32 key halves, sharded over rows (N) across the mesh;
    counts: (n_shards,) valid rows per shard.  Returns per-shard packed index
    bytes (C, N*width//8 sharded), per-column global dictionaries (replicated
    (C, G) key halves + (C,) sizes), the psum'd global row count, and an
    overflow indicator.  Pass ``has_hi=False`` when the hi plane is
    statically zero (32-bit column dtypes): sorts and searches then run
    single-key, the CPU-mesh fast path and one less gather on ICI.
    """

    def kernel(h, l, c):
        count = c[0]

        def one_column(hc, lc):
            indices, mhi, mlo, gk, rows, ovf = _merge_kernel(
                hc, lc, count, cap, has_hi=has_hi)
            n = indices.shape[0]
            masked = jnp.where(jnp.arange(n, dtype=jnp.int32) < count, indices, 0)
            packed = bitpack_device(masked, width)
            # indices wider than `width` bits would silently wrap in the pack
            ovf = ovf + (gk > (1 << width)).astype(jnp.int32)
            return packed, mhi, mlo, gk, rows, ovf

        packed, mhi, mlo, gk, rows, ovf = jax.vmap(one_column)(h, l)
        return packed, mhi, mlo, gk, rows[0], jnp.max(ovf)

    fn = jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(None, AXIS), P(None, AXIS), P(AXIS)),
        out_specs=(P(None, AXIS), P(), P(), P(), P(), P()),
        check_vma=False,  # replicated-by-construction outputs, as in dict_merge
    )
    return fn(hi, lo, counts)


def _bounded_merge_core(l, c, *, nhi: int, pack: str):
    """shard_map body shared by the packed flagship step and the
    production index route: per-shard histogram, ONE psum (the merge),
    presence -> dictionary, per-row rank lookup.  Returns
    (masked_indices (C, n_local) uint32, ulo, gk, rows)."""
    from ..ops.pallas_rank import (S_LO, hist_pages_core, presence_to_dict,
                                   rank_pages_core)

    vb = nhi * S_LO
    count = c[0]
    n = l.shape[1]
    iota = jnp.arange(n, dtype=jnp.int32)
    valid = iota < count
    lo_m = jnp.where(valid[None, :], l, jnp.uint32(vb))
    if pack != "xla":
        # the VMEM-fused kernels (ops.pallas_rank) — the one-hot
        # matrices never exist in HBM (the XLA formulation below
        # measured memory-bound single-chip)
        local = hist_pages_core(lo_m, nhi, interpret=pack == "interpret")
    else:
        def hist_one(lc):
            # portable fallback (virtual CPU meshes, n % 128 != 0):
            # int8 one-hot matmul, int32 accumulation — exact on
            # every backend; the sentinel vb maps to hi == nhi,
            # whose one-hot row is all-zero, so invalid rows join
            # no bin
            hi = (lc // S_LO).astype(jnp.int32)
            lo6 = (lc % S_LO).astype(jnp.int32)
            H = (hi[:, None] == jnp.arange(nhi)[None, :]).astype(jnp.int8)
            L = (lo6[:, None] == jnp.arange(S_LO)[None, :]).astype(jnp.int8)
            return jax.lax.dot_general(H, L, (((0,), (0,)), ((), ())),
                                       preferred_element_type=jnp.int32)

        local = jax.vmap(hist_one)(lo_m)     # (C, nhi, 64)
    gcounts = jax.lax.psum(local, AXIS)      # THE merge: one psum,
    # constant nhi*64*4 B per column regardless of rows or k
    rt, ulo, gk = presence_to_dict(gcounts, nhi)
    if pack != "xla":
        ranks = rank_pages_core(lo_m, rt,
                                interpret=pack == "interpret")
        masked = jnp.where(valid[None, :], ranks.astype(jnp.uint32), 0)
    else:
        def rank_one(lc, rt_c):
            safe = jnp.where(valid, lc, 0)
            return rt_c.reshape(-1)[safe].astype(jnp.uint32)

        masked = jnp.where(valid[None, :],
                           jax.vmap(rank_one)(l, rt), 0)
    rows = jax.lax.psum(count, AXIS)
    return masked, ulo, gk, rows


@functools.partial(jax.jit, static_argnames=("mesh", "width", "nhi", "pack"))
def _sharded_bounded_impl(lo, counts, *, mesh: Mesh, width: int, nhi: int,
                          pack: str):
    def kernel(l, c):
        masked, ulo, gk, rows = _bounded_merge_core(l, c, nhi=nhi, pack=pack)
        packed = jax.vmap(lambda m: bitpack_device(m, width))(masked)
        ovf = jnp.max((gk > (1 << width)).astype(jnp.int32))
        return packed, ulo, gk, rows, ovf

    fn = jax.shard_map(
        kernel, mesh=mesh,
        in_specs=(P(None, AXIS), P(AXIS)),
        out_specs=(P(None, AXIS), P(), P(), P(), P()),
        check_vma=False,  # replicated-by-construction, as in dict_merge
    )
    return fn(lo, counts)


@functools.partial(jax.jit, static_argnames=("mesh", "nhi", "pack"))
def _bounded_indices_impl(lo, counts, *, mesh: Mesh, nhi: int, pack: str):
    """The production-route variant: same psum merge, but the per-row
    dictionary indices come back RAW (uint32, sharded) instead of
    bit-packed — the writer's native page assembly owns the pack."""
    fn = jax.shard_map(
        lambda l, c: _bounded_merge_core(l, c, nhi=nhi, pack=pack),
        mesh=mesh,
        in_specs=(P(None, AXIS), P(AXIS)),
        out_specs=(P(None, AXIS), P(), P(), P()),
        check_vma=False,  # replicated-by-construction, as in dict_merge
    )
    return fn(lo, counts)


def bounded_psum_payload_bytes(value_bound: int) -> int:
    """The histogram-psum merge's per-column ICI payload: the BUCKETED
    bin-count matrix, nhi*64*4 bytes with nhi the smallest bucket
    covering the bound — constant in rows/shard and cardinality."""
    for nhi in _MATMUL_NHI_BUCKETS:
        if nhi * 64 >= int(value_bound):
            return nhi * 64 * 4
    raise ValueError(f"value_bound={value_bound} exceeds "
                     f"{_MATMUL_MAX_BOUND}")


def sharded_encode_step_bounded(lo, counts, *, mesh: Mesh, width: int = 16,
                                value_bound: int):
    """The mesh encode step for planner-bounded 32-bit columns
    (``value_bound`` <= 2^13, globally valid across every shard — derive
    it from psum'd stats, never a guess): the global dictionary merge is
    literally ONE ``psum`` of per-shard bin-count histograms (the
    BASELINE config-4 north star, "psum-based global dictionary merge"),
    so the ICI payload is a CONSTANT :func:`bounded_psum_payload_bytes`
    = bucketed nhi*64*4 bytes per column — independent of rows per shard
    AND of the cardinality k, vs the two-phase gather's
    ``pad_bucket(k_max)``-proportional payload (parallel.dict_merge).
    Presence/rank-table/dictionary then derive identically on every
    shard (ops.pallas_rank.presence_to_dict); on TPU meshes the local
    histogram and rank extraction run the VMEM-fused Pallas kernels,
    with an exact int8-matmul/table-lookup XLA fallback elsewhere.

    Returns (packed (C, N*width//8) sharded, gdict (C, bucketed vb)
    uint32 ascending-unique-padded, gk (C,), rows, overflow) —
    dictionary and indices bit-identical to :func:`sharded_encode_step`
    with ``has_hi=False`` on the same data."""
    from ..ops.packing import use_pallas

    if int(value_bound) > _MATMUL_MAX_BOUND:
        raise ValueError(f"value_bound={value_bound} exceeds the "
                         f"histogram-psum design bound {_MATMUL_MAX_BOUND}")
    n_local = lo.shape[1] // max(mesh.shape[AXIS], 1)
    # the kernels run on per-shard slices: size the Pallas heuristic by the
    # per-shard batch, not the global one (ADVICE r4 — on a large mesh the
    # global size can clear the minimum while each shard's slice is tiny)
    pal, interp = use_pallas(lo.shape[0] * n_local)
    pack = ("interpret" if pal and interp else "pallas" if pal else "xla")
    if n_local % 128:
        pack = "xla"  # kernel layout needs whole lane rows per shard
    for nhi in _MATMUL_NHI_BUCKETS:
        if nhi * 64 >= int(value_bound):
            return _sharded_bounded_impl(lo, counts, mesh=mesh, width=width,
                                         nhi=nhi, pack=pack)
    raise AssertionError("unreachable: buckets cover the design bound")


def bounded_global_dictionary_encode(values, mesh: Mesh, *, vmin: int,
                                     stride: int, value_bound: int,
                                     dispatch_lock=None,
                                     stats_out: dict | None = None,
                                     trusted: bool = False):
    """Writer-reachable histogram-psum dictionary merge (VERDICT r4 next
    #2): the production counterpart of
    ``dict_merge.global_dictionary_encode`` for planner-bounded integer
    columns — ``(values - vmin) / stride`` lies in ``[0, value_bound)``
    with ``value_bound <= 2^13`` (derive vmin/stride/bound from the fused
    native min/max/gcd stats pass, ops.dictionary._int_stats — never a
    guess: a violated bound silently corrupts the histogram).

    The global merge is ONE ``psum`` of per-shard bin-count histograms —
    a CONSTANT :func:`bounded_psum_payload_bytes` per column over ICI,
    independent of rows/shard and cardinality, vs the gather route's
    ``pad_bucket(k_max)``-proportional payload.  Returns
    (dict_values ascending, indices) as host arrays, byte-identical to
    the gather merge and the host backends: offsets are non-negative, so
    ascending offset order IS ascending bit-pattern order of the
    reconstructed ``vmin + stride * offset`` values (callers guard
    ``vmin >= 0`` for exactly this reason).

    ``stats_out`` accumulates ``bounded_columns`` /
    ``bounded_psum_bytes`` next to the gather route's keys so the cfg4
    artifact records which merge each column rode."""
    import contextlib

    import numpy as np

    from ..ops.packing import pad_bucket, use_pallas

    if int(value_bound) > _MATMUL_MAX_BOUND:
        raise ValueError(f"value_bound={value_bound} exceeds the "
                         f"histogram-psum design bound {_MATMUL_MAX_BOUND}")
    if int(vmin) < 0:
        # byte-identity depends on it: ascending offsets reconstruct to
        # ascending BIT-PATTERN order only for non-negative values (a
        # negative int64 sorts above the positives by bit pattern)
        raise ValueError(f"vmin={vmin} < 0: bounded route requires "
                         "non-negative values for bit-pattern dict order")
    arr = np.ascontiguousarray(values)
    n = len(arr)
    t = arr.dtype.type
    # ``trusted=True`` (the mesh encoder, whose vmin/stride/bound come
    # from the exact fused min/max/gcd stats pass) skips the two O(n)
    # defensive rescans — they would re-prove facts the caller just
    # derived; direct callers keep them, because a non-dividing stride or
    # violated bound silently corrupts the dictionary.
    if not trusted and stride > 1 and n and ((arr - t(vmin)) % t(stride)).any():
        raise ValueError(f"stride={stride} does not divide every "
                         f"(value - vmin): offsets would collide")
    offsets = (arr - t(vmin)) // t(stride)
    if not trusted and n and int(offsets.max()) >= int(value_bound):
        raise ValueError(
            f"max offset {int(offsets.max())} >= value_bound={value_bound}: "
            "a violated bound silently corrupts the histogram")
    n_shards = mesh.devices.size
    rows_per = max((n + n_shards - 1) // n_shards, 1)
    per = pad_bucket(rows_per)  # power of two >= 256: n_local % 128 == 0
    lo_p = np.zeros(n_shards * per, np.uint32)
    counts = np.zeros(n_shards, np.int32)
    for s in range(n_shards):
        a = s * rows_per
        take = max(0, min(rows_per, n - a))
        if take:
            lo_p[s * per : s * per + take] = offsets[a : a + take]
        counts[s] = take
    pal, interp = use_pallas(per)  # per-shard batch sizes the heuristic
    pack = "interpret" if pal and interp else "pallas" if pal else "xla"
    nhi = next(b for b in _MATMUL_NHI_BUCKETS if b * 64 >= int(value_bound))
    shard = NamedSharding(mesh, P(AXIS))
    with dispatch_lock if dispatch_lock is not None else contextlib.nullcontext():
        lo_d = jax.device_put(lo_p.reshape(1, -1),
                              NamedSharding(mesh, P(None, AXIS)))
        cnt_d = jax.device_put(counts, shard)
        idx_d, ulo_d, gk_d, rows_d = _bounded_indices_impl(
            lo_d, cnt_d, mesh=mesh, nhi=nhi, pack=pack)
        gk = int(jax.device_get(gk_d)[0])
        rows_i = int(jax.device_get(rows_d))
        ulo = np.asarray(ulo_d)[0]
        idx = np.asarray(idx_d)[0]
        if stats_out is not None:
            # inside the dispatch lock, like dict_merge's accounting: a
            # shared stats dict under concurrent workers must not take
            # unlocked read-modify-writes
            stats_out["bounded_columns"] = (
                stats_out.get("bounded_columns", 0) + 1)
            stats_out["bounded_psum_bytes"] = (
                stats_out.get("bounded_psum_bytes", 0) + nhi * 64 * 4)
            stats_out["bounded_nhi_max"] = max(
                stats_out.get("bounded_nhi_max", 0), nhi)
    assert rows_i == n
    dict_values = (ulo[:gk].astype(np.uint64) * np.uint64(stride)
                   + np.uint64(vmin)).astype(arr.dtype)
    parts = [idx[s * per : s * per + int(counts[s])] for s in range(n_shards)]
    out_idx = np.concatenate(parts) if parts else np.zeros(0, np.uint32)
    return dict_values, out_idx


# Static pack-width buckets for the device kernels: a fully static program
# per (batch bucket, width) pair, so lifting the old fixed-16 cap costs at
# most 5 extra compiles, not one per cardinality.
_WIDTH_BUCKETS = (16, 20, 24, 28, 32)


def index_width_bucket(k_bound: int) -> int:
    """Smallest static width bucket whose bit budget covers dictionary
    indices 0..k_bound-1.  Pass the ROW COUNT N: ``encode_step_single``
    guards on N <= 2**width (k <= N always holds, and the kernel cannot
    verify a tighter data-dependent cardinality bound statically — a wrong
    one would silently wrap the pack)."""
    need = max((max(k_bound, 1) - 1).bit_length(), 1)
    for w in _WIDTH_BUCKETS:
        if need <= w:
            return w
    raise ValueError(f"dictionary indices need {need} bits; max is 32")


def encode_step_single(lo, count, width: int = 16, value_bound: int | None = None):
    """Single-chip flagship forward step: vmapped dictionary build + index
    bit-pack over a (C, N) batch of 32-bit column keys.  ``width`` is the
    static pack width (pick it with :func:`index_width_bucket` from any
    host-known cardinality bound); N is bounded only by ``2**width`` —
    indices are dictionary slots < k <= N, so N <= 2**width guarantees the
    pack never wraps, at any row count or cardinality.

    ``value_bound`` is an optional *static* host-known exclusive upper bound
    on the VALID values (e.g. ``vmax - vmin + 1`` after the caller bias-
    subtracts the column minimum — kpw's planner knows min/max from its
    stats pass).  Bounds <= 2^13 leave the comparator network entirely:
    the build becomes a histogram + rank extraction on the MXU
    (:func:`_encode_step_single_matmul`, fused Pallas kernels in
    ops.pallas_rank — measured ~2x the packed sort at the 16-col 64Ki
    13-bit shape).  Wider bounds keep the sort formulation: when
    ``value_bits + pos_bits <= 32`` the build sort collapses to ONE
    single-operand u32 sort of ``(value << pos_bits) | pos`` (stability is
    free: the unique position is the tiebreak), and the dictionary
    compaction sorts narrow u16 when the bound fits 16 bits — together the
    two widest data movements through the v5e comparator network roughly
    halve (VERDICT r3 next #1: sub-32-bit sort keys).  Output is
    bit-identical to the unbounded path either way; a wrong bound (a valid
    value >= value_bound) silently corrupts the build, so callers must
    derive it from a real scan.

    Fused build: because the dictionary IS the unique set of these same
    values, ranking falls out of the build sort.  One variadic sort of
    (value, position) does the build; the two derived reorders then ride
    XLA's SINGLE-OPERAND sort fast path instead of variadic sorts
    (measured on v5e: each variadic sort of (key, payload) at 64x65Ki
    costs ~4.2 ms where the build sort costs 2.3 — the payload plane
    roughly doubles the comparator network's data movement):

    - dictionary: ascending uniques are extracted by sorting
      ``where(is_new, value, MAX)`` alone — the k uniques land in the
      first k slots in ascending order (a real 0xFFFFFFFF value is always
      the LAST unique, so colliding with the pad sentinel still places it
      correctly at slot k-1);
    - unscramble: position and slot id pack into ONE uint32 key
      ``(pos << width) | uid`` whenever position bits + width <= 32
      (positions are unique, so sorting the packed key sorts by position
      and the low bits come back as the row-ordered indices); wider
      shapes fall back to the variadic sort.

    On TPU with enough work the final bit-pack runs as the Pallas Mosaic
    kernel over the whole (C, N) batch (ops.pallas_bitpack: VMEM-resident
    bit expand + MXU byte fold); otherwise the fused-XLA pack.

    ``packed``, ``k`` and ``ulo[:k]`` are identical to composing
    ``_local_unique(cap=n)`` + ``_rank_against_dict``; the ``ulo[k:]`` pad
    region is unspecified (pad sentinels — do not read past k).  No
    gathers or scatters anywhere (TPU vector units, see
    default_rank_method).

    The pack-backend choice (use_pallas: env + platform + batch size) is
    made HERE, outside the jit, and baked into a separately-compiled
    variant per choice — so flipping KPW_PALLAS between calls re-selects
    the kernel instead of silently reusing a stale cached executable
    (same dispatch pattern as ops.packing.pack_pages_multi)."""
    from ..ops.packing import use_pallas

    n = lo.shape[1]
    if n > (1 << width):
        raise ValueError(
            f"N={n} rows could hold up to {n} uniques, which do not fit "
            f"{width}-bit indices; pick width with index_width_bucket(N)")
    val_bits = None
    if value_bound is not None:
        vb = max(int(value_bound) - 1, 1).bit_length()
        if vb + max((n - 1).bit_length(), 1) <= 32:
            val_bits = vb  # else: bound too wide to pack; standard path
    pal, interp = use_pallas(lo.shape[0] * n)
    pack = ("interpret" if pal and interp else "pallas" if pal else "xla")
    if (value_bound is not None and int(value_bound) <= _MATMUL_MAX_BOUND
            and pack != "xla" and n % 128 == 0):
        # sort-free histogram+rank path (ops.pallas_rank): measured 0.92
        # vs the sort formulation's 1.80 ms/step at the 16-col 64Ki-row
        # 13-bit probe shape.  nhi buckets bound the compile count.
        for nhi in _MATMUL_NHI_BUCKETS:
            if nhi * 64 >= int(value_bound):
                return _encode_step_single_matmul(lo, count, width=width,
                                                  pack=pack, nhi=nhi)
    return _encode_step_single_impl(lo, count, width=width, pack=pack,
                                    val_bits=val_bits)


# The matmul dictionary path serves planner-bounded values <= 2^13 (the
# gcd-stride/affine offsets and any narrow-range column); nhi = padded
# value_bound/64 buckets to a fixed set so jit compiles stay bounded.
_MATMUL_MAX_BOUND = 1 << 13
_MATMUL_NHI_BUCKETS = (8, 32, 128)


@functools.partial(jax.jit, static_argnames=("width", "pack", "nhi"))
def _encode_step_single_matmul(lo, count, width: int, pack: str, nhi: int):
    """Sort-free variant of :func:`_encode_step_single_impl` for values
    with a static bound <= 2^13 (see ops.pallas_rank for the layout and
    exactness story): a fused Pallas histogram over (hi, lo6)-decomposed
    one-hot matmuls yields presence -> dictionary (ascending present bin
    values, one TINY 8192-bin sort per column instead of a 64Ki-row one)
    and a rank table; a second fused kernel extracts per-row ranks.
    Output contract identical to the sort path: (packed, ulo (C, N) with
    [k:] unspecified pad, k)."""
    from ..ops.pallas_rank import (S_LO, hist_pages_core, presence_to_dict,
                                   rank_pages_core)

    n = lo.shape[1]
    vb = nhi * S_LO
    big = jnp.uint32(0xFFFFFFFF)
    iota = jnp.arange(n, dtype=jnp.int32)
    valid = iota < count
    interp = pack == "interpret"
    lo_masked = jnp.where(valid[None, :], lo, jnp.uint32(vb))
    counts = hist_pages_core(lo_masked, nhi, interpret=interp)
    rt, ulo_v, k = presence_to_dict(counts, nhi)
    ranks = rank_pages_core(lo_masked, rt, interpret=interp).astype(jnp.uint32)
    masked = jnp.where(valid[None, :], ranks, 0)
    # contract shape (C, n): k <= min(count, vb) uniques always fit
    if vb < n:
        pad = jnp.full((ulo_v.shape[0], n - vb), big)
        ulo = jnp.concatenate([ulo_v, pad], axis=1)
    else:
        ulo = ulo_v[:, :n]
    # the dispatch gate guarantees a pallas pack mode (pack != "xla")
    from ..ops.pallas_bitpack import bitpack_pages_core

    packed = bitpack_pages_core(masked, width, interp)
    return packed, ulo, k


@functools.partial(jax.jit, static_argnames=("width", "pack", "val_bits"))
def _encode_step_single_impl(lo, count, width: int, pack: str,
                             val_bits: int | None = None):
    n = lo.shape[1]
    iota = jnp.arange(n, dtype=jnp.int32)
    valid = iota < count
    nvalid = jnp.sum(valid.astype(jnp.int32))
    big = jnp.uint32(0xFFFFFFFF)
    pos_bits = max((n - 1).bit_length(), 1)
    fast_unscramble = pos_bits + width <= 32

    def one_column(lc):
        if val_bits is not None:
            # Packed build sort: value and position share one u32 key, so
            # the build rides XLA's single-operand fast path and is stable
            # by construction (positions are unique).  Invalid slots lift
            # to the max key; a VALID key can only equal the sentinel when
            # value == value_bound-1 at pos == n-1 with the bits exactly
            # filling 32 — and pos n-1 being valid means count == n, i.e.
            # no invalid slots exist to collide with.
            key = jnp.where(valid,
                            (lc << pos_bits) | iota.astype(jnp.uint32), big)
            s = jnp.sort(key)
            slo = s >> pos_bits
            spos = (s & jnp.uint32((1 << pos_bits) - 1)).astype(jnp.int32)
        else:
            llo = jnp.where(valid, lc, big)  # invalids sort to the tail
            # is_stable is load-bearing: a VALID value whose bit pattern
            # equals the 0xFFFFFFFF pad sentinel (int -1, some NaNs) ties
            # with the pads, and the prefix-validity claim below
            # (sval = iota < nvalid) holds only if stability keeps the
            # valid entries (earlier input positions) ahead of the pads on
            # that tie.
            slo, spos = jax.lax.sort((llo, iota), num_keys=1, is_stable=True)
        sval = iota < nvalid
        same = jnp.concatenate(
            [jnp.zeros((1,), bool), slo[1:] == slo[:-1]])
        is_new = sval & ~same
        k = jnp.sum(is_new.astype(jnp.int32))
        uid = jnp.cumsum(is_new.astype(jnp.int32)) - 1
        # dictionary by single-operand sort (see docstring); with a 16-bit
        # value bound the compaction sorts HALF the comparator payload as
        # u16 (the pad sentinel shrinks with it: a real 0xFFFF value is
        # still the last unique, so sharing its bit pattern with the pads
        # still places it at slot k-1)
        if val_bits is not None and val_bits <= 16:
            ulo = jnp.sort(jnp.where(is_new, slo, big).astype(jnp.uint16)
                           ).astype(jnp.uint32)
        else:
            ulo = jnp.sort(jnp.where(is_new, slo, big))
        if fast_unscramble:
            indices, _ = packed_reorder(spos, uid, width)
        else:
            _, indices = jax.lax.sort((spos, uid), num_keys=1)
            indices = indices.astype(jnp.uint32)
        return jnp.where(valid, indices, 0), ulo, k

    masked, ulo, k = jax.vmap(one_column)(lo)
    if pack != "xla":
        from ..ops.pallas_bitpack import bitpack_pages_core

        packed = bitpack_pages_core(masked, width, pack == "interpret")
    else:
        packed = jax.vmap(lambda m: bitpack_device(m, width))(masked)
    return packed, ulo, k
