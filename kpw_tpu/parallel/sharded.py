"""The full sharded encode step: data-parallel rows, collective dictionary.

This is the TPU-native equivalent of the reference's whole hot loop
(KafkaProtoParquetWriter.java:253-292) at multi-chip scale: a (columns, rows)
batch is sharded over the ``shard`` mesh axis (rows = records polled from the
shards' Kafka partitions), every column is dictionary-encoded against a
mesh-global dictionary (all_gather/psum over ICI, see dict_merge), and the
dictionary indices are bit-packed on device.  One jitted program; XLA
schedules the collectives.

``encode_step_single`` is the single-chip flagship forward step used by
``__graft_entry__.entry`` — identical math minus the collectives.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.packing import bitpack_device
from .dict_merge import AXIS, _local_unique, _merge_kernel, _rank_against_dict


@functools.partial(jax.jit, static_argnames=("mesh", "cap", "width", "has_hi"))
def sharded_encode_step(hi, lo, counts, *, mesh: Mesh, cap: int = 4096,
                        width: int = 16, has_hi: bool = True):
    """One SPMD encode step.

    hi, lo: (C, N) uint32 key halves, sharded over rows (N) across the mesh;
    counts: (n_shards,) valid rows per shard.  Returns per-shard packed index
    bytes (C, N*width//8 sharded), per-column global dictionaries (replicated
    (C, G) key halves + (C,) sizes), the psum'd global row count, and an
    overflow indicator.  Pass ``has_hi=False`` when the hi plane is
    statically zero (32-bit column dtypes): sorts and searches then run
    single-key, the CPU-mesh fast path and one less gather on ICI.
    """

    def kernel(h, l, c):
        count = c[0]

        def one_column(hc, lc):
            indices, mhi, mlo, gk, rows, ovf = _merge_kernel(
                hc, lc, count, cap, has_hi=has_hi)
            n = indices.shape[0]
            masked = jnp.where(jnp.arange(n, dtype=jnp.int32) < count, indices, 0)
            packed = bitpack_device(masked, width)
            # indices wider than `width` bits would silently wrap in the pack
            ovf = ovf + (gk > (1 << width)).astype(jnp.int32)
            return packed, mhi, mlo, gk, rows, ovf

        packed, mhi, mlo, gk, rows, ovf = jax.vmap(one_column)(h, l)
        return packed, mhi, mlo, gk, rows[0], jnp.max(ovf)

    fn = jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(None, AXIS), P(None, AXIS), P(AXIS)),
        out_specs=(P(None, AXIS), P(), P(), P(), P(), P()),
        check_vma=False,  # replicated-by-construction outputs, as in dict_merge
    )
    return fn(hi, lo, counts)


# Static pack-width buckets for the device kernels: a fully static program
# per (batch bucket, width) pair, so lifting the old fixed-16 cap costs at
# most 5 extra compiles, not one per cardinality.
_WIDTH_BUCKETS = (16, 20, 24, 28, 32)


def index_width_bucket(k_bound: int) -> int:
    """Smallest static width bucket whose bit budget covers dictionary
    indices 0..k_bound-1.  Pass the ROW COUNT N: ``encode_step_single``
    guards on N <= 2**width (k <= N always holds, and the kernel cannot
    verify a tighter data-dependent cardinality bound statically — a wrong
    one would silently wrap the pack)."""
    need = max((max(k_bound, 1) - 1).bit_length(), 1)
    for w in _WIDTH_BUCKETS:
        if need <= w:
            return w
    raise ValueError(f"dictionary indices need {need} bits; max is 32")


@functools.partial(jax.jit, static_argnames=("width",))
def encode_step_single(lo, count, width: int = 16):
    """Single-chip flagship forward step: vmapped dictionary build + index
    bit-pack over a (C, N) batch of 32-bit column keys.  ``width`` is the
    static pack width (pick it with :func:`index_width_bucket` from any
    host-known cardinality bound); N is bounded only by ``2**width`` —
    indices are dictionary slots < k <= N, so N <= 2**width guarantees the
    pack never wraps, at any row count or cardinality.

    Fused build: because the dictionary IS the unique set of these same
    values, ranking falls out of the build sort — three sorts of N
    (value+position, rank compaction, position unscramble) replace the
    sharded path's unique-then-rank composition (a sort of N plus two
    sorts of 2N).  ``packed``, ``k`` and ``ulo[:k]`` are identical to
    composing ``_local_unique(cap=n)`` + ``_rank_against_dict``; the
    ``ulo[k:]`` pad region is unspecified (leftover sorted duplicates and
    lifted-max sentinels — do not read past k).  No gathers or scatters
    anywhere (TPU vector units, see default_rank_method)."""
    n = lo.shape[1]
    if n > (1 << width):
        raise ValueError(
            f"N={n} rows could hold up to {n} uniques, which do not fit "
            f"{width}-bit indices; pick width with index_width_bucket(N)")
    iota = jnp.arange(n, dtype=jnp.int32)
    valid = iota < count
    nvalid = jnp.sum(valid.astype(jnp.int32))
    big = jnp.uint32(0xFFFFFFFF)

    def one_column(lc):
        llo = jnp.where(valid, lc, big)  # invalids sort to the tail
        # is_stable is load-bearing: a VALID value whose bit pattern equals
        # the 0xFFFFFFFF pad sentinel (int -1, some NaNs) ties with the
        # pads, and the prefix-validity claim below (sval = iota < nvalid)
        # holds only if stability keeps the valid entries (earlier input
        # positions) ahead of the pads on that tie.
        slo, spos = jax.lax.sort((llo, iota), num_keys=1, is_stable=True)
        sval = iota < nvalid
        same = jnp.concatenate(
            [jnp.zeros((1,), bool), slo[1:] == slo[:-1]])
        is_new = sval & ~same
        k = jnp.sum(is_new.astype(jnp.int32))
        uid = jnp.cumsum(is_new.astype(jnp.int32)) - 1
        # ascending sort => uid is the dictionary slot; compact the keys
        # to the front by one more sort on rank (pads rank n, tail)
        rank = jnp.where(is_new, uid, n)
        _, ulo = jax.lax.sort((rank, slo), num_keys=1)
        # unscramble: indices back to original row order, sort-not-scatter
        _, indices = jax.lax.sort((spos, uid), num_keys=1)
        masked = jnp.where(valid, indices.astype(jnp.uint32), 0)
        packed = bitpack_device(masked, width)
        return packed, ulo, k

    return jax.vmap(one_column)(lo)
