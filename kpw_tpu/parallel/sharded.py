"""The full sharded encode step: data-parallel rows, collective dictionary.

This is the TPU-native equivalent of the reference's whole hot loop
(KafkaProtoParquetWriter.java:253-292) at multi-chip scale: a (columns, rows)
batch is sharded over the ``shard`` mesh axis (rows = records polled from the
shards' Kafka partitions), every column is dictionary-encoded against a
mesh-global dictionary (all_gather/psum over ICI, see dict_merge), and the
dictionary indices are bit-packed on device.  One jitted program; XLA
schedules the collectives.

``encode_step_single`` is the single-chip flagship forward step used by
``__graft_entry__.entry`` — identical math minus the collectives.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.packing import bitpack_device
from .dict_merge import AXIS, _local_unique, _merge_kernel, _rank_against_dict


@functools.partial(jax.jit, static_argnames=("mesh", "cap", "width", "has_hi"))
def sharded_encode_step(hi, lo, counts, *, mesh: Mesh, cap: int = 4096,
                        width: int = 16, has_hi: bool = True):
    """One SPMD encode step.

    hi, lo: (C, N) uint32 key halves, sharded over rows (N) across the mesh;
    counts: (n_shards,) valid rows per shard.  Returns per-shard packed index
    bytes (C, N*width//8 sharded), per-column global dictionaries (replicated
    (C, G) key halves + (C,) sizes), the psum'd global row count, and an
    overflow indicator.  Pass ``has_hi=False`` when the hi plane is
    statically zero (32-bit column dtypes): sorts and searches then run
    single-key, the CPU-mesh fast path and one less gather on ICI.
    """

    def kernel(h, l, c):
        count = c[0]

        def one_column(hc, lc):
            indices, mhi, mlo, gk, rows, ovf = _merge_kernel(
                hc, lc, count, cap, has_hi=has_hi)
            n = indices.shape[0]
            masked = jnp.where(jnp.arange(n, dtype=jnp.int32) < count, indices, 0)
            packed = bitpack_device(masked, width)
            # indices wider than `width` bits would silently wrap in the pack
            ovf = ovf + (gk > (1 << width)).astype(jnp.int32)
            return packed, mhi, mlo, gk, rows, ovf

        packed, mhi, mlo, gk, rows, ovf = jax.vmap(one_column)(h, l)
        return packed, mhi, mlo, gk, rows[0], jnp.max(ovf)

    fn = jax.shard_map(
        kernel,
        mesh=mesh,
        in_specs=(P(None, AXIS), P(None, AXIS), P(AXIS)),
        out_specs=(P(None, AXIS), P(), P(), P(), P(), P()),
        check_vma=False,  # replicated-by-construction outputs, as in dict_merge
    )
    return fn(hi, lo, counts)


@jax.jit
def encode_step_single(lo, count):
    """Single-chip flagship forward step: vmapped dictionary build + index
    bit-pack over a (C, N) batch of 32-bit column keys.  Width fixed at 16
    (dictionaries capped at 65536 entries) so the program is fully static.

    Fused build: because the dictionary IS the unique set of these same
    values, ranking falls out of the build sort — three sorts of N
    (value+position, rank compaction, position unscramble) replace the
    sharded path's unique-then-rank composition (a sort of N plus two
    sorts of 2N).  ``packed``, ``k`` and ``ulo[:k]`` are identical to
    composing ``_local_unique(cap=n)`` + ``_rank_against_dict``; the
    ``ulo[k:]`` pad region is unspecified (leftover sorted duplicates and
    lifted-max sentinels — do not read past k).  No gathers or scatters
    anywhere (TPU vector units, see default_rank_method)."""
    n = lo.shape[1]
    if n > (1 << 16):
        raise ValueError("encode_step_single packs at 16 bits; N must be <= 65536")
    iota = jnp.arange(n, dtype=jnp.int32)
    valid = iota < count
    nvalid = jnp.sum(valid.astype(jnp.int32))
    big = jnp.uint32(0xFFFFFFFF)

    def one_column(lc):
        llo = jnp.where(valid, lc, big)  # invalids sort to the tail
        slo, spos = jax.lax.sort((llo, iota), num_keys=1)
        sval = iota < nvalid
        same = jnp.concatenate(
            [jnp.zeros((1,), bool), slo[1:] == slo[:-1]])
        is_new = sval & ~same
        k = jnp.sum(is_new.astype(jnp.int32))
        uid = jnp.cumsum(is_new.astype(jnp.int32)) - 1
        # ascending sort => uid is the dictionary slot; compact the keys
        # to the front by one more sort on rank (pads rank n, tail)
        rank = jnp.where(is_new, uid, n)
        _, ulo = jax.lax.sort((rank, slo), num_keys=1)
        # unscramble: indices back to original row order, sort-not-scatter
        _, indices = jax.lax.sort((spos, uid), num_keys=1)
        masked = jnp.where(valid, indices.astype(jnp.uint32), 0)
        packed = bitpack_device(masked, 16)
        return packed, ulo, k

    return jax.vmap(one_column)(lo)
