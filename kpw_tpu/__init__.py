"""kpw_tpu — TPU-native streaming Kafka→Parquet writer framework.

Built from scratch (JAX/XLA/Pallas for the encode path, C++ for host codecs)
with the capability surface of the reference Java library
``sahabpardaz/kafka-parquet-writer`` (see SURVEY.md): smart-commit Kafka
consumption with at-least-once delivery, multi-worker parquet writing with
size/time rotation and atomic publish — tmp→rename on rename-capable
sinks, multipart-complete on object stores (the publish protocol is a
capability of the target FileSystem, io/fs.py ``publish_file``) — and a
pluggable EncoderBackend (CPU numpy reference vs vmapped TPU kernels).
"""

__version__ = "0.1.0"

from .runtime import (  # noqa: E402,F401
    Builder,
    CallablePartitioner,
    EventTimePartitioner,
    FieldPartitioner,
    Gauge,
    KafkaProtoParquetWriter,
    MetricRegistry,
    MultiWriter,
    Partitioner,
    PublishVerificationError,
    RetryBudgetExceeded,
    RetryPolicy,
    SchemaIncompatibleError,
    TenantQuotaLedger,
    WriterFailedError,
    registry_to_json,
    registry_to_prometheus,
)
from .io.compact import Compactor  # noqa: E402,F401
from .ingest import (  # noqa: E402,F401
    FakeBroker,
    FaultInjectingBroker,
    KafkaBrokerClient,
    PartitionOffset,
    RecordBatch,
    SmartCommitConsumer,
)
from .io import (  # noqa: E402,F401
    BandwidthBudget,
    EmulatedObjectStore,
    FailoverFileSystem,
    FaultInjectingFileSystem,
    FaultSchedule,
    HdfsFileSystem,
    InjectedFault,
    LocalFileSystem,
    MemoryFileSystem,
    ObjectStoreFileSystem,
    objectstore_persona,
)
