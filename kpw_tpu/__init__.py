"""kpw_tpu — TPU-native streaming Kafka→Parquet writer framework.

Built from scratch (JAX/XLA/Pallas for the encode path, C++ for host codecs)
with the capability surface of the reference Java library
``sahabpardaz/kafka-parquet-writer`` (see SURVEY.md): smart-commit Kafka
consumption with at-least-once delivery, multi-worker parquet writing with
size/time rotation and atomic tmp→rename publish, and a pluggable
EncoderBackend (CPU numpy reference vs vmapped TPU kernels).
"""

__version__ = "0.1.0"

from .runtime import (  # noqa: E402,F401
    Builder,
    CallablePartitioner,
    EventTimePartitioner,
    FieldPartitioner,
    Gauge,
    KafkaProtoParquetWriter,
    MetricRegistry,
    Partitioner,
    PublishVerificationError,
    RetryBudgetExceeded,
    RetryPolicy,
    WriterFailedError,
    registry_to_json,
    registry_to_prometheus,
)
from .io.compact import Compactor  # noqa: E402,F401
from .ingest import (  # noqa: E402,F401
    FakeBroker,
    FaultInjectingBroker,
    KafkaBrokerClient,
    PartitionOffset,
    RecordBatch,
    SmartCommitConsumer,
)
from .io import (  # noqa: E402,F401
    FailoverFileSystem,
    FaultInjectingFileSystem,
    FaultSchedule,
    HdfsFileSystem,
    InjectedFault,
    LocalFileSystem,
    MemoryFileSystem,
)
