"""In-process broker: partitioned append logs + consumer groups + committed
offsets.

Test-infra analog of the reference's embedded ``KafkaRule`` broker
(KafkaProtoParquetWriterTest.java:58-59) promoted to a first-class component:
the framework's default record source in tests and benchmarks, and the
interface a real Kafka wire client can implement later.  Scale-out data
parallelism (multiple writer instances sharing a consumer group —
KafkaProtoParquetWriter.java:72-76) is modeled with range partition
assignment and rebalance-on-membership-change.

Storage is batch-native: each partition log is ONE contiguous payload
buffer plus a record-offset table (record i = ``buf[offs[i]:offs[i+1]]``),
guarded by its own lock — the wire-page layout a real broker hands a fetch
response in.  ``fetch_batch`` returns that layout directly as a
:class:`RecordBatch` (one buffer copy per batch, no per-record objects);
``fetch`` is the compatibility surface that materializes one frozen
:class:`Record` dataclass per payload, the per-record cost the batch path
exists to avoid.  Group membership / committed offsets stay under one
metadata lock; produce/fetch never contend across partitions.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import numpy as np

from ..utils import schedcheck


class StaleGenerationError(RuntimeError):
    """A commit was rejected by the group-coordination fence: it came from
    a member that no longer owns the partition (expired, superseded, or
    carrying a generation the coordinator never issued).  The committer is
    a zombie — paused or partitioned through a rebalance while another
    instance took over.  Typed, and deliberately NOT an OSError: the IO
    retry loop must not spin on it — the only correct reaction is to drop
    the in-flight state and rejoin the group."""


@dataclass(frozen=True)
class Record:
    topic: str
    partition: int
    offset: int
    key: bytes | None
    value: bytes
    timestamp: float = 0.0


class RecordBatch:
    """Batch-native ingest handoff: ``count`` serialized payloads in one
    contiguous immutable buffer plus an int64 offset table (record i =
    ``payload[offsets[i]:offsets[i+1]]``; ``offsets[0]`` may be nonzero —
    a :meth:`slice` shares the parent's buffer) and the
    ``(partition, start_offset, count)`` run metadata the run-native ack
    machinery (``poll_many_runs``/``ack_run``) consumes directly.

    Offsets within a batch are contiguous BY CONTRACT (``start_offset + i``
    is record i's offset): a source with offset gaps (a compacted real
    topic) must deliver per-record ``Record`` lists instead — the batch
    run shortcut would otherwise ack offsets that were never delivered.
    Record keys do not ride the batch path (the writer never reads them);
    :meth:`to_records` materializes keyless Records for the per-record
    compatibility route.
    """

    __slots__ = ("topic", "partition", "start_offset", "payload", "offsets",
                 "timestamp")

    def __init__(self, topic: str, partition: int, start_offset: int,
                 payload: bytes, offsets: np.ndarray,
                 timestamp: float = 0.0) -> None:
        self.topic = topic
        self.partition = partition
        self.start_offset = start_offset
        self.payload = payload
        self.offsets = offsets  # int64, len == count + 1, ascending
        self.timestamp = timestamp

    def __len__(self) -> int:
        return len(self.offsets) - 1

    @property
    def run(self) -> tuple[int, int, int]:
        """The batch as one contiguous (partition, start_offset, count)
        ack run."""
        return (self.partition, self.start_offset, len(self))

    def payload_at(self, i: int) -> bytes:
        o = self.offsets
        return self.payload[int(o[i]): int(o[i + 1])]

    def slice(self, start: int, count: int) -> "RecordBatch":
        """Zero-copy window [start, start+count): shares the payload
        buffer, the offset table is a numpy view."""
        return RecordBatch(self.topic, self.partition,
                           self.start_offset + start, self.payload,
                           self.offsets[start: start + count + 1],
                           self.timestamp)

    def to_records(self) -> list[Record]:
        """Materialize per-record frozen ``Record`` dataclasses — the
        compatibility/fallback route (poison-pill reparse, dead-letter)."""
        o, pl = self.offsets, self.payload
        t, p, base, ts = (self.topic, self.partition, self.start_offset,
                          self.timestamp)
        return [Record(t, p, base + i, None, pl[int(o[i]): int(o[i + 1])], ts)
                for i in range(len(o) - 1)]


class _PartitionLog:
    """One partition's contiguous append log, under its own lock.  The
    offset table is a growable int64 numpy array (``offs[0..n]`` valid)
    so a fetch slices it in C instead of converting a Python list per
    batch."""

    __slots__ = ("lock", "buf", "offs", "n", "keys", "ts")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.buf = bytearray()
        self.offs = np.zeros(64, np.int64)  # byte offsets; offs[0..n] valid
        self.n = 0  # record count
        self.keys: dict[int, bytes] = {}  # record offset -> key (sparse)
        self.ts: list[float] = []

    def _ensure(self, extra: int) -> None:
        need = self.n + 1 + extra
        if need > len(self.offs):
            new = np.empty(max(need, 2 * len(self.offs)), np.int64)
            new[: self.n + 1] = self.offs[: self.n + 1]
            self.offs = new

    def append_one(self, value: bytes, key, now: float) -> int:
        with self.lock:
            self._ensure(1)
            off = self.n
            self.buf += value
            self.offs[off + 1] = self.offs[off] + len(value)
            self.n = off + 1
            self.ts.append(now)
            if key is not None:
                self.keys[off] = key
            return off

    def append_many(self, values, now: float) -> tuple[int, int]:
        """One lock round for the whole batch; returns (first_offset, n)."""
        if not values:
            return self.n, 0
        lens = np.fromiter(map(len, values), np.int64, count=len(values))
        blob = b"".join(values)
        with self.lock:
            first = self.n
            self._ensure(len(values))
            self.buf += blob
            base = self.offs[first]
            np.cumsum(lens, out=self.offs[first + 1: first + 1 + len(values)])
            self.offs[first + 1: first + 1 + len(values)] += base
            self.n = first + len(values)
            self.ts.extend([now] * len(values))
            return first, len(values)


class FakeBroker:
    """Thread-safe in-memory broker (sharded per-partition log locks).

    With ``session_timeout_s`` set the broker runs the full group
    coordination protocol (ISSUE 18): members heartbeat to stay live, a
    missed session window expels them, every membership change bumps the
    group **generation**, partitions moving between two live members pass
    through a cooperative **drain window** (withheld from the new owner
    until the old owner confirms revocation or ``revocation_drain_s``
    lapses), and commits carrying a member identity are **fenced** — a
    zombie's stale commit raises :class:`StaleGenerationError` instead of
    clobbering the new owner's offset state.  ``session_timeout_s=None``
    (the default) keeps the legacy instant-reassignment broker: no expiry,
    no drain windows, unfenced commits.
    """

    def __init__(self, session_timeout_s: float | None = None,
                 revocation_drain_s: float = 5.0) -> None:
        # metadata lock: topic map shape, consumer groups, committed
        # offsets, the round-robin cursor.  Payload appends/reads take only
        # the owning partition's log lock.
        self._lock = threading.RLock()
        self._logs: dict[str, list[_PartitionLog]] = {}
        self._committed: dict[tuple[str, str, int], int] = {}  # (group, topic, part) -> next offset
        self._groups: dict[tuple[str, str], list[str]] = {}  # (group, topic) -> member ids
        self._generation: dict[tuple[str, str], int] = {}
        self._rr = 0
        # group coordination: heartbeat stamps (monotonic — liveness
        # bookkeeping must not expire members on a wall-clock step),
        # in-drain partitions awaiting cooperative handoff
        # (partition -> {owner, deadline, old_gen}), and the per-group
        # protocol counters group_stats() reports.
        self.session_timeout_s = session_timeout_s
        self.revocation_drain_s = revocation_drain_s
        self._hb: dict[tuple[str, str], dict[str, float]] = {}
        self._revoking: dict[tuple[str, str], dict[int, dict]] = {}
        self._fenced: dict[tuple[str, str], int] = {}
        self._rebalances: dict[tuple[str, str], int] = {}
        self._expired: dict[tuple[str, str], int] = {}

    # -- topics / produce --------------------------------------------------
    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            if topic in self._logs:
                raise ValueError(f"topic exists: {topic}")
            self._logs[topic] = [_PartitionLog() for _ in range(partitions)]

    def num_partitions(self, topic: str) -> int:
        with self._lock:
            return len(self._logs[topic])

    def _route(self, topic: str, key: bytes | None, partition: int | None,
               advance_rr: int = 1) -> tuple[list[_PartitionLog], int, int]:
        """Resolve (logs, partition, rr_base) under the metadata lock;
        auto-creates a 1-partition topic on first produce."""
        with self._lock:
            if topic not in self._logs:
                self._logs[topic] = [_PartitionLog()]
            parts = self._logs[topic]
            rr_base = self._rr
            if partition is None:
                if key is not None:
                    partition = hash(key) % len(parts)
                else:
                    partition = self._rr % len(parts)
                    self._rr += advance_rr
            return parts, partition, rr_base

    def produce(self, topic: str, value: bytes, key: bytes | None = None,
                partition: int | None = None) -> tuple[int, int]:
        parts, partition, _ = self._route(topic, key, partition)
        return partition, parts[partition].append_one(value, key, time.time())

    def produce_many(self, topic: str, values,
                     partition: int | None = None) -> dict[int, tuple[int, int]]:
        """Append a whole batch of payloads with ONE lock round per
        partition touched (vs one per record via :meth:`produce`) — the
        topic-priming fast path for benchmarks and chaos tests.

        ``partition=None`` stripes round-robin exactly like a
        ``produce()`` loop would (value i lands on partition
        ``(rr + i) % n``), so indexed-identity checks built on the loop's
        placement hold unchanged.  Returns ``{partition: (first_offset,
        count)}``."""
        values = list(values)
        if not values:
            return {}
        parts, part0, rr_base = self._route(topic, None, partition,
                                            advance_rr=len(values))
        now = time.time()
        if partition is not None or len(parts) == 1:
            first, n = parts[part0].append_many(values, now)
            return {part0: (first, n)}
        out: dict[int, tuple[int, int]] = {}
        nparts = len(parts)
        for i in range(nparts):
            p = (rr_base + i) % nparts
            sub = values[i::nparts]
            if sub:
                out[p] = parts[p].append_many(sub, now)
        return out

    # -- fetch -------------------------------------------------------------
    def fetch(self, topic: str, partition: int, offset: int,
              max_records: int = 500) -> list[Record]:
        """Per-record compatibility fetch: materializes one frozen
        ``Record`` per payload (the cost :meth:`fetch_batch` avoids)."""
        with self._lock:
            parts = self._logs.get(topic)
            if parts is None or partition >= len(parts):
                return []
            log = parts[partition]
        with log.lock:
            if offset >= log.n:
                return []
            j = min(offset + max_records, log.n)
            mv = memoryview(log.buf)
            offs = log.offs
            keys, ts = log.keys, log.ts
            return [Record(topic, partition, i, keys.get(i),
                           bytes(mv[offs[i]: offs[i + 1]]), ts[i])
                    for i in range(offset, j)]

    def fetch_batch(self, topic: str, partition: int, offset: int,
                    max_records: int = 2000) -> RecordBatch | None:
        """Batch-native fetch: up to ``max_records`` payloads as ONE
        contiguous buffer + offset table (a single copy out of the log
        page, no per-record object construction).  Returns None when
        nothing is available at ``offset``."""
        with self._lock:
            parts = self._logs.get(topic)
            if parts is None or partition >= len(parts):
                return None
            log = parts[partition]
        with log.lock:
            if offset >= log.n:
                return None
            j = min(offset + max_records, log.n)
            a = int(log.offs[offset])
            payload = bytes(memoryview(log.buf)[a: int(log.offs[j])])
            offsets = log.offs[offset: j + 1].copy()  # C slice copy
            ts = log.ts[offset]
        if a:
            offsets -= a
        return RecordBatch(topic, partition, offset, payload, offsets, ts)

    def end_offset(self, topic: str, partition: int) -> int:
        with self._lock:
            log = self._logs[topic][partition]
        with log.lock:
            return log.n

    # -- consumer groups ---------------------------------------------------
    @staticmethod
    def _range_map(members: list[str], n_parts: int) -> dict[int, str]:
        """partition -> owner under range assignment (``members`` already
        sorted) — the single source of truth :meth:`assignment` and the
        commit fence share."""
        out: dict[int, str] = {}
        if not members:
            return out
        per = n_parts // len(members)
        extra = n_parts % len(members)
        for idx, m in enumerate(members):
            start = idx * per + min(idx, extra)
            count = per + (1 if idx < extra else 0)
            for p in range(start, start + count):
                out[p] = m
        return out

    def _owner_map(self, key: tuple[str, str]) -> dict[int, str]:
        group, topic = key
        if topic not in self._logs:
            return {}
        return self._range_map(sorted(self._groups.get(key, [])),
                               len(self._logs[topic]))

    def _membership_changed(self, key: tuple[str, str],
                            old_members: list[str]) -> None:
        """Caller holds the lock; membership already mutated.  Bump the
        generation and diff the old/new range maps: a partition moving
        between two LIVE members enters a cooperative drain window
        (coordination-enabled brokers only); every other movement hands
        off instantly."""
        group, topic = key
        self._generation[key] = self._generation.get(key, 0) + 1
        self._rebalances[key] = self._rebalances.get(key, 0) + 1
        live = self._groups.get(key, [])
        if topic not in self._logs:
            return  # no partitions yet: nothing can move
        n_parts = len(self._logs[topic])
        old_map = self._range_map(sorted(old_members), n_parts)
        new_map = self._range_map(sorted(live), n_parts)
        rev = self._revoking.setdefault(key, {})
        coop = self.session_timeout_s is not None
        now = time.monotonic()
        for p, owner in new_map.items():
            prev = old_map.get(p)
            if prev == owner:
                continue
            if coop and prev is not None and prev in live and p not in rev:
                # cooperative handoff: withhold the partition from the new
                # owner until the old owner confirms its drain (or the
                # window lapses)
                rev[p] = {"owner": prev,
                          "deadline": now + self.revocation_drain_s,
                          "old_gen": self._generation[key] - 1}
            else:
                schedcheck.note_partition_owner(id(self), key + (p,), owner)
        # drain entries whose recorded owner died, or whose current target
        # IS the recorded owner again (membership flapped back), resolve
        # instantly — nobody is left to confirm them
        stale = [p for p, e in rev.items()
                 if e["owner"] not in live or new_map.get(p) == e["owner"]]
        for p in stale:
            del rev[p]
            owner = new_map.get(p)
            if owner is not None:
                schedcheck.note_partition_owner(id(self), key + (p,), owner)

    def _complete_handoffs(self, key: tuple[str, str],
                           parts: list[int]) -> None:
        """Caller holds the lock.  Pop drain entries and make the handoff
        visible: ONE generation bump (when anything completed) so the new
        owners' next refresh picks the partitions up."""
        rev = self._revoking.get(key)
        if not rev:
            return
        done = [p for p in parts if p in rev]
        if not done:
            return
        for p in done:
            del rev[p]
        self._generation[key] = self._generation.get(key, 0) + 1
        new_map = self._owner_map(key)
        for p in done:
            owner = new_map.get(p)
            if owner is not None:
                schedcheck.note_partition_owner(id(self), key + (p,), owner)

    def _sweep_locked(self, key: tuple[str, str]) -> None:
        """Caller holds the lock: expel members that missed their session
        window, then complete drain windows whose deadline lapsed."""
        st = self.session_timeout_s
        now = time.monotonic()
        if st is not None:
            hb = self._hb.get(key, {})
            members = self._groups.get(key, [])
            dead = [m for m in members if now - hb.get(m, now) > st]
            if dead:
                old = list(members)
                for m in dead:
                    members.remove(m)
                    hb.pop(m, None)
                self._expired[key] = self._expired.get(key, 0) + len(dead)
                self._membership_changed(key, old)
        rev = self._revoking.get(key)
        if rev:
            lapsed = [p for p, e in rev.items() if now >= e["deadline"]]
            if lapsed:
                self._complete_handoffs(key, lapsed)

    def join_group(self, group: str, topic: str, member_id: str) -> None:
        with self._lock:
            key = (group, topic)
            members = self._groups.setdefault(key, [])
            self._hb.setdefault(key, {})[member_id] = time.monotonic()
            if member_id not in members:
                old = list(members)
                members.append(member_id)
                self._membership_changed(key, old)

    def leave_group(self, group: str, topic: str, member_id: str) -> None:
        with self._lock:
            key = (group, topic)
            members = self._groups.get(key, [])
            if member_id in members:
                old = list(members)
                members.remove(member_id)
                self._hb.get(key, {}).pop(member_id, None)
                self._membership_changed(key, old)

    def heartbeat(self, group: str, topic: str, member_id: str) -> dict:
        """Stamp the member's liveness and run the expiry/drain sweep.
        Returns the current generation plus ``rejoin=True`` when the
        member missed its session window and was expelled — its only way
        back in is :meth:`join_group` (a fresh assignment epoch)."""
        with self._lock:
            key = (group, topic)
            if member_id in self._groups.get(key, []):
                self._hb.setdefault(key, {})[member_id] = time.monotonic()
            self._sweep_locked(key)
            return {"generation": self._generation.get(key, 0),
                    "rejoin": member_id not in self._groups.get(key, [])}

    def generation(self, group: str, topic: str) -> int:
        with self._lock:
            self._sweep_locked((group, topic))
            return self._generation.get((group, topic), 0)

    def assignment(self, group: str, topic: str, member_id: str) -> list[int]:
        """Range assignment over the current membership (sorted member
        ids).  Partitions inside a cooperative drain window are withheld
        — the new owner sees them only after the old owner confirms (or
        the window lapses)."""
        with self._lock:
            key = (group, topic)
            self._sweep_locked(key)
            members = sorted(self._groups.get(key, []))
            if member_id not in members or topic not in self._logs:
                return []  # unknown topic: no partitions until first produce
            n_parts = len(self._logs[topic])
            idx = members.index(member_id)
            per = n_parts // len(members)
            extra = n_parts % len(members)
            start = idx * per + min(idx, extra)
            count = per + (1 if idx < extra else 0)
            rev = self._revoking.get(key, {})
            return [p for p in range(start, start + count) if p not in rev]

    def confirm_revocation(self, group: str, topic: str, member_id: str,
                           partitions) -> None:
        """The old owner finished draining ``partitions``: complete their
        handoff now instead of waiting out the drain window."""
        with self._lock:
            key = (group, topic)
            rev = self._revoking.get(key, {})
            mine = [p for p in partitions
                    if p in rev and rev[p]["owner"] == member_id]
            if mine:
                self._complete_handoffs(key, mine)

    def group_stats(self, group: str, topic: str) -> dict:
        """Protocol observability for tests/bench: membership, generation,
        and the rebalance/fence/expiry counters."""
        with self._lock:
            key = (group, topic)
            self._sweep_locked(key)
            return {
                "members": sorted(self._groups.get(key, [])),
                "generation": self._generation.get(key, 0),
                "rebalances": self._rebalances.get(key, 0),
                "fenced_commits": self._fenced.get(key, 0),
                "expired_members": self._expired.get(key, 0),
                "revoking": sorted(self._revoking.get(key, {})),
            }

    # -- offsets -----------------------------------------------------------
    def _commit_allowed_locked(self, key: tuple[str, str], partition: int,
                               generation: int, member_id: str) -> bool:
        """Caller holds the lock: the fence predicate.  Accept the old
        owner through its drain window; otherwise ownership under the
        CURRENT range map is authoritative (strict generation equality
        would spuriously fence live owners of retained partitions across
        handoff-completion bumps)."""
        rev = self._revoking.get(key, {})
        e = rev.get(partition)
        if e is not None and e["owner"] == member_id:
            return True  # drain window: the old owner flushing in-flight
        if generation > self._generation.get(key, 0):
            return False  # a generation the coordinator never issued
        owners = self._owner_map(key)
        if owners:
            return owners.get(partition) == member_id
        # topic unknown (commit before first produce): membership is the
        # best fence available
        return member_id in self._groups.get(key, [])

    def commit(self, group: str, topic: str, partition: int, offset: int,
               generation: int | None = None,
               member_id: str | None = None) -> None:
        """offset = next offset to consume (Kafka convention).

        When the committer identifies itself (``generation`` +
        ``member_id``, the coordinated path), the commit is FENCED: it
        must come from the partition's current owner — or, during a
        cooperative drain window, from the old owner finishing its
        in-flight files.  A zombie (expired or superseded member) gets
        the typed :class:`StaleGenerationError` instead of silently
        clobbering the new owner's offset state."""
        # deliberately outside the metadata lock: a schedule-explorer
        # delay here must let the rebalance/handoff parties run, not
        # block them behind a held lock
        schedcheck.point("broker.commit.fence")
        with self._lock:
            key = (group, topic)
            self._sweep_locked(key)
            if generation is not None and member_id is not None:
                if not self._commit_allowed_locked(key, partition,
                                                  generation, member_id):
                    self._fenced[key] = self._fenced.get(key, 0) + 1
                    raise StaleGenerationError(
                        f"fenced commit: member {member_id!r} gen "
                        f"{generation} is not the owner of "
                        f"{topic}[{partition}] (current gen "
                        f"{self._generation.get(key, 0)})")
                schedcheck.note_commit_accepted(id(self), key + (partition,),
                                                member_id)
            ckey = (group, topic, partition)
            if offset > self._committed.get(ckey, 0):
                self._committed[ckey] = offset

    def commit_allowed(self, group: str, topic: str, partition: int,
                       generation: int | None = None,
                       member_id: str | None = None) -> bool:
        """The commit fence as a side-effect-free predicate: would a
        commit from this member at this generation be accepted right
        now?  The writer consults it before PUBLISHING a file whose
        runs it may no longer be allowed to ack."""
        with self._lock:
            key = (group, topic)
            self._sweep_locked(key)
            if generation is None or member_id is None:
                return True
            return self._commit_allowed_locked(key, partition, generation,
                                               member_id)

    def committed(self, group: str, topic: str, partition: int) -> int:
        with self._lock:
            return self._committed.get((group, topic, partition), 0)
