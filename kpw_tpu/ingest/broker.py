"""In-process broker: partitioned append logs + consumer groups + committed
offsets.

Test-infra analog of the reference's embedded ``KafkaRule`` broker
(KafkaProtoParquetWriterTest.java:58-59) promoted to a first-class component:
the framework's default record source in tests and benchmarks, and the
interface a real Kafka wire client can implement later.  Scale-out data
parallelism (multiple writer instances sharing a consumer group —
KafkaProtoParquetWriter.java:72-76) is modeled with range partition
assignment and rebalance-on-membership-change.

Storage is batch-native: each partition log is ONE contiguous payload
buffer plus a record-offset table (record i = ``buf[offs[i]:offs[i+1]]``),
guarded by its own lock — the wire-page layout a real broker hands a fetch
response in.  ``fetch_batch`` returns that layout directly as a
:class:`RecordBatch` (one buffer copy per batch, no per-record objects);
``fetch`` is the compatibility surface that materializes one frozen
:class:`Record` dataclass per payload, the per-record cost the batch path
exists to avoid.  Group membership / committed offsets stay under one
metadata lock; produce/fetch never contend across partitions.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class Record:
    topic: str
    partition: int
    offset: int
    key: bytes | None
    value: bytes
    timestamp: float = 0.0


class RecordBatch:
    """Batch-native ingest handoff: ``count`` serialized payloads in one
    contiguous immutable buffer plus an int64 offset table (record i =
    ``payload[offsets[i]:offsets[i+1]]``; ``offsets[0]`` may be nonzero —
    a :meth:`slice` shares the parent's buffer) and the
    ``(partition, start_offset, count)`` run metadata the run-native ack
    machinery (``poll_many_runs``/``ack_run``) consumes directly.

    Offsets within a batch are contiguous BY CONTRACT (``start_offset + i``
    is record i's offset): a source with offset gaps (a compacted real
    topic) must deliver per-record ``Record`` lists instead — the batch
    run shortcut would otherwise ack offsets that were never delivered.
    Record keys do not ride the batch path (the writer never reads them);
    :meth:`to_records` materializes keyless Records for the per-record
    compatibility route.
    """

    __slots__ = ("topic", "partition", "start_offset", "payload", "offsets",
                 "timestamp")

    def __init__(self, topic: str, partition: int, start_offset: int,
                 payload: bytes, offsets: np.ndarray,
                 timestamp: float = 0.0) -> None:
        self.topic = topic
        self.partition = partition
        self.start_offset = start_offset
        self.payload = payload
        self.offsets = offsets  # int64, len == count + 1, ascending
        self.timestamp = timestamp

    def __len__(self) -> int:
        return len(self.offsets) - 1

    @property
    def run(self) -> tuple[int, int, int]:
        """The batch as one contiguous (partition, start_offset, count)
        ack run."""
        return (self.partition, self.start_offset, len(self))

    def payload_at(self, i: int) -> bytes:
        o = self.offsets
        return self.payload[int(o[i]): int(o[i + 1])]

    def slice(self, start: int, count: int) -> "RecordBatch":
        """Zero-copy window [start, start+count): shares the payload
        buffer, the offset table is a numpy view."""
        return RecordBatch(self.topic, self.partition,
                           self.start_offset + start, self.payload,
                           self.offsets[start: start + count + 1],
                           self.timestamp)

    def to_records(self) -> list[Record]:
        """Materialize per-record frozen ``Record`` dataclasses — the
        compatibility/fallback route (poison-pill reparse, dead-letter)."""
        o, pl = self.offsets, self.payload
        t, p, base, ts = (self.topic, self.partition, self.start_offset,
                          self.timestamp)
        return [Record(t, p, base + i, None, pl[int(o[i]): int(o[i + 1])], ts)
                for i in range(len(o) - 1)]


class _PartitionLog:
    """One partition's contiguous append log, under its own lock.  The
    offset table is a growable int64 numpy array (``offs[0..n]`` valid)
    so a fetch slices it in C instead of converting a Python list per
    batch."""

    __slots__ = ("lock", "buf", "offs", "n", "keys", "ts")

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.buf = bytearray()
        self.offs = np.zeros(64, np.int64)  # byte offsets; offs[0..n] valid
        self.n = 0  # record count
        self.keys: dict[int, bytes] = {}  # record offset -> key (sparse)
        self.ts: list[float] = []

    def _ensure(self, extra: int) -> None:
        need = self.n + 1 + extra
        if need > len(self.offs):
            new = np.empty(max(need, 2 * len(self.offs)), np.int64)
            new[: self.n + 1] = self.offs[: self.n + 1]
            self.offs = new

    def append_one(self, value: bytes, key, now: float) -> int:
        with self.lock:
            self._ensure(1)
            off = self.n
            self.buf += value
            self.offs[off + 1] = self.offs[off] + len(value)
            self.n = off + 1
            self.ts.append(now)
            if key is not None:
                self.keys[off] = key
            return off

    def append_many(self, values, now: float) -> tuple[int, int]:
        """One lock round for the whole batch; returns (first_offset, n)."""
        if not values:
            return self.n, 0
        lens = np.fromiter(map(len, values), np.int64, count=len(values))
        blob = b"".join(values)
        with self.lock:
            first = self.n
            self._ensure(len(values))
            self.buf += blob
            base = self.offs[first]
            np.cumsum(lens, out=self.offs[first + 1: first + 1 + len(values)])
            self.offs[first + 1: first + 1 + len(values)] += base
            self.n = first + len(values)
            self.ts.extend([now] * len(values))
            return first, len(values)


class FakeBroker:
    """Thread-safe in-memory broker (sharded per-partition log locks)."""

    def __init__(self) -> None:
        # metadata lock: topic map shape, consumer groups, committed
        # offsets, the round-robin cursor.  Payload appends/reads take only
        # the owning partition's log lock.
        self._lock = threading.RLock()
        self._logs: dict[str, list[_PartitionLog]] = {}
        self._committed: dict[tuple[str, str, int], int] = {}  # (group, topic, part) -> next offset
        self._groups: dict[tuple[str, str], list[str]] = {}  # (group, topic) -> member ids
        self._generation: dict[tuple[str, str], int] = {}
        self._rr = 0

    # -- topics / produce --------------------------------------------------
    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            if topic in self._logs:
                raise ValueError(f"topic exists: {topic}")
            self._logs[topic] = [_PartitionLog() for _ in range(partitions)]

    def num_partitions(self, topic: str) -> int:
        with self._lock:
            return len(self._logs[topic])

    def _route(self, topic: str, key: bytes | None, partition: int | None,
               advance_rr: int = 1) -> tuple[list[_PartitionLog], int, int]:
        """Resolve (logs, partition, rr_base) under the metadata lock;
        auto-creates a 1-partition topic on first produce."""
        with self._lock:
            if topic not in self._logs:
                self._logs[topic] = [_PartitionLog()]
            parts = self._logs[topic]
            rr_base = self._rr
            if partition is None:
                if key is not None:
                    partition = hash(key) % len(parts)
                else:
                    partition = self._rr % len(parts)
                    self._rr += advance_rr
            return parts, partition, rr_base

    def produce(self, topic: str, value: bytes, key: bytes | None = None,
                partition: int | None = None) -> tuple[int, int]:
        parts, partition, _ = self._route(topic, key, partition)
        return partition, parts[partition].append_one(value, key, time.time())

    def produce_many(self, topic: str, values,
                     partition: int | None = None) -> dict[int, tuple[int, int]]:
        """Append a whole batch of payloads with ONE lock round per
        partition touched (vs one per record via :meth:`produce`) — the
        topic-priming fast path for benchmarks and chaos tests.

        ``partition=None`` stripes round-robin exactly like a
        ``produce()`` loop would (value i lands on partition
        ``(rr + i) % n``), so indexed-identity checks built on the loop's
        placement hold unchanged.  Returns ``{partition: (first_offset,
        count)}``."""
        values = list(values)
        if not values:
            return {}
        parts, part0, rr_base = self._route(topic, None, partition,
                                            advance_rr=len(values))
        now = time.time()
        if partition is not None or len(parts) == 1:
            first, n = parts[part0].append_many(values, now)
            return {part0: (first, n)}
        out: dict[int, tuple[int, int]] = {}
        nparts = len(parts)
        for i in range(nparts):
            p = (rr_base + i) % nparts
            sub = values[i::nparts]
            if sub:
                out[p] = parts[p].append_many(sub, now)
        return out

    # -- fetch -------------------------------------------------------------
    def fetch(self, topic: str, partition: int, offset: int,
              max_records: int = 500) -> list[Record]:
        """Per-record compatibility fetch: materializes one frozen
        ``Record`` per payload (the cost :meth:`fetch_batch` avoids)."""
        with self._lock:
            parts = self._logs.get(topic)
            if parts is None or partition >= len(parts):
                return []
            log = parts[partition]
        with log.lock:
            if offset >= log.n:
                return []
            j = min(offset + max_records, log.n)
            mv = memoryview(log.buf)
            offs = log.offs
            keys, ts = log.keys, log.ts
            return [Record(topic, partition, i, keys.get(i),
                           bytes(mv[offs[i]: offs[i + 1]]), ts[i])
                    for i in range(offset, j)]

    def fetch_batch(self, topic: str, partition: int, offset: int,
                    max_records: int = 2000) -> RecordBatch | None:
        """Batch-native fetch: up to ``max_records`` payloads as ONE
        contiguous buffer + offset table (a single copy out of the log
        page, no per-record object construction).  Returns None when
        nothing is available at ``offset``."""
        with self._lock:
            parts = self._logs.get(topic)
            if parts is None or partition >= len(parts):
                return None
            log = parts[partition]
        with log.lock:
            if offset >= log.n:
                return None
            j = min(offset + max_records, log.n)
            a = int(log.offs[offset])
            payload = bytes(memoryview(log.buf)[a: int(log.offs[j])])
            offsets = log.offs[offset: j + 1].copy()  # C slice copy
            ts = log.ts[offset]
        if a:
            offsets -= a
        return RecordBatch(topic, partition, offset, payload, offsets, ts)

    def end_offset(self, topic: str, partition: int) -> int:
        with self._lock:
            log = self._logs[topic][partition]
        with log.lock:
            return log.n

    # -- consumer groups ---------------------------------------------------
    def join_group(self, group: str, topic: str, member_id: str) -> None:
        with self._lock:
            key = (group, topic)
            members = self._groups.setdefault(key, [])
            if member_id not in members:
                members.append(member_id)
                self._generation[key] = self._generation.get(key, 0) + 1

    def leave_group(self, group: str, topic: str, member_id: str) -> None:
        with self._lock:
            key = (group, topic)
            members = self._groups.get(key, [])
            if member_id in members:
                members.remove(member_id)
                self._generation[key] = self._generation.get(key, 0) + 1

    def generation(self, group: str, topic: str) -> int:
        with self._lock:
            return self._generation.get((group, topic), 0)

    def assignment(self, group: str, topic: str, member_id: str) -> list[int]:
        """Range assignment over the current membership (sorted member ids)."""
        with self._lock:
            members = sorted(self._groups.get((group, topic), []))
            if member_id not in members or topic not in self._logs:
                return []  # unknown topic: no partitions until first produce
            n_parts = len(self._logs[topic])
            idx = members.index(member_id)
            per = n_parts // len(members)
            extra = n_parts % len(members)
            start = idx * per + min(idx, extra)
            count = per + (1 if idx < extra else 0)
            return list(range(start, start + count))

    # -- offsets -----------------------------------------------------------
    def commit(self, group: str, topic: str, partition: int, offset: int) -> None:
        """offset = next offset to consume (Kafka convention)."""
        with self._lock:
            key = (group, topic, partition)
            if offset > self._committed.get(key, 0):
                self._committed[key] = offset

    def committed(self, group: str, topic: str, partition: int) -> int:
        with self._lock:
            return self._committed.get((group, topic, partition), 0)
