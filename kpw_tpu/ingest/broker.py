"""In-process broker: partitioned append logs + consumer groups + committed
offsets.

Test-infra analog of the reference's embedded ``KafkaRule`` broker
(KafkaProtoParquetWriterTest.java:58-59) promoted to a first-class component:
the framework's default record source in tests and benchmarks, and the
interface a real Kafka wire client can implement later.  Scale-out data
parallelism (multiple writer instances sharing a consumer group —
KafkaProtoParquetWriter.java:72-76) is modeled with range partition
assignment and rebalance-on-membership-change.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field


@dataclass(frozen=True)
class Record:
    topic: str
    partition: int
    offset: int
    key: bytes | None
    value: bytes
    timestamp: float = 0.0


class FakeBroker:
    """Thread-safe in-memory broker."""

    def __init__(self) -> None:
        self._lock = threading.RLock()
        self._logs: dict[str, list[list[Record]]] = {}
        self._committed: dict[tuple[str, str, int], int] = {}  # (group, topic, part) -> next offset
        self._groups: dict[tuple[str, str], list[str]] = {}  # (group, topic) -> member ids
        self._generation: dict[tuple[str, str], int] = {}
        self._rr = 0

    # -- topics / produce --------------------------------------------------
    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            if topic in self._logs:
                raise ValueError(f"topic exists: {topic}")
            self._logs[topic] = [[] for _ in range(partitions)]

    def num_partitions(self, topic: str) -> int:
        with self._lock:
            return len(self._logs[topic])

    def produce(self, topic: str, value: bytes, key: bytes | None = None,
                partition: int | None = None) -> tuple[int, int]:
        with self._lock:
            if topic not in self._logs:
                self._logs[topic] = [[]]
            parts = self._logs[topic]
            if partition is None:
                if key is not None:
                    partition = hash(key) % len(parts)
                else:
                    partition = self._rr % len(parts)
                    self._rr += 1
            log = parts[partition]
            rec = Record(topic, partition, len(log), key, value, time.time())
            log.append(rec)
            return partition, rec.offset

    # -- fetch -------------------------------------------------------------
    def fetch(self, topic: str, partition: int, offset: int,
              max_records: int = 500) -> list[Record]:
        with self._lock:
            parts = self._logs.get(topic)
            if parts is None or partition >= len(parts):
                return []
            return parts[partition][offset : offset + max_records]

    def end_offset(self, topic: str, partition: int) -> int:
        with self._lock:
            return len(self._logs[topic][partition])

    # -- consumer groups ---------------------------------------------------
    def join_group(self, group: str, topic: str, member_id: str) -> None:
        with self._lock:
            key = (group, topic)
            members = self._groups.setdefault(key, [])
            if member_id not in members:
                members.append(member_id)
                self._generation[key] = self._generation.get(key, 0) + 1

    def leave_group(self, group: str, topic: str, member_id: str) -> None:
        with self._lock:
            key = (group, topic)
            members = self._groups.get(key, [])
            if member_id in members:
                members.remove(member_id)
                self._generation[key] = self._generation.get(key, 0) + 1

    def generation(self, group: str, topic: str) -> int:
        with self._lock:
            return self._generation.get((group, topic), 0)

    def assignment(self, group: str, topic: str, member_id: str) -> list[int]:
        """Range assignment over the current membership (sorted member ids)."""
        with self._lock:
            members = sorted(self._groups.get((group, topic), []))
            if member_id not in members or topic not in self._logs:
                return []  # unknown topic: no partitions until first produce
            n_parts = len(self._logs[topic])
            idx = members.index(member_id)
            per = n_parts // len(members)
            extra = n_parts % len(members)
            start = idx * per + min(idx, extra)
            count = per + (1 if idx < extra else 0)
            return list(range(start, start + count))

    # -- offsets -----------------------------------------------------------
    def commit(self, group: str, topic: str, partition: int, offset: int) -> None:
        """offset = next offset to consume (Kafka convention)."""
        with self._lock:
            key = (group, topic, partition)
            if offset > self._committed.get(key, 0):
                self._committed[key] = offset

    def committed(self, group: str, topic: str, partition: int) -> int:
        with self._lock:
            return self._committed.get((group, topic, partition), 0)
