"""Backpressure autotuning: ingest knobs derived from measured stage rates.

The fixed constants the reference exposes (``fetch_max_records=2000``,
``maxQueuedRecordsInConsumer=100_000`` — KafkaProtoParquetWriter.java:468)
encode one assumed throughput.  This module generalizes the worker loop's
EWMA carry-estimate pattern (the live bytes/record rotation estimate in
``runtime/writer.py``) to the whole ingest leg: measure how fast records
actually move through each stage, then size the knobs as *time horizons*
of those rates —

* **fetch batch** — ``fetch_horizon_s`` of the queue's drain rate: big
  enough to amortize a broker round-trip + one tracker round over
  thousands of records, small enough that one fetch never represents more
  than a few tens of milliseconds of redeliverable work.
* **queue depth** — ``queue_horizon_s`` of the drain rate: deep enough to
  ride out a publish stall without starving the workers, shallow enough
  to bound memory and crash redelivery.  The configured
  ``max_queued_records`` stays a HARD ceiling (the reference's
  BlockingQueue capacity semantics): autotuning only ever shrinks below
  it, never overshoots it.
* **poll batch** (worker side) — ``poll_horizon_s`` of that worker's own
  measured shred+append rate, still clipped by the rotation-overshoot cap
  (``_rotation_batch_cap``) that bounds file-size error.

Tuned values are surfaced via :meth:`IngestAutotuner.snapshot` into
``SmartCommitConsumer.stats()`` / ``writer.stats()`` so a reader can see
what the system chose and from which measured rates.
"""

from __future__ import annotations


def _clamp(v: int, lo: int, hi: int) -> int:
    return max(lo, min(hi, v))


class IngestAutotuner:
    """EWMA rate observer feeding the tuned ingest knobs.

    Owned by the writer, ticked by the consumer's fetcher thread
    (:meth:`observe` with the queue's cumulative in/out counters), read by
    the fetcher (``fetch_max``, ``queue_cap``) and by workers
    (:meth:`poll_batch` with their own processing rate).  Single-writer
    per field; readers tolerate a stale int (they re-read every loop).
    """

    def __init__(self, fetch_max0: int, queue_max0: int, *,
                 interval_s: float = 0.25, alpha: float = 0.3,
                 fetch_horizon_s: float = 0.05,
                 queue_horizon_s: float = 0.5,
                 poll_horizon_s: float = 0.05,
                 min_fetch: int = 256, max_fetch: int = 65536,
                 min_queue: int = 4096) -> None:
        self.fetch_max = fetch_max0          # live tuned values (start at
        self.queue_cap = queue_max0          # the configured constants)
        self._fetch_max0 = fetch_max0
        self._queue_max0 = queue_max0        # hard ceiling, never exceeded
        self.interval_s = interval_s
        self.alpha = alpha
        self.fetch_horizon_s = fetch_horizon_s
        self.queue_horizon_s = queue_horizon_s
        self.poll_horizon_s = poll_horizon_s
        self.min_fetch = min_fetch
        self.max_fetch = max_fetch
        self.min_queue = min(min_queue, queue_max0)
        self._fetch_rate = 0.0  # rec/s INTO the queue (EWMA)
        self._drain_rate = 0.0  # rec/s OUT of the queue (EWMA)
        self._last: tuple[float, int, int] | None = None
        self._retunes = 0

    def observe(self, now: float, records_in: int, records_out: int) -> None:
        """Fold one (time, cumulative in, cumulative out) sample; recomputes
        the knobs at most once per ``interval_s``."""
        if self._last is None:
            self._last = (now, records_in, records_out)
            return
        t0, in0, out0 = self._last
        dt = now - t0
        if dt < self.interval_s:
            return
        self._last = (now, records_in, records_out)
        a = self.alpha
        self._fetch_rate += a * ((records_in - in0) / dt - self._fetch_rate)
        self._drain_rate += a * ((records_out - out0) / dt - self._drain_rate)
        if self._drain_rate <= 0:
            return  # nothing drained yet: keep the configured seeds
        self.fetch_max = _clamp(int(self._drain_rate * self.fetch_horizon_s),
                                self.min_fetch, self.max_fetch)
        self.queue_cap = _clamp(int(self._drain_rate * self.queue_horizon_s),
                                self.min_queue, self._queue_max0)
        self._retunes += 1

    def poll_batch(self, proc_rate: float, floor: int = 64) -> int:
        """Worker-side poll batch: ``poll_horizon_s`` of the worker's own
        measured processing rate (caller still clips by the rotation
        cap)."""
        if proc_rate <= 0:
            return max(floor, self._fetch_max0)
        return _clamp(int(proc_rate * self.poll_horizon_s), floor,
                      self.max_fetch)

    def snapshot(self) -> dict:
        return {
            "enabled": True,
            "fetch_max_records": self.fetch_max,
            "max_queued_records": self.queue_cap,
            "configured_fetch_max_records": self._fetch_max0,
            "configured_max_queued_records": self._queue_max0,
            "fetch_rate_rps": round(self._fetch_rate, 1),
            "drain_rate_rps": round(self._drain_rate, 1),
            "retunes": self._retunes,
            "horizons_s": {"fetch": self.fetch_horizon_s,
                           "queue": self.queue_horizon_s,
                           "poll": self.poll_horizon_s},
        }
