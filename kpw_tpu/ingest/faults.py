"""Broker-seam fault injection: the ingest counterpart of
:class:`kpw_tpu.io.faults.FaultInjectingFileSystem`.

Wraps any broker (FakeBroker or a real client behind the same surface) and
consults a shared :class:`~kpw_tpu.io.faults.FaultSchedule` on the two IO
paths the smart-commit consumer drives — ``fetch`` (the fetcher thread's
poll) and ``commit`` (the post-publish ack) — plus a scheduled ``rebalance``
event that revokes every partition mid-batch the way a real group rebalance
does: the generation number jumps, the consumer re-resolves its assignment
and rewinds each partition to the committed frontier, and everything
delivered-but-unacked is redelivered (at-least-once allows the duplicates).

Opt-in at the Builder seam only: a writer built without the wrapper never
consults a schedule, so the disabled hot-path cost is zero.
"""

from __future__ import annotations

from ..io.faults import FaultSchedule


class FaultInjectingBroker:
    """Delegating broker wrapper with schedule-driven fetch/commit faults
    and forced rebalances.

    ``rebalance_on_fetch`` lists fetch-call ordinals at which the
    generation bumps (partition revocation mid-batch); each firing is
    recorded into the shared schedule's fault log so the chaos artifact
    carries one merged timeline.
    """

    def __init__(self, inner, schedule: FaultSchedule,
                 rebalance_on_fetch: tuple = ()) -> None:
        import threading

        self.inner = inner
        self.schedule = schedule
        self._gen_extra = 0
        self._rebalance_at = set(rebalance_on_fetch)
        self._fetch_n = 0
        self._lock = threading.Lock()
        if not callable(getattr(inner, "fetch_batch", None)):
            # shadow the class method so feature detection
            # (callable(getattr(broker, "fetch_batch", None))) sees exactly
            # what the inner broker offers
            self.fetch_batch = None

    # -- faulted surface -----------------------------------------------------
    def _fetch_gate(self) -> None:
        """Shared ordinal counting + rebalance/fault firing for both fetch
        shapes: the schedule sees ONE stream of fetch ops, so an ordinal
        fires regardless of which path the consumer rides."""
        with self._lock:
            self._fetch_n += 1
            n = self._fetch_n
        if n in self._rebalance_at:
            self._gen_extra += 1
            self.schedule.note("rebalance", n)
        self.schedule.check("fetch")

    def fetch(self, topic: str, partition: int, offset: int,
              max_records: int = 500):
        self._fetch_gate()
        return self.inner.fetch(topic, partition, offset, max_records)

    def fetch_batch(self, topic: str, partition: int, offset: int,
                    max_records: int = 2000):
        """Batch-native fetch rides the same fault gate as :meth:`fetch`
        (instances wrapping a broker without ``fetch_batch`` shadow this
        method with None in ``__init__`` so feature detection matches the
        inner broker)."""
        self._fetch_gate()
        return self.inner.fetch_batch(topic, partition, offset, max_records)

    def commit(self, group: str, topic: str, partition: int, offset: int,
               generation: int | None = None,
               member_id: str | None = None) -> None:
        self.schedule.check("commit")
        if generation is not None or member_id is not None:
            self.inner.commit(group, topic, partition, offset,
                              generation=generation, member_id=member_id)
        else:
            self.inner.commit(group, topic, partition, offset)

    def generation(self, group: str, topic: str) -> int:
        return self.inner.generation(group, topic) + self._gen_extra

    def force_rebalance(self) -> None:
        """Bump the generation so every consumer in the group re-resolves
        its assignment and rewinds to the committed frontier — partition
        revocation mid-batch without changing membership."""
        self._gen_extra += 1

    # -- passthrough ---------------------------------------------------------
    def __getattr__(self, name):
        return getattr(self.inner, name)
