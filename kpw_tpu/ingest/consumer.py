"""Smart-commit consumer: bounded shared queue + paged offset tracking +
open-page backpressure.

Interface parity with the external library the reference wires at
KafkaProtoParquetWriter.java:153-163: ``subscribe(topic)``, ``start()``,
``poll()`` (non-blocking, many workers concurrently), ``ack(PartitionOffset)``,
``close()``; auto-commit is never used — the committed offset only advances
over acked pages (at-least-once anchor, README.MD:6).  A single fetcher
thread owns broker I/O (the reference's consumer thread), workers share the
bounded queue (``maxQueuedRecordsInConsumer``, KPW.java:468).
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from collections import deque

from ..runtime.retry import RetryInterrupted, RetryPolicy
from ..utils.tracing import stage
from .autotune import IngestAutotuner
from .broker import FakeBroker, Record, RecordBatch, StaleGenerationError
from .offsets import PagedOffsetTracker, PartitionOffset

logger = logging.getLogger(__name__)


class SmartCommitConsumer:
    def __init__(
        self,
        broker: FakeBroker,
        group_id: str,
        page_size: int = 300_000,
        max_open_pages_per_partition: int = 1,
        max_queued_records: int = 100_000,
        fetch_max_records: int = 2000,
        member_id: str | None = None,
        retry_policy: RetryPolicy | None = None,
        batch_ingest: bool = False,
        autotuner: IngestAutotuner | None = None,
        queue_listener=None,
        drain_deadline_s: float = 5.0,
        rebalance_listener=None,
    ) -> None:
        self.broker = broker
        self.group_id = group_id
        self.member_id = member_id or f"member-{uuid.uuid4().hex[:8]}"
        self.tracker = PagedOffsetTracker(page_size, max_open_pages_per_partition)
        # Batch-native bounded buffer: a deque of record *batches* under one
        # condition, so the fetcher pays one lock round per fetch and
        # workers one per poll_many — the per-record queue.Queue handoff was
        # the throughput ceiling (~2 us/record each side).  Entries are
        # either plain ``list[Record]`` (compatibility route, redelivery)
        # or zero-copy :class:`RecordBatch` fetch slices (``batch_ingest``:
        # contiguous payload buffer + offsets, no per-record objects).  The
        # record-count bound is hard (reference BlockingQueue capacity
        # semantics): oversized batches are admitted in slices, see
        # _put_batch.
        self._buf: "deque[list[Record] | RecordBatch]" = deque()
        self._head_pos = 0  # consumed prefix of _buf[0]
        self._buf_count = 0
        self._buf_max = max_queued_records
        self._buf_cond = threading.Condition()
        # queue observability (all mutated under _buf_cond, so a stats()
        # reader sees a consistent snapshot): live depth is _buf_count;
        # high watermark + cumulative fetcher blocked-on-put / worker
        # blocked-on-get stall seconds are the backpressure evidence
        self._buf_hwm = 0
        self._put_stall_s = 0.0
        self._get_stall_s = 0.0
        self._records_in = 0
        self._records_out = 0
        # fetch-loop skips because a partition hit the open-page bound
        # (reference offsetTrackerMaxOpenPagesPerPartition backpressure);
        # only the fetcher thread writes it
        self._backpressure_skips = 0
        self._fetch_max = fetch_max_records
        self._topic: str | None = None
        self._thread: threading.Thread | None = None
        self._running = False
        self._positions: dict[int, int] = {}  # partition -> next fetch offset
        self._assigned: list[int] = []
        self._generation = -1
        self._commit_lock = threading.Lock()
        # broker-IO retry: transient fetch/commit failures (a sick broker,
        # an injected chaos fault) back off and retry instead of killing the
        # fetcher thread / the acking worker.  Default policy = infinite
        # attempts with backoff (reference delivery semantics).
        self._retry = retry_policy or RetryPolicy()
        self._stop_event = threading.Event()
        self._broker_retries = 0   # fetch+commit retry count (stats)
        self._redelivered = 0      # records re-injected by redeliver_run
        self._fetcher_error: str | None = None
        # batch-native ingest: ride broker.fetch_batch (contiguous buffer +
        # offsets, no per-record Record construction) when the broker has
        # one; falls back to the per-record fetch path silently otherwise
        self._batch_ingest = batch_ingest
        self._batch_fetches = 0    # fetch_batch calls that delivered (stats)
        # backpressure autotuning (owned by the writer; ticked from the
        # fetch loop): None = fixed knobs, reference parity
        self._autotune = autotuner
        # queue-occupancy listener (the multi-tenant quota ledger's
        # charge/credit seam, runtime/multiwriter.py): ``on_enqueued(n)``
        # fires per admitted slice, ``on_drained(n)`` per drain round,
        # both under the buffer condition so charge and credit see the
        # same admission the queue accounting saw.  The listener must not
        # block and may only take its OWN lock (buffer-cond -> listener
        # lock is the one ordering; the ledger never takes this one).
        self._listener = queue_listener
        # end-to-end ack-latency plane: per-partition deques of
        # (start_offset, end_offset, ingest_wall_ts) stamped at queue
        # admission and popped when acks cover them — the writer's
        # observer receives time-to-durable seconds per covered stamp.
        # Own leaf lock (the buffer condition may be held when stamping;
        # the ack path takes only this lock — acyclic).  Bounded per
        # partition: if acks never come, old stamps age out silently
        # rather than growing without bound.
        self._stamp_lock = threading.Lock()
        self._stamps: dict[int, deque] = {}
        self._stamp_cap = 4096
        self._latency_observer = None
        self._lat_runs = 0
        self._lat_records = 0
        # group coordination (ISSUE 18): the consumer runs the full
        # protocol — heartbeats, cooperative incremental rebalance, fenced
        # commits — only against a coordination-enabled broker (one with a
        # heartbeat surface AND a session timeout configured); every other
        # broker keeps the legacy full-reset-on-generation-change path.
        _st = getattr(broker, "session_timeout_s", None)
        self._coordinated = (callable(getattr(broker, "heartbeat", None))
                             and _st is not None)
        self._hb_interval_s = (max(0.02, _st / 4.0)
                               if self._coordinated else None)
        self._last_hb = 0.0  # monotonic
        self._drain_deadline_s = drain_deadline_s
        self._rejoin_drain_timeouts = 0  # hard-bounded rejoin waits
        self._rebalance_listener = rebalance_listener
        # in-progress cooperative revocation: {"parts": set[int],
        # "deadline": monotonic} — only the fetcher thread touches it
        self._revoke_pending: dict | None = None
        # SIGSTOP analog for the zombie drill: a suspended fetcher stops
        # heartbeating/fetching but the thread stays parked (resumable)
        self._suspended = False
        self._killed = False  # hard_kill(): no leave_group on close
        self._cooperative_rebalances = 0
        self._full_resets = 0
        self._rejoins = 0
        self._fenced_commits = 0
        self._revoked_purged = 0

    # -- lifecycle ---------------------------------------------------------
    def subscribe(self, topic: str) -> None:
        if self._topic is not None:
            raise ValueError("already subscribed")
        self._topic = topic

    def start(self) -> None:
        if self._topic is None:
            raise ValueError("subscribe() before start()")
        if self._thread is not None:
            raise ValueError("already started")
        self.broker.join_group(self.group_id, self._topic, self.member_id)
        self._running = True
        self._thread = threading.Thread(target=self._fetch_loop,
                                        name=f"smart-consumer-{self.member_id}",
                                        daemon=True)
        self._thread.start()

    def close(self, timeout: float = 10.0) -> None:
        self._running = False
        self._stop_event.set()
        # wake a fetcher blocked in a put-stall NOW (full buffer, worker
        # gone or slow): _put_batch re-checks _running on wake and bails,
        # so close never deadlocks behind a wedged producer — without the
        # notify it still exits, but only at the next 50 ms wait tick
        # (pinned by test_consumer_close_releases_blocked_put)
        with self._buf_cond:
            self._buf_cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            self._thread = None
        if self._topic is not None and not self._killed:
            self.broker.leave_group(self.group_id, self._topic, self.member_id)

    def hard_kill(self) -> None:
        """kill -9 analog at the protocol level: stop without
        ``leave_group`` — the broker learns of the death only through the
        missed session window, exactly like a SIGKILLed process's silent
        socket drop.  The chaos drill's victim path."""
        self._killed = True
        self.close()

    def suspend(self, flag: bool) -> None:
        """SIGSTOP/SIGCONT analog: a suspended fetcher stops heartbeating
        and fetching but stays parked and resumable — the zombie drill
        pauses an instance through a full rebalance this way."""
        self._suspended = bool(flag)

    def set_rebalance_listener(self, listener) -> None:
        """Bind the cooperative-revocation listener (the writer).  Surface:
        ``on_generation(gen, revoked, added)``,
        ``on_partitions_revoked(parts)`` (begin fencing in-flight files),
        ``revocation_drained(parts) -> bool`` (polled until True or the
        drain deadline), ``on_revocation_timeout(parts)`` (deadline lapsed:
        abandon what is still in flight), ``on_partitions_lost(parts)``
        (non-cooperative loss — expelled from the group).  Every callback
        fires on the fetcher thread and must not block."""
        self._rebalance_listener = listener

    # -- worker API --------------------------------------------------------
    def poll(self, timeout: float | None = None) -> Record | None:
        """Non-blocking by default (reference workers sleep 1 ms on null,
        KPW.java:260-263).  With a timeout, waits under the buffer condition
        (wait_for: no check-then-wait race, no spurious early None)."""
        with self._buf_cond:
            if timeout is not None and not self._buf:
                t0 = time.perf_counter()
                self._buf_cond.wait_for(lambda: bool(self._buf), timeout)
                self._get_stall_s += time.perf_counter() - t0
            got = self._drain_locked(1)
        return got[0] if got else None

    def poll_many(self, max_records: int) -> list[Record]:
        """Drain up to ``max_records`` without blocking — one lock round for
        the whole batch (the batch counterpart of :meth:`poll`)."""
        with self._buf_cond:
            return self._drain_locked(max_records)

    def poll_many_runs(self, max_records: int):
        """Like :meth:`poll_many` but also returns the drained records as
        contiguous (partition, start_offset, count) runs, in record order.
        Buffered batches are single-partition fetch slices, so runs come out
        O(1) per slice instead of the caller re-deriving them per record —
        the ack-bookkeeping fast path for the streaming worker.  A gapped
        batch (compacted topic) falls back to exact per-record runs: the
        run shortcut must never claim an offset that was not delivered."""
        runs: list[tuple[int, int, int]] = []
        with self._buf_cond:
            recs = self._drain_locked(max_records, runs)
        return recs, runs

    def poll_many_batches(self, max_records: int):
        """Batch-native drain: up to ``max_records`` without blocking,
        returned as the raw queue chunks — zero-copy :class:`RecordBatch`
        views of the fetcher's contiguous fetch slices and/or plain
        ``list[Record]`` chunks (redelivered runs, record-mode leftovers)
        — plus the (partition, start, count) ack runs, in record order.
        The streaming worker's fast path: payload buffers go straight to
        the wire shredder, Records are never materialized."""
        runs: list[tuple[int, int, int]] = []
        items: list = []
        with self._buf_cond:
            self._drain_locked(max_records, runs, items)
        return items, runs

    def _drain_locked(self, max_records: int, runs: list | None = None,
                      items: list | None = None) -> list[Record]:
        """Drain up to ``max_records`` under the buffer condition.  Returns
        the drained records materialized (the poll/poll_many surface);
        with ``items`` supplied the raw chunks (RecordBatch views / Record
        lists) are appended there instead and the return list stays empty.
        ``runs`` collects the drained ack runs either way."""
        out: list[Record] = []
        taken = 0
        while self._buf and taken < max_records:
            head = self._buf[0]
            avail = len(head) - self._head_pos
            want = max_records - taken
            take = avail if want >= avail else want
            if isinstance(head, RecordBatch):
                # zero-copy window; a RecordBatch is contiguous by contract
                # so its run is O(1)
                chunk = (head if take == len(head)
                         else head.slice(self._head_pos, take))
                if runs is not None:
                    runs.append(chunk.run)
            else:
                # partial drain: advance an index into the head batch (O(1)
                # per-record consumption for poll() users; no reslicing)
                chunk = (head[self._head_pos: self._head_pos + take]
                         if (self._head_pos or take < len(head)) else head)
                if runs is not None and chunk:
                    first, last = chunk[0], chunk[-1]
                    if last.offset - first.offset == len(chunk) - 1:
                        runs.append((first.partition, first.offset,
                                     len(chunk)))
                    else:  # gap inside a batch (compacted topic): exact
                        runs.extend((r.partition, r.offset, 1)
                                    for r in chunk)
            if take == avail:
                self._buf.popleft()
                self._head_pos = 0
            else:
                self._head_pos += take
            self._buf_count -= take
            self._records_out += take
            taken += take
            if items is not None:
                items.append(chunk)
            elif isinstance(chunk, RecordBatch):
                out.extend(chunk.to_records())
            else:
                out.extend(chunk)
        if taken:
            if self._listener is not None:
                self._listener.on_drained(taken)
            self._buf_cond.notify_all()
        return out

    def _put_batch(self, records: "list[Record] | RecordBatch",
                   stop_event: threading.Event | None = None) -> bool:
        """Fetcher side: enqueue one tracked batch (a Record list or a
        zero-copy RecordBatch), blocking while the record-count bound is
        reached.  The bound is HARD (the reference's
        maxQueuedRecordsInConsumer is a BlockingQueue capacity): an
        oversized batch is admitted in slices as space opens, never
        overshooting ``max_queued_records``.  Returns False when shut down
        (or ``stop_event`` fires — the supervisor's redelivery must not
        stay wedged on a full queue through close) before everything was
        admitted (caller must not advance its fetch position;
        already-admitted slices may be redelivered — at-least-once allows
        the duplicates)."""
        pos = 0
        n = len(records)
        is_batch = isinstance(records, RecordBatch)
        with self._buf_cond:
            while pos < n:
                space = self._buf_max - self._buf_count
                if space <= 0:
                    if not self._running or (stop_event is not None
                                             and stop_event.is_set()):
                        return False
                    t0 = time.perf_counter()
                    self._buf_cond.wait(0.05)
                    self._put_stall_s += time.perf_counter() - t0
                    continue
                take = min(space, n - pos)
                if pos == 0 and take == n:
                    part = records
                elif is_batch:
                    part = records.slice(pos, take)
                else:
                    part = records[pos: pos + take]
                self._buf.append(part)
                self._buf_count += take
                self._records_in += take
                if self._buf_count > self._buf_hwm:
                    self._buf_hwm = self._buf_count
                if self._listener is not None:
                    self._listener.on_enqueued(take)
                if is_batch:
                    self._stamp_ingest(part.partition, part.start_offset,
                                       part.start_offset + take,
                                       part.timestamp)
                elif part:
                    self._stamp_ingest(part[0].partition, part[0].offset,
                                       part[-1].offset + 1,
                                       part[0].timestamp)
                pos += take
                self._buf_cond.notify_all()
        return True

    # -- end-to-end ack latency --------------------------------------------
    def set_latency_observer(self, fn) -> None:
        """``fn(seconds, records)`` fires per ingest stamp an ack covers
        — the writer binds the ``parquet.writer.ack.latency`` Histogram
        here.  The observer must be cheap and must not raise."""
        self._latency_observer = fn

    def _stamp_ingest(self, partition: int, start: int, end: int,
                      ts: float = 0.0) -> None:
        # the broker's record-append timestamp when the source carries one
        # (FakeBroker batches do): the latency origin then SURVIVES a
        # partition handoff — the new owner's re-fetch of an unacked run
        # carries the same append stamp the dead owner saw, so the
        # measured time-to-durable spans the rebalance blackout instead
        # of restarting at redelivery.  Wall clock deliberately (not
        # monotonic): the stamp crosses process boundaries (ring
        # descriptor) and renders as operator-facing seconds; sources
        # without record timestamps fall back to ingest wall time.
        if not ts:
            ts = time.time()
        with self._stamp_lock:
            dq = self._stamps.get(partition)
            if dq is None:
                dq = self._stamps[partition] = deque(maxlen=self._stamp_cap)
            dq.append((start, end, ts))

    def ingest_stamp(self, partition: int, offset: int) -> float | None:
        """The ingest wall-time of the stamp covering ``offset`` (None
        when unknown) — the dispatcher reads it to stamp ring unit
        descriptors.  Front-of-deque hits dominate (the oldest unacked
        run is the one being dispatched)."""
        with self._stamp_lock:
            dq = self._stamps.get(partition)
            if not dq:
                return None
            for s, e, ts in dq:
                if s <= offset < e:
                    return ts
        return None

    def _observe_ack(self, partition: int, start: int, end: int) -> None:
        """Pop every stamp the acked run [start, end) covers and feed the
        observer its time-to-durable.  Handles out-of-order acks (runs
        ack at file granularity across workers): stamps entirely below
        the run are kept for their own later ack; a stamp the run only
        partially covers is split, its tail re-queued.  Redelivered runs
        re-stamp at redelivery but carry the broker's ORIGINAL append
        timestamp, so duplicates measure the true end-to-end latency
        (clamped at zero for sources whose stamps fall back to ingest
        wall time)."""
        obs = self._latency_observer
        hits: list[tuple[float, int]] = []
        now = time.time()
        with self._stamp_lock:
            dq = self._stamps.get(partition)
            if not dq:
                return
            keep: list[tuple[int, int, float]] = []
            while dq and dq[0][0] < end:
                s, e, ts = dq.popleft()
                if e <= start:
                    keep.append((s, e, ts))  # earlier run, not ours
                    continue
                hits.append((max(0.0, now - ts),
                             min(e, end) - max(s, start)))
                if e > end:  # tail extends past the ack: re-stamp it
                    keep.append((end, e, ts))
            for item in reversed(keep):
                dq.appendleft(item)
            if hits:
                self._lat_runs += len(hits)
                self._lat_records += sum(n for _, n in hits)
        if obs is not None:
            for lat_s, n in hits:
                obs(lat_s, n)

    def queue_depth(self) -> int:
        """Live record count in the shared bounded buffer."""
        with self._buf_cond:
            return self._buf_count

    def fetcher_alive(self) -> bool:
        """True while the fetcher thread is running and has not died to an
        unretryable broker error — the consumer half of writer.healthy()."""
        return (self._thread is not None and self._thread.is_alive()
                and self._fetcher_error is None)

    def redeliver_run(self, partition: int, start: int, count: int,
                      stop_event: threading.Event | None = None) -> int:
        """Re-inject the already-tracked offset run [start, start+count)
        into the shared buffer by re-fetching it from the broker.

        The supervised-restart redelivery path: a dead worker's held
        (written-but-unacked and polled-but-unwritten) offsets were consumed
        from the queue and will never be acked by anyone — without
        re-injection the commit frontier stalls behind them forever.  The
        run is NOT tracked again (its pages are already open in the
        tracker); duplicates with a survivor's output are allowed by the
        at-least-once contract.  ``stop_event`` (e.g. the supervisor's
        close signal) aborts promptly — the consumer's own stop is honored
        too.  Returns the number of records re-injected."""
        if self._coordinated and partition not in self._assigned:
            return 0  # revoked/handed off: the NEW owner redelivers from
            #           the committed frontier — re-injecting here would
            #           write rows this member can no longer ack (fenced)
        stop = stop_event or self._stop_event
        end = start + count
        off = start
        while (off < end and not stop.is_set()
               and not self._stop_event.is_set()):
            recs = self._retry.call(
                lambda off=off: self.broker.fetch(
                    self._topic, partition, off,
                    min(self._fetch_max, end - off)),
                stop_event=stop,
                on_retry=self._count_retry, label="broker.refetch")
            recs = [r for r in recs if r.offset < end]
            if not recs:
                break  # run no longer materializable (compacted away)
            if not self._put_batch(recs, stop_event=stop):
                break  # shutting down
            self._redelivered += len(recs)
            off = recs[-1].offset + 1
        return off - start

    def stats(self) -> dict:
        """Pull-based consumer observability snapshot: the shared queue's
        depth / high-watermark / stall accounting, the fetcher's
        open-page-backpressure skip count, and the offset tracker's
        per-partition ack frontier (the delivered-but-uncommitted records
        behind the at-least-once commit)."""
        with self._buf_cond:
            q = {
                "depth": self._buf_count,
                "capacity": self._buf_max,
                "high_watermark": self._buf_hwm,
                "put_stall_s": round(self._put_stall_s, 6),
                "get_stall_s": round(self._get_stall_s, 6),
                "records_in": self._records_in,
                "records_out": self._records_out,
            }
        return {
            "queue": q,
            "backpressure_skips": self._backpressure_skips,
            "fetcher_alive": self.fetcher_alive(),
            "fetcher_error": self._fetcher_error,
            "broker_retries": self._broker_retries,
            "redelivered_records": self._redelivered,
            "batch_ingest": self._batch_ingest,
            "batch_fetches": self._batch_fetches,
            "autotune": (self._autotune.snapshot()
                         if self._autotune is not None
                         else {"enabled": False}),
            "ack_latency": self.latency_snapshot(),
            "rebalance": {
                "coordinated": self._coordinated,
                "generation": self._generation,
                "assigned": sorted(self._assigned),
                "cooperative_rebalances": self._cooperative_rebalances,
                "full_resets": self._full_resets,
                "rejoins": self._rejoins,
                "fenced_commits": self._fenced_commits,
                "revoked_purged_records": self._revoked_purged,
                "revoke_pending": (sorted(self._revoke_pending["parts"])
                                   if self._revoke_pending else []),
            },
            "tracker": self.tracker.snapshot(),
        }

    def latency_snapshot(self) -> dict:
        with self._stamp_lock:
            return {
                "observed_runs": self._lat_runs,
                "observed_records": self._lat_records,
                "stamps_pending": sum(len(d) for d in
                                      self._stamps.values()),
            }

    def ack(self, po: PartitionOffset) -> None:
        # observe BEFORE the commit round: durability happened at
        # publish, and a commit retry backing off for seconds must not
        # inflate the measured time-to-durable
        self._observe_ack(po.partition, po.offset, po.offset + 1)
        new_commit = self.tracker.ack(po)
        if new_commit is not None:
            self._commit_with_retry(po.partition, new_commit)

    def ack_run(self, partition: int, start: int, count: int) -> None:
        """Batch ack of a contiguous offset run — one tracker round and at
        most one broker commit for a whole published batch (the worker acks
        whole files' worth of offsets at publish time)."""
        if count <= 0:
            return
        self._observe_ack(partition, start, start + count)
        new_commit = self.tracker.ack_run(partition, start, count)
        if new_commit is not None:
            self._commit_with_retry(partition, new_commit)

    def _commit_with_retry(self, partition: int, offset: int) -> None:
        """Commit the advanced frontier, retrying transient broker errors.
        Safe to retry indefinitely: commit is idempotent and the records it
        covers are already durably published — losing the commit only costs
        redelivery (at-least-once), never data.  Each attempt re-reads the
        tracker's (monotonic) frontier: a retry that backed off for seconds
        must not push a stale lower offset over a newer commit another
        worker made meanwhile (FakeBroker guards monotonicity; a real
        Kafka commit does not)."""
        def do() -> None:
            with self._commit_lock:
                cur = self.tracker.committed(partition)
                if self._coordinated:
                    # fenced commit: carry our identity so a stale member
                    # (zombie through a rebalance) is rejected broker-side.
                    # lint: lock-discipline ok — the lock exists precisely
                    # to make frontier-read + broker commit one atomic
                    # step: a real Kafka broker does NOT guard commit
                    # monotonicity, so committing outside it lets a
                    # backed-off retry push a stale lower offset over a
                    # newer one.  Retry sleeps happen in _retry.call,
                    # outside this closure/lock.
                    self.broker.commit(self.group_id, self._topic, partition,
                                       max(offset, cur),
                                       generation=self._generation,
                                       member_id=self.member_id)
                else:
                    # lint: lock-discipline ok — same atomic
                    # frontier-read + commit step as the fenced branch
                    self.broker.commit(self.group_id, self._topic, partition,
                                       max(offset, cur))
        try:
            self._retry.call(do, stop_event=self._stop_event,
                             on_retry=self._count_retry,
                             label="broker.commit")
        except StaleGenerationError:
            # typed, NOT retried (not an OSError): the caller — a worker
            # acking a just-published file — must unpublish and drop the
            # fenced runs, never spin
            self._fenced_commits += 1
            raise

    def commit_allowed(self, partition: int) -> bool:
        """Would an ack-commit for ``partition`` from this member be
        accepted right now?  The writer's PRE-publish fence check: a file
        about to be renamed into the tree whose runs can no longer be
        acked is abandoned instead (the new owner redelivers)."""
        if not self._coordinated:
            return True
        fn = getattr(self.broker, "commit_allowed", None)
        if not callable(fn):
            return True
        return bool(fn(self.group_id, self._topic, partition,
                       generation=self._generation,
                       member_id=self.member_id))

    def _count_retry(self, attempt, exc, sleep_s) -> None:
        self._broker_retries += 1

    # -- internals ---------------------------------------------------------
    def _track_batch(self, partition: int, records: list[Record]) -> list[Record]:
        """Track a fetch batch in contiguous runs, chunked at offset-tracker
        page boundaries with a backpressure re-check per chunk (granularity:
        the open-page bound may be exceeded by at most the one page that
        trips it, mirroring the per-record loop this replaces at page
        resolution instead of record resolution)."""
        tr = self.tracker
        page = tr.page_size
        accepted_until = 0  # index into records
        i = 0
        n = len(records)
        # a partition fetch is one contiguous offset run in the common case
        # (gaps only on compacted topics): one O(1) check replaces the
        # per-record walk below — offsets are strictly increasing, so
        # last-first == n-1 proves contiguity
        contiguous = n > 0 and (records[-1].offset - records[0].offset
                                == n - 1)
        while i < n:
            if tr.is_backpressured(partition):
                break
            # contiguous run starting at i, clipped at the next page boundary
            start = records[i].offset
            if i > 0 and start > records[i - 1].offset + 1:
                # compacted-topic gap INSIDE the batch: those offsets can
                # never be delivered or acked, and an un-ackable hole would
                # park the commit frontier forever — skip them (marked
                # delivered+acked; Kafka semantics: the committed offset may
                # pass compacted-away offsets).  Any frontier advance rides
                # the next real ack's broker commit.
                tr.skip_run(partition, records[i - 1].offset + 1,
                            start - records[i - 1].offset - 1)
            page_end_off = (start // page + 1) * page
            if contiguous:
                j = i + min(n - i, page_end_off - start)
            else:
                j = i + 1
                while (j < n and records[j].offset == records[j - 1].offset + 1
                       and records[j].offset < page_end_off):
                    j += 1
            tr.track_run(partition, start, records[j - 1].offset - start + 1)
            accepted_until = j
            i = j
        return records[:accepted_until] if accepted_until < n else records

    def _track_run_batch(self, partition: int, pos: int,
                         rb: RecordBatch) -> RecordBatch | None:
        """Track one contiguous RecordBatch run, chunked at offset-tracker
        page boundaries with a backpressure re-check per chunk — the batch
        analog of :meth:`_track_batch` at the same granularity (the
        open-page bound may be exceeded by at most the one page that trips
        it).  A head gap (the batch starts past the fetch position:
        offsets compacted away at the source) is pre-acked so the commit
        frontier can cross it — the ack-correctness seam the RecordBatch
        contiguity contract must honor.  Returns the accepted prefix as a
        zero-copy slice, or None when backpressure admitted nothing."""
        tr = self.tracker
        start = rb.start_offset
        if start > pos:
            tr.skip_run(partition, pos, start - pos)
        page = tr.page_size
        end = start + len(rb)
        off = start
        while off < end:
            if tr.is_backpressured(partition):
                break
            take = min(end, (off // page + 1) * page) - off
            tr.track_run(partition, off, take)
            off += take
        accepted = off - start
        if accepted == 0:
            return None
        return rb if accepted == len(rb) else rb.slice(0, accepted)

    def _refresh_assignment(self) -> None:
        gen = self.broker.generation(self.group_id, self._topic)
        if gen == self._generation:
            return
        if not self._coordinated or self._generation < 0:
            # legacy brokers (and the first assignment after a join/
            # rejoin): FULL reset — every partition rewinds to the
            # committed frontier and delivered-but-unacked records
            # redeliver (at-least-once allows the duplicates)
            if self._generation >= 0:
                self._full_resets += 1
            self._generation = gen
            self._assigned = self.broker.assignment(self.group_id,
                                                    self._topic,
                                                    self.member_id)
            self._positions = {}
            for p in self._assigned:
                base = self.broker.committed(self.group_id, self._topic, p)
                self._positions[p] = base
                self.tracker.reset_partition(p, base)
            return
        # cooperative (incremental) rebalance: only the delta moves.
        # Retained partitions keep their queue contents, tracker pages and
        # fetch positions — unaffected flow never stalls.
        self._cooperative_rebalances += 1
        new_assigned = self.broker.assignment(self.group_id, self._topic,
                                              self.member_id)
        old, new = set(self._assigned), set(new_assigned)
        revoked = sorted(old - new)
        added = sorted(new - old)
        self._generation = gen
        self._assigned = new_assigned
        lis = self._rebalance_listener
        if lis is not None:
            try:
                lis.on_generation(gen, revoked, added)
            # lint: swallowed-exceptions ok — listener callbacks are
            # observability hooks on the fetcher thread; a raising hook
            # must not kill the fetch loop mid-rebalance
            except Exception:
                logger.exception("rebalance listener on_generation raised")
        if revoked:
            self._begin_revocation(revoked)
        for p in added:
            base = self.broker.committed(self.group_id, self._topic, p)
            self._positions[p] = base
            self.tracker.reset_partition(p, base)

    def _begin_revocation(self, revoked: list[int]) -> None:
        """Fetcher thread: stop serving ``revoked`` — purge their queued-
        but-unpolled records (a worker must not write rows this member can
        no longer ack), drop their fetch positions, tell the writer to
        fence its in-flight files, and open the drain window
        :meth:`_poll_revocation` completes."""
        rev = set(revoked)
        dropped = 0
        with self._buf_cond:
            kept: deque = deque()
            for i, chunk in enumerate(self._buf):
                part = (chunk.partition if isinstance(chunk, RecordBatch)
                        else (chunk[0].partition if chunk else None))
                if part in rev:
                    n = len(chunk) - (self._head_pos if i == 0 else 0)
                    dropped += n
                    self._buf_count -= n
                    if i == 0:
                        self._head_pos = 0
                else:
                    kept.append(chunk)
            self._buf = kept
            if dropped:
                self._revoked_purged += dropped
                if self._listener is not None:
                    # credit the queue-occupancy ledger: purged records
                    # left the queue exactly like a drain round
                    self._listener.on_drained(dropped)
                self._buf_cond.notify_all()
        for p in revoked:
            self._positions.pop(p, None)
        lis = self._rebalance_listener
        if lis is not None:
            try:
                lis.on_partitions_revoked(list(revoked))
            # lint: swallowed-exceptions ok — same contract as
            # on_generation: a raising hook must not kill the fetcher
            except Exception:
                logger.exception("rebalance listener on_revoked raised")
        deadline = time.monotonic() + self._drain_deadline_s
        pend = self._revoke_pending
        if pend is None:
            self._revoke_pending = {"parts": rev, "deadline": deadline}
        else:  # back-to-back rebalances: merge, keep the later deadline
            pend["parts"] |= rev
            pend["deadline"] = max(pend["deadline"], deadline)

    def _poll_revocation(self) -> None:
        """Fetcher thread: complete an open drain window once the writer
        reports its in-flight files for the revoked partitions are
        published-and-acked (or the deadline lapses — then whatever is
        still in flight is abandoned and the new owner redelivers it)."""
        pend = self._revoke_pending
        if pend is None:
            return
        parts = sorted(pend["parts"])
        lis = self._rebalance_listener
        drained = True
        if lis is not None:
            try:
                drained = bool(lis.revocation_drained(parts))
            # lint: swallowed-exceptions ok — a raising drain probe must
            # not wedge the window open forever; treat as drained and let
            # at-least-once redelivery cover whatever was in flight
            except Exception:
                logger.exception("rebalance listener drain probe raised")
        timed_out = time.monotonic() >= pend["deadline"]
        if not drained and not timed_out:
            return
        if not drained and lis is not None:
            try:
                lis.on_revocation_timeout(parts)
            # lint: swallowed-exceptions ok — observability hook, same
            # fetcher-thread contract as the callbacks above
            except Exception:
                logger.exception("rebalance listener timeout hook raised")
        for p in parts:
            # this member is done with p: clear its tracker state down to
            # the committed frontier (whatever did not get acked in the
            # window is the new owner's redelivery)
            self.tracker.reset_partition(
                p, self.broker.committed(self.group_id, self._topic, p))
        self._retry.call(
            lambda: self.broker.confirm_revocation(
                self.group_id, self._topic, self.member_id, parts),
            stop_event=self._stop_event,
            on_retry=self._count_retry, label="broker.confirm_revocation")
        self._revoke_pending = None

    def _heartbeat_tick(self) -> None:
        """Fetcher thread, throttled to a quarter of the session window:
        stamp liveness; a ``rejoin`` response means this member missed its
        window and was expelled — everything it held is LOST."""
        now = time.monotonic()
        if now - self._last_hb < self._hb_interval_s:
            return
        self._last_hb = now
        resp = self._retry.call(
            lambda: self.broker.heartbeat(self.group_id, self._topic,
                                          self.member_id),
            stop_event=self._stop_event,
            on_retry=self._count_retry, label="broker.heartbeat")
        if resp.get("rejoin"):
            self._rejoin()

    def _rejoin(self) -> None:
        """Expelled (missed session window — the zombie path): drop every
        held partition as LOST, then WAIT until the writer has resolved
        its in-flight files for them BEFORE rejoining.  The wait is the
        exactly-once keystone: a worker blocked mid-publish must finish,
        take its fenced-commit rejection, and unpublish while this member
        is still an outsider — rejoining first would make it an owner
        again and its stale ack would be accepted."""
        self._rejoins += 1
        lost = sorted(self._assigned)
        lis = self._rebalance_listener
        if lost:
            self._begin_revocation(lost)  # purge queue + writer fencing
            self._revoke_pending = None   # not a drain window: LOST, no
            #                               confirm_revocation to send
            self._assigned = []
            self._positions = {}
            if lis is not None:
                try:
                    lis.on_partitions_lost(lost)
                # lint: swallowed-exceptions ok — observability hook on
                # the fetcher thread; the rejoin must proceed regardless
                except Exception:
                    logger.exception("rebalance listener on_lost raised")
        if lis is not None and lost:
            warned = False
            deadline = time.monotonic() + self._drain_deadline_s
            # hard bound at 4x the drain deadline: a worker that can no
            # longer respond (a SIGKILL-orphaned or parked child process
            # whose abandon descriptor it will never service) must not
            # wedge the rejoin forever — its runs were never acked, so
            # proceeding costs only at-least-once redelivery, while a
            # member that never rejoins starves its share of the topic
            hard_stop = deadline + 3 * self._drain_deadline_s
            while not self._stop_event.is_set():
                try:
                    if lis.revocation_drained(lost):
                        break
                # lint: swallowed-exceptions ok — a raising drain probe
                # treated as drained: at-least-once redelivery covers it
                except Exception:
                    logger.exception("drain probe raised during rejoin")
                    break
                now = time.monotonic()
                if not warned and now > deadline:
                    warned = True
                    logger.warning(
                        "rejoin of %s waiting on in-flight files for lost "
                        "partitions %s past the drain deadline",
                        self.member_id, lost)
                if now > hard_stop:
                    self._rejoin_drain_timeouts += 1
                    logger.error(
                        "rejoin of %s abandoning the drain wait for lost "
                        "partitions %s (4x drain deadline): in-flight "
                        "files stay un-acked and redeliver",
                        self.member_id, lost)
                    break
                time.sleep(0.005)
        self.broker.join_group(self.group_id, self._topic, self.member_id)
        self._generation = -1  # force a FULL reset on the next refresh

    def _fetch_loop(self) -> None:
        try:
            self._fetch_loop_inner()
        except RetryInterrupted:
            pass  # close() during a fetch retry: clean shutdown
        except Exception as e:
            self._fetcher_error = repr(e)
            logger.exception(
                "consumer fetcher thread died; poll() will starve")
            raise

    def _fetch_loop_inner(self) -> None:
        # feature-detect ONCE: batch-native fetch needs a broker with
        # fetch_batch (FakeBroker, a batch-capable client, or a fault
        # wrapper mirroring one); anything else rides the Record path
        use_batch = (self._batch_ingest
                     and callable(getattr(self.broker, "fetch_batch", None)))
        while self._running:
            if self._suspended:
                time.sleep(0.005)  # SIGSTOP analog: no heartbeat, no fetch
                continue
            if self._coordinated:
                self._heartbeat_tick()
                self._poll_revocation()
            self._refresh_assignment()
            if self._autotune is not None:
                self._apply_autotune()
            fetched = 0
            for p in list(self._assigned):
                if not self._running:
                    break
                if self.tracker.is_backpressured(p):
                    # open-page backpressure (KPW.java:596-611): counted so
                    # a stalled partition is visible from stats(), not just
                    # inferred from a flat-lining consumer rate
                    self._backpressure_skips += 1
                    continue
                pos = self._positions.get(p, 0)
                if use_batch:
                    with stage("consumer.fetch"):
                        rb = self._retry.call(
                            lambda: self.broker.fetch_batch(
                                self._topic, p, pos, self._fetch_max),
                            stop_event=self._stop_event,
                            on_retry=self._count_retry, label="broker.fetch")
                    if rb is None or len(rb) == 0:
                        continue
                    self._batch_fetches += 1
                    with stage("consumer.track"):
                        rb = self._track_run_batch(p, pos, rb)
                    if rb is None:
                        continue
                    if not self._put_batch(rb):
                        break  # shutting down: position not advanced
                    self._positions[p] = rb.start_offset + len(rb)
                    fetched += len(rb)
                    continue
                with stage("consumer.fetch"):
                    # transient poll errors back off and retry in place;
                    # only a fatal-classified error (or retry-budget
                    # exhaustion on a bounded policy) kills the fetcher
                    records = self._retry.call(
                        lambda: self.broker.fetch(self._topic, p, pos,
                                                  self._fetch_max),
                        stop_event=self._stop_event,
                        on_retry=self._count_retry, label="broker.fetch")
                with stage("consumer.track"):
                    if records and records[0].offset > pos:
                        # head gap (offsets compacted away at the source):
                        # pre-ack so the frontier can cross it, mirroring
                        # the interior-gap handling in _track_batch
                        self.tracker.skip_run(p, pos,
                                              records[0].offset - pos)
                    accepted = self._track_batch(p, records)
                if not accepted:
                    continue
                if not self._put_batch(accepted):
                    break  # shutting down: position not advanced, redelivered
                self._positions[p] = accepted[-1].offset + 1
                fetched += len(accepted)
            if fetched == 0:
                time.sleep(0.001)

    def _apply_autotune(self) -> None:
        """Tick the autotuner with the queue's cumulative counters and
        apply the tuned knobs.  Raising the queue bound must wake a
        fetcher/redelivery blocked on the old (smaller) bound — they
        re-read ``_buf_max`` under the condition."""
        tun = self._autotune
        tun.observe(time.perf_counter(), self._records_in, self._records_out)
        self._fetch_max = tun.fetch_max
        if tun.queue_cap != self._buf_max:
            with self._buf_cond:
                self._buf_max = tun.queue_cap
                self._buf_cond.notify_all()
