"""Smart-commit consumer: bounded shared queue + paged offset tracking +
open-page backpressure.

Interface parity with the external library the reference wires at
KafkaProtoParquetWriter.java:153-163: ``subscribe(topic)``, ``start()``,
``poll()`` (non-blocking, many workers concurrently), ``ack(PartitionOffset)``,
``close()``; auto-commit is never used — the committed offset only advances
over acked pages (at-least-once anchor, README.MD:6).  A single fetcher
thread owns broker I/O (the reference's consumer thread), workers share the
bounded queue (``maxQueuedRecordsInConsumer``, KPW.java:468).
"""

from __future__ import annotations

import queue
import threading
import uuid

from .broker import FakeBroker, Record
from .offsets import PagedOffsetTracker, PartitionOffset


class SmartCommitConsumer:
    def __init__(
        self,
        broker: FakeBroker,
        group_id: str,
        page_size: int = 300_000,
        max_open_pages_per_partition: int = 1,
        max_queued_records: int = 100_000,
        fetch_max_records: int = 2000,
        member_id: str | None = None,
    ) -> None:
        self.broker = broker
        self.group_id = group_id
        self.member_id = member_id or f"member-{uuid.uuid4().hex[:8]}"
        self.tracker = PagedOffsetTracker(page_size, max_open_pages_per_partition)
        self._queue: queue.Queue[Record] = queue.Queue(maxsize=max_queued_records)
        self._fetch_max = fetch_max_records
        self._topic: str | None = None
        self._thread: threading.Thread | None = None
        self._running = False
        self._positions: dict[int, int] = {}  # partition -> next fetch offset
        self._assigned: list[int] = []
        self._generation = -1
        self._commit_lock = threading.Lock()

    # -- lifecycle ---------------------------------------------------------
    def subscribe(self, topic: str) -> None:
        if self._topic is not None:
            raise ValueError("already subscribed")
        self._topic = topic

    def start(self) -> None:
        if self._topic is None:
            raise ValueError("subscribe() before start()")
        if self._thread is not None:
            raise ValueError("already started")
        self.broker.join_group(self.group_id, self._topic, self.member_id)
        self._running = True
        self._thread = threading.Thread(target=self._fetch_loop,
                                        name=f"smart-consumer-{self.member_id}",
                                        daemon=True)
        self._thread.start()

    def close(self) -> None:
        self._running = False
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._topic is not None:
            self.broker.leave_group(self.group_id, self._topic, self.member_id)

    # -- worker API --------------------------------------------------------
    def poll(self, timeout: float | None = None) -> Record | None:
        """Non-blocking by default (reference workers sleep 1 ms on null,
        KPW.java:260-263)."""
        try:
            if timeout is None:
                return self._queue.get_nowait()
            return self._queue.get(timeout=timeout)
        except queue.Empty:
            return None

    def ack(self, po: PartitionOffset) -> None:
        new_commit = self.tracker.ack(po)
        if new_commit is not None:
            with self._commit_lock:
                self.broker.commit(self.group_id, self._topic, po.partition,
                                   new_commit)

    # -- internals ---------------------------------------------------------
    def _refresh_assignment(self) -> None:
        gen = self.broker.generation(self.group_id, self._topic)
        if gen == self._generation:
            return
        self._generation = gen
        self._assigned = self.broker.assignment(self.group_id, self._topic,
                                                self.member_id)
        self._positions = {}
        for p in self._assigned:
            base = self.broker.committed(self.group_id, self._topic, p)
            self._positions[p] = base
            self.tracker.reset_partition(p, base)

    def _fetch_loop(self) -> None:
        import logging
        import time

        try:
            self._fetch_loop_inner()
        except Exception:
            logging.getLogger(__name__).exception(
                "consumer fetcher thread died; poll() will starve")
            raise

    def _fetch_loop_inner(self) -> None:
        import time

        while self._running:
            self._refresh_assignment()
            fetched = 0
            for p in list(self._assigned):
                if not self._running:
                    break
                if self.tracker.is_backpressured(p):
                    continue  # open-page backpressure (KPW.java:596-611)
                pos = self._positions.get(p, 0)
                records = self.broker.fetch(self._topic, p, pos, self._fetch_max)
                for rec in records:
                    if self.tracker.is_backpressured(p):
                        break  # re-check mid-batch: one fetch must not blow the bound
                    self.tracker.track(p, rec.offset)
                    while self._running:
                        try:
                            self._queue.put(rec, timeout=0.05)
                            break
                        except queue.Full:
                            continue
                    if not self._running:
                        break
                    self._positions[p] = rec.offset + 1
                    fetched += 1
            if fetched == 0:
                time.sleep(0.001)
