"""Real-Kafka broker client behind the same surface as the in-process
``FakeBroker`` (SURVEY.md §7 step 3: "Real-broker client optional behind the
same interface").

``SmartCommitConsumer`` consumes the broker through seven methods —
``join_group / leave_group / generation / assignment / committed / fetch /
commit`` — so pointing the writer at a real cluster is just

    broker = KafkaBrokerClient(bootstrap_servers="host:9092")
    Builder().broker(broker)...

The adapter maps that surface onto ``kafka-python`` (the same wire client
family the reference uses from the JVM, KafkaProtoParquetWriter.java:30-32):

- group membership and rebalancing ride Kafka's own consumer-group protocol
  via one subscribed ``KafkaConsumer`` per (group, member), with auto-commit
  forced off exactly like the reference forcing ``enable.auto.commit=false``
  (KPW.java:156);
- ``KafkaConsumer`` is not thread-safe, so every touch of a member's
  consumer happens under that member's lock — the writer's fetcher thread
  (fetch) and worker threads (commit on ack) serialize here;
- ``fetch``/``commit`` route to the member that *owns* the partition (the
  assignment can be split across several members of the same client);
- the group join needs poll() calls to make progress, so ``generation()`` —
  which the smart consumer's fetch loop calls every iteration — drives a
  short poll on any member that still has no assignment.

``kafka-python`` is an optional dependency — constructing the client without
it raises ImportError with install guidance; nothing here is imported at
package import time.  No broker exists in the test image, but every branch
here (join/pump/assign/fetch/seek/pause/resume/commit/rebalance) is driven
by a scripted fake ``kafka.KafkaConsumer`` in tests/test_real_adapters.py
(see tests/fake_kafka.py); the FakeBroker-backed integration suite drives
the identical consumer surface (tests/test_ingest.py,
test_writer_integration.py).
"""

from __future__ import annotations

import threading

from .broker import Record


class _Member:
    __slots__ = ("consumer", "lock", "generation", "closed")

    def __init__(self, consumer) -> None:
        self.consumer = consumer
        self.lock = threading.Lock()
        self.generation = 0
        # set (under lock) by leave_group before consumer.close(): a closed
        # kafka-python consumer can still report its last assignment, so
        # liveness cannot be inferred from assignment() alone
        self.closed = False


class KafkaBrokerClient:
    """FakeBroker-compatible consumer surface over a real Kafka cluster."""

    def __init__(self, bootstrap_servers: str | list[str],
                 client_config: dict | None = None,
                 poll_timeout_ms: int = 100) -> None:
        try:
            import kafka  # noqa: F401
        except ImportError as e:  # pragma: no cover - exercised without dep
            raise ImportError(
                "KafkaBrokerClient needs the 'kafka-python' package "
                "(pip install kafka-python); for broker-less operation use "
                "kpw_tpu.ingest.FakeBroker") from e
        self._bootstrap = bootstrap_servers
        self._config = dict(client_config or {})
        self._poll_timeout_ms = poll_timeout_ms
        self._reg_lock = threading.Lock()  # guards the member registry only
        self._members: dict[tuple[str, str], _Member] = {}
        # generation() must be MONOTONE per group: a departing member takes
        # its rebalance count out of the sum, which could cancel a
        # survivor's increment and hide the rebalance from the smart
        # consumer — fold removed members' counts (plus one for the leave
        # itself) into a per-group base
        self._gen_base: dict[str, int] = {}
        # last known (group, TopicPartition) -> owning member; a pure
        # accelerator for _owner() — every hit is re-validated against the
        # member's live assignment, so stale entries only cost a rescan
        self._owner_cache: dict[tuple, _Member] = {}

    # -- group membership --------------------------------------------------
    def join_group(self, group: str, topic: str, member_id: str) -> None:
        from kafka import ConsumerRebalanceListener, KafkaConsumer

        key = (group, member_id)
        with self._reg_lock:
            if key in self._members:
                return
            cfg = dict(self._config)
            # Smart-commit invariant: the broker-side offset only moves via
            # our explicit commit() after durable publish (KPW.java:156).
            cfg.update(enable_auto_commit=False, group_id=group,
                       auto_offset_reset="earliest",
                       key_deserializer=None, value_deserializer=None)
            consumer = KafkaConsumer(bootstrap_servers=self._bootstrap, **cfg)
            member = _Member(consumer)

            class _Listener(ConsumerRebalanceListener):
                def on_partitions_revoked(self, revoked):
                    pass

                def on_partitions_assigned(self, assigned):
                    member.generation += 1  # fires inside member's poll()

            consumer.subscribe([topic], listener=_Listener())
            self._members[key] = member

    def leave_group(self, group: str, topic: str, member_id: str) -> None:
        with self._reg_lock:
            member = self._members.pop((group, member_id), None)
            if member is not None:
                self._gen_base[group] = (self._gen_base.get(group, 0)
                                         + member.generation + 1)
                # a closed kafka-python consumer can still report its old
                # assignment, so the cache's validity check would pass and
                # route commits to a dead consumer — drop its entries now
                for key in [k for k, m in self._owner_cache.items()
                            if m is member]:
                    self._owner_cache.pop(key, None)
        if member is not None:
            with member.lock:
                member.closed = True
                member.consumer.close()

    def _group_members(self, group: str) -> list[_Member]:
        with self._reg_lock:
            return [m for (g, _), m in self._members.items() if g == group]

    def generation(self, group: str, topic: str) -> int:
        """Sum of rebalance counts — changes whenever any member's
        assignment changes.  Also pumps the group protocol: a member that
        has no assignment yet only completes its join inside poll(), and the
        smart consumer calls generation() every fetch-loop iteration."""
        with self._reg_lock:
            # base + snapshot under ONE lock round: a concurrent leave_group
            # folds the departed member's count into the base, and reading
            # them separately could transiently dip below the last reported
            # value — the exact hidden-rebalance window this base closes
            total = self._gen_base.get(group, 0)
            members = [m for (g, _), m in self._members.items() if g == group]
        for member in members:
            with member.lock:
                if not member.consumer.assignment():
                    member.consumer.poll(timeout_ms=self._poll_timeout_ms,
                                         max_records=1, update_offsets=False)
                total += member.generation
        return total

    def assignment(self, group: str, topic: str, member_id: str) -> list[int]:
        with self._reg_lock:
            member = self._members.get((group, member_id))
        if member is None:
            return []
        with member.lock:
            return sorted(tp.partition for tp in member.consumer.assignment()
                          if tp.topic == topic)

    def _owner(self, group: str, topic: str, partition: int) -> _Member | None:
        from kafka import TopicPartition

        tp = TopicPartition(topic, partition)
        # Fast path: the last known owner, validated with one O(1)
        # assignment lookup under its own lock.  commit() runs per ack round
        # — a full members scan (locking every consumer) per commit is
        # O(members) of lock traffic that this cache avoids; a rebalance
        # invalidates the entry naturally (the membership check fails).
        cached = self._owner_cache.get((group, tp))
        if cached is not None:
            try:
                with cached.lock:
                    # closed check under the SAME lock as the assignment
                    # probe: an entry fetched just before leave_group's
                    # purge would otherwise pass the assignment check
                    # against a closed consumer whose assignment() persists
                    if not cached.closed and tp in cached.consumer.assignment():
                        return cached
            # lint: swallowed-exceptions ok — probing a cached owner that
            # may be mid-close: kafka-python raises client-internal types
            # here; any failure just invalidates the cache and the full
            # member scan below re-resolves authoritatively
            except Exception:
                pass  # closed/leaving consumer: fall through to the scan
        for member in self._group_members(group):
            with member.lock:
                # same closed check as the fast path: the members snapshot
                # can include one that leave_group closed a moment later
                if not member.closed and tp in member.consumer.assignment():
                    self._owner_cache[(group, tp)] = member
                    return member
        self._owner_cache.pop((group, tp), None)
        return None

    # -- offsets -----------------------------------------------------------
    def committed(self, group: str, topic: str, partition: int) -> int:
        from kafka import TopicPartition
        from kafka.structs import OffsetAndMetadata

        members = self._group_members(group)
        if not members:
            return 0
        member = self._owner(group, topic, partition) or members[0]
        with member.lock:
            got = member.consumer.committed(TopicPartition(topic, partition))
        if isinstance(got, OffsetAndMetadata):
            got = got.offset
        return int(got or 0)

    def commit(self, group: str, topic: str, partition: int, offset: int,
               generation: int | None = None,
               member_id: str | None = None) -> None:
        """Commit via the partition's owning member.  During a rebalance the
        ownership snapshot can go stale between resolve and commit — the
        broker then rejects the commit (CommitFailedError).  That window is
        retriable, not fatal: re-resolve the owner and try again for a
        bounded number of rounds before surfacing (a raise here would kill
        the worker mid-rebalance for a transient condition).

        ``generation``/``member_id`` are accepted for FakeBroker signature
        parity but unused: a real cluster runs Kafka's own generation
        fencing — a zombie's commit is rejected broker-side as
        CommitFailedError by the group coordinator itself."""
        import time as _time

        from kafka import TopicPartition
        from kafka.errors import CommitFailedError
        from kafka.structs import OffsetAndMetadata

        last_err: Exception | None = None
        for attempt in range(8):
            member = self._owner(group, topic, partition)
            if member is None:
                members = self._group_members(group)
                if not members:
                    raise RuntimeError(f"no consumer joined for group {group}")
                member = members[0]
            try:
                with member.lock:
                    # lint: lock-discipline ok — kafka-python KafkaConsumer
                    # is not thread-safe; member.lock IS the serialization
                    # of every call into it, so the (network-blocking)
                    # commit must run under it by the client's contract
                    member.consumer.commit({TopicPartition(topic, partition):
                                            OffsetAndMetadata(offset, None, -1)})
                return
            except CommitFailedError as e:  # the rebalance window; anything
                last_err = e                # else is not retriable here
                # let the group protocol make progress before re-resolving
                _time.sleep(0.05 * (attempt + 1))
        raise RuntimeError(
            f"commit of {topic}/{partition}@{offset} kept failing across "
            "rebalance retries") from last_err

    # -- records -----------------------------------------------------------
    def fetch(self, topic: str, partition: int, offset: int,
              max_records: int) -> list[Record]:
        from kafka import TopicPartition

        # group is not part of the FakeBroker fetch signature; all members
        # of this client share the data path, so route by ownership across
        # every registered member.
        with self._reg_lock:
            members = list(self._members.values())
        tp = TopicPartition(topic, partition)
        for member in members:
            with member.lock:
                consumer = member.consumer
                if tp not in consumer.assignment():
                    continue
                if consumer.position(tp) != offset:
                    consumer.seek(tp, offset)
                # Steady state keeps every partition except the fetch target
                # paused, issuing pause/resume only for the DELTA vs the
                # consumer's current pause set — consecutive fetches of the
                # same partition cost zero calls, round-robining costs two,
                # versus 2*(n-1) for pause-all/resume-all per fetch.  A
                # rebalance self-heals: revoked partitions drop out of
                # paused(), newly assigned ones arrive unpaused and land in
                # want_paused on the next call.
                assigned = set(consumer.assignment())
                cur_paused = set(consumer.paused())
                want_paused = assigned - {tp}
                to_pause = want_paused - cur_paused
                if to_pause:
                    consumer.pause(*to_pause)
                to_resume = cur_paused - want_paused
                if to_resume:
                    consumer.resume(*to_resume)
                batch = consumer.poll(timeout_ms=self._poll_timeout_ms,
                                      max_records=max_records)
                return [Record(topic=topic, partition=partition,
                               offset=r.offset, key=r.key, value=r.value,
                               timestamp=r.timestamp / 1000.0)
                        for r in batch.get(tp, [])]
        return []
