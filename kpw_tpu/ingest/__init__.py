"""Ingest layer: record sources with smart-commit at-least-once semantics.

Rebuilds the capability the reference imports as the external
``smart-commit-kafka-consumer`` library (SURVEY.md §2.2): a bounded shared
queue many workers poll, a paged per-partition offset tracker whose commit
frontier advances only over fully-acked consecutive pages, and open-page
backpressure.  The broker itself is pluggable: the in-process ``FakeBroker``
(partitioned append logs + consumer groups, the §4 test-infra analog of an
embedded Kafka broker) or any client implementing the same small interface.
"""

from .broker import FakeBroker, Record, RecordBatch  # noqa: F401
from .offsets import PagedOffsetTracker, PartitionOffset  # noqa: F401
from .consumer import SmartCommitConsumer  # noqa: F401
from .kafka_client import KafkaBrokerClient  # noqa: F401  (needs kafka-python at construction)
# lint: fault-isolation ok — the package's public opt-in seam: tests and
# benchmarks import FaultInjectingBroker from here; no production call
# path references it (enforced by tools/analyze's fault-isolation pass)
from .faults import FaultInjectingBroker  # noqa: F401
