"""Paged per-partition offset tracking for at-least-once commit.

Semantics rebuilt from the reference's smart-commit consumer configuration
surface (KafkaProtoParquetWriter.java:584-622): delivered offsets are grouped
into fixed-size consecutive *pages*; the committed frontier advances only past
pages whose every delivered offset has been acked; the number of open
(delivered-but-not-fully-acked) pages per partition is bounded and exposed for
backpressure.  Memory is O(open pages), not O(outstanding offsets) — pages
hold numpy bitmaps.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np


@dataclass(frozen=True)
class PartitionOffset:
    """(partition, offset) ack handle — reference PartitionOffset
    (KPW.java:10,233,278)."""

    partition: int
    offset: int


class _Page:
    __slots__ = ("start", "acked", "acked_count", "delivered_end")

    def __init__(self, start: int, size: int) -> None:
        self.start = start
        self.acked = np.zeros(size, bool)
        self.acked_count = 0
        self.delivered_end = start  # exclusive frontier of delivery in page


class _PartitionTracker:
    def __init__(self, page_size: int, base: int) -> None:
        self.page_size = page_size
        self.committed = base  # next offset to commit (all below acked)
        self.delivered = base  # next expected delivery
        self.pages: dict[int, _Page] = {}  # page index -> page

    def _page_for(self, offset: int) -> _Page:
        idx = offset // self.page_size
        page = self.pages.get(idx)
        if page is None:
            page = _Page(idx * self.page_size, self.page_size)
            self.pages[idx] = page
        return page

    def track(self, offset: int) -> None:
        page = self._page_for(offset)
        if offset >= page.delivered_end:
            page.delivered_end = offset + 1
        if offset >= self.delivered:
            self.delivered = offset + 1

    def track_run(self, start: int, count: int) -> None:
        """Track a contiguous run [start, start+count) in O(pages touched)
        instead of O(count) — fetch batches arrive as runs, and per-offset
        tracking was the streaming fetcher's hottest line."""
        end = start + count
        off = start
        while off < end:
            page = self._page_for(off)
            page_end = min(end, page.start + self.page_size)
            if page_end > page.delivered_end:
                page.delivered_end = page_end
            off = page_end
        if end > self.delivered:
            self.delivered = end

    def ack(self, offset: int) -> None:
        if offset < self.committed:
            return  # duplicate delivery from a previous generation
        page = self._page_for(offset)
        slot = offset - page.start
        if not page.acked[slot]:
            page.acked[slot] = True
            page.acked_count += 1

    def ack_run(self, start: int, count: int) -> None:
        """Ack a contiguous run [start, start+count): numpy slice per page
        touched (the worker publishes whole poll batches at once)."""
        end = start + count
        off = max(start, self.committed)  # skip pre-commit duplicates
        while off < end:
            page = self._page_for(off)
            page_end = min(end, page.start + self.page_size)
            a, b = off - page.start, page_end - page.start
            seg = page.acked[a:b]
            newly = (b - a) - int(seg.sum())
            if newly:
                seg[:] = True
                page.acked_count += newly
            off = page_end

    def advance(self) -> int | None:
        """Advance the committed frontier across fully-acked pages (and a
        final partially-delivered page that is fully acked).  Returns the new
        commit offset if it moved."""
        moved = False
        while True:
            idx = self.committed // self.page_size
            page = self.pages.get(idx)
            if page is None:
                break
            delivered_in_page = page.delivered_end - page.start
            if delivered_in_page <= 0:
                break
            # consecutive acked run from the committed position (vectorized:
            # argmin finds the first un-acked flag)
            pos = self.committed - page.start
            sub = page.acked[pos:delivered_in_page]
            pos += len(sub) if sub.all() else int(np.argmin(sub))
            new_commit = page.start + pos
            if new_commit == self.committed:
                break
            self.committed = new_commit
            moved = True
            if pos == self.page_size:
                del self.pages[idx]  # page fully consumed
                continue
            break
        return self.committed if moved else None

    def open_pages(self) -> int:
        return len(self.pages)


class PagedOffsetTracker:
    """All-partition tracker; thread-safe."""

    def __init__(self, page_size: int = 300_000,
                 max_open_pages_per_partition: int = 1) -> None:
        if page_size <= 0:
            raise ValueError("page_size must be positive")
        self.page_size = page_size
        self.max_open_pages = max_open_pages_per_partition
        self._parts: dict[int, _PartitionTracker] = {}
        self._lock = threading.Lock()

    def _part(self, partition: int, base: int = 0) -> _PartitionTracker:
        t = self._parts.get(partition)
        if t is None:
            t = _PartitionTracker(self.page_size, base)
            self._parts[partition] = t
        return t

    def reset_partition(self, partition: int, base: int) -> None:
        with self._lock:
            self._parts[partition] = _PartitionTracker(self.page_size, base)

    def track(self, partition: int, offset: int) -> None:
        with self._lock:
            self._part(partition).track(offset)

    def track_run(self, partition: int, start: int, count: int) -> None:
        with self._lock:
            self._part(partition).track_run(start, count)

    def ack(self, po: PartitionOffset) -> int | None:
        """Record an ack; returns a new commit offset for the partition if
        the frontier advanced."""
        with self._lock:
            t = self._part(po.partition)
            t.ack(po.offset)
            return t.advance()

    def ack_run(self, partition: int, start: int, count: int) -> int | None:
        """Batch ack of a contiguous offset run; returns a new commit offset
        for the partition if the frontier advanced."""
        with self._lock:
            t = self._part(partition)
            t.ack_run(start, count)
            return t.advance()

    def skip_run(self, partition: int, start: int, count: int) -> None:
        """Mark [start, start+count) as never-deliverable (offsets
        compacted away at the source): delivered AND acked in one pass,
        so the commit frontier can cross the hole — an ack alone leaves
        ``delivered_end`` behind on every page the gap covers and
        ``advance()`` would park at the gap page forever (and the stuck
        open pages would trip backpressure permanently).  Any frontier
        advance is committed by the next real ack."""
        if count <= 0:
            return
        with self._lock:
            t = self._part(partition)
            t.track_run(start, count)
            t.ack_run(start, count)

    def committed(self, partition: int) -> int:
        with self._lock:
            return self._part(partition).committed

    def is_backpressured(self, partition: int) -> bool:
        """True when the partition has too many open pages: delivery must
        pause until acks catch up (reference `offsetTrackerMaxOpenPagesPerPartition`)."""
        with self._lock:
            t = self._parts.get(partition)
            if t is None:
                return False
            return t.open_pages() > self.max_open_pages

    def pending(self, partition: int) -> int:
        """Delivered-but-uncommitted count (diagnostics)."""
        with self._lock:
            t = self._parts.get(partition)
            return 0 if t is None else t.delivered - t.committed

    def snapshot(self) -> dict:
        """All-partition ack-frontier snapshot, one lock round: per
        partition the committed / delivered frontiers, the pending
        (delivered-but-uncommitted) gap, and the open-page count that
        drives backpressure — plus pre-summed totals.  ``pending`` is the
        tracker-level ack lag: records the consumer delivered whose
        offsets have not all been acked past the commit frontier yet."""
        with self._lock:
            parts = {
                p: {
                    "committed": t.committed,
                    "delivered": t.delivered,
                    "pending": t.delivered - t.committed,
                    "open_pages": t.open_pages(),
                }
                for p, t in sorted(self._parts.items())
            }
        return {
            "partitions": parts,
            "pending_total": sum(v["pending"] for v in parts.values()),
            "open_pages_total": sum(v["open_pages"] for v in parts.values()),
            "max_open_pages_per_partition": self.max_open_pages,
            "page_size": self.page_size,
        }
