"""Flat record-model bridge: avro-style field specs and plain dict/array
records -> parquet schema + ColumnBatch.

Covers the BASELINE.json benchmark record shapes ("flat Avro schema (8 int64 +
4 string cols)" etc.) without requiring protobuf classes.
"""

from __future__ import annotations

import numpy as np

from ..core.pages import ColumnChunkData
from ..core.schema import PhysicalType, Repetition, Schema, leaf
from ..core.writer import ColumnBatch

from ..core.schema import NUMPY_DTYPES as _NUMPY_DTYPES  # noqa: E402


def flat_schema(fields: list[tuple[str, str] | tuple[str, str, bool]],
                name: str = "record") -> Schema:
    """fields: (name, type_name[, nullable]) with type names from
    core.schema.leaf ('int64', 'string', 'double', ...)."""
    out = []
    for spec in fields:
        fname, tname = spec[0], spec[1]
        nullable = spec[2] if len(spec) > 2 else False
        out.append(leaf(fname, tname,
                        Repetition.OPTIONAL if nullable else Repetition.REQUIRED))
    return Schema(out, name=name)


def arrays_to_batch(schema: Schema, arrays: dict) -> ColumnBatch:
    """{name: ndarray | list[bytes] | (values, valid_mask)} -> ColumnBatch."""
    from ..core.writer import columns_from_arrays

    return columns_from_arrays(schema, arrays)


def dicts_to_batch(schema: Schema, records: list[dict]) -> ColumnBatch:
    """Row-major dict records -> ColumnBatch (None means null for OPTIONAL)."""
    n = len(records)
    chunks = []
    for col in schema.columns:
        key = col.name
        pt = col.leaf.physical_type
        dtype = _NUMPY_DTYPES.get(pt)
        if col.max_def > 0:
            raw = [r.get(key) for r in records]
            valid = np.array([v is not None for v in raw], bool)
            present = [v for v in raw if v is not None]
            def_levels = valid.astype(np.int32) * col.max_def
            values = (np.asarray(present, dtype) if dtype is not None
                      else [_to_bytes(v) for v in present])
            chunks.append(ColumnChunkData(col, values, def_levels, None, n))
        else:
            raw = [r[key] for r in records]
            values = (np.asarray(raw, dtype) if dtype is not None
                      else [_to_bytes(v) for v in raw])
            chunks.append(ColumnChunkData(col, values, None, None, n))
    return ColumnBatch(chunks, n)


def _to_bytes(v) -> bytes:
    if isinstance(v, bytes):
        return v
    return str(v).encode("utf-8")
