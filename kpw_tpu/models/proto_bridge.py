"""protobuf Message class -> parquet schema + Dremel columnarizer.

The reference's data model is "any com.google.protobuf.Message subclass"
(KafkaProtoParquetWriter.java:671-684) shredded by parquet-protobuf's
ProtoWriteSupport (ParquetFile.java:97-99).  Here the shredding is batched:
a list of parsed messages becomes one ColumnBatch (per-leaf value arrays +
repetition/definition levels), which the pluggable EncoderBackend turns into
pages — the boundary where the TPU path takes over.
"""

from __future__ import annotations

import os

import numpy as np

from google.protobuf.descriptor import FieldDescriptor as FD

from ..core.schema import (
    ColumnDescriptor,
    ConvertedType,
    Field,
    PhysicalType,
    Repetition,
    Schema,
)
from ..core.bytecol import ByteColumn
from ..core.writer import ColumnBatch
from ..core.pages import ColumnChunkData

# proto field type -> (physical type, converted type)
_SCALAR_MAP = {
    FD.TYPE_INT64: (PhysicalType.INT64, None),
    FD.TYPE_SINT64: (PhysicalType.INT64, None),
    FD.TYPE_SFIXED64: (PhysicalType.INT64, None),
    FD.TYPE_UINT64: (PhysicalType.INT64, ConvertedType.UINT_64),
    FD.TYPE_FIXED64: (PhysicalType.INT64, ConvertedType.UINT_64),
    FD.TYPE_INT32: (PhysicalType.INT32, None),
    FD.TYPE_SINT32: (PhysicalType.INT32, None),
    FD.TYPE_SFIXED32: (PhysicalType.INT32, None),
    FD.TYPE_UINT32: (PhysicalType.INT32, ConvertedType.UINT_32),
    FD.TYPE_FIXED32: (PhysicalType.INT32, ConvertedType.UINT_32),
    FD.TYPE_BOOL: (PhysicalType.BOOLEAN, None),
    FD.TYPE_FLOAT: (PhysicalType.FLOAT, None),
    FD.TYPE_DOUBLE: (PhysicalType.DOUBLE, None),
    FD.TYPE_STRING: (PhysicalType.BYTE_ARRAY, ConvertedType.UTF8),
    FD.TYPE_BYTES: (PhysicalType.BYTE_ARRAY, None),
    FD.TYPE_ENUM: (PhysicalType.BYTE_ARRAY, ConvertedType.ENUM),
}

from ..core.schema import NUMPY_DTYPES as _NUMPY_DTYPES  # noqa: E402


def _is_repeated(fd) -> bool:
    try:
        return fd.is_repeated
    except AttributeError:  # older protobuf runtimes
        return fd.label == FD.LABEL_REPEATED


def _is_required(fd) -> bool:
    try:
        return fd.is_required
    except AttributeError:
        return fd.label == FD.LABEL_REQUIRED


def _repetition_for(fd) -> int:
    if _is_repeated(fd):
        return Repetition.REPEATED
    if _is_required(fd):
        return Repetition.REQUIRED
    # proto3 no-presence scalars always carry a value (the default), so they
    # map to REQUIRED; explicit-presence fields map to OPTIONAL
    if not fd.has_presence:
        return Repetition.REQUIRED
    return Repetition.OPTIONAL


def _field_from_descriptor(fd) -> Field:
    rep = _repetition_for(fd)
    if fd.type == FD.TYPE_MESSAGE:
        children = [_field_from_descriptor(c) for c in fd.message_type.fields]
        return Field(name=fd.name, repetition=rep, children=children,
                     field_id=fd.number)
    if fd.type == FD.TYPE_GROUP:
        raise NotImplementedError("proto1 groups are not supported")
    phys, conv = _SCALAR_MAP[fd.type]
    return Field(name=fd.name, repetition=rep, physical_type=phys,
                 converted_type=conv, field_id=fd.number)


def proto_to_schema(msg_class) -> Schema:
    """Build the parquet schema for a protobuf message class."""
    desc = msg_class.DESCRIPTOR
    return Schema([_field_from_descriptor(fd) for fd in desc.fields],
                  name=desc.name)


def _file_syntax(file_desc) -> str:
    """'proto2' | 'proto3' (upb FileDescriptor hides .syntax; recover it
    from the serialized FileDescriptorProto).  Drives UTF-8 validation
    parity: proto3 parsers reject invalid UTF-8 in strings, proto2 parsers
    pass the raw bytes through."""
    syntax = getattr(file_desc, "syntax", None)
    if syntax:
        return syntax
    try:
        from google.protobuf import descriptor_pb2

        fdp = descriptor_pb2.FileDescriptorProto()
        file_desc.CopyToProto(fdp)
        return fdp.syntax or "proto2"
    except Exception:
        return "proto2"


class WireShredError(Exception):
    """The native wire-format shredder could not prove a record clean; the
    caller must re-parse the batch in Python (exact per-record semantics,
    including the poison-pill policies)."""

    def __init__(self, record_index: int) -> None:
        super().__init__(f"wire shred failed at record {record_index}")
        self.record_index = record_index


# field kinds — mirrored in kpw_tpu/native/src/shred.cc enum Kind
_K_VARINT64, _K_VARINT32, _K_SINT64, _K_SINT32 = 0, 1, 2, 3
_K_FIXED64, _K_FIXED32, _K_BOOL, _K_SPAN, _K_SPAN_UTF8 = 4, 5, 6, 7, 8
_F_REQUIRED = 1

# proto type -> (kind, numpy slot dtype or None for spans)
_WIRE_KINDS = {
    FD.TYPE_INT64: (_K_VARINT64, np.int64),
    FD.TYPE_UINT64: (_K_VARINT64, np.int64),   # raw bits = UINT_64 wrap
    FD.TYPE_SINT64: (_K_SINT64, np.int64),
    FD.TYPE_FIXED64: (_K_FIXED64, np.int64),
    FD.TYPE_SFIXED64: (_K_FIXED64, np.int64),
    FD.TYPE_INT32: (_K_VARINT32, np.int32),
    FD.TYPE_UINT32: (_K_VARINT32, np.int32),   # raw bits = UINT_32 wrap
    FD.TYPE_SINT32: (_K_SINT32, np.int32),
    FD.TYPE_FIXED32: (_K_FIXED32, np.int32),
    FD.TYPE_SFIXED32: (_K_FIXED32, np.int32),
    FD.TYPE_BOOL: (_K_BOOL, np.bool_),
    FD.TYPE_DOUBLE: (_K_FIXED64, np.float64),
    FD.TYPE_FLOAT: (_K_FIXED32, np.float32),
    FD.TYPE_STRING: (_K_SPAN, None),
    FD.TYPE_BYTES: (_K_SPAN, None),
    # TYPE_ENUM deliberately absent: proto2 closed-enum semantics (unknown
    # values land in unknown fields) need the Python path
}


class _WirePlan:
    """Precomputed arrays driving kpw_proto_shred for a flat schema."""

    __slots__ = ("fnum", "kinds", "flags", "dtypes", "optional", "_cont")

    def __init__(self, fnum, kinds, flags, dtypes, optional) -> None:
        self.fnum = fnum          # uint32 (n_fields,)
        self.kinds = kinds        # uint8
        self.flags = flags        # uint8
        self.dtypes = dtypes      # numpy dtype or None (span) per field
        self.optional = optional  # bool per field (needs presence/def levels)
        self._cont = None         # cached (fnum, kinds, flags) buffer forms
        #                           for the C-extension shred_flat_buf entry


# nested-plan kinds/flags — mirrored in kpw_tpu/native/src/shred_nested.cc
_K_MESSAGE, _K_ENUM = 9, 10
_FN_REQUIRED, _FN_REPEATED, _FN_DEF_INC = 1, 2, 4
_FN_EMIT_DEFAULT, _FN_CLOSED_ENUM = 8, 16


class _NestedPlan:
    """Node-table arrays driving kpw_proto_shred_nested: the schema tree
    flattened breadth-first (children contiguous), per-message-node direct
    field-number tables, closed-enum membership tables, and per-message
    descendant-leaf lists for absence emission."""

    __slots__ = ("n_nodes", "n_leaves", "fnum", "kind", "flags",
                 "child_begin", "child_end", "leaf_idx", "ftab", "ftab_off",
                 "max_fn", "enum_vals", "enum_off", "enum_len",
                 "null_leaves", "null_off", "null_len",
                 "leaf_kinds", "leaf_dtypes", "enum_names", "_cont")

    _TAB_NAMES = ("child_begin", "child_end", "leaf_idx", "ftab",
                  "ftab_off", "max_fn", "enum_vals", "enum_off", "enum_len",
                  "null_leaves", "null_off", "null_len")

    def cont(self):
        """Cached contiguous buffer forms for the C-extension fused entry
        (shred_nested_buf): (fnum u32, kind bytes, flags bytes, 12 int32
        table buffers) — built once per columnarizer, like _WirePlan._cont."""
        c = getattr(self, "_cont", None)
        if c is None:
            c = self._cont = (
                np.ascontiguousarray(self.fnum, np.uint32),
                bytes(np.ascontiguousarray(self.kind, np.uint8)),
                bytes(np.ascontiguousarray(self.flags, np.uint8)),
                tuple(np.ascontiguousarray(getattr(self, name), np.int32)
                      for name in self._TAB_NAMES))
        return c


class _LeafBuffer:
    __slots__ = ("values", "defs", "reps")

    def __init__(self) -> None:
        self.values: list = []
        self.defs: list[int] = []
        self.reps: list[int] = []


class ProtoColumnarizer:
    """Shreds batches of parsed proto messages into a ColumnBatch.

    Implements the Dremel record-shredding algorithm over the proto object
    tree; one Python pass per record (the CPU ingest cost the TPU encode path
    amortizes behind — SURVEY.md §2.4 pipeline parallel analog).
    """

    def __init__(self, msg_class, schema: Schema | None = None) -> None:
        self.msg_class = msg_class
        self.schema = schema or proto_to_schema(msg_class)
        # plan: walk descriptor parallel to schema columns, precomputing
        # (leaf order, per-field presence semantics)
        self._leaf_index: dict[tuple[str, ...], int] = {
            c.path: i for i, c in enumerate(self.schema.columns)
        }
        # fused nested shred opt-out (KPW_NESTED_FUSED=0 restores the
        # ctypes NestedShredResult route byte-identically — the bench's
        # fused A/B arm and a triage lever), read at construction so a
        # live writer's route never flips mid-stream
        self._nested_fused = os.environ.get("KPW_NESTED_FUSED", "1") != "0"

    # -- shredding ---------------------------------------------------------
    def _flat_plan(self):
        """Per-column (field descriptor, optional?, converter) when the
        message is flat (top-level scalar leaves only) — the common case
        (reference test schema, BASELINE flat configs), worth a tight loop
        instead of the generic Dremel visitor (~2.5x shredding throughput)."""
        desc = self.msg_class.DESCRIPTOR
        if any(_is_repeated(fd) or fd.type == FD.TYPE_MESSAGE
               for fd in desc.fields):
            return None
        plan = []
        for col in self.schema.columns:
            fd = desc.fields_by_name[col.path[0]]
            if fd.type == FD.TYPE_STRING:
                # proto2 runtimes surface invalid-UTF-8 strings as bytes;
                # pass them through unchanged (same output as the wire path)
                conv = lambda v: v.encode("utf-8") if isinstance(v, str) else bytes(v)
            elif fd.type == FD.TYPE_ENUM:
                values_by_number = fd.enum_type.values_by_number

                def conv(v, _vb=values_by_number):
                    ev = _vb.get(v)
                    return (ev.name if ev is not None
                            else f"UNKNOWN_ENUM_{v}").encode("ascii")
            elif fd.type in (FD.TYPE_UINT64, FD.TYPE_FIXED64):
                conv = lambda v: v - (1 << 64) if v >= 1 << 63 else v
            elif fd.type in (FD.TYPE_UINT32, FD.TYPE_FIXED32):
                conv = lambda v: v - (1 << 32) if v >= 1 << 31 else v
            else:
                conv = None
            plan.append((fd, _repetition_for(fd) == Repetition.OPTIONAL, conv))
        return plan

    def _columnarize_flat(self, records, plan) -> ColumnBatch:
        n = len(records)
        chunks = []
        for col, (fd, optional, conv) in zip(self.schema.columns, plan):
            name = fd.name
            if optional:
                defs = np.empty(n, np.int32)
                values = []
                for i, m in enumerate(records):
                    if m.HasField(name):
                        defs[i] = 1
                        values.append(getattr(m, name))
                    else:
                        defs[i] = 0
            else:
                defs = None
                values = [getattr(m, name) for m in records]
            if conv is not None:
                values = [conv(v) for v in values]
            chunks.append(ColumnChunkData(
                col, self._finalize_values(col, values), defs, None, n))
        return ColumnBatch(chunks, n)

    # -- native wire-format fast path --------------------------------------
    def _wire_plan(self):
        """Build (once) the kpw_proto_shred plan, or None when the schema or
        environment disqualifies the fast path (non-flat schema, enum
        fields, native lib unavailable)."""
        desc = self.msg_class.DESCRIPTOR
        if any(_is_repeated(fd) or fd.type in (FD.TYPE_MESSAGE, FD.TYPE_GROUP,
                                               FD.TYPE_ENUM)
               for fd in desc.fields):
            return None
        try:
            from ..native import lib as _native_lib

            if _native_lib() is None:
                return None
        except Exception:
            return None
        syntax = _file_syntax(desc.file)
        if syntax not in ("proto2", "proto3"):
            # editions (and anything newer): per-field UTF-8/presence
            # semantics this plan does not model — Python path only
            return None
        fnum, kinds, flags, dtypes, optional = [], [], [], [], []
        for col in self.schema.columns:
            fd = desc.fields_by_name[col.path[0]]
            kd = _WIRE_KINDS.get(fd.type)
            if kd is None:
                return None
            if fd.number > 65535:
                # beyond the C++ decoder's direct-address field table;
                # legal in proto (up to 2^29-1) but rare — Python path
                return None
            kind, dtype = kd
            if kind == _K_SPAN and fd.type == FD.TYPE_STRING and syntax == "proto3":
                kind = _K_SPAN_UTF8  # proto3 parsers reject invalid UTF-8
            fnum.append(fd.number)
            kinds.append(kind)
            flags.append(_F_REQUIRED if _is_required(fd) else 0)
            dtypes.append(dtype)
            optional.append(_repetition_for(fd) == Repetition.OPTIONAL)
        return _WirePlan(np.asarray(fnum, np.uint32),
                         np.asarray(kinds, np.uint8),
                         np.asarray(flags, np.uint8),
                         dtypes, optional)

    def _nested_plan(self):
        """Build (once) the kpw_proto_shred_nested node tables, or None when
        the schema or environment disqualifies the nested fast path.  Covers
        everything the flat plan covers plus repeated fields, nested /
        repeated submessages, and enums — the reference's full Message
        surface (KafkaProtoParquetWriter.java:671-684 accepts any subclass;
        ParquetFile.java:97-99 shreds it through ProtoWriteSupport)."""
        desc = self.msg_class.DESCRIPTOR
        try:
            from ..native import lib as _native_lib

            if _native_lib() is None:
                return None
        except Exception:
            return None
        if any(c.max_def > 254 or c.max_rep > 254
               for c in self.schema.columns):
            return None  # uint8 level outputs (no real schema nests so deep)

        def syntax_of(fd_or_desc):
            f = getattr(fd_or_desc, "file", None)
            return _file_syntax(f if f is not None else desc.file)

        fnum, kind, flags = [0], [_K_MESSAGE], [0]
        child_begin, child_end, leaf_idx = [0], [0], [-1]
        node_desc = {0: desc}
        node_queue = [0]
        enum_tables: dict[int, list[int]] = {}  # node -> sorted numbers
        enum_names: dict[int, dict[int, bytes]] = {}  # leaf -> num -> name
        leaf_kinds = [None] * len(self.schema.columns)
        leaf_dtypes = [None] * len(self.schema.columns)
        node_path = {0: ()}
        # A finite schema's node tree is bounded by its leaf count; a
        # self-recursive message type (message Tree { Tree child = 1; })
        # would otherwise grow the BFS forever — guard locally instead of
        # relying on proto_to_schema's RecursionError upstream.
        max_nodes = 8 * max(len(self.schema.columns), 1) + 256
        while node_queue:
            m = node_queue.pop(0)
            if len(fnum) > max_nodes:
                return None  # recursive (or pathologically deep) schema
            d = node_desc[m]
            child_begin[m] = len(fnum)
            for fd in d.fields:
                idx = len(fnum)
                path = node_path[m] + (fd.name,)
                if fd.number > 65535:
                    return None  # beyond the direct-address field tables
                # editions gate covers EVERY field kind (message, enum,
                # scalar): per-field presence/UTF-8/enum-closedness features
                # this plan does not model — Python path only
                if syntax_of(fd) not in ("proto2", "proto3"):
                    return None
                rep = _repetition_for(fd)
                fl = 0
                if _is_repeated(fd):
                    fl |= _FN_REPEATED
                if _is_required(fd):
                    fl |= _FN_REQUIRED
                if rep == Repetition.OPTIONAL:
                    fl |= _FN_DEF_INC
                if (not _is_repeated(fd) and rep == Repetition.REQUIRED
                        and not _is_required(fd)):
                    fl |= _FN_EMIT_DEFAULT  # proto3 no-presence default
                if fd.type == FD.TYPE_MESSAGE:
                    k, dtype = _K_MESSAGE, None
                    node_desc[idx] = fd.message_type
                    node_path[idx] = path
                    node_queue.append(idx)
                    leaf_idx.append(-1)
                elif fd.type == FD.TYPE_GROUP:
                    return None
                elif fd.type == FD.TYPE_ENUM:
                    k, dtype = _K_ENUM, None
                    li = self._leaf_index[path]
                    leaf_idx.append(li)
                    # open/closed follows the file DEFINING the enum
                    enum_syn = syntax_of(fd.enum_type)
                    if enum_syn not in ("proto2", "proto3"):
                        return None  # editions-defined enum: unmodeled
                    closed = enum_syn == "proto2"
                    if closed:
                        fl |= _FN_CLOSED_ENUM
                        enum_tables[idx] = sorted(
                            fd.enum_type.values_by_number)
                    enum_names[li] = {
                        num: ev.name.encode("ascii")
                        for num, ev in fd.enum_type.values_by_number.items()}
                    leaf_kinds[li] = k
                else:
                    kd = _WIRE_KINDS.get(fd.type)
                    if kd is None:
                        return None
                    k, dtype = kd
                    if (k == _K_SPAN and fd.type == FD.TYPE_STRING
                            and syntax_of(fd) == "proto3"):
                        k = _K_SPAN_UTF8
                    li = self._leaf_index[path]
                    leaf_idx.append(li)
                    leaf_kinds[li] = k
                    leaf_dtypes[li] = dtype
                fnum.append(fd.number)
                kind.append(k)
                flags.append(fl)
                child_begin.append(0)
                child_end.append(0)
            child_end[m] = len(fnum)
        n_nodes = len(fnum)

        # per-message-node direct field tables
        ftab: list[int] = []
        ftab_off = [0] * n_nodes
        max_fn = [0] * n_nodes
        for m in range(n_nodes):
            if kind[m] != _K_MESSAGE:
                continue
            kids = range(child_begin[m], child_end[m])
            mfn = max((fnum[c] for c in kids), default=0)
            ftab_off[m] = len(ftab)
            max_fn[m] = mfn
            table = [-1] * (mfn + 1)
            for c in kids:
                table[fnum[c]] = c
            ftab.extend(table)
            if len(ftab) > (1 << 20):
                return None  # sparse giant field numbers: tables too big
        # closed-enum membership tables
        enum_vals: list[int] = []
        enum_off = [0] * n_nodes
        enum_len = [0] * n_nodes
        for m, nums in enum_tables.items():
            enum_off[m] = len(enum_vals)
            enum_len[m] = len(nums)
            enum_vals.extend(nums)
        # descendant leaves per message node (absence emission)
        null_leaves: list[int] = []
        null_off = [0] * n_nodes
        null_len = [0] * n_nodes

        def leaves_under(m) -> list[int]:
            out = []
            for c in range(child_begin[m], child_end[m]):
                if kind[c] == _K_MESSAGE:
                    out.extend(leaves_under(c))
                else:
                    out.append(leaf_idx[c])
            return out

        for m in range(n_nodes):
            if kind[m] != _K_MESSAGE:
                continue
            ls = leaves_under(m)
            null_off[m] = len(null_leaves)
            null_len[m] = len(ls)
            null_leaves.extend(ls)

        p = _NestedPlan()
        p.n_nodes = n_nodes
        p.n_leaves = len(self.schema.columns)
        p.fnum = np.asarray(fnum, np.uint32)
        p.kind = np.asarray(kind, np.uint8)
        p.flags = np.asarray(flags, np.uint8)
        p.child_begin = np.asarray(child_begin, np.int32)
        p.child_end = np.asarray(child_end, np.int32)
        p.leaf_idx = np.asarray(leaf_idx, np.int32)
        p.ftab = np.asarray(ftab or [0], np.int32)
        p.ftab_off = np.asarray(ftab_off, np.int32)
        p.max_fn = np.asarray(max_fn, np.int32)
        p.enum_vals = np.asarray(enum_vals or [0], np.int32)
        p.enum_off = np.asarray(enum_off, np.int32)
        p.enum_len = np.asarray(enum_len, np.int32)
        p.null_leaves = np.asarray(null_leaves or [0], np.int32)
        p.null_off = np.asarray(null_off, np.int32)
        p.null_len = np.asarray(null_len, np.int32)
        p.leaf_kinds = leaf_kinds
        p.leaf_dtypes = leaf_dtypes
        p.enum_names = enum_names
        return p

    @property
    def wire_capable(self) -> bool:
        """True when columnarize_payloads can take a native path (flat
        decoder for flat scalar schemas, nested decoder otherwise)."""
        plan = getattr(self, "_wire", False)
        if plan is False:
            plan = self._wire = self._wire_plan()
        if plan is not None:
            return True
        nplan = getattr(self, "_nested", False)
        if nplan is False:
            nplan = self._nested = self._nested_plan()
        return nplan is not None

    def _alloc_flat_outputs(self, plan: "_WirePlan", n: int):
        """Per-field output arrays for one flat wire-shred call."""
        out_vals, out_pos, out_len, out_pres = [], [], [], []
        for f in range(len(plan.fnum)):
            dt = plan.dtypes[f]
            if dt is None:
                out_vals.append(None)
                out_pos.append(np.zeros(n, np.int64))
                out_len.append(np.zeros(n, np.int32))
            else:
                out_vals.append(np.zeros(n, dt))
                out_pos.append(None)
                out_len.append(None)
            out_pres.append(np.zeros(n, np.uint8) if plan.optional[f] else None)
        return out_vals, out_pos, out_len, out_pres

    def _flat_chunks(self, plan: "_WirePlan", n: int, out_vals, out_pos,
                     out_len, out_pres, pys, payloads, buf, L,
                     gather_buf=None) -> list:
        """Assemble ColumnChunkData from flat shredder outputs.  With
        ``pys`` the span positions are record-relative and strings gather
        from the payload objects (gather_iov); on the contiguous path
        (``pys=None``) positions are absolute into ``buf`` and strings
        gather with ``gather_buf`` (the C extension's GIL-releasing
        gather) or ctypes gather_spans.  One shared implementation: the
        RecordBatch buffer path and the payload-list path must stay
        byte-identical by construction."""
        all_recs = None
        chunks = []
        for f, col in enumerate(self.schema.columns):
            pres = out_pres[f]
            def_levels = None
            if pres is not None:
                mask = pres.view(np.bool_)
                def_levels = pres.astype(np.int32)
            if plan.dtypes[f] is None:
                pos, ln = out_pos[f], out_len[f]
                rec_idx = None
                if pres is not None:
                    pos, ln = pos[mask], ln[mask]
                    if pys is not None:
                        rec_idx = np.nonzero(mask)[0].astype(np.int32)
                elif pys is not None:
                    if all_recs is None:
                        all_recs = np.arange(n, dtype=np.int32)
                    rec_idx = all_recs
                offsets = np.zeros(len(ln) + 1, np.int64)
                np.cumsum(ln, out=offsets[1:])
                if pys is not None:
                    payload = pys.gather_iov(payloads, rec_idx, pos, ln)
                elif gather_buf is not None:
                    payload = gather_buf(
                        buf, np.ascontiguousarray(pos, np.int64),
                        np.ascontiguousarray(ln, np.int32))
                else:
                    payload = L.gather_spans(buf, pos, ln)
                values = ByteColumn(payload, offsets)
            else:
                values = out_vals[f]
                if pres is not None:
                    values = values[mask]
            chunks.append(ColumnChunkData(col, values, def_levels, None, n))
        return chunks

    def columnarize_payloads(self, payloads: list) -> ColumnBatch:
        """Shred serialized (un-parsed) messages straight to a ColumnBatch
        via the C++ wire decoders — no Python message objects.  Flat scalar
        schemas ride kpw_proto_shred; anything else (repeated / nested /
        enum) rides kpw_proto_shred_nested.  Raises WireShredError when any
        record needs the Python fallback; raises ValueError when the schema
        is not wire-capable (check :attr:`wire_capable` first)."""
        if not self.wire_capable:
            raise ValueError("schema is not wire-shreddable")
        n = len(payloads)
        if self._wire is None:
            lens = np.fromiter(map(len, payloads), np.int64, count=n)
            offs = np.zeros(n + 1, np.int64)
            np.cumsum(lens, out=offs[1:])
            return self._shred_nested(b"".join(payloads), offs)
        plan: _WirePlan = self._wire
        from ..native import lib as _native_lib, pyshred as _pyshred

        L = _native_lib()
        out_vals, out_pos, out_len, out_pres = \
            self._alloc_flat_outputs(plan, n)

        # zero-copy C-extension entry: reads the payload bytes objects in
        # place (no b"".join, no fromiter length walk — ~35 ms per 300k
        # records on the streaming hot path); span positions come back
        # record-relative and strings gather straight into their final
        # ByteColumn payload (one copy total)
        pys = _pyshred()
        buf = None
        if pys is not None:
            try:
                err, total = pys.shred_flat(
                    payloads, plan.fnum, plan.kinds, plan.flags,
                    tuple(out_vals), tuple(out_pos), tuple(out_len),
                    tuple(out_pres))
            except TypeError:
                pys = None  # non-bytes payloads: ctypes join path below
        if pys is None:
            lens = np.fromiter(map(len, payloads), np.int64, count=n)
            offs = np.zeros(n + 1, np.int64)
            np.cumsum(lens, out=offs[1:])
            buf = b"".join(payloads)
            total = int(offs[-1])
            err = L.proto_shred(buf, offs, len(plan.fnum), plan.fnum,
                                plan.kinds, plan.flags, out_vals, out_pos,
                                out_len, out_pres)
        if err >= 0:
            raise WireShredError(int(err))
        chunks = self._flat_chunks(plan, n, out_vals, out_pos, out_len,
                                   out_pres, pys, payloads, buf, L)
        batch = ColumnBatch(chunks, n)
        batch.wire_bytes = int(total)  # payload bytes, for byte metering
        return batch

    def columnarize_buffer(self, buf, offsets) -> ColumnBatch:
        """Batch-native zero-copy intake: shred serialized records that
        already live in ONE contiguous buffer (record i =
        ``buf[offsets[i]:offsets[i+1]]``; int64 offsets of length n+1,
        ascending, ``offsets[0]`` may be nonzero — a RecordBatch slice
        shares its parent's buffer) straight to a ColumnBatch.  This is
        the :class:`~kpw_tpu.ingest.broker.RecordBatch` handoff's
        consumer: no per-record ``bytes`` objects, no join — the broker's
        fetch buffer goes to the C++ shredder as-is.  Output is
        byte-identical to :meth:`columnarize_payloads` over the same
        records (shared assembly, pinned by test_batch_ingest).  Raises
        WireShredError / ValueError exactly like
        :meth:`columnarize_payloads`."""
        if not self.wire_capable:
            raise ValueError("schema is not wire-shreddable")
        offs = np.ascontiguousarray(offsets, np.int64)
        n = len(offs) - 1
        # validate the caller-supplied offset table before any decoder
        # (C entries re-check too, but the ctypes and nested routes read
        # it raw): one malformed interior offset is an out-of-bounds read
        if n > 0 and (int(offs[0]) < 0 or int(offs[-1]) > len(buf)
                      or not bool((np.diff(offs) >= 0).all())):
            raise ValueError(
                "offsets must be ascending and within the buffer")
        if self._wire is None:
            # the fused entry takes any buffer (a RecordBatch / ring-slot
            # memoryview stays zero-copy); only the ctypes fallback inside
            # _shred_nested materializes bytes
            return self._shred_nested(buf, offs)
        plan: _WirePlan = self._wire
        from ..native import lib as _native_lib, pyshred as _pyshred

        L = _native_lib()
        # prefer the C-extension entry (shred_flat_buf/gather_buf): decode
        # and gather run with the GIL RELEASED, so the encode pipeline
        # thread overlaps them — the ctypes route's per-call marshalling
        # was measurable GIL pressure on the 2-core streaming path
        pys = _pyshred()
        shred_buf = getattr(pys, "shred_flat_buf", None)
        gather_buf = getattr(pys, "gather_buf", None)
        if shred_buf is None or gather_buf is None:
            # ctypes fallback route needs real bytes; the C entries take
            # any buffer (a memoryview of a shared-memory ring slot stays
            # zero-copy — the process-workers handoff depends on it)
            buf = bytes(buf)
        out_vals, out_pos, out_len, out_pres = \
            self._alloc_flat_outputs(plan, n)
        if shred_buf is not None:
            if not plan._cont:
                plan._cont = (np.ascontiguousarray(plan.fnum, np.uint32),
                              bytes(np.ascontiguousarray(plan.kinds, np.uint8)),
                              bytes(np.ascontiguousarray(plan.flags, np.uint8)))
            fnum_c, kinds_c, flags_c = plan._cont
            err, _ = shred_buf(buf, offs, fnum_c, kinds_c, flags_c,
                               tuple(out_vals), tuple(out_pos),
                               tuple(out_len), tuple(out_pres))
        else:
            err = L.proto_shred(buf, offs, len(plan.fnum), plan.fnum,
                                plan.kinds, plan.flags, out_vals, out_pos,
                                out_len, out_pres)
        if err >= 0:
            raise WireShredError(int(err))
        chunks = self._flat_chunks(plan, n, out_vals, out_pos, out_len,
                                   out_pres, None, None, buf, L,
                                   gather_buf=gather_buf)
        batch = ColumnBatch(chunks, n)
        batch.wire_bytes = int(offs[-1] - offs[0])
        return batch

    def _shred_nested(self, buf, offs: np.ndarray) -> ColumnBatch:
        """Nested/repeated/enum wire shred over a contiguous buffer +
        record offsets; the output (values for present entries + per-visit
        def/rep levels) is element-identical to :meth:`columnarize` over
        the parsed messages (asserted by tests/test_nested_shred.py).

        Two routes, byte-identical output (pinned by
        tests/test_nested_fused.py):

        * **fused** (default when the C extension carries the entries) —
          ONE GIL-released decode (``shred_nested_buf``) plus ONE
          GIL-released materialization (``nested_fill``) that lands every
          leaf in its final packed form: span payloads gathered straight
          into their ByteColumn payload bytes with the int64 offset table
          built in the same pass, def/rep levels widened to the uint32 the
          nogil page assembler's RLE ops slice with zero further copies.
          Accepts any buffer (a RecordBatch / shared-memory ring view
          stays zero-copy).
        * **ctypes fallback** (stale .so, ``_nested_fused = False``) — the
          historical NestedShredResult route: per-leaf accessor round
          trips + numpy copies + a separate gather_spans pass."""
        from ..native import lib as _native_lib, pyshred as _pyshred

        plan: _NestedPlan = self._nested
        n = len(offs) - 1
        pys = _pyshred()
        if (pys is not None and getattr(self, "_nested_fused", True)
                and getattr(pys, "shred_nested_buf", None) is not None):
            batch = self._shred_nested_fused(pys, buf, offs, plan, n)
            batch.wire_bytes = int(offs[-1] - offs[0]) if n else 0
            return batch
        L = _native_lib()
        if not isinstance(buf, bytes):
            buf = bytes(buf)  # ctypes c_char_p route needs real bytes
        res = L.proto_shred_nested(buf, offs, plan)
        if isinstance(res, int):
            raise WireShredError(res)
        try:
            chunks = []
            for li, col in enumerate(self.schema.columns):
                k = plan.leaf_kinds[li]
                defs_u8, reps_u8 = res.levels(li)
                if k in (_K_SPAN, _K_SPAN_UTF8):
                    pos, ln = res.spans(li)
                    offsets = np.zeros(len(ln) + 1, np.int64)
                    np.cumsum(ln, out=offsets[1:])
                    values = ByteColumn(L.gather_spans(buf, pos, ln), offsets)
                elif k == _K_ENUM:
                    values = self._enum_bytecol(
                        L, res.values(li, np.int32), plan.enum_names[li])
                else:
                    values = res.values(li, plan.leaf_dtypes[li])
                def_levels = (defs_u8.astype(np.int32)
                              if col.max_def > 0 else None)
                rep_levels = (reps_u8.astype(np.int32)
                              if col.max_rep > 0 else None)
                chunks.append(ColumnChunkData(col, values, def_levels,
                                              rep_levels, n))
        finally:
            res.close()
        batch = ColumnBatch(chunks, n)
        batch.wire_bytes = int(offs[-1] - offs[0]) if n else 0
        return batch

    def _shred_nested_fused(self, pys, buf, offs: np.ndarray,
                            plan: "_NestedPlan", n: int) -> ColumnBatch:
        """The fused decode+materialize route (see :meth:`_shred_nested`).
        Output element-identical to the ctypes route by construction —
        same decoder object code, same emission order — with levels
        arriving as uint32 (the dtype every downstream consumer treats
        numerically; the RLE lowering in core/pages.py now slices them
        with no conversion copy at all)."""
        from ..native import lib as _native_lib

        fnum_c, kind_c, flags_c, tabs = plan.cont()
        rc, cap, sizes_b = pys.shred_nested_buf(
            buf, offs, plan.n_nodes, plan.n_leaves, fnum_c, kind_c, flags_c,
            tabs)
        if cap is None:
            raise WireShredError(int(rc))
        sizes = np.frombuffer(sizes_b, np.int64)
        cols = self.schema.columns
        vals_t, offsets_t, defs_t, reps_t = [], [], [], []
        for li, col in enumerate(cols):
            k = plan.leaf_kinds[li]
            row = 4 * li
            nlev = int(sizes[row + 3])
            if k in (_K_SPAN, _K_SPAN_UTF8):
                vals_t.append(None)
                offsets_t.append(np.empty(int(sizes[row + 1]) + 1, np.int64))
            else:
                dt = np.dtype(np.int32 if k == _K_ENUM
                              else plan.leaf_dtypes[li])
                vals_t.append(np.empty(int(sizes[row]) // dt.itemsize, dt))
                offsets_t.append(None)
            defs_t.append(np.empty(nlev, np.uint32)
                          if col.max_def > 0 else None)
            reps_t.append(np.empty(nlev, np.uint32)
                          if col.max_rep > 0 else None)
        payloads = pys.nested_fill(cap, buf, tuple(vals_t), tuple(offsets_t),
                                   tuple(defs_t), tuple(reps_t))
        chunks = []
        for li, col in enumerate(cols):
            k = plan.leaf_kinds[li]
            if k in (_K_SPAN, _K_SPAN_UTF8):
                values = ByteColumn(payloads[li], offsets_t[li])
            elif k == _K_ENUM:
                values = self._enum_bytecol(_native_lib(), vals_t[li],
                                            plan.enum_names[li])
            else:
                values = vals_t[li]
            chunks.append(ColumnChunkData(col, values, defs_t[li],
                                          reps_t[li], n))
        return ColumnBatch(chunks, n)

    @staticmethod
    def _enum_bytecol(L, nums: np.ndarray, names: dict) -> ByteColumn:
        """Enum numbers -> name ByteColumn without a per-record Python loop:
        unique the numbers (small cardinality), render each unique name once
        (open-enum unknowns as UNKNOWN_ENUM_{v}, proto_bridge._emit_value
        parity), and gather the payload by inverse index."""
        if len(nums) == 0:
            return ByteColumn.from_list([])
        uniq, inverse = np.unique(nums, return_inverse=True)
        rendered = [names.get(int(v), b"") or f"UNKNOWN_ENUM_{int(v)}".encode("ascii")
                    for v in uniq]
        ulens = np.fromiter(map(len, rendered), np.int32, count=len(rendered))
        upos = np.zeros(len(rendered), np.int64)
        np.cumsum(ulens[:-1], out=upos[1:])
        blob = b"".join(rendered)
        out_lens = ulens[inverse]
        payload = L.gather_spans(blob, upos[inverse], out_lens)
        offsets = np.zeros(len(nums) + 1, np.int64)
        np.cumsum(out_lens, out=offsets[1:])
        return ByteColumn(payload, offsets)

    def columnarize(self, records) -> ColumnBatch:
        plan = getattr(self, "_flat", False)
        if plan is False:
            plan = self._flat = self._flat_plan()
        if plan is not None:
            return self._columnarize_flat(records, plan)
        cols = self.schema.columns
        buffers = [_LeafBuffer() for _ in cols]
        # map descriptor walk to leaf indices via path
        desc = self.msg_class.DESCRIPTOR

        def emit_nulls(fd_path_prefix, sub_fields, r, d) -> None:
            """Record absence for every leaf under a subtree."""
            for fd in sub_fields:
                path = fd_path_prefix + (fd.name,)
                if fd.type == FD.TYPE_MESSAGE:
                    emit_nulls(path, fd.message_type.fields, r, d)
                else:
                    buf = buffers[self._leaf_index[path]]
                    buf.defs.append(d)
                    buf.reps.append(r)

        def visit_fields(msg, fields, path_prefix, r0, d0, rep_depth) -> None:
            for fd in fields:
                path = path_prefix + (fd.name,)
                if _is_repeated(fd):
                    items = getattr(msg, fd.name)
                    if len(items) == 0:
                        if fd.type == FD.TYPE_MESSAGE:
                            emit_nulls(path, fd.message_type.fields, r0, d0)
                        else:
                            buf = buffers[self._leaf_index[path]]
                            buf.defs.append(d0)
                            buf.reps.append(r0)
                        continue
                    # repetition level of items after the first is the depth
                    # of *this* repeated field (Dremel), not the leaf's max
                    item_rep = rep_depth + 1
                    d1 = d0 + 1
                    for i, item in enumerate(items):
                        r = r0 if i == 0 else item_rep
                        if fd.type == FD.TYPE_MESSAGE:
                            visit_fields(item, fd.message_type.fields, path,
                                         r, d1, item_rep)
                        else:
                            self._emit_value(buffers[self._leaf_index[path]],
                                             fd, item, r, d1)
                elif fd.type == FD.TYPE_MESSAGE:
                    if msg.HasField(fd.name):
                        d1 = d0 + (1 if _repetition_for(fd) == Repetition.OPTIONAL else 0)
                        visit_fields(getattr(msg, fd.name),
                                     fd.message_type.fields, path, r0, d1,
                                     rep_depth)
                    else:
                        emit_nulls(path, fd.message_type.fields, r0, d0)
                else:
                    rep = _repetition_for(fd)
                    if rep == Repetition.OPTIONAL and not msg.HasField(fd.name):
                        buf = buffers[self._leaf_index[path]]
                        buf.defs.append(d0)
                        buf.reps.append(r0)
                    else:
                        d1 = d0 + (1 if rep == Repetition.OPTIONAL else 0)
                        self._emit_value(buffers[self._leaf_index[path]],
                                         fd, getattr(msg, fd.name), r0, d1)

        for rec in records:
            visit_fields(rec, desc.fields, (), 0, 0, 0)

        chunks = []
        n = len(records)
        for col, buf in zip(cols, buffers):
            values = self._finalize_values(col, buf.values)
            def_levels = (np.asarray(buf.defs, np.int32)
                          if col.max_def > 0 else None)
            rep_levels = (np.asarray(buf.reps, np.int32)
                          if col.max_rep > 0 else None)
            chunks.append(ColumnChunkData(col, values, def_levels, rep_levels, n))
        return ColumnBatch(chunks, n)

    # -- helpers -----------------------------------------------------------
    @staticmethod
    def _emit_value(buf: _LeafBuffer, fd, value, r: int, d: int) -> None:
        if fd.type == FD.TYPE_STRING:
            value = (value.encode("utf-8") if isinstance(value, str)
                     else bytes(value))
        elif fd.type == FD.TYPE_ENUM:
            ev = fd.enum_type.values_by_number.get(value)
            # open enums (proto3): unknown numbers survive parsing; encode a
            # stable placeholder instead of killing the worker
            value = (ev.name if ev is not None
                     else f"UNKNOWN_ENUM_{value}").encode("ascii")
        elif fd.type in (FD.TYPE_UINT64, FD.TYPE_FIXED64) and value >= 1 << 63:
            value = value - (1 << 64)  # store as wrapped int64 per UINT_64
        elif fd.type in (FD.TYPE_UINT32, FD.TYPE_FIXED32) and value >= 1 << 31:
            value = value - (1 << 32)  # store as wrapped int32 per UINT_32
        buf.values.append(value)
        buf.defs.append(d)
        buf.reps.append(r)

    @staticmethod
    def _finalize_values(col: ColumnDescriptor, values: list):
        pt = col.leaf.physical_type
        dtype = _NUMPY_DTYPES.get(pt)
        if dtype is not None:
            return np.asarray(values, dtype)
        if pt in (PhysicalType.BYTE_ARRAY, PhysicalType.FIXED_LEN_BYTE_ARRAY):
            return ByteColumn.from_list(values)
        return values
