"""Record models: bridges from user record schemas (protobuf message classes,
flat avro-style specs) to the parquet schema + columnar batches.

Replaces parquet-protobuf's ``ProtoWriteSupport`` (the reference plugs it in
at ParquetFile.java:97-99; the user contract is "any Message subclass + its
Parser", KafkaProtoParquetWriter.java:671-684)."""

from .proto_bridge import proto_to_schema, ProtoColumnarizer  # noqa: F401
from .record_bridge import flat_schema, dicts_to_batch, arrays_to_batch  # noqa: F401
