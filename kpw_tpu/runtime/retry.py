"""Retry policy for the write path's IO seams.

Reference ``tryUntilSucceeds`` (KafkaProtoParquetWriter.java:410-443): retry
forever on IOException with a fixed 100 ms sleep, propagate interruption,
wrap other checked failures.  That loop has two production problems the
robustness PR hardens away:

* **No error classification** — a full disk (``ENOSPC``) or a read-only
  remount (``EROFS``) is retried forever at 100 ms with only a warning log;
  the writer spins silently degraded instead of surfacing a worker death the
  supervisor (or operator) can act on.
* **Fixed sleep** — a transiently sick sink gets hammered every 100 ms by
  every worker in lockstep; exponential backoff with decorrelated jitter
  (the AWS architecture-blog variant: ``sleep = min(cap, uniform(base,
  prev*3))``) spreads the herd and backs off hard failures.

:class:`RetryPolicy` keeps the reference's *default delivery semantics* —
infinite attempts, so a transient outage never drops records — while adding
fatal-by-default classification of non-transient errnos and optional
attempt/deadline budgets.  ``RetryPolicy.reference()`` restores the pure
reference loop (fixed 100 ms, no classification, no budget) as the escape
hatch.  ``try_until_succeeds`` remains as the thin compatibility wrapper all
existing call sites keep using.
"""

from __future__ import annotations

import errno
import logging
import random
import threading
import time

logger = logging.getLogger(__name__)

RETRY_SLEEP_SECONDS = 0.1

#: errnos that almost never heal by retrying in place: disk full, read-only
#: filesystem, quota exceeded.  A worker hitting one dies loudly (and the
#: supervisor, when enabled, surfaces/restarts it) instead of spinning.
FATAL_ERRNOS = frozenset({errno.ENOSPC, errno.EROFS, errno.EDQUOT})


class RetryInterrupted(Exception):
    """Raised when a stop event fires while retrying."""


class RetryBudgetExceeded(Exception):
    """Raised when a bounded policy runs out of attempts or deadline; the
    last underlying error is chained as ``__cause__``."""


class RetryPolicy:
    """Classify-and-backoff retry loop.

    Parameters
    ----------
    base_sleep:
        First backoff sleep (seconds); also the jitter floor.
    max_sleep:
        Backoff cap.  With the default decorrelated jitter each sleep is
        drawn from ``uniform(base_sleep, prev*3)`` then clamped here.
    max_attempts:
        Total call budget (``None`` = unbounded, the reference semantics).
        Exhaustion raises :class:`RetryBudgetExceeded`.
    deadline:
        Wall-clock budget in seconds from the first attempt (``None`` =
        unbounded).  Checked before sleeping: the loop never starts a sleep
        it knows will overrun.
    retry_on:
        Exception types that are retry *candidates*; anything else
        propagates immediately.
    fatal_errnos:
        Within ``retry_on``, OSErrors whose ``errno`` is listed here are
        re-raised immediately (fatal, not transient).  Pass an empty set to
        restore pure reference behavior.
    jitter:
        ``True`` = decorrelated jitter; ``False`` = deterministic
        exponential doubling (used by tests that assert exact sleeps).
    rng:
        Seedable ``random.Random`` for deterministic chaos runs.
    """

    def __init__(self,
                 base_sleep: float = RETRY_SLEEP_SECONDS,
                 max_sleep: float = 5.0,
                 max_attempts: int | None = None,
                 deadline: float | None = None,
                 retry_on: tuple = (OSError,),
                 fatal_errnos: frozenset = FATAL_ERRNOS,
                 jitter: bool = True,
                 rng: random.Random | None = None) -> None:
        if base_sleep <= 0:
            raise ValueError("base_sleep must be positive")
        if max_sleep < base_sleep:
            raise ValueError("max_sleep must be >= base_sleep")
        if max_attempts is not None and max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        self.base_sleep = base_sleep
        self.max_sleep = max_sleep
        self.max_attempts = max_attempts
        self.deadline = deadline
        self.retry_on = retry_on
        self.fatal_errnos = frozenset(fatal_errnos)
        self.jitter = jitter
        self._rng = rng or random.Random()

    @classmethod
    def reference(cls) -> "RetryPolicy":
        """Pure reference semantics (KPW.java:410-443): retry *every*
        OSError forever at a fixed 100 ms — no classification, no backoff
        growth, no budget."""
        return cls(base_sleep=RETRY_SLEEP_SECONDS,
                   max_sleep=RETRY_SLEEP_SECONDS,
                   max_attempts=None, deadline=None,
                   fatal_errnos=frozenset(), jitter=False)

    # -- classification ------------------------------------------------------
    def is_fatal(self, exc: BaseException) -> bool:
        """True when ``exc`` should NOT be retried despite matching
        ``retry_on`` (non-transient errno class)."""
        return (isinstance(exc, OSError)
                and exc.errno in self.fatal_errnos)

    # -- backoff -------------------------------------------------------------
    def next_sleep(self, prev: float | None) -> float:
        """Next backoff sleep given the previous one (``None`` on the first
        failure)."""
        if prev is None:
            return self.base_sleep
        if self.jitter:
            # decorrelated jitter: uniform over [base, prev*3], capped
            hi = max(self.base_sleep, min(prev * 3.0, self.max_sleep))
            return self._rng.uniform(self.base_sleep, hi)
        return min(prev * 2.0, self.max_sleep)

    # -- the loop ------------------------------------------------------------
    def call(self, fn, stop_event: threading.Event | None = None,
             on_retry=None, label: str = ""):
        """Call ``fn`` until it returns.

        Retries ``retry_on`` failures with backoff; fatal-classified errors
        and budget exhaustion raise instead of spinning.  ``on_retry`` (if
        given) is invoked as ``on_retry(attempt, exc, sleep_s)`` before each
        backoff sleep — the metrics seam (retry counts, backoff seconds,
        last error) without coupling this module to the registry.
        """
        attempt = 0
        sleep: float | None = None
        started = time.monotonic()
        while True:
            attempt += 1
            try:
                return fn()
            except self.retry_on as e:
                if stop_event is not None and stop_event.is_set():
                    raise RetryInterrupted() from e
                if self.is_fatal(e):
                    logger.error("fatal (non-retryable) IO failure%s: %r",
                                 f" in {label}" if label else "", e)
                    raise
                if (self.max_attempts is not None
                        and attempt >= self.max_attempts):
                    raise RetryBudgetExceeded(
                        f"gave up after {attempt} attempts"
                        f"{f' in {label}' if label else ''}") from e
                sleep = self.next_sleep(sleep)
                if (self.deadline is not None
                        and time.monotonic() + sleep - started > self.deadline):
                    raise RetryBudgetExceeded(
                        f"deadline {self.deadline}s exceeded after "
                        f"{attempt} attempts"
                        f"{f' in {label}' if label else ''}") from e
                if on_retry is not None:
                    try:
                        on_retry(attempt, e, sleep)
                    except Exception:
                        logger.exception("on_retry hook failed (ignored)")
                logger.warning("IO failure%s, retrying in %.0f ms: %r",
                               f" in {label}" if label else "",
                               sleep * 1000, e)
                if stop_event is not None:
                    if stop_event.wait(sleep):
                        raise RetryInterrupted() from e
                else:
                    time.sleep(sleep)


def try_until_succeeds(fn, stop_event: threading.Event | None = None,
                       retry_on: tuple = (OSError,),
                       sleep: float = RETRY_SLEEP_SECONDS,
                       policy: RetryPolicy | None = None,
                       on_retry=None, label: str = ""):
    """Call ``fn`` until it returns; retry on ``retry_on`` failures.

    Compatibility wrapper over :class:`RetryPolicy`.  Without an explicit
    ``policy`` it builds the default one (infinite attempts, exponential
    backoff + decorrelated jitter from ``sleep``, fatal errno
    classification) — reference delivery semantics with modern backoff."""
    if policy is None:
        policy = RetryPolicy(base_sleep=sleep, retry_on=retry_on)
    return policy.call(fn, stop_event=stop_event, on_retry=on_retry,
                       label=label)
