"""Infinite-retry-on-IO helper.

Reference ``tryUntilSucceeds`` (KafkaProtoParquetWriter.java:410-443): retry
forever on IOException with a 100 ms sleep, propagate interruption, wrap other
checked failures.  Python translation of the *semantics*: retry on
OSError, abort promptly when the owning worker is shutting down.
"""

from __future__ import annotations

import logging
import threading
import time

logger = logging.getLogger(__name__)

RETRY_SLEEP_SECONDS = 0.1


class RetryInterrupted(Exception):
    """Raised when a stop event fires while retrying."""


def try_until_succeeds(fn, stop_event: threading.Event | None = None,
                       retry_on: tuple = (OSError,),
                       sleep: float = RETRY_SLEEP_SECONDS):
    """Call ``fn`` until it returns; retry on ``retry_on`` failures."""
    while True:
        try:
            return fn()
        except retry_on as e:
            if stop_event is not None and stop_event.is_set():
                raise RetryInterrupted() from e
            logger.warning("IO failure, retrying in %.0f ms: %r",
                           sleep * 1000, e)
            if stop_event is not None:
                if stop_event.wait(sleep):
                    raise RetryInterrupted() from e
            else:
                time.sleep(sleep)
