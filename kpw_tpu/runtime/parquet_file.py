"""One open parquet output file, record-at-a-time API with batched encode.

API parity with the reference's ``ParquetFile`` wrapper (ParquetFile.java:
24-123: ctor, write(T), close(), getDataSize(), getCreationDate(),
getNumWrittenRecords()) — but where the reference funnels each record
straight into parquet-mr's column writers (PF.java:59-62), this wrapper
buffers records and shreds/encodes them in columnar *batches*, which is what
lets the encode hop to vmapped TPU kernels (the north-star EncoderBackend
boundary)."""

from __future__ import annotations

import time

from ..core.writer import ParquetFileWriter, WriterProperties
from ..io.fs import FileSystem
from ..models.proto_bridge import ProtoColumnarizer


class ParquetFile:
    """Not thread-safe; thread-confined to one worker (reference PF.java:20)."""

    def __init__(
        self,
        fs: FileSystem,
        path: str,
        columnarizer: ProtoColumnarizer,
        properties: WriterProperties,
        batch_size: int = 4096,
        encoder=None,
        pipeline: bool = False,
        est_record_bytes: float = 64.0,
        retry_policy=None,
        heartbeat=None,
    ) -> None:
        self.path = path
        self._fs = fs
        self._sink = fs.open_write(path)
        self._writer = ParquetFileWriter(self._sink, columnarizer.schema,
                                         properties, encoder=encoder,
                                         pipeline=pipeline,
                                         retry_policy=retry_policy,
                                         heartbeat=heartbeat)
        self._columnarizer = columnarizer
        self._batch: list = []
        self._batch_size = batch_size
        self._num_records = 0
        # EWMA of encoded bytes per record; seedable so a rotated-away
        # file's measured estimate carries into its successor (tight
        # size-based rotation needs a warm estimate from record one)
        self._est_record_bytes = float(est_record_bytes)
        # snapshot for assembly_info()'s per-FILE delta (the encoder may
        # be shared across rotated files by a custom Builder backend)
        self._asm_baseline = self._writer.assembly_info()
        self._creation_time = time.time()
        self._closed = False
        # why this file left service: "size" (crossed max_file_size),
        # "time" (max_file_open_duration), "close" (writer shutdown
        # abandoned the open tmp), "error" (worker died), or None while
        # still open.  Set by the worker at the rotation decision point;
        # feeds the rotation-cause meters and per-file observability
        self.rotation_reason: str | None = None

    # -- reference API -----------------------------------------------------
    def write(self, record) -> None:
        """Buffer one parsed record; encodes when the batch fills.

        NOT retry-safe as a whole (a retry would re-append the record); the
        worker runtime uses :meth:`append_record` + :meth:`flush_if_full` so
        only the idempotent flush is retried."""
        self.append_record(record)
        self.flush_if_full()

    def append_record(self, record) -> None:
        """Pure-memory append; cannot fail."""
        self._batch.append(record)
        self._num_records += 1

    def append_records(self, records: list) -> None:
        """Bulk pure-memory append; cannot fail."""
        self._batch.extend(records)
        self._num_records += len(records)

    def append_batch(self, batch) -> None:
        """Pure-memory append of an already-columnarized ColumnBatch (the
        wire-shred fast path: records never exist as Python messages).
        Cannot fail; pair with :meth:`maybe_flush_row_group` for the
        retryable IO step.

        Callers interleaving this with the record-buffer path must drain the
        record buffer first (:meth:`flush_buffered`) or rows would reorder:
        buffered records only reach the writer at the next threshold flush,
        which would land them AFTER this batch."""
        self._writer.append_batch(batch)
        self._observe_record_bytes(batch)
        self._num_records += batch.num_rows

    def flush_buffered(self) -> None:
        """Columnarize + hand over any buffered records now (regardless of
        the batch threshold).  Row-order seam between the record-buffer path
        and :meth:`append_batch`.  Safe to retry: records move out of the
        buffer before any IO can raise; a retried call re-runs only the
        pending row-group flush."""
        self._flush_batch()

    def maybe_flush_row_group(self) -> None:
        """Idempotent, retry-safe row-group flush for the fast path."""
        self._writer.maybe_flush_row_group()

    def flush_if_full(self) -> None:
        """Idempotent: encodes the pending batch when it crossed the
        threshold; safe to retry after transient IO failures (records are
        never re-appended, see ParquetFileWriter.write_batch ownership)."""
        if len(self._batch) >= self._batch_size:
            self._flush_batch()

    def close(self) -> None:
        """Flush pages + footer.  File contents are durable in the sink after
        this (the rename/publish is the caller's job, as in the reference)."""
        if self._closed:
            return
        self._flush_batch()
        self._writer.close()
        self._sink.close()
        self._closed = True

    def abandon(self) -> None:
        """Drop the file without footer or publish (reference close-time
        semantics: the open tmp is abandoned, KPW.java:381-398).  Stops any
        pipeline threads so a rotated-away worker leaks nothing."""
        if self._closed:
            return
        self._writer.abandon()
        self._sink.close()
        self._closed = True

    def get_data_size(self) -> int:
        """In-flight size estimate for rotation (reference getDataSize,
        PF.java:77-79): bytes already written + estimate for buffered rows."""
        return self._writer.estimated_size() + int(
            len(self._batch) * self._est_record_bytes)

    def _observe_record_bytes(self, batch) -> None:
        """Fold one columnar batch into the bytes/record EWMA.  Uses the
        batch's raw estimate scaled by the writer's measured encoded/raw
        ratio — NOT a before/after diff of estimated_size(), which the
        pipeline's IO thread mutates concurrently (a row-group commit
        between the two reads would inject its estimate-vs-actual delta
        into this sample)."""
        n = batch.num_rows
        grew = self._writer.size_ratio * batch.estimated_bytes()
        if n and grew > 0:
            self._est_record_bytes += 0.5 * (grew / n - self._est_record_bytes)

    @property
    def est_record_bytes(self) -> float:
        """Live EWMA of encoded bytes per record — the worker's rotation
        poll cap reads this to stop polling just past the size threshold."""
        return self._est_record_bytes

    def index_info(self) -> dict:
        """Query-ready-section counters of the underlying writer (pages
        indexed, index/bloom bytes, sorting declarations) — populated at
        close; the worker's publish path reads this to mark the
        ``parquet.writer.indexed`` / ``parquet.writer.bloom.bytes``
        meters."""
        return self._writer.index_info()

    def encoding_info(self) -> dict:
        """Per-column value-encoding decisions of the underlying writer
        (core/select_encoding.py): dotted path -> chosen encoding,
        dictionary verdict, trigger reason and the row-group-1 stats.
        Per-FILE by construction — the writer resets the chooser's pins
        at open even when a custom Builder backend shares one encoder
        across rotated files."""
        return self._writer.encoding_info()

    def assembly_info(self) -> dict:
        """Nogil-assembly counters for THIS file (chunks/pages assembled
        by the GIL-released native call) — the worker's publish path reads
        this to mark the ``parquet.writer.assembly.native.chunks`` /
        ``.pages`` meters.  Reported as the delta from the counters at
        open: encoder counters are per-encoder-lifetime, and a custom
        Builder backend hands the SAME encoder object to every rotated
        file (cumulative readings would double-count across rotations)."""
        now = self._writer.assembly_info()
        return {k: now[k] - self._asm_baseline.get(k, 0) for k in now}

    def get_creation_time(self) -> float:
        return self._creation_time

    def get_num_written_records(self) -> int:
        return self._num_records

    def writer_overlap_stats(self) -> dict:
        """Per-stage busy seconds of the underlying writer's overlapped
        row-group pipeline (dispatch / assemble / io, zeros on the sync
        path) plus whether the host-assembly stage is split onto its own
        thread — the evidence the bench's ``hostasm_overlap`` breakdown
        and the runtime metrics read, without installing a tracer."""
        w = self._writer
        return {"split_assembly": w.has_assembly_stage, **w.stage_busy_s}

    def pipeline_stats(self) -> dict:
        """Full pipeline observability snapshot of the underlying writer:
        per-stage busy seconds plus each stage queue's depth /
        high-watermark / blocked-on-put / blocked-on-get stall accounting
        (core.writer.StatQueue).  Readable after close/abandon — the
        worker folds rotated-away files' stats into its running totals."""
        out = self._writer.pipeline_stats()
        out["rotation_reason"] = self.rotation_reason
        out["records"] = self._num_records
        return out

    # -- internals ---------------------------------------------------------
    def _flush_batch(self) -> None:
        if not self._batch:
            return
        batch = self._columnarizer.columnarize(self._batch)
        self._batch = []
        self._writer.write_batch(batch)
        self._observe_record_bytes(batch)
