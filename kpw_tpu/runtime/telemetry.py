"""Cross-process telemetry plane: the shm counter-cell field layout,
the parent-side child-metric aggregator, and the crash flight recorder.

Under ``process_workers(n)`` the children run in their own interpreters:
their stage timers, span buffers, and per-worker counters are invisible
to the parent's :class:`~kpw_tpu.runtime.metrics.MetricRegistry` unless
something carries them across the process boundary.  This module is
that carrier's *data plane*, built on the PR-11 heartbeat-cell pattern
(``procworkers.ShmBatchRing`` owns the bytes; this module owns the
meaning):

* **TM cells** — one fixed 16-slot int64 vector per child in the shared
  ring (``TM_FIELDS`` names the slots).  The child overwrites its cell
  from the heartbeat publisher thread (~20 Hz); the parent reads it on
  every scrape.  Single-writer, torn reads benign: every field is a
  monotonic counter or a cheap gauge, so a half-updated cell is merely
  a counter a tick stale, never garbage.
* **Dead-child banking** (:class:`ChildTelemetry`) — before a dead
  child's slot is respawned (and its cell cleared for the successor),
  the parent *banks* the final cell values.  Merged totals are
  ``banked + sum(live cells)``: monotonic across restarts, and a dead
  or half-torn cell can never poison the scrape (reads never raise —
  they degrade to the banked totals).
* **Flight recorder** (:class:`FlightRecorder`) — a bounded black box
  of recent fault-path events (heartbeat stalls, pauses, quarantines,
  child deaths) plus a gather hook for live state (recent spans, metric
  snapshot, worker/watchdog observability).  ``dump()`` writes one JSON
  post-mortem naming the trigger and the stalled stage; it is wired to
  the three fatal paths (watchdog SIGKILL, fatal-sink pause, poison
  quarantine) and NEVER raises into them.  Dumps go to the LOCAL
  filesystem under ``<target_dir>/flightrec/`` deliberately — a black
  box that publishes through the (possibly failing) sink would lose
  exactly the crashes it exists to explain.

The side channel for full snapshots (child registry view + drained span
buffers) rides the existing ack queue as ``("telemetry", widx, payload)``
descriptors — low-rate, sent at rotation/seal boundaries and child exit,
absorbed by the parent into :class:`~kpw_tpu.utils.tracing.
MultiProcessTrace` and ``stats()['telemetry']``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque

logger = logging.getLogger(__name__)

# The per-child shm telemetry cell layout: index in this tuple = int64
# slot in the child's TM cell (procworkers sizes the cell at 16 slots;
# trailing slots are spare headroom for future fields — the layout is
# shared memory, so append-only).  Every field is child-cumulative.
TM_FIELDS = (
    "written_records",     # records shredded+appended into open files
    "written_bytes",       # payload bytes of those records
    "flushed_records",     # records in durably published files
    "flushed_bytes",       # file bytes of those publishes
    "files_published",     # published file count
    "units_processed",     # ring units consumed
    "retries",             # sink-retry attempts
    "backoff_ms",          # cumulative retry backoff
    "deadletter_records",  # records routed to the dead-letter file
    "rotations_size",      # size-triggered file rotations
    "rotations_time",      # time-triggered file rotations
    "spans_recorded",      # spans the child's SpanRecorder accepted
    "spans_dropped",       # spans its ring buffer overwrote
    "stage_time_us",       # cumulative stage() wall-time, microseconds
    "rebalance_fenced",    # files flushed under a revoke fence
    "rebalance_abandoned",  # open files abandoned on revoke/lost
)

TM_INDEX = {name: i for i, name in enumerate(TM_FIELDS)}


class ChildTelemetry:
    """Parent-side merged view over the children's TM cells.

    ``ring`` duck-types ``tm_read(widx)`` / ``tm_clear(widx)``;
    ``live_indices`` is a zero-arg callable yielding the worker indices
    whose cells are currently owned by a live child.  ``bank(widx)``
    folds a dead child's final cell into the banked totals and clears
    the cell for its successor — call it before respawn and at pool
    close so :meth:`totals` stays monotonic across the whole tree's
    lifetime."""

    def __init__(self, ring, live_indices) -> None:
        self._ring = ring
        self._live = live_indices
        self._lock = threading.Lock()
        self._banked = [0] * len(TM_FIELDS)
        self._snapshots: dict[int, dict] = {}

    # -- banking -------------------------------------------------------------
    def bank(self, widx: int) -> None:
        """Fold worker ``widx``'s final cell into the banked totals and
        clear the cell (the successor starts from zero)."""
        try:
            vals = self._ring.tm_read(widx)
        # lint: swallowed-exceptions ok — banking races pool teardown
        # (ring views already nulled); losing one dead child's tail
        # counters beats raising into respawn/close
        except Exception:
            logger.exception("telemetry bank of worker %d failed (ignored)",
                             widx)
            return
        with self._lock:
            for i in range(len(TM_FIELDS)):
                self._banked[i] += int(vals[i])
        try:
            self._ring.tm_clear(widx)
        # lint: swallowed-exceptions ok — same teardown race as the read;
        # the cell is about to be recycled or unmapped either way
        except Exception:
            logger.exception("telemetry clear of worker %d failed (ignored)",
                             widx)

    # -- side-channel snapshots ---------------------------------------------
    def absorb_snapshot(self, widx: int, payload: dict) -> None:
        """Store a child's low-rate registry snapshot (the ``telemetry``
        ack-queue descriptor payload) for ``stats()``."""
        if not isinstance(payload, dict):
            return
        with self._lock:
            self._snapshots[int(widx)] = payload

    def snapshots(self) -> dict[int, dict]:
        with self._lock:
            return dict(self._snapshots)

    # -- merged reads --------------------------------------------------------
    def totals(self) -> dict[str, int]:
        """banked + sum over live cells, per field.  Never raises: a
        dead ring view degrades to the banked totals (the dead-child
        cell can never poison the scrape)."""
        with self._lock:
            out = list(self._banked)
        for widx in tuple(self._live()):
            try:
                vals = self._ring.tm_read(widx)
            # lint: swallowed-exceptions ok — scrape racing ring close /
            # child respawn; the banked half of the sum is still valid
            # and the next scrape re-reads
            except Exception:
                continue
            for i in range(len(TM_FIELDS)):
                out[i] += int(vals[i])
        return {name: out[i] for i, name in enumerate(TM_FIELDS)}

    def field(self, name: str) -> int:
        return self.totals()[name]

    def snapshot(self) -> dict:
        """The ``stats()['telemetry']`` block: merged totals plus the
        last side-channel snapshot per child."""
        return {"children_merged": self.totals(),
                "child_snapshots": self.snapshots()}


class FlightRecorder:
    """Bounded black box for the fault paths: :meth:`note` appends
    timestamped events to a ring of ``capacity``; :meth:`dump` writes
    one JSON post-mortem combining those events with whatever the
    ``gather`` hook can still collect (recent spans, metric snapshot,
    worker/watchdog observability) — naming the ``trigger`` and, when
    the watchdog attributed one, the ``stalled_stage``.

    Dumps never raise and never publish through the writer's sink: they
    go to the local filesystem under ``<base_dir>/flightrec/``."""

    def __init__(self, base_dir: str, instance: str, capacity: int = 256,
                 meter=None, keep: int = 16) -> None:
        self.dir = os.path.join(base_dir, "flightrec")
        self._instance = instance
        self._events: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._meter = meter
        self._gather = None
        self._seq = 0
        self._recent: deque = deque(maxlen=keep)

    def set_gather(self, fn) -> None:
        """``fn() -> dict`` of extra sections folded into every dump
        (the writer wires spans/metrics/worker observability here)."""
        self._gather = fn

    # -- the event ring ------------------------------------------------------
    def note(self, kind: str, **fields) -> None:
        """Append one fault-path event.  Cheap and exception-free by
        construction — called from watchdog/collector hot paths."""
        evt = {"wall_time_unix_s": round(time.time(), 6), "kind": kind}
        evt.update(fields)
        with self._lock:
            self._events.append(evt)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    # -- the post-mortem -----------------------------------------------------
    def dump(self, trigger: str, stalled_stage: str | None = None,
             **detail) -> str | None:
        """Write one JSON post-mortem; returns its path, or None when
        the write itself failed (logged, never raised — the fault paths
        that call this are already handling a worse problem)."""
        try:
            sections = self._gather() if self._gather is not None else {}
        # lint: swallowed-exceptions ok — the gather hook walks live
        # writer state mid-fault; a partial black box with the trigger
        # and event ring beats no black box
        except Exception as e:
            logger.exception("flight recorder gather failed (degraded dump)")
            sections = {"gather_error": repr(e)}
        with self._lock:
            self._seq += 1
            seq = self._seq
        doc = {
            "flight_recorder": 1,
            "instance": self._instance,
            "trigger": trigger,
            "stalled_stage": stalled_stage,
            "wall_time_unix_s": round(time.time(), 6),
            "detail": detail,
            "events": self.events(),
        }
        doc.update(sections)
        path = os.path.join(
            self.dir, f"flightrec_{self._instance}_{seq:03d}_{trigger}.json")
        try:
            os.makedirs(self.dir, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump(doc, f, indent=2, sort_keys=True, default=repr)
            os.replace(tmp, path)
        # lint: swallowed-exceptions ok — dump runs inside the watchdog
        # condemn / fatal-pause / quarantine paths; a failed post-mortem
        # write must never worsen the fault it documents
        except OSError:
            logger.exception("flight recorder dump to %s failed (ignored)",
                             path)
            return None
        if self._meter is not None:
            self._meter.mark()
        with self._lock:
            self._recent.append(path)
        logger.error("flight recorder: %s dump (stalled_stage=%s) -> %s",
                     trigger, stalled_stage, path)
        return path

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "dir": self.dir,
                "events_buffered": len(self._events),
                "dumps_written": self._seq,
                "recent_dumps": list(self._recent),
            }
