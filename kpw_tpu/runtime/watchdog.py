"""Hung-IO watchdog: detect workers whose storage stalls instead of erroring.

The retry/supervision layers (runtime/retry.py, the PR-3 supervisor) only
see failures that *return* — an errno, an exception, a dead thread.  The
failure shape that dominates long-running production ingest is different:
a write into a wedged HDFS pipeline or a hung NFS mount simply never
comes back.  The worker blocks inside the IO call forever, `healthy()`
stays true (the thread is alive), ack-lag grows silently, and no retry
policy ever fires because nothing ever raised.

This module closes that blind spot with two small pieces:

* :class:`Heartbeat` — a monotonic progress publisher each worker (and
  the pipelined row-group IO thread, via ``ParquetFileWriter``) updates
  around every IO seam: ``io_started(label)`` before a potentially
  blocking call, ``io_finished()`` after, ``beat()`` from the retry
  loop's ``on_retry`` hook so a *progressing* backoff loop is never
  mistaken for a hang.  Pending ops are keyed by publishing thread, so
  one worker slot's heartbeat covers both its own thread and its open
  file's IO stage.
* :class:`Watchdog` — a supervisor-owned scanner thread that flags any
  worker whose oldest pending IO op is older than ``io_stall_deadline``:
  the stall flips ``writer.healthy()`` false, marks the
  ``parquet.writer.stalled`` meter (once per stall episode), and surfaces
  the per-worker stall age + seam label in ``writer.stats()``.  With
  ``abandon_stalled=True`` it goes further: the stuck worker is
  *condemned* — declared failed while its thread is still parked in the
  hung call — so the existing PR-3 supervisor restarts the slot and
  re-injects the held (never-acked) offset runs.  Redelivery preserves
  at-least-once; if the hung call eventually returns, the zombie thread
  sees its stop event and exits without acking (duplicates allowed, loss
  impossible).  The stuck tmp file is left un-published and is swept on
  the next start.

A watchdog abandon consumes a SUPERVISOR restart, never a retry budget:
the hung call never returned, so the retry policy never saw an attempt
fail (pinned by ``test_watchdog_abandon_consumes_no_retry_budget``).
"""

from __future__ import annotations

import logging
import threading
import time

logger = logging.getLogger(__name__)


class Heartbeat:
    """Monotonic IO-progress publisher for one worker slot.

    Pending ops are keyed by the publishing thread's ident: the worker
    thread and its current file's pipelined IO thread share the slot's
    heartbeat without coordinating.  All methods are safe to call from
    any thread; the watchdog reads :meth:`stall` concurrently.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._pending: dict[int, tuple[str, float]] = {}
        self.beats = 0
        self.last_progress = time.monotonic()

    def io_started(self, label: str) -> int:
        """Record a potentially-blocking IO op starting on this thread;
        returns the token :meth:`io_finished` takes (the thread ident —
        returned rather than re-derived so a finally block can't pop a
        different thread's entry after an executor hand-off)."""
        tid = threading.get_ident()
        with self._lock:
            self._pending[tid] = (label, time.monotonic())
        return tid

    def io_finished(self, token: int) -> None:
        with self._lock:
            self._pending.pop(token, None)
            self.beats += 1
            self.last_progress = time.monotonic()

    def beat(self) -> None:
        """Re-stamp this thread's pending op: the op is still failing but
        the retry loop around it is PROGRESSING (attempt returned, backoff
        chosen).  A retrying seam is a retry-policy problem, not a hang —
        the watchdog must not abandon a worker the policy is handling."""
        tid = threading.get_ident()
        with self._lock:
            entry = self._pending.get(tid)
            if entry is not None:
                self._pending[tid] = (entry[0], time.monotonic())
            self.beats += 1
            self.last_progress = time.monotonic()

    def stall(self) -> tuple[float, str | None]:
        """(age_seconds, seam_label) of the OLDEST pending IO op, or
        ``(0.0, None)`` when nothing is in flight — no pending op means
        the slot is computing or idle, which is never a hang."""
        now = time.monotonic()
        with self._lock:
            if not self._pending:
                return 0.0, None
            label, t0 = min(self._pending.values(), key=lambda e: e[1])
            return now - t0, label


class Watchdog:
    """Scanner thread over every worker slot's heartbeat.

    Owned by the writer (created at ``start()`` when
    ``Builder.watchdog(...)`` was configured, stopped at ``close()``).
    ``on_stall(index, worker, age, label)`` fires once per stall episode
    — the writer uses it to meter, log, optionally condemn the worker
    and declare a failover filesystem's primary down.
    """

    def __init__(self, workers_fn, deadline_s: float,
                 poll_interval_s: float | None = None,
                 on_stall=None) -> None:
        if deadline_s <= 0:
            raise ValueError("deadline_s must be positive")
        self.deadline_s = deadline_s
        self.poll_interval_s = (poll_interval_s if poll_interval_s is not None
                                else max(0.02, min(1.0, deadline_s / 4.0)))
        self._workers_fn = workers_fn  # () -> list of worker slots
        self._on_stall = on_stall
        self._stop = threading.Event()
        self._lock = threading.Lock()
        # index -> {"since", "age_s", "label"} for currently-stalled slots
        self._stalled: dict[int, dict] = {}
        self.stalls_total = 0
        self._thread = threading.Thread(target=self._run,
                                        name="KPW-watchdog", daemon=True)

    def start(self) -> None:
        self._thread.start()

    def close(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=timeout)

    def any_stalled(self) -> bool:
        with self._lock:
            return bool(self._stalled)

    def snapshot(self) -> dict:
        """stats() block: the live stalled set + episode count."""
        with self._lock:
            return {
                "deadline_s": self.deadline_s,
                "stalled_workers": [
                    {"worker": i, **dict(info)}
                    for i, info in sorted(self._stalled.items())],
                "stalls_total": self.stalls_total,
            }

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self._scan()
            except Exception:
                logger.exception("watchdog scan failed (ignored)")

    def _scan(self) -> None:
        now = time.monotonic()
        for i, w in enumerate(self._workers_fn()):
            hb = getattr(w, "heartbeat", None)
            if hb is None:
                continue
            age, label = hb.stall()
            with self._lock:
                cur = self._stalled.get(i)
                if age >= self.deadline_s:
                    new_episode = cur is None
                    self._stalled[i] = {
                        "since": (cur["since"] if cur else now - age),
                        "age_s": round(age, 3),
                        "label": label,
                    }
                    if new_episode:
                        self.stalls_total += 1
                else:
                    new_episode = False
                    if cur is not None:
                        del self._stalled[i]
            if age >= self.deadline_s and new_episode \
                    and self._on_stall is not None:
                try:
                    self._on_stall(i, w, age, label)
                except Exception:
                    logger.exception("watchdog on_stall hook failed "
                                     "(ignored)")
