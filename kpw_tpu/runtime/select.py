"""Encoder backend selection: probe the accelerator link, pick the plan.

The framework has three interchangeable encode paths behind the
``EncoderBackend`` boundary (SURVEY.md §1, the L1/L0 seam): the numpy
reference (oracle), the native C++ host path, and the TPU kernel path.
Offload only pays when the host↔device link can stream batches faster than
the host can encode them — on a production TPU host (PCIe/ICI, tens of
GB/s) the TPU path wins; behind a slow tunnel or on a CPU-only platform the
native path wins.  ``auto`` measures instead of assuming.
"""

from __future__ import annotations

import time

import numpy as np

# Offload threshold: the native host encoder sustains roughly 0.5-1 GB/s of
# input per core, so a link below ~1 GB/s (or with non-interactive dispatch
# latency) makes device offload a net loss for streaming encode.
_MIN_H2D_MBPS = 1000.0
_MAX_DISPATCH_MS = 10.0

_cached: str | None = None
_probe_cached: dict | None = None


def probe_link(size_bytes: int = 4 << 20) -> dict:
    """Measure host->device bandwidth and dispatch round-trip latency for the
    default JAX device (cached per process).  Returns {platform, h2d_mbps,
    dispatch_ms}."""
    global _probe_cached
    if _probe_cached is not None:
        return _probe_cached
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    if dev.platform == "cpu":
        _probe_cached = {"platform": "cpu", "h2d_mbps": float("inf"),
                         "dispatch_ms": 0.0}
        return _probe_cached
    # Everything is timed through a device->host readback: on tunneled /
    # proxied backends block_until_ready() can ack before the transfer has
    # actually landed, so only a round trip measures the real link.
    f = jax.jit(lambda a: a + 1)
    y = jnp.zeros((8,), jnp.int32)
    np.asarray(f(y))  # compile + transfer paths outside the timed region
    t0 = time.perf_counter()
    np.asarray(f(y))
    dispatch_ms = (time.perf_counter() - t0) * 1e3
    # Incompressible payload (a tunnel may compress constant pages), reduced
    # on device to a scalar so the H2D transfer must complete.
    rng = np.random.default_rng(0)
    x = np.frombuffer(rng.bytes(size_bytes), np.uint8)
    warm = np.frombuffer(rng.bytes(size_bytes), np.uint8)
    g = jax.jit(lambda a: jnp.sum(a, dtype=jnp.int32))
    np.asarray(g(warm))  # compile at full shape, outside the timed region
    t0 = time.perf_counter()
    np.asarray(g(x))
    dt = time.perf_counter() - t0
    h2d = size_bytes / 1e6 / max(dt - dispatch_ms / 1e3, 1e-9)
    _probe_cached = {"platform": dev.platform, "h2d_mbps": h2d,
                     "dispatch_ms": dispatch_ms}
    return _probe_cached


def choose_backend() -> str:
    """'tpu' when the measured link supports profitable offload, else
    'native'.  The probe runs once per process."""
    global _cached
    if _cached is None:
        try:
            p = probe_link()
            offload = (p["platform"] != "cpu"
                       and p["h2d_mbps"] >= _MIN_H2D_MBPS
                       and p["dispatch_ms"] <= _MAX_DISPATCH_MS)
            _cached = "tpu" if offload else "native"
        except Exception:
            _cached = "native"
    return _cached


def make_encoder(options, backend: str = "auto"):
    """Instantiate a chunk encoder for ``backend`` ('auto' | 'tpu' |
    'native' | 'cpu' | 'mesh')."""
    if backend == "auto":
        backend = choose_backend()
    if backend == "tpu":
        from ..ops.backend import TpuChunkEncoder

        return TpuChunkEncoder(options)
    if backend == "native":
        from ..native.encoder import NativeChunkEncoder

        return NativeChunkEncoder(options)
    if backend == "cpu":
        from ..core.pages import CpuChunkEncoder

        return CpuChunkEncoder(options)
    if backend == "mesh":
        # multi-chip: mesh-global dictionary merge over every visible
        # device (never auto-selected — a topology decision, not a link
        # probe; see parallel/mesh_encoder.py)
        from ..parallel.mesh_encoder import MeshChunkEncoder

        return MeshChunkEncoder(options)
    raise ValueError(f"unknown encoder backend: {backend!r}")
