"""Pull-based metric exporters: Prometheus text + JSON renderers.

The reference delegates exposition to whatever Dropwizard reporter the
host app wires up (KafkaProtoParquetWriter.java:144-151 only registers);
this module is the rebuild's equivalent seam, kept dependency-free: a
scrape endpoint calls :func:`registry_to_prometheus` (Prometheus
text-exposition format 0.0.4) or :func:`registry_to_json` on whatever
cadence it likes — nothing here runs a server or a thread, and gauges
backed by callables are sampled only at render time.

Both renderers are generic over the registry, so every canonical metric a
writer registers — including the degraded-operation set (the
``parquet.writer.stalled`` meter, the ``parquet.writer.paused`` gauge, and
the failover composite's ``parquet.writer.spilled`` /
``parquet.writer.reconciled`` / ``parquet.writer.reconcile.failed``
meters) — shows up in both formats with no per-metric wiring (pinned by
``test_degraded_metrics_render_in_exporters``).
"""

from __future__ import annotations

import json
import re

from .metrics import Gauge, Histogram, Meter

_PROM_BAD = re.compile(r"[^a-zA-Z0-9_:]")
_PROM_LEAD = re.compile(r"^[^a-zA-Z_:]")


def prometheus_name(name: str) -> str:
    """Dotted metric name -> Prometheus metric name (`[a-zA-Z_:][a-zA-Z0-9_:]*`):
    ``parquet.writer.written.records`` -> ``parquet_writer_written_records``."""
    out = _PROM_BAD.sub("_", name)
    if _PROM_LEAD.match(out):
        out = "_" + out
    return out


def _num(v: float) -> str:
    """Prometheus sample value: repr-roundtrippable, NaN spelled ``NaN``."""
    if v != v:  # NaN (a dead gauge provider)
        return "NaN"
    return f"{v:.10g}"


def registry_to_json(registry) -> dict:
    """One JSON-serializable snapshot of every registered metric, keyed by
    its canonical (dotted) name, with a ``type`` discriminator per entry."""
    out: dict = {}
    for name in registry.names():
        m = registry.get(name)
        if isinstance(m, Meter):
            out[name] = {"type": "meter", **m.snapshot()}
        elif isinstance(m, Histogram):
            out[name] = {"type": "histogram", **m.snapshot()}
        elif isinstance(m, Gauge):
            v = m.value
            # NaN (a dead provider) is not valid RFC JSON — null instead,
            # so one broken gauge can't invalidate the whole document
            out[name] = {"type": "gauge", "value": None if v != v else v}
        else:  # a foreign metric object: expose what it shows
            out[name] = {"type": type(m).__name__}
    return out


def registry_to_json_str(registry, **dumps_kwargs) -> str:
    return json.dumps(registry_to_json(registry), **dumps_kwargs)


def registry_to_prometheus(registry) -> str:
    """Prometheus text-exposition rendering:

    - Meter  -> ``<name>_total`` counter + ``<name>_rate{window=...}``
      gauges (1m/5m/15m EWMAs + lifetime mean, events/second)
    - Histogram -> ``<name>`` summary (p50/p95/p99 quantile samples +
      ``_count``) and ``<name>_min``/``_max``/``_mean`` gauges
    - Gauge  -> plain gauge (callable-backed gauges sampled now; a raising
      provider renders ``NaN`` rather than failing the scrape)
    """
    lines: list[str] = []
    for name in registry.names():
        m = registry.get(name)
        pname = prometheus_name(name)
        if isinstance(m, Meter):
            s = m.snapshot()
            lines.append(f"# TYPE {pname}_total counter")
            lines.append(f"{pname}_total {s['count']}")
            lines.append(f"# TYPE {pname}_rate gauge")
            for window, key in (("1m", "m1_rate"), ("5m", "m5_rate"),
                                ("15m", "m15_rate"), ("mean", "mean_rate")):
                lines.append(
                    f'{pname}_rate{{window="{window}"}} {_num(s[key])}')
        elif isinstance(m, Histogram):
            s = m.snapshot()
            lines.append(f"# TYPE {pname} summary")
            for q, key in (("0.5", "p50"), ("0.95", "p95"), ("0.99", "p99")):
                lines.append(f'{pname}{{quantile="{q}"}} {_num(s[key])}')
            lines.append(f"{pname}_count {s['count']}")
            for suffix in ("min", "max", "mean"):
                lines.append(f"# TYPE {pname}_{suffix} gauge")
                lines.append(f"{pname}_{suffix} {_num(s[suffix])}")
        elif isinstance(m, Gauge):
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_num(m.value)}")
    return "\n".join(lines) + "\n"
